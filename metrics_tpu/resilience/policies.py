"""Unified resilience policies: retry, deadline, and circuit-breaker vocabulary.

Before this module each plane hand-rolled its own loop: the async engine's
inline exponential backoff, the KV-store subgroup channel's fixed
per-peer-read timeout (N peers could wait N x the budget), and the
durability plane's save-retry logic in its callers. One vocabulary now
covers all three, with per-plane defaults and overrides:

* :class:`RetryPolicy` — bounded exponential backoff with a multiplier cap.
  The async engine's degraded-link loop runs on it
  (``AsyncSyncEngine(retry_policy=...)``; the legacy
  ``max_retries``/``backoff_s`` knobs construct one), and the checkpoint
  auto-save policy retries failed background saves through it.
* :class:`DeadlineBudget` — one wall-clock budget shared across the
  sequential steps of a compound operation. The KV-store subgroup channel
  charges every per-peer blocking read against ONE budget for the whole
  round,
  so a round over N peers can never wait N x the timeout.
* :class:`CircuitBreaker` — consecutive-failure trip with timed half-open
  probes. The admission queue can front its dispatch with one
  (``AdmissionQueue(breaker=...)``): while open, cohorts shed immediately
  under the exact reason ``breaker_open`` instead of burning a doomed
  dispatch per flush, and a half-open probe closes it again on the first
  success.

Per-plane defaults live in :data:`PLANE_POLICIES`
(:func:`retry_policy_for` / :func:`set_retry_policy`): a deployment can
tighten the checkpoint plane's backoff without touching the sync engine's.

Everything here is host-side and allocation-light; decisions surface in the
``resilience.*`` counters (``policy_retries``, ``deadline_exhausted``,
``breaker_opens``, ``breaker_short_circuits``).
"""
import threading
import time
from typing import Dict, Optional

from metrics_tpu.resilience.telemetry import RESILIENCE_STATS

__all__ = [
    "CircuitBreaker",
    "DeadlineBudget",
    "DeadlineExhausted",
    "PLANE_POLICIES",
    "RetryPolicy",
    "retry_policy_for",
    "set_retry_policy",
]


class DeadlineExhausted(TimeoutError):
    """A :class:`DeadlineBudget` ran out before the compound operation
    finished."""


class RetryPolicy:
    """Bounded exponential backoff: attempt ``k`` (1-based retry index)
    sleeps ``min(backoff_s * multiplier**(k-1), max_backoff_s)``; after
    ``max_retries`` retries the caller's terminal path runs. Immutable and
    shareable across threads."""

    __slots__ = ("max_retries", "backoff_s", "multiplier", "max_backoff_s")

    def __init__(
        self,
        max_retries: int = 2,
        backoff_s: float = 0.05,
        *,
        multiplier: float = 2.0,
        max_backoff_s: float = 2.0,
    ) -> None:
        if int(max_retries) < 0:
            raise ValueError(f"max_retries must be >= 0, got {max_retries}")
        if float(backoff_s) < 0:
            raise ValueError(f"backoff_s must be >= 0, got {backoff_s}")
        if float(multiplier) < 1.0:
            raise ValueError(f"multiplier must be >= 1, got {multiplier}")
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.multiplier = float(multiplier)
        self.max_backoff_s = float(max_backoff_s)

    def backoff(self, attempt: int) -> float:
        """Sleep length before retry ``attempt`` (1-based)."""
        if attempt < 1:
            return 0.0
        return min(
            self.backoff_s * self.multiplier ** (attempt - 1), self.max_backoff_s
        )

    def should_retry(self, attempt: int) -> bool:
        """True while retry ``attempt`` (1-based) is inside the bound."""
        return attempt <= self.max_retries

    def sleep(self, attempt: int) -> float:
        """Count and perform the backoff sleep for retry ``attempt``;
        returns the slept duration."""
        RESILIENCE_STATS.inc("policy_retries")
        dur = self.backoff(attempt)
        if dur > 0:
            time.sleep(dur)
        return dur

    def with_overrides(
        self, max_retries: Optional[int] = None, backoff_s: Optional[float] = None
    ) -> "RetryPolicy":
        """A copy with the legacy per-call knobs applied (how the async
        engine's ``max_retries=``/``backoff_s=`` arguments map onto the
        unified vocabulary)."""
        if max_retries is None and backoff_s is None:
            return self
        return RetryPolicy(
            self.max_retries if max_retries is None else int(max_retries),
            self.backoff_s if backoff_s is None else float(backoff_s),
            multiplier=self.multiplier,
            max_backoff_s=self.max_backoff_s,
        )

    def __repr__(self) -> str:
        return (
            f"RetryPolicy(max_retries={self.max_retries}, backoff_s={self.backoff_s},"
            f" multiplier={self.multiplier}, max_backoff_s={self.max_backoff_s})"
        )

    def __eq__(self, other: object) -> bool:
        return isinstance(other, RetryPolicy) and all(
            getattr(self, f) == getattr(other, f) for f in RetryPolicy.__slots__
        )


class DeadlineBudget:
    """One wall-clock budget shared by the sequential steps of a compound
    operation (a subgroup round's N per-peer reads, an auto-save's
    snapshot+write). The clock starts at construction; each step asks
    :meth:`remaining` (or :meth:`remaining_ms`) for ITS bound, so the total
    can never exceed ``total_s`` no matter how many steps run.

    ``total_s=None`` is the unbounded budget (remaining is ``None``/huge) —
    callers keep one code path."""

    __slots__ = ("total_s", "_t0")

    def __init__(self, total_s: Optional[float]) -> None:
        if total_s is not None and float(total_s) <= 0:
            raise ValueError(f"total_s must be > 0 (or None), got {total_s}")
        self.total_s = None if total_s is None else float(total_s)
        self._t0 = time.monotonic()

    def elapsed(self) -> float:
        return time.monotonic() - self._t0

    def remaining(self, *, floor: float = 0.0) -> Optional[float]:
        """Seconds left (``None`` when unbounded); never below ``floor``."""
        if self.total_s is None:
            return None
        return max(floor, self.total_s - self.elapsed())

    def remaining_ms(self, *, floor_ms: float = 1.0) -> Optional[int]:
        rem = self.remaining()
        if rem is None:
            return None
        return int(max(floor_ms, rem * 1e3))

    @property
    def expired(self) -> bool:
        return self.total_s is not None and self.elapsed() >= self.total_s

    def check(self, what: str = "operation") -> None:
        """Raise :class:`DeadlineExhausted` (and count it) when expired."""
        if self.expired:
            RESILIENCE_STATS.inc("deadline_exhausted")
            raise DeadlineExhausted(
                f"{what} exceeded its {self.total_s}s deadline budget"
                f" ({self.elapsed():.3f}s elapsed)"
            )

    def __repr__(self) -> str:
        return f"DeadlineBudget(total_s={self.total_s}, elapsed={self.elapsed():.3f})"


class CircuitBreaker:
    """Consecutive-failure circuit with timed half-open probes.

    ``closed`` (normal) → ``open`` after ``failure_threshold`` consecutive
    :meth:`record_failure` calls (counted ``breaker_opens``); while open,
    :meth:`allow` returns False (counted ``breaker_short_circuits``) until
    ``reset_after_s`` elapses, when exactly one caller is admitted as the
    half-open probe — its success closes the circuit, its failure re-opens
    (and re-arms the timer). Thread-safe."""

    def __init__(self, failure_threshold: int = 5, reset_after_s: float = 30.0) -> None:
        if int(failure_threshold) < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        if float(reset_after_s) <= 0:
            raise ValueError(f"reset_after_s must be > 0, got {reset_after_s}")
        self.failure_threshold = int(failure_threshold)
        self.reset_after_s = float(reset_after_s)
        self._lock = threading.Lock()
        self._failures = 0
        self._state = "closed"
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            if (
                self._state == "open"
                and time.monotonic() - self._opened_at >= self.reset_after_s
            ):
                return "half_open"
            return self._state

    def allow(self) -> bool:
        """May the caller attempt the protected operation NOW?"""
        with self._lock:
            if self._state == "closed":
                return True
            if time.monotonic() - self._opened_at >= self.reset_after_s:
                if not self._probing:
                    self._probing = True  # exactly one half-open probe
                    return True
            RESILIENCE_STATS.inc("breaker_short_circuits")
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            self._state = "closed"

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probing = False
            if self._state == "open":
                # a failed half-open probe re-arms the timer
                self._opened_at = time.monotonic()
                return
            if self._failures >= self.failure_threshold:
                self._state = "open"
                self._opened_at = time.monotonic()
                RESILIENCE_STATS.inc("breaker_opens")

    def reset(self) -> None:
        with self._lock:
            self._failures = 0
            self._state = "closed"
            self._probing = False

    def __repr__(self) -> str:
        return (
            f"CircuitBreaker(state={self.state!r}, failures={self._failures},"
            f" threshold={self.failure_threshold})"
        )


#: per-plane retry defaults — override with :func:`set_retry_policy`
PLANE_POLICIES: Dict[str, RetryPolicy] = {
    "async_sync": RetryPolicy(max_retries=2, backoff_s=0.05),
    "subgroup": RetryPolicy(max_retries=1, backoff_s=0.02),
    "checkpoint": RetryPolicy(max_retries=2, backoff_s=0.2),
}
_PLANE_LOCK = threading.Lock()


def retry_policy_for(plane: str) -> RetryPolicy:
    """The plane's current retry policy (falls back to the ``async_sync``
    default for unknown planes — one vocabulary, forgiving lookup)."""
    with _PLANE_LOCK:
        return PLANE_POLICIES.get(plane) or PLANE_POLICIES["async_sync"]


def set_retry_policy(plane: str, policy: RetryPolicy) -> RetryPolicy:
    """Install a per-plane override; returns the previous policy."""
    if not isinstance(policy, RetryPolicy):
        raise TypeError(f"policy must be a RetryPolicy, got {type(policy).__name__}")
    with _PLANE_LOCK:
        previous = PLANE_POLICIES.get(plane)
        PLANE_POLICIES[plane] = policy
        return previous if previous is not None else PLANE_POLICIES["async_sync"]
