"""The ``resilience.*`` telemetry family: evidence for the robustness plane.

One process-global :class:`ResilienceStats` ledger records every injected
fault (by seam and mode), every failure-detector verdict, every membership
epoch transition (failures and rejoins separately), and every policy
decision (retries spent, deadline exhaustions, circuit-breaker opens and
short-circuits). The ledger surfaces in the same three places as the
serving and durability families:

* ``observability.snapshot()["resilience"]`` — the JSON view below, ``{}``
  until the resilience plane is first touched (processes that never inject
  a fault or run the detector keep a clean snapshot). Fleet aggregation
  works day one: :data:`~metrics_tpu.observability.aggregate.MERGE_RULES`
  declares counters sum and the membership epoch maxes (the fleet view's
  epoch is the newest any process has seen).
* the ``metrics_tpu_resilience_*`` Prometheus series
  (:func:`~metrics_tpu.observability.export.render_prometheus`).
* ``resilience`` timeline events: one per injected fault and one per
  membership transition, so a chaos run's fault schedule and the
  detector's reactions line up on the same Perfetto timeline as the
  collectives they perturbed.

Everything here is host-side bookkeeping behind the lock-free
``TELEMETRY.enabled`` gate — with one deliberate exception: **membership
epoch transitions are always counted**, like the admission queue's exact
ledger, because the epoch is correctness-bearing (consumers compare it),
not diagnostic. The compiled metric programs are untouched (the
zero-overhead gate's resilience-off sweep pins it).
"""
import threading
from typing import Any, Dict

from metrics_tpu.observability.events import EVENTS
from metrics_tpu.observability.registry import TELEMETRY

__all__ = [
    "RESILIENCE_STATS",
    "ResilienceStats",
    "note_fault",
    "note_transition",
    "summary",
]


class ResilienceStats:
    """Thread-safe counters for the resilience plane (one process-global
    instance, :data:`RESILIENCE_STATS`; private instances supported for
    tests). ``touched`` stays False until the first fault fires, detector
    verdict lands, or epoch moves, so an idle process's snapshot omits the
    section entirely."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._touched = False
        self._counters: Dict[str, int] = {
            "faults_injected": 0,
            "detector_suspects": 0,
            "peer_failures": 0,
            "peer_rejoins": 0,
            "epoch_transitions": 0,
            "policy_retries": 0,
            "deadline_exhausted": 0,
            "breaker_opens": 0,
            "breaker_short_circuits": 0,
        }
        self._faults_by_seam: Dict[str, int] = {}
        self._epoch = 0

    # -- recording ----------------------------------------------------------

    def inc(self, counter: str, n: int = 1) -> None:
        if not TELEMETRY.enabled:
            return
        with self._lock:
            self._touched = True
            self._counters[counter] = self._counters.get(counter, 0) + int(n)

    def fault(self, seam: str, mode: str) -> None:
        """One injected fault — the per-(seam, mode) split and the total
        move together, so the fault-schedule accounting can never drift."""
        if not TELEMETRY.enabled:
            return
        key = f"{seam}:{mode}"
        with self._lock:
            self._touched = True
            self._counters["faults_injected"] += 1
            self._faults_by_seam[key] = self._faults_by_seam.get(key, 0) + 1

    def transition(self, epoch: int, kind: str) -> None:
        """One membership epoch transition (``kind`` = ``failure`` /
        ``rejoin``). Counted unconditionally: the epoch is part of the
        cross-process contract, not a diagnostic."""
        with self._lock:
            self._touched = True
            self._counters["epoch_transitions"] += 1
            self._counters["peer_failures" if kind == "failure" else "peer_rejoins"] += 1
            if epoch > self._epoch:
                self._epoch = int(epoch)

    # -- reading ------------------------------------------------------------

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def summary(self) -> Dict[str, Any]:
        """The ``snapshot()["resilience"]`` section (``{}`` when
        untouched)."""
        with self._lock:
            if not self._touched:
                return {}
            return {
                **dict(self._counters),
                "faults_by_seam": dict(self._faults_by_seam),
                "epoch": self._epoch,
            }

    def reset(self) -> None:
        """Zero every counter and the epoch high-water (the live membership
        object keeps its own epoch — reset it separately, and like any
        cross-process state, on every process together or on none)."""
        with self._lock:
            for k in self._counters:
                self._counters[k] = 0
            self._faults_by_seam.clear()
            self._epoch = 0
            self._touched = False


#: the process-global resilience ledger
RESILIENCE_STATS = ResilienceStats()


def summary() -> Dict[str, Any]:
    """Module-level accessor ``observability.snapshot()`` reads."""
    return RESILIENCE_STATS.summary()


def note_fault(seam: str, mode: str, **payload: Any) -> None:
    """One injected fault: counter + a ``resilience`` timeline event, so the
    chaos schedule is reconstructible from the exported trace."""
    RESILIENCE_STATS.fault(seam, mode)
    if EVENTS.enabled:
        EVENTS.record(
            "resilience", seam, path="fault", mode=mode,
            **{k: v for k, v in payload.items() if v is not None},
        )


def note_transition(epoch: int, kind: str, peer: int, reason: str) -> None:
    """One membership transition: counter (unconditional) + a ``resilience``
    timeline event (telemetry-gated like every event)."""
    RESILIENCE_STATS.transition(epoch, kind)
    if EVENTS.enabled:
        EVENTS.record(
            "resilience", "membership", path=kind, epoch=int(epoch),
            peer=int(peer), reason=reason,
        )
