"""Unified, seeded fault injection at named seams.

Every plane in the library grew its own fault hooks as it grew its own
defenses: the durability plane's ``inject_crash`` crash points, the async
engine's flaky-peer test shims, ad-hoc monkeypatched transport failures in
the test suite. This module replaces them with ONE vocabulary the tests and
the chaos soak share:

* a **seam** is a named host-side injection point the library consults on
  its fault-relevant paths (:data:`SEAMS` — transport rounds, the subgroup
  channel exchange, async-engine attempts, admission-queue dispatch, every
  checkpoint protocol step);
* a :class:`FaultSpec` arms one seam with a **mode** — ``delay`` (sleep
  before the operation), ``drop`` (the operation is abandoned:
  :class:`DroppedFault`), ``error`` (a transient failure:
  :class:`FaultInjected`), ``corrupt`` (the call site is handed a
  deterministic byte-corruptor to apply to its payload), ``crash`` (a
  process-death stand-in: :class:`CrashFault`; the checkpoint seams
  translate it to the durability plane's ``CheckpointCrash``) — firing at
  explicit hit indices (``at``), with a seeded probability (``prob``), or
  on every hit, optionally capped (``times``) and restricted to one
  simulated process (``process``);
* a :class:`FaultPlan` bundles specs under one seed. **Determinism is the
  point**: a plan built from ``(seed, specs)`` fires the same faults at the
  same seam hit counts on every run, so a chaos soak failure reproduces
  from its seed alone.

Install a plan process-wide with :func:`install_fault_plan` (or the
scoped :func:`fault_plan` context manager); the library's seams call
:func:`maybe_fault`, which is a single attribute read when no plan is
installed — fault injection disabled adds zero traced ops AND near-zero
host work (the zero-overhead gate's resilience-off sweep pins the former).

Every fired fault is counted (``resilience.faults_injected``, split by
seam and mode) and lands on the event timeline, so a chaos run's schedule
is reconstructible from its telemetry.
"""
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from metrics_tpu.resilience.telemetry import note_fault

__all__ = [
    "CrashFault",
    "DroppedFault",
    "FaultInjected",
    "FaultPlan",
    "FaultSpec",
    "MODES",
    "SEAMS",
    "current_fault_plan",
    "fault_plan",
    "install_fault_plan",
    "maybe_fault",
]

#: the named seams the library consults (grouped by plane). The checkpoint
#: seams mirror ``durability.checkpoint.CRASH_POINTS`` one-to-one, so the
#: legacy ``inject_crash`` hook and a FaultPlan arm the same places.
SEAMS = (
    # eager gather transport (utilities/distributed.py::_gather_all_leaves)
    "transport.descriptor",
    "transport.payload",
    # the registered subgroup channel (transport/gather.py)
    "subgroup.exchange",
    # background sync engine attempts (utilities/async_sync.py)
    "async.attempt",
    # admission-queue coalesced dispatch (serving/queue.py)
    "serving.dispatch",
    # checkpoint protocol steps (durability/checkpoint.py::CRASH_POINTS)
    "checkpoint.before_shard",
    "checkpoint.after_shard",
    "checkpoint.before_manifest",
    "checkpoint.after_manifest",
    "checkpoint.before_rename",
    "checkpoint.after_rename",
    "checkpoint.before_latest",
)

#: the fault modes a spec can arm
MODES = ("delay", "drop", "error", "corrupt", "crash")


class FaultInjected(RuntimeError):
    """A seam fired in ``error`` mode — a transient failure the surrounding
    policy (retry / stale / quorum / shed accounting) must absorb."""

    def __init__(self, seam: str, mode: str = "error") -> None:
        super().__init__(f"injected {mode} fault at seam {seam!r}")
        self.seam = seam
        self.mode = mode


class DroppedFault(FaultInjected):
    """A seam fired in ``drop`` mode — the operation (a transport round, an
    engine attempt) is abandoned as if the payload never arrived."""

    def __init__(self, seam: str) -> None:
        super().__init__(seam, mode="drop")


class CrashFault(FaultInjected):
    """A seam fired in ``crash`` mode — the process-death stand-in (the
    checkpoint seams translate it to ``CheckpointCrash`` so the crash-safe
    protocol tests see their native exception type)."""

    def __init__(self, seam: str) -> None:
        super().__init__(seam, mode="crash")


class FaultSpec:
    """One armed seam. Fires when ALL its filters match a hit:

    Args:
        seam: one of :data:`SEAMS`.
        mode: one of :data:`MODES`.
        at: explicit 0-based hit indices at which to fire (the
            deterministic schedule a chaos soak uses). ``None`` = every hit
            (subject to ``prob``/``times``).
        prob: seeded firing probability per hit (only when ``at`` is
            ``None``; drawn from the plan's per-spec RNG stream, so the
            firing pattern is a pure function of the plan seed).
        times: cap on total fires (``None`` = unlimited).
        delay_s: sleep length for ``delay`` mode.
        process: restrict to one (simulated) process index — the hit's
            ``process=`` context value must match.
        exc: exception class raised for ``error``/``drop``/``crash`` modes
            (defaults by mode; the class is called with the seam name).
    """

    __slots__ = ("seam", "mode", "at", "prob", "times", "delay_s", "process", "exc")

    def __init__(
        self,
        seam: str,
        mode: str,
        *,
        at: Optional[Sequence[int]] = None,
        prob: Optional[float] = None,
        times: Optional[int] = None,
        delay_s: float = 0.05,
        process: Optional[int] = None,
        exc: Optional[Type[BaseException]] = None,
    ) -> None:
        if seam not in SEAMS:
            raise ValueError(f"unknown seam {seam!r}; one of {SEAMS}")
        if mode not in MODES:
            raise ValueError(f"unknown fault mode {mode!r}; one of {MODES}")
        if at is not None and prob is not None:
            raise ValueError("pass at= (a deterministic schedule) OR prob=, not both")
        if prob is not None and not 0.0 <= float(prob) <= 1.0:
            raise ValueError(f"prob must be in [0, 1], got {prob}")
        self.seam = seam
        self.mode = mode
        self.at = frozenset(int(i) for i in at) if at is not None else None
        self.prob = float(prob) if prob is not None else None
        self.times = int(times) if times is not None else None
        self.delay_s = float(delay_s)
        self.process = int(process) if process is not None else None
        self.exc = exc

    def __repr__(self) -> str:
        sched = (
            f"at={sorted(self.at)}" if self.at is not None
            else f"prob={self.prob}" if self.prob is not None
            else "always"
        )
        return f"FaultSpec({self.seam}, {self.mode}, {sched})"


class _Corruptor:
    """Deterministic byte corruptor handed to ``corrupt``-mode call sites:
    flips one seeded byte per kilobyte of the payload (enough to break any
    checksum, deterministic from the plan seed + fire index)."""

    def __init__(self, seed: int) -> None:
        self.mode = "corrupt"
        self._seed = int(seed)

    def corrupt(self, data: Any) -> np.ndarray:
        arr = np.asarray(data)
        flat = arr.reshape(-1).view(np.uint8).copy()
        if flat.size == 0:
            return arr
        rng = np.random.RandomState(self._seed)
        idx = rng.randint(0, flat.size, size=max(1, flat.size // 1024))
        flat[idx] ^= 0xFF
        return flat.view(arr.dtype.newbyteorder("="))[: arr.size].reshape(arr.shape)


class FaultPlan:
    """A seeded, deterministic fault schedule over the named seams.

    Per-seam hit counters advance on every :func:`maybe_fault` consult
    (whether or not a spec fires), so ``at=[k]`` names the k-th time the
    library reaches that seam — a stable coordinate across runs. Seams that
    pass a ``process=`` context (the transport rounds, the subgroup
    channel) count per ``(seam, process)``: with several simulated ranks
    hitting one seam concurrently, ``at=[0]`` + ``process=1`` names rank
    1's OWN first hit, not a thread-interleaving-dependent global index.
    Thread safety: counters advance under one lock; with ``prob`` specs the
    draw order across threads follows the (locked) hit order.
    """

    def __init__(self, seed: int = 0, specs: Sequence[FaultSpec] = ()) -> None:
        self.seed = int(seed)
        self.specs: List[FaultSpec] = list(specs)
        for s in self.specs:
            if not isinstance(s, FaultSpec):
                raise TypeError(f"specs must be FaultSpec, got {type(s).__name__}")
        self._lock = threading.Lock()
        self._hits: Dict[str, int] = {}
        self._fires: Dict[int, int] = {}  # spec index -> fires
        self._fired_log: List[Tuple[str, str, int]] = []  # (seam, mode, hit)
        # one independent seeded stream per prob-spec: the firing pattern is
        # a pure function of (plan seed, spec index, hit order)
        self._rngs: Dict[int, np.random.RandomState] = {
            i: np.random.RandomState((self.seed * 1_000_003 + i) % (2**32))
            for i, s in enumerate(self.specs)
            if s.prob is not None
        }

    def add(self, spec: FaultSpec) -> "FaultPlan":
        """Append one spec (chainable); ``prob`` specs get their seeded
        stream keyed by their index, as at construction."""
        with self._lock:
            self.specs.append(spec)
            i = len(self.specs) - 1
            if spec.prob is not None:
                self._rngs[i] = np.random.RandomState(
                    (self.seed * 1_000_003 + i) % (2**32)
                )
        return self

    # -- firing --------------------------------------------------------------

    def fire(self, seam: str, ctx: Dict[str, Any]) -> Optional[Any]:
        """Consult the plan at ``seam``: advance the hit counter, find the
        first matching armed spec, and APPLY its mode — sleep for ``delay``,
        raise for ``drop``/``error``/``crash``, return a corruptor for
        ``corrupt`` (``None`` when nothing fired)."""
        counter_key = (
            f"{seam}@{ctx['process']}" if "process" in ctx else seam
        )
        with self._lock:
            hit = self._hits.get(counter_key, 0)
            self._hits[counter_key] = hit + 1
            chosen: Optional[Tuple[int, FaultSpec]] = None
            for i, spec in enumerate(self.specs):
                if spec.seam != seam:
                    continue
                if spec.process is not None and ctx.get("process") != spec.process:
                    continue
                if spec.times is not None and self._fires.get(i, 0) >= spec.times:
                    continue
                if spec.at is not None:
                    if hit not in spec.at:
                        continue
                elif spec.prob is not None:
                    if self._rngs[i].random_sample() >= spec.prob:
                        continue
                chosen = (i, spec)
                break
            if chosen is None:
                return None
            i, spec = chosen
            self._fires[i] = self._fires.get(i, 0) + 1
            self._fired_log.append((seam, spec.mode, hit))
            fire_index = len(self._fired_log)
        note_fault(seam, spec.mode, hit=hit, **_jsonable(ctx))
        if spec.mode == "delay":
            time.sleep(spec.delay_s)
            return None
        if spec.mode == "corrupt":
            return _Corruptor(self.seed * 97 + fire_index)
        exc = spec.exc
        if exc is not None:
            raise exc(seam)
        if spec.mode == "drop":
            raise DroppedFault(seam)
        if spec.mode == "crash":
            raise CrashFault(seam)
        raise FaultInjected(seam)

    # -- reading -------------------------------------------------------------

    def hits(self, seam: Optional[str] = None) -> Any:
        """Hit counters: one seam's count, or the whole dict."""
        with self._lock:
            if seam is not None:
                return self._hits.get(seam, 0)
            return dict(self._hits)

    def fired(self) -> List[Tuple[str, str, int]]:
        """Chronological ``(seam, mode, hit_index)`` log of every fired
        fault — the chaos soak's schedule evidence."""
        with self._lock:
            return list(self._fired_log)

    def report(self) -> Dict[str, Any]:
        with self._lock:
            by_seam: Dict[str, int] = {}
            for seam, mode, _ in self._fired_log:
                key = f"{seam}:{mode}"
                by_seam[key] = by_seam.get(key, 0) + 1
            return {
                "seed": self.seed,
                "specs": len(self.specs),
                "fired": len(self._fired_log),
                "fired_by_seam": by_seam,
                "hits": dict(self._hits),
            }

    def __repr__(self) -> str:
        return f"FaultPlan(seed={self.seed}, specs={len(self.specs)}, fired={len(self._fired_log)})"


def _jsonable(ctx: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in ctx.items() if isinstance(v, (str, int, float, bool))}


#: the installed plan — ``None`` (the default) keeps every seam a single
#: attribute read; the soak and the fault tests install one scoped plan
_PLAN: Optional[FaultPlan] = None
_PLAN_LOCK = threading.Lock()


def install_fault_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install ``plan`` process-wide (or clear with ``None``); returns the
    previously installed plan. Prefer the scoped :func:`fault_plan` context
    manager in tests."""
    global _PLAN
    if plan is not None and not isinstance(plan, FaultPlan):
        raise TypeError(f"plan must be a FaultPlan or None, got {type(plan).__name__}")
    with _PLAN_LOCK:
        previous = _PLAN
        _PLAN = plan
    return previous


def current_fault_plan() -> Optional[FaultPlan]:
    """The installed plan, or ``None``."""
    return _PLAN


@contextmanager
def fault_plan(plan: FaultPlan):
    """Install ``plan`` for the duration of the block (exception-safe; the
    previous plan — usually none — is restored on exit)."""
    previous = install_fault_plan(plan)
    try:
        yield plan
    finally:
        install_fault_plan(previous)


def maybe_fault(seam: str, **ctx: Any) -> Optional[Any]:
    """The seam call: a single attribute read when no plan is installed
    (the overwhelmingly common case); otherwise consult the plan — which
    may sleep, raise, or return a corruptor (see :meth:`FaultPlan.fire`)."""
    plan = _PLAN
    if plan is None:
        return None
    return plan.fire(seam, ctx)
