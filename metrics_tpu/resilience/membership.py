"""Epoch-based process membership: the versioned "who is alive" view.

PR-8's :func:`~metrics_tpu.observability.tracing.degraded_processes` is a
per-attempt HINT — each degraded-link policy consulted it independently,
right before its own transport attempt, and nothing tied one plane's view
of the fleet to another's. This module promotes it to a **versioned
membership epoch**:

* :class:`Membership` holds ``(epoch, alive set)``; every transition —
  a peer marked failed by the detector, a recovered peer explicitly
  rejoining — **bumps the epoch** and is recorded (the
  ``resilience.epoch_transitions`` counter and a ``resilience`` timeline
  event per transition, with peer/reason/epoch).
* Consumers read :meth:`current` and compare epochs instead of re-deriving
  peer health: the async engine's quorum forms its healthy subgroup from
  the membership's alive set (unioned with the per-attempt straggler hint
  — the hint can only narrow, never resurrect), and the serving
  scheduler's read path treats a cached value from an older epoch as
  expired (a fleet transition invalidates values computed under the old
  peer set).
* A recovered peer REJOINS only explicitly (:meth:`mark_recovered` /
  :meth:`rejoin`) — recovery is an operator/detector decision with its own
  epoch bump, never an implicit timeout, so two processes can never
  disagree about whether an epoch's peer set includes a flapping node.

The membership object is process-local state about the fleet (like the
span tracker): each process maintains its own view, converging through the
same signals. The epoch is monotonic; ``snapshot()["resilience"]["epoch"]``
merges as ``max`` across the fleet.
"""
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

from metrics_tpu.resilience.telemetry import note_transition

__all__ = [
    "MEMBERSHIP",
    "Membership",
    "MembershipView",
    "alive_processes",
    "current_epoch",
    "current_view",
    "dead_processes",
]

#: bound on retained transition records (~100 bytes each)
_TRANSITION_CAP = 256


class MembershipView(NamedTuple):
    """One immutable epoch: the version number and the peer partition."""

    epoch: int
    alive: Tuple[int, ...]
    dead: Tuple[int, ...]


def _world() -> int:
    from metrics_tpu.utilities.distributed import world_size

    return world_size()


class Membership:
    """Versioned fleet membership (one process-global instance,
    :data:`MEMBERSHIP`; private instances supported for tests).

    ``world=None`` sizes lazily from
    :func:`~metrics_tpu.utilities.distributed.world_size` at first use, so
    constructing the module costs nothing on a single-process run."""

    def __init__(self, world: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._world = int(world) if world is not None else None
        self._epoch = 0
        self._dead: set = set()
        self._transitions: List[Dict[str, Any]] = []

    # -- internals -----------------------------------------------------------

    def _ensure_world(self) -> int:
        if self._world is None:
            self._world = _world()
        return self._world

    def _view_locked(self) -> MembershipView:
        world = self._ensure_world()
        dead = tuple(sorted(p for p in self._dead if p < world))
        alive = tuple(p for p in range(world) if p not in self._dead)
        return MembershipView(self._epoch, alive, dead)

    def _record(self, kind: str, peer: int, reason: str) -> None:
        self._transitions.append(
            {
                "epoch": self._epoch,
                "kind": kind,
                "peer": int(peer),
                "reason": reason,
                "at_s": time.monotonic(),
            }
        )
        if len(self._transitions) > _TRANSITION_CAP:
            del self._transitions[: len(self._transitions) - _TRANSITION_CAP]

    # -- transitions ---------------------------------------------------------

    def mark_failed(self, peer: int, *, reason: str = "detector") -> MembershipView:
        """Remove ``peer`` from the alive set with an epoch bump (idempotent:
        re-marking a dead peer neither bumps nor records)."""
        peer = int(peer)
        with self._lock:
            world = self._ensure_world()
            if peer < 0 or peer >= world:
                raise ValueError(f"peer {peer} outside world of {world}")
            if peer in self._dead:
                return self._view_locked()
            if len(self._dead) + 1 >= world:
                raise ValueError(
                    f"refusing to mark peer {peer} failed: the alive set would be"
                    " empty — at least one process must remain a member"
                )
            self._dead.add(peer)
            self._epoch += 1
            self._record("failure", peer, reason)
            view = self._view_locked()
        note_transition(view.epoch, "failure", peer, reason)
        return view

    def mark_recovered(self, peer: int, *, reason: str = "rejoin") -> MembershipView:
        """Re-admit ``peer`` with an EXPLICIT epoch bump (idempotent). This
        is the only way back in — recovery is a decision, not a timeout."""
        peer = int(peer)
        with self._lock:
            if peer not in self._dead:
                return self._view_locked()
            self._dead.discard(peer)
            self._epoch += 1
            self._record("rejoin", peer, reason)
            view = self._view_locked()
        note_transition(view.epoch, "rejoin", peer, reason)
        return view

    #: the operator-facing alias — "the peer is back, bump the epoch"
    rejoin = mark_recovered

    # -- reading -------------------------------------------------------------

    def current(self) -> MembershipView:
        with self._lock:
            return self._view_locked()

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def alive(self) -> List[int]:
        return list(self.current().alive)

    def dead(self) -> List[int]:
        return list(self.current().dead)

    def is_alive(self, peer: int) -> bool:
        with self._lock:
            return int(peer) not in self._dead

    def transitions(self) -> List[Dict[str, Any]]:
        """The bounded transition history (newest last) — every epoch bump
        with its peer, direction and reason."""
        with self._lock:
            return [dict(t) for t in self._transitions]

    def summary(self) -> Dict[str, Any]:
        view = self.current()
        return {
            "epoch": view.epoch,
            "alive": list(view.alive),
            "dead": list(view.dead),
            "transitions": len(self.transitions()),
        }

    def reset(self, world: Optional[int] = None) -> None:
        """Back to epoch 0, everyone alive (tests; like any cross-process
        state, reset on every process together or on none)."""
        with self._lock:
            self._epoch = 0
            self._dead.clear()
            self._transitions.clear()
            if world is not None:
                self._world = int(world)

    def __repr__(self) -> str:
        view = self.current()
        return f"Membership(epoch={view.epoch}, alive={list(view.alive)}, dead={list(view.dead)})"


#: the process-global membership view
MEMBERSHIP = Membership()


def current_view() -> MembershipView:
    """The global membership's current ``(epoch, alive, dead)``."""
    return MEMBERSHIP.current()


def current_epoch() -> int:
    """The global membership epoch (0 until the first transition)."""
    return MEMBERSHIP.epoch


def alive_processes() -> List[int]:
    return MEMBERSHIP.alive()


def dead_processes() -> List[int]:
    """Peers the current epoch excludes — what the async engine unions with
    the per-attempt straggler hint."""
    return MEMBERSHIP.dead()
