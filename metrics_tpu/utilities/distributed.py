"""Distributed communication backend (L0).

Capability parity with the reference's ``torchmetrics/utilities/distributed.py``
(``reduce``/``class_reduce``/``gather_all_tensors`` over torch.distributed),
re-designed TPU-first with two complementary sync paths:

* **In-graph sync** (the TPU-idiomatic hot path): metric state lives inside a
  ``pjit``/``shard_map`` program over a ``jax.sharding.Mesh``; per-state
  reductions compile directly to XLA collectives over named mesh axes —
  ``lax.psum`` for "sum" states (skipping the reference's gather+host-reduce
  dance entirely), ``lax.pmean`` for "mean", ``lax.pmax``/``pmin`` for
  extrema, and a tiled ``lax.all_gather`` for "cat"/gather-only states.
  See :func:`sync_in_graph`.

* **Host (eager) sync** for epoch-boundary ``compute()`` across JAX processes:
  :func:`gather_all_arrays` mirrors the reference's protocol (shape gather ->
  pad to elementwise-max -> all-gather -> trim) on top of
  ``jax.experimental.multihost_utils`` since XLA collectives need static,
  equal shapes across participants.
"""
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Array = jax.Array

AxisName = Union[str, Tuple[str, ...]]


# ---------------------------------------------------------------------------
# Host-side reducers (parity: utilities/distributed.py:21-89)
# ---------------------------------------------------------------------------


def reduce(to_reduce: Array, reduction: str) -> Array:
    """Reduce an array with ``'elementwise_mean'``, ``'sum'`` or ``'none'``."""
    if reduction == "elementwise_mean":
        return jnp.mean(to_reduce)
    if reduction == "none":
        return to_reduce
    if reduction == "sum":
        return jnp.sum(to_reduce)
    raise ValueError("Reduction parameter unknown.")


def class_reduce(
    num: Array,
    denom: Array,
    weights: Array,
    class_reduction: str = "none",
) -> Array:
    """Reduce per-class fractions ``num / denom`` with micro/macro/weighted/none.

    NaNs arising from empty classes (0/0) are zeroed, matching the reference's
    semantics (``utilities/distributed.py:73-75``); infinities are untouched.
    """
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    if class_reduction == "micro":
        fraction = jnp.sum(num) / jnp.sum(denom)
    else:
        fraction = num / denom

    fraction = jnp.where(jnp.isnan(fraction), jnp.zeros_like(fraction), fraction)

    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        w = weights.astype(fraction.dtype)
        return jnp.sum(fraction * (w / jnp.sum(w)))
    if class_reduction == "none" or class_reduction is None:
        return fraction
    raise ValueError(
        f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}"
    )


# ---------------------------------------------------------------------------
# Process-level (multi-host) eager gather
# ---------------------------------------------------------------------------


def distributed_available() -> bool:
    """True when more than one JAX process participates in the runtime."""
    try:
        return jax.process_count() > 1
    except Exception:  # pragma: no cover
        return False


def world_size() -> int:
    return jax.process_count()


def _process_allgather(x: Array) -> Array:
    """All-gather ``x`` across processes -> stacked ``(num_processes, ...)``."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(np.asarray(x)))


#: descriptor layout for the ragged gather: [ndim, d0..d7, dtype_code]
_MAX_GATHER_NDIM = 8
#: dtypes the ragged gather can align across ranks (code = list index);
#: covers every dtype the library stores in states
_GATHER_DTYPES = (
    np.dtype(np.bool_),
    np.dtype(np.uint8),
    np.dtype(np.int8),
    np.dtype(np.int16),
    np.dtype(np.int32),
    np.dtype(np.int64),
    np.dtype(np.float16),
    np.dtype(np.float32),
    np.dtype(np.float64),
)


def gather_all_arrays(result: Array, group: Optional[Any] = None) -> List[Array]:
    """Gather one array from every process into a list (eager, epoch-boundary path).

    Handles per-process shape raggedness with the pad-to-max/trim protocol the
    reference uses (``utilities/distributed.py:126-149``): gather all shape
    descriptors, pad each local tensor to the elementwise max, all-gather,
    then trim each result back to its true shape. A rank with NO data (a
    never-updated list state — 0 elements, possibly of a different rank and
    placeholder dtype, the reference's 0-length case
    ``tests/bases/test_ddp.py:63-81``) still participates: the descriptor
    exchange aligns its contribution to the peers' ndim/dtype and its
    trimmed result is a 0-row tensor. ``group`` is accepted for API parity;
    use mesh-axis names with the in-graph path for sub-group reductions.
    """
    result = jnp.asarray(result)
    if not distributed_available():
        return [result]

    nprocs = world_size()

    if result.ndim == 0:
        gathered = _process_allgather(result)
        return [jnp.asarray(gathered[i]) for i in range(nprocs)]

    if result.ndim > _MAX_GATHER_NDIM:
        raise ValueError(f"gather_all_arrays supports up to {_MAX_GATHER_NDIM} dims, got {result.ndim}")
    np_dtype = np.dtype(result.dtype)
    if np_dtype not in _GATHER_DTYPES:
        raise ValueError(f"gather_all_arrays cannot align dtype {np_dtype} across ranks")

    desc = np.zeros(_MAX_GATHER_NDIM + 2, dtype=np.int64)
    desc[0] = result.ndim
    desc[1 : 1 + result.ndim] = result.shape
    desc[-1] = _GATHER_DTYPES.index(np_dtype)
    all_desc = _process_allgather(desc)  # (nprocs, 10)

    ndims = all_desc[:, 0].astype(int)
    counts = np.array(
        [int(np.prod(all_desc[i, 1 : 1 + ndims[i]])) if ndims[i] else 0 for i in range(nprocs)]
    )
    nonempty = counts > 0
    if nonempty.any():
        ref_ranks = np.where(nonempty)[0]
        if len({int(ndims[i]) for i in ref_ranks}) > 1:
            raise ValueError(
                f"gather_all_arrays: ranks hold data of different ranks (ndims {ndims.tolist()})"
            )
        if len({int(all_desc[i, -1]) for i in ref_ranks}) > 1:
            raise ValueError("gather_all_arrays: ranks hold data of different dtypes")
        ref_ndim = int(ndims[ref_ranks[0]])
        target_dtype = _GATHER_DTYPES[int(all_desc[ref_ranks[0], -1])]
    else:  # every rank is empty: any consistent alignment works
        ref_ndim = int(ndims.max())
        target_dtype = _GATHER_DTYPES[int(all_desc[0, -1])]

    # per-rank true shapes aligned to ref_ndim; an empty rank's contribution
    # becomes 0 rows of the peers' trailing dims
    shapes = np.zeros((nprocs, ref_ndim), dtype=np.int64)
    for i in range(nprocs):
        nd = min(int(ndims[i]), ref_ndim)
        shapes[i, :nd] = all_desc[i, 1 : 1 + nd]
    max_shape = shapes[nonempty].max(axis=0) if nonempty.any() else np.ones(ref_ndim, np.int64)
    for i in np.where(~nonempty)[0]:
        shapes[i] = np.concatenate([[0], max_shape[1:]])  # 0 rows of the peers' trailing dims

    rank = jax.process_index()
    local = result.astype(target_dtype)
    if counts[rank] == 0:
        local = jnp.zeros(tuple(shapes[rank]), target_dtype)

    if bool((shapes == max_shape[None, :]).all()):
        gathered = _process_allgather(local)
        return [jnp.asarray(gathered[i]) for i in range(nprocs)]

    pad_width = [(0, int(m - s)) for s, m in zip(local.shape, max_shape)]
    padded = jnp.pad(local, pad_width)
    gathered = _process_allgather(padded)
    out = []
    for i in range(nprocs):
        trim = tuple(slice(int(d)) for d in shapes[i])
        out.append(jnp.asarray(gathered[i][trim]))
    return out


# ---------------------------------------------------------------------------
# In-graph (mesh-axis) sync — the TPU-native hot path
# ---------------------------------------------------------------------------

#: reduction spec accepted by ``add_state`` and resolved here
ReduceFx = Optional[Union[str, Callable]]


def sync_value_in_graph(value: Array, reduce_fx: ReduceFx, axis_name: AxisName) -> Array:
    """Synchronize one state array across the named mesh axis, inside a traced program.

    "sum"/"mean"/"max"/"min" compile to single fused XLA collectives —
    deliberately *not* the reference's gather-then-host-reduce (psum over ICI
    is the TPU-idiomatic fusion). "cat" compiles to a tiled all-gather so the
    result is the cross-shard concatenation. ``None`` gathers with a leading
    participant axis. A custom callable receives the stacked ``(world, ...)``
    gather, mirroring the reference's custom ``dist_reduce_fx`` contract.
    """
    if reduce_fx == "sum":
        return lax.psum(value, axis_name)
    if reduce_fx == "mean":
        return lax.pmean(value, axis_name)
    if reduce_fx == "max":
        return lax.pmax(value, axis_name)
    if reduce_fx == "min":
        return lax.pmin(value, axis_name)
    if reduce_fx == "cat":
        return lax.all_gather(jnp.atleast_1d(value), axis_name, axis=0, tiled=True)
    stacked = lax.all_gather(value, axis_name, axis=0, tiled=False)
    if reduce_fx is None:
        return stacked
    if callable(reduce_fx):
        return reduce_fx(stacked)
    raise ValueError(f"Unknown dist_reduce_fx: {reduce_fx!r}")


def sync_in_graph(
    state: Dict[str, Union[Array, List[Array]]],
    reductions: Dict[str, ReduceFx],
    axis_name: AxisName,
) -> Dict[str, Union[Array, List[Array]]]:
    """Synchronize a whole state dict across mesh axes inside a traced program.

    List states ("cat"/gather-only accumulators) are pre-concatenated into one
    array so each costs exactly one collective, matching the reference's
    pre-concatenation optimization (``metric.py:203-206``).
    """
    from metrics_tpu.utilities.data import dim_zero_cat

    synced: Dict[str, Union[Array, List[Array]]] = {}
    for name, value in state.items():
        fx = reductions.get(name)
        if isinstance(value, (list, tuple)):
            if len(value) == 0:
                synced[name] = value
                continue
            value = dim_zero_cat(list(value))
            gathered = sync_value_in_graph(value, "cat" if fx in ("cat", None) else fx, axis_name)
            synced[name] = [gathered] if fx in ("cat", None) else gathered
        else:
            synced[name] = sync_value_in_graph(value, fx, axis_name)
    return synced
