"""Distributed communication backend (L0).

Capability parity with the reference's ``torchmetrics/utilities/distributed.py``
(``reduce``/``class_reduce``/``gather_all_tensors`` over torch.distributed),
re-designed TPU-first with two complementary sync paths:

* **In-graph sync** (the TPU-idiomatic hot path): metric state lives inside a
  ``pjit``/``shard_map`` program over a ``jax.sharding.Mesh``; per-state
  reductions compile directly to XLA collectives over named mesh axes —
  ``lax.psum`` for "sum" states (skipping the reference's gather+host-reduce
  dance entirely), ``lax.pmean`` for "mean", ``lax.pmax``/``pmin`` for
  extrema, and a tiled ``lax.all_gather`` for "cat"/gather-only states.
  See :func:`sync_in_graph`.

* **Host (eager) sync** for epoch-boundary ``compute()`` across JAX processes:
  :func:`gather_all_arrays` mirrors the reference's protocol (shape gather ->
  pad to elementwise-max -> all-gather -> trim) on top of
  ``jax.experimental.multihost_utils`` since XLA collectives need static,
  equal shapes across participants.

Both paths additionally ship a **bucketed/packed** form — the classic
small-tensor fusion of PyTorch DDP's gradient bucketing and Horovod's tensor
fusion, applied to metric state:

* :func:`sync_state_packed` groups state leaves by (collective kind, dtype),
  concatenates each bucket into one flat buffer, and issues **one collective
  per bucket** — a whole classification collection's sum states ride a single
  ``psum`` instead of one per leaf. Callable custom reductions keep the
  per-leaf path (their contract is the stacked per-leaf gather).
* :func:`gather_all_pytrees` extends the ragged descriptor/payload protocol so
  an entire state bundle (every leaf of every metric in a collection) rides
  **one descriptor round + one payload round**, instead of two transport
  rounds per leaf per metric, while preserving the deadlock-safety invariants
  (fixed collective count per rank, 0-length placeholder alignment, deferred
  group-error raising).

The in-graph packed engine additionally ships a **hierarchical** mode
(:class:`Hierarchy` / the ``levels=`` argument of :func:`sync_state_packed`):
at pod scale a single flat collective pushes every byte over the slowest
link, so each packed bucket instead lowers to one collective per *level* —
reduce within-host over ICI first, then across hosts over DCN — the metric
-state analogue of Horovod's hierarchical allreduce / NCCL tree reductions.
One collective per **(level, kind, dtype)** bucket, results identical to the
flat sync (bit-identical for integer/extremal reductions and gathers, which
is what metric states overwhelmingly are; rounding float sums agree up to
reassociation of the level partials, ≤1 ulp).

Since 0.13.0 both engines sit behind the **pluggable transport seam**
(``metrics_tpu.transport``): the public :func:`sync_state_packed`,
:func:`gather_all_arrays` and :func:`gather_all_pytrees` dispatch through
the ACTIVE strategy backend (in-graph packed / byte gather / loopback /
device-sharded), while ``_sync_state_packed_impl`` and
``_gather_pytrees_impl`` remain the default engines those backends run.
The dispatch is host-side only — with the default backends the traced
programs are byte-identical to direct engine calls.
"""
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Array = jax.Array

AxisName = Union[str, Tuple[str, ...]]


# ---------------------------------------------------------------------------
# Host-side reducers (parity: utilities/distributed.py:21-89)
# ---------------------------------------------------------------------------


def reduce(to_reduce: Array, reduction: str) -> Array:
    """Reduce an array with ``'elementwise_mean'``, ``'sum'`` or ``'none'``."""
    if reduction == "elementwise_mean":
        return jnp.mean(to_reduce)
    if reduction == "none":
        return to_reduce
    if reduction == "sum":
        return jnp.sum(to_reduce)
    raise ValueError("Reduction parameter unknown.")


def class_reduce(
    num: Array,
    denom: Array,
    weights: Array,
    class_reduction: str = "none",
) -> Array:
    """Reduce per-class fractions ``num / denom`` with micro/macro/weighted/none.

    NaNs arising from empty classes (0/0) are zeroed, matching the reference's
    semantics (``utilities/distributed.py:73-75``); infinities are untouched.
    """
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    if class_reduction == "micro":
        fraction = jnp.sum(num) / jnp.sum(denom)
    else:
        fraction = num / denom

    fraction = jnp.where(jnp.isnan(fraction), jnp.zeros_like(fraction), fraction)

    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        w = weights.astype(fraction.dtype)
        return jnp.sum(fraction * (w / jnp.sum(w)))
    if class_reduction == "none" or class_reduction is None:
        return fraction
    raise ValueError(
        f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}"
    )


# ---------------------------------------------------------------------------
# Process-level (multi-host) eager gather
# ---------------------------------------------------------------------------


def distributed_available() -> bool:
    """True when more than one JAX process participates in the runtime."""
    try:
        return jax.process_count() > 1
    except Exception:  # pragma: no cover
        return False


def world_size() -> int:
    return jax.process_count()


def _process_allgather(x: Array) -> Array:
    """All-gather ``x`` across processes -> stacked ``(num_processes, ...)``."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(np.asarray(x)))


def shard_map_compat(fn, *, mesh, in_specs, out_specs, check_vma=False, **kwargs):
    """``jax.shard_map`` across jax versions: the top-level API (with
    ``check_vma``) when present, else ``jax.experimental.shard_map`` (with
    the equivalent ``check_rep``). Replication checking is disabled either
    way — ``lax.all_gather`` outputs are semantically replicated but the
    static checker cannot prove it. Drop-in for the ``jax.shard_map`` call
    shape the test/bench/dryrun harnesses use."""
    if hasattr(jax, "shard_map"):  # pragma: no cover - newer jax
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False, **kwargs
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False, **kwargs
    )


class Hierarchy:
    """Multi-level mesh-axis spec for hierarchical (two-level) bucketed sync.

    ``Hierarchy(("ici", "intra"), ("dcn", "inter"))`` names the levels a
    packed sync reduces over, **innermost first**: level 0 is the within-host
    ICI axis (reduced/gathered first), the last level the cross-host DCN axis.
    Each level's axis may itself be a tuple of mesh axes. Usable anywhere an
    ``axis_name`` is accepted — ``Metric(process_group=...)``,
    ``apply_compute(axis_name=...)``, :meth:`Metric.sync_state`, the
    collection presync — and :func:`sync_state_packed` lowers each packed
    bucket to one collective per level instead of one flat collective.

    :attr:`flat` is the equivalent flat axis tuple (**outermost first**):
    hierarchical results are ordered identically to a flat sync over
    ``hierarchy.flat`` (gathers stack outer-major, exactly as
    ``lax.all_gather`` over the tuple does). Per-leaf paths
    (:func:`sync_in_graph`, callable custom reductions) lower over
    :attr:`flat` directly — hierarchy is a packed-engine optimization, never
    a semantic change.
    """

    __slots__ = ("levels",)

    def __init__(self, *levels: Tuple[str, Any]) -> None:
        if len(levels) == 1 and isinstance(levels[0], (list, tuple)) and levels[0] \
                and isinstance(levels[0][0], (list, tuple)):
            levels = tuple(levels[0])  # Hierarchy([("ici", a), ("dcn", b)])
        norm: List[Tuple[str, Any]] = []
        for entry in levels:
            try:
                label, axis = entry
            except (TypeError, ValueError):
                raise TypeError(
                    f"each hierarchy level must be a (label, axis) pair, got {entry!r}"
                )
            norm.append((str(label), tuple(axis) if isinstance(axis, (list, tuple)) else axis))
        if len(norm) < 2:
            raise ValueError(
                f"a Hierarchy needs at least 2 levels (got {len(norm)}); use the plain"
                " axis name for single-level sync"
            )
        labels = [label for label, _ in norm]
        if len(set(labels)) != len(labels):
            raise ValueError(f"hierarchy level labels must be unique, got {labels}")
        object.__setattr__(self, "levels", tuple(norm))

    def __setattr__(self, name: str, value: Any) -> None:
        raise AttributeError("Hierarchy is immutable")

    @property
    def flat(self) -> Tuple[str, ...]:
        """The equivalent flat axis tuple, outermost level first."""
        axes: List[str] = []
        for _, axis in reversed(self.levels):
            axes.extend(axis if isinstance(axis, tuple) else (axis,))
        return tuple(axes)

    @classmethod
    def from_mesh(cls, mesh: Any, intra: str, inter: str) -> "Hierarchy":
        """The canonical two-level spec from a mesh's axis names: ``intra``
        is the within-host (ICI) axis, ``inter`` the cross-host (DCN) axis.
        Validates both axes exist on ``mesh``."""
        names = tuple(getattr(mesh, "axis_names", ()))
        for axis in (intra, inter):
            if axis not in names:
                raise ValueError(f"mesh {names} has no axis {axis!r}")
        return cls(("ici", intra), ("dcn", inter))

    def __repr__(self) -> str:
        inner = ", ".join(f"{label}={axis!r}" for label, axis in self.levels)
        return f"Hierarchy({inner})"

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Hierarchy) and self.levels == other.levels

    def __hash__(self) -> int:
        return hash(self.levels)

    def __reduce__(self):
        return (Hierarchy, tuple(self.levels))


def hierarchical_axis(intra: Any, inter: Any) -> Hierarchy:
    """The canonical two-level spec: ``intra`` (within-host ICI axis, reduced
    first) then ``inter`` (cross-host DCN axis) — shorthand for
    ``Hierarchy(("ici", intra), ("dcn", inter))``."""
    return Hierarchy(("ici", intra), ("dcn", inter))


#: thread-scoped overrides for the eager gather transport (the async sync
#: engine's hooks; see :func:`transport_overrides`)
_EAGER_OVERRIDES = threading.local()


class transport_overrides:
    """Thread-scoped overrides for the eager gather transport (a REENTRANT
    context manager).

    ``quorum`` restricts the decode/reduce membership of every gather issued
    on this thread to the given process indices — the degraded-link
    ``on_degraded="quorum"`` policy's hook: when the active transport has no
    true-subgroup channel the underlying round still spans all processes,
    but only the healthy subgroup's contributions enter the result, exactly
    as an explicit ``group=`` argument would select. A quorum never widens a
    group: it intersects with whatever group each gather names.
    ``transport_label`` relabels the round-trip telemetry (histogram
    ``transport=`` label, sync events) so the async engine's cross-host DCN
    legs are distinguishable from inline gathers.

    Overrides nest and the SAME instance may be re-entered (each
    ``__enter__`` pushes the previous values, each ``__exit__`` pops and
    restores under ``try``/``finally`` semantics) — a gather raising
    mid-attempt can never leave a stale quorum installed to poison the next
    flat sync. Arguments are validated at CONSTRUCTION, before anything is
    installed. Deliberately **thread-local**: the background sync engine's
    worker applies its policy without perturbing inline syncs on other
    threads — the saved-snapshot stack is itself per-thread, so ONE
    instance entered concurrently from several threads restores each
    thread's own prior state; :func:`current_transport_overrides` /
    :func:`applied_transport_overrides` propagate a snapshot onto helper
    threads (the engine's per-round-timeout runner).
    """

    def __init__(
        self, *, quorum: Optional[Sequence[int]] = None, transport_label: Optional[str] = None
    ) -> None:
        self._quorum = sorted({int(i) for i in quorum}) if quorum is not None else None
        self._label = str(transport_label) if transport_label is not None else None
        # per-THREAD snapshot stacks: the overrides being restored are
        # thread-local, so a shared instance list would interleave pushes
        # and pops across threads and restore the wrong thread's snapshot
        self._saved = threading.local()

    def __enter__(self) -> "transport_overrides":
        stack = getattr(self._saved, "stack", None)
        if stack is None:
            stack = self._saved.stack = []
        stack.append(current_transport_overrides())
        if self._quorum is not None:
            _EAGER_OVERRIDES.quorum = self._quorum
        if self._label is not None:
            _EAGER_OVERRIDES.transport_label = self._label
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        prev_quorum, prev_label = self._saved.stack.pop()
        _EAGER_OVERRIDES.quorum = prev_quorum
        _EAGER_OVERRIDES.transport_label = prev_label
        return False


def current_transport_overrides() -> Tuple[Optional[List[int]], Optional[str]]:
    """This thread's ``(quorum, transport_label)`` override snapshot."""
    return (
        getattr(_EAGER_OVERRIDES, "quorum", None),
        getattr(_EAGER_OVERRIDES, "transport_label", None),
    )


@contextmanager
def applied_transport_overrides(snapshot: Tuple[Optional[List[int]], Optional[str]]):
    """Install an override snapshot (from
    :func:`current_transport_overrides`) on THIS thread for the duration of
    the block — how the async engine's timeout helper threads inherit the
    worker's quorum/label. Exception-safe: always restores."""
    quorum, label = snapshot
    prev = current_transport_overrides()
    _EAGER_OVERRIDES.quorum = quorum
    _EAGER_OVERRIDES.transport_label = label
    try:
        yield
    finally:
        _EAGER_OVERRIDES.quorum, _EAGER_OVERRIDES.transport_label = prev


#: descriptor layout for the ragged gather: [ndim, d0..d7, dtype_code]
_MAX_GATHER_NDIM = 8
#: dtypes the ragged gather can align across ranks (code = list index);
#: covers every dtype the library stores in states
_GATHER_DTYPES = (
    np.dtype(np.bool_),
    np.dtype(np.uint8),
    np.dtype(np.int8),
    np.dtype(np.int16),
    np.dtype(np.int32),
    np.dtype(np.int64),
    np.dtype(np.float16),
    np.dtype(np.float32),
    np.dtype(np.float64),
)


def _tracer():
    """The enabled global span tracker, or ``None`` (lazy import: tracing
    lives in observability, which must stay optional for this module)."""
    try:
        from metrics_tpu.observability.tracing import TRACER

        return TRACER if TRACER.enabled else None
    except Exception:  # pragma: no cover - tracing must never break a sync
        return None


def _resolve_group(group: Optional[Any], nprocs: int) -> List[int]:
    """Resolve a ``process_group`` argument to the member process indices.

    ``None`` -> all processes. A collection of ints -> that subgroup (the
    eager analogue of the reference's ``torch.distributed`` group handle,
    ``utilities/distributed.py:113-135``). Mesh-axis names (a str, a
    :class:`Hierarchy`, or a collection of strs) are the IN-GRAPH sub-group
    mechanism; on the eager path they cannot name a process subset, so they
    gather everything — the documented fallback for metrics whose
    ``process_group`` is an axis.
    A collection MIXING axis names and indices (e.g. ``("data", 0)``) is
    ambiguous and raises ``TypeError``.

    Raises eagerly when called directly; :func:`gather_all_arrays` defers
    these raises until after its collective rounds so a bad argument on one
    rank cannot hang peers mid-collective.
    """
    if group is None or isinstance(group, (str, Hierarchy)):
        return list(range(nprocs))
    try:
        items = list(group)
    except TypeError:
        raise TypeError(
            f"group must be None, a mesh-axis name, or a collection of process indices; got {group!r}"
        )
    if any(isinstance(i, str) for i in items):
        if all(isinstance(i, str) for i in items):
            return list(range(nprocs))  # tuple of mesh-axis names
        raise TypeError(
            "group mixes mesh-axis names and process indices; pass either a (tuple of)"
            f" mesh-axis name(s) or a collection of ints, got {group!r}"
        )
    try:
        members = sorted({int(i) for i in items})
    except (TypeError, ValueError):
        raise TypeError(
            f"group must be None, a mesh-axis name, or a collection of process indices; got {group!r}"
        )
    if not members:
        raise ValueError("group must name at least one process index")
    if members[0] < 0 or members[-1] >= nprocs:
        raise ValueError(f"group {group!r} names process indices outside [0, {nprocs})")
    return members


def _leaf_descriptor(arr: Array) -> Tuple["np.ndarray", Optional[str]]:
    """Descriptor row ``[ndim, d0..d7, dtype_code]`` for one leaf.

    A leaf the protocol cannot align (too many dims, dtype outside
    :data:`_GATHER_DTYPES`) gets an EMPTY placeholder descriptor plus the
    error message — the caller marches it through the transport as a 0-length
    contribution and raises only after the collective rounds complete, so a
    bad leaf on one rank can never hang its peers mid-collective.
    """
    row = np.zeros(_MAX_GATHER_NDIM + 2, dtype=np.int64)
    if arr.ndim > _MAX_GATHER_NDIM:
        row[0] = 1  # 1-D, 0-length, f32: a valid empty contribution
        row[-1] = _GATHER_DTYPES.index(np.dtype(np.float32))
        return row, f"gather_all_arrays supports up to {_MAX_GATHER_NDIM} dims, got {arr.ndim}"
    np_dtype = np.dtype(arr.dtype)
    if np_dtype not in _GATHER_DTYPES:
        row[0] = 1
        row[-1] = _GATHER_DTYPES.index(np.dtype(np.float32))
        return row, f"gather_all_arrays cannot align dtype {np_dtype} across ranks"
    row[0] = arr.ndim
    row[1 : 1 + arr.ndim] = arr.shape
    row[-1] = _GATHER_DTYPES.index(np_dtype)
    return row, None


def _align_leaf(
    leaf_desc: "np.ndarray", members: List[int]
) -> Tuple[Dict[int, "np.ndarray"], "np.ndarray", "np.dtype", Optional[str]]:
    """Intra-group alignment of one leaf from its per-rank descriptors.

    Returns ``(shapes, counts, target_dtype, group_error)``. Consistency is
    required over the NONEMPTY members of the caller's group only — other
    groups may hold anything in the same transport round. A violation must
    NOT raise before the payload round: other (valid) groups are already
    committed to that global collective, and a rank that bails early would
    leave them hung. The error is returned for a deferred raise.
    """
    nprocs = leaf_desc.shape[0]
    ndims = leaf_desc[:, 0].astype(int)
    # np.prod([]) == 1.0, so a 0-d scalar naturally counts as one element
    counts = np.array([int(np.prod(leaf_desc[i, 1 : 1 + ndims[i]])) for i in range(nprocs)])
    dtype_codes = leaf_desc[:, -1].astype(int)

    group_error: Optional[str] = None
    member_nonempty = [i for i in members if counts[i] > 0]
    if member_nonempty:
        if len({int(ndims[i]) for i in member_nonempty}) > 1:
            group_error = (
                "gather_all_arrays: group members hold data of different ranks"
                f" (ndims {[int(ndims[i]) for i in members]})"
            )
        elif len({int(dtype_codes[i]) for i in member_nonempty}) > 1:
            group_error = "gather_all_arrays: group members hold data of different dtypes"
        ref_ndim = int(ndims[member_nonempty[0]])
        target_dtype = _GATHER_DTYPES[int(dtype_codes[member_nonempty[0]])]
    else:  # every member is empty: any consistent alignment works
        ref_ndim = int(max(ndims[i] for i in members))
        target_dtype = _GATHER_DTYPES[int(dtype_codes[members[0]])]

    # per-member true shapes aligned to ref_ndim; an empty member's
    # contribution becomes 0 rows of the peers' trailing dims (0-d peers
    # have no row axis to borrow, so it degrades to a 0-length vector —
    # never a fabricated scalar)
    shapes: Dict[int, "np.ndarray"] = {}
    for i in members:
        s = np.zeros(ref_ndim, dtype=np.int64)
        nd = min(int(ndims[i]), ref_ndim)
        s[:nd] = leaf_desc[i, 1 : 1 + nd]
        shapes[i] = s
    if member_nonempty:
        max_shape = np.stack([shapes[i] for i in member_nonempty]).max(axis=0)
    else:
        max_shape = np.ones(ref_ndim, dtype=np.int64)
    for i in members:
        if counts[i] == 0:
            shapes[i] = np.concatenate([[0], max_shape[1:]]) if ref_ndim > 0 else np.array([0])
    return shapes, counts, target_dtype, group_error


def _gather_all_leaves(
    leaves: List[Array],
    group: Optional[Any],
    *,
    participants: Optional[Sequence[int]] = None,
    label: Optional[str] = None,
) -> List[List[Array]]:
    """Packed transport core: gather EVERY leaf across processes in ONE
    descriptor round plus (at most) one payload round.

    Returns, per leaf, the list of group members' arrays in ascending process
    order. Every error — a bad ``group`` argument, an unalignable local leaf,
    an intra-group shape/dtype mismatch — is deferred until after the last
    collective so no rank can desync the fixed per-call round count its peers
    are committed to.

    ``participants`` (a transport-level subgroup, from
    ``GatherTransport.subgroup``) restricts the processes the rounds
    physically touch: with a registered subgroup channel
    (``metrics_tpu.transport.gather.set_subgroup_allgather``) the
    descriptor/payload exchanges run among exactly those peers — a dead
    non-participant is never contacted; without one, the rounds fall back to
    the global collective and only the decode narrows (the legacy quorum
    behavior). ``label`` names the backend for the round telemetry; a
    thread-scoped ``transport_overrides(transport_label=...)`` wins.
    """
    transport_start = time.perf_counter()
    nprocs = world_size()
    # A bad group ARGUMENT must not desync the transport: fall back to the
    # all-process group for the rounds, record the error, raise it after.
    arg_error: Optional[Exception] = None
    try:
        members = _resolve_group(group, nprocs)
    except (TypeError, ValueError) as err:
        arg_error = err
        members = list(range(nprocs))
    # a thread-scoped quorum (the degraded-link policy hook) narrows the
    # decoded membership to the healthy subgroup
    quorum = getattr(_EAGER_OVERRIDES, "quorum", None)
    if quorum is not None:
        narrowed = [m for m in members if m in quorum]
        if narrowed:
            members = narrowed
    transport_label = (
        getattr(_EAGER_OVERRIDES, "transport_label", None) or label or "gather"
    )

    # -- transport-level subgroup formation ---------------------------------
    # ranks = the processes this round's exchanges span; slot = a rank's row
    # index in the exchanged arrays. Default: all processes, global rounds.
    ranks = list(range(nprocs))
    exchange = _process_allgather
    uses_channel = False
    local_rank = int(jax.process_index()) if nprocs > 1 else 0
    if participants is not None:
        want = sorted({int(p) for p in participants if 0 <= int(p) < nprocs})
        if want and want != ranks:
            channel = _subgroup_channel()
            if channel is not None:
                # true subgroup: rounds touch ONLY these peers (callers
                # outside the set publish-and-read without contributing)
                ranks = want
                uses_channel = True

                def exchange(x, _channel=channel, _want=tuple(want)):
                    return np.asarray(_channel(np.asarray(x), list(_want)))

            # either way the decoded membership narrows to the subgroup
            narrowed = [m for m in members if m in want]
            if narrowed:
                members = narrowed
    slot_of = {r: i for i, r in enumerate(ranks)}
    nslots = len(ranks)
    members = [m for m in members if m in slot_of] or list(ranks)
    member_slots = [slot_of[m] for m in members]
    local_slot = slot_of.get(local_rank)

    # collective spans: one deterministic id per transport (and per round)
    # shared by every participating process — the fleet-timeline correlation
    # key (observability/tracing.py). Host-side bookkeeping only.
    tracer = _tracer()
    group_label = ",".join(str(m) for m in members)
    t_span = tracer.begin("gather", group=group_label, bucket="transport") if tracer else None

    num_leaves = len(leaves)
    desc = np.zeros((num_leaves, _MAX_GATHER_NDIM + 2), dtype=np.int64)
    local_error: Optional[str] = None
    local_parts: List[bytes] = []
    for j, arr in enumerate(leaves):
        row, err = _leaf_descriptor(arr)
        desc[j] = row
        if err is not None:
            local_error = local_error or err  # empty contribution rides the rounds
        else:
            local_parts.append(np.ascontiguousarray(np.asarray(arr)).tobytes())
    # the resilience seams: a consult is a single attribute read with no
    # fault plan installed; an armed seam may sleep (delay) or raise
    # (drop/error) — the raise is the injected failure the surrounding
    # policy must absorb (metrics_tpu/resilience/faults.py)
    _consult_fault_seam("transport.descriptor", process=local_rank, leaves=num_leaves)
    d_span = tracer.begin("gather", group=group_label, bucket="descriptor") if tracer else None
    desc_start = time.perf_counter()
    all_desc = np.asarray(exchange(desc))  # (nslots, num_leaves, 10)
    desc_dur = time.perf_counter() - desc_start
    if tracer:
        tracer.end(d_span, leaves=num_leaves, bytes=int(desc.nbytes))

    aligned = [_align_leaf(all_desc[:, j, :], member_slots) for j in range(num_leaves)]
    group_error = next((a[3] for a in aligned if a[3] is not None), None)

    # per-rank byte layout: each rank's payload is the concatenation of its
    # leaves' raw bytes in leaf order (offsets recomputed per rank from that
    # rank's own descriptors, so ragged per-rank shapes need no padding
    # between leaves)
    dtype_codes = all_desc[:, :, -1].astype(int)  # (nslots, num_leaves)
    leaf_nbytes = np.zeros((nslots, num_leaves), dtype=np.int64)
    for j in range(num_leaves):
        counts_j = aligned[j][1]
        for i in range(nslots):
            leaf_nbytes[i, j] = int(counts_j[i]) * _GATHER_DTYPES[int(dtype_codes[i, j])].itemsize
    offsets = np.concatenate([np.zeros((nslots, 1), np.int64), np.cumsum(leaf_nbytes, axis=1)], axis=1)
    totals = offsets[:, -1]
    max_bytes = int(totals.max())

    # ONE payload round carries every participant's whole bundle (each
    # group decodes only its own members), padded to the round's max byte
    # length; skipped entirely — on EVERY participant, keeping the
    # collective count aligned — when all contributions are empty
    payload_dur = 0.0
    if max_bytes == 0:
        gathered = None
    else:
        buf = np.zeros(max_bytes, dtype=np.uint8)
        local_bytes = np.frombuffer(b"".join(local_parts), np.uint8)
        buf[: local_bytes.size] = local_bytes
        # Anything that raises AFTER the descriptor round but BEFORE this
        # process enters the payload exchange (an injected payload fault, a
        # hard host error) must still CONSUME the subgroup channel's round:
        # the peers, having seen this rank's descriptors, will run the
        # payload round regardless, and a channel whose per-peer-set round
        # counter lags by one desyncs every subsequent sync over that peer
        # set (the rounds would rendezvous under mismatched keys forever).
        # A raise from INSIDE the exchange is already consistent — the
        # channel advances its counter on entry.
        payload_round_pending = uses_channel
        try:
            _consult_fault_seam(
                "transport.payload", process=local_rank, bytes=max_bytes
            )
            p_span = tracer.begin("gather", group=group_label, bucket="payload") if tracer else None
            payload_start = time.perf_counter()
            payload_round_pending = False
            gathered = np.asarray(exchange(buf))  # (nslots, max_bytes)
        except BaseException:
            if payload_round_pending:
                _consume_subgroup_round(ranks)
            if tracer:
                try:
                    tracer.end(t_span, leaves=num_leaves, error=True)
                except Exception:  # pragma: no cover - diagnostics only
                    pass
            raise
        payload_dur = time.perf_counter() - payload_start
        if tracer:
            tracer.end(p_span, leaves=num_leaves, bytes=nslots * max_bytes)

    span_id = (
        tracer.end(t_span, leaves=num_leaves, members=[int(m) for m in members])
        if tracer
        else None
    )
    _record_gather_telemetry(
        bytes_out=int(totals[local_slot]) if local_slot is not None else 0,
        bytes_in=int(sum(int(leaf_nbytes[s, j]) for s in member_slots for j in range(num_leaves))),
        members=members,
        nprocs=nprocs,
        leaves=num_leaves,
        desc_bytes=int(desc.nbytes),
        max_bytes=max_bytes,
        error=arg_error is not None or local_error is not None or group_error is not None,
        dur_s=time.perf_counter() - transport_start,
        t_start=transport_start,
        descriptor_s=desc_dur,
        payload_s=payload_dur,
        span_id=span_id,
        transport=transport_label,
        participants=list(ranks),
    )

    if arg_error is not None:
        raise arg_error
    if local_error is not None:
        raise ValueError(local_error)
    if group_error is not None:
        raise ValueError(group_error)

    out: List[List[Array]] = []
    for j in range(num_leaves):
        shapes, counts, target_dtype, _ = aligned[j]
        per_member: List[Array] = []
        for s in member_slots:
            shape = tuple(int(d) for d in shapes[s])
            if counts[s] == 0:
                per_member.append(jnp.zeros(shape, target_dtype))
                continue
            raw = np.frombuffer(
                gathered[s].tobytes(),
                dtype=target_dtype,
                count=int(counts[s]),
                offset=int(offsets[s, j]),
            )
            per_member.append(jnp.asarray(raw.reshape(shape)))
        out.append(per_member)
    return out


def _subgroup_channel():
    """The registered transport-subgroup exchange channel, or ``None`` (lazy
    import: the strategy layer must stay optional for this module)."""
    try:
        from metrics_tpu.transport.gather import subgroup_allgather

        return subgroup_allgather()
    except Exception:  # pragma: no cover - the seam must never break a sync
        return None


def _consult_fault_seam(seam: str, **ctx: Any) -> Any:
    """Consult the resilience plane's fault plan at ``seam``. Only the
    IMPORT is guarded — a raise from the plan itself IS the injected fault
    and must propagate (metrics_tpu/resilience/faults.py)."""
    try:
        from metrics_tpu.resilience.faults import maybe_fault
    except Exception:  # pragma: no cover - resilience plane optional
        return None
    return maybe_fault(seam, **ctx)


def _consume_subgroup_round(participants: Sequence[int]) -> bool:
    """Advance the registered subgroup channel's round counter for a round
    this process is skipping while its peers still run it (see the payload
    fault path in :func:`_gather_all_leaves`)."""
    try:
        from metrics_tpu.transport.gather import consume_subgroup_round

        return consume_subgroup_round(participants)
    except Exception:  # pragma: no cover - consistency is best-effort here
        return False


def gather_all_arrays(result: Array, group: Optional[Any] = None) -> List[Array]:
    """Gather one array per group member into a list (eager, epoch-boundary path).

    The analogue of the reference's ``gather_all_tensors``
    (``utilities/distributed.py:113-149``), including its ragged protocol:
    shape descriptors are exchanged first, then payloads, and each member's
    result is restored to its true shape. A member with NO data (a
    never-updated list state — 0 elements, possibly of a different rank and
    placeholder dtype, the reference's 0-length case
    ``tests/bases/test_ddp.py:63-81``) still participates: its contribution
    is a 0-row tensor aligned to the peers' ndim/dtype (a 0-length vector
    when the peers are 0-d scalars, which have no row axis to borrow).

    ``group`` restricts the RESULT to a subset of processes (see
    :func:`_resolve_group`): only members' arrays are returned, in ascending
    process order, and non-members' data never enters the output. Because
    JAX's ``process_allgather`` is a global collective, the underlying
    transport always spans all processes — so disjoint groups sync
    *concurrently*: every process must call ``gather_all_arrays`` the same
    number of times (each with its own group), and one transport round
    serves all groups at once. Payloads ride a byte-level buffer, so
    different groups may hold data of entirely different shapes, ndims and
    dtypes in the same round; consistency is only required *within* a group.

    Every validation error — including an unalignable local array (too many
    dims, unsupported dtype) — is raised only AFTER the transport rounds
    complete, so one rank's bad input cannot hang its peers mid-collective.
    To gather many arrays at once, :func:`gather_all_pytrees` packs a whole
    state bundle into the same two transport rounds this function spends on
    a single array.

    Dispatches through the ACTIVE transport
    (:func:`metrics_tpu.transport.resolve_transport`): the default
    loopback/byte-gather pair reproduces the historical behavior exactly;
    an installed backend (subgrouped gather, sharded, custom) owns the
    round instead.
    """
    from metrics_tpu.transport import resolve_transport

    return resolve_transport().gather_array(jnp.asarray(result), group=group)


def gather_all_pytrees(trees: List[Any], group: Optional[Any] = None) -> List[Any]:
    """Gather every array leaf of ``trees`` in ONE descriptor round + ONE
    payload round (eager, epoch-boundary path).

    The bundle-level form of :func:`gather_all_arrays`: where the per-array
    protocol pays two ``process_allgather`` transport rounds *per leaf* —
    ~100 µs of link round-trip each on the benched TPU tunnel — this packs
    all leaves of all ``trees`` (e.g. every state of every metric in a
    ``MetricCollection``) into a single descriptor exchange and a single
    byte-level payload exchange, then slices each member's leaves back out.

    Returns one tree per input tree, with the same structure, where each
    array leaf is replaced by the list of group members' arrays (ascending
    process order) — exactly what mapping :func:`gather_all_arrays` over the
    leaves would produce, at two transport rounds total instead of
    ``2 × num_leaves``.

    Deadlock-safety invariants are preserved: every rank issues the same
    fixed number of collectives per call (the payload round is skipped on
    every rank at once when all contributions are empty), per-leaf 0-length
    placeholders align to the peers' ndim/dtype, and every error — bad
    ``group`` argument, unalignable leaf, intra-group mismatch — raises only
    after the transport completes. The total LEAF count must agree across
    all processes per call — the packed analogue of the per-leaf protocol's
    equal-call-count invariant (N leaves used to mean N aligned
    ``gather_all_arrays`` calls on every rank; packed, they mean one N-leaf
    bundle on every rank). Per-leaf shapes, ndims and dtypes may still
    differ arbitrarily across groups.

    Dispatches through the ACTIVE transport
    (:func:`metrics_tpu.transport.resolve_transport`); the default
    loopback/byte-gather pair reproduces the historical behavior exactly.
    """
    from metrics_tpu.transport import resolve_transport

    return resolve_transport().gather_pytrees(trees, group=group)


def _gather_pytrees_impl(
    trees: List[Any],
    group: Optional[Any] = None,
    *,
    participants: Optional[Sequence[int]] = None,
    label: Optional[str] = None,
) -> List[Any]:
    """The byte-transport engine behind :func:`gather_all_pytrees` (what the
    default gather backend runs): descriptor+payload rounds when
    distributed, the world-1 identity otherwise."""
    flat = [jax.tree_util.tree_flatten(t) for t in trees]
    all_leaves = [jnp.asarray(leaf) for leaves, _ in flat for leaf in leaves]
    if not distributed_available():
        gathered: List[List[Array]] = [[leaf] for leaf in all_leaves]
    else:
        gathered = _gather_all_leaves(all_leaves, group, participants=participants, label=label)
    out, pos = [], 0
    for leaves, treedef in flat:
        out.append(jax.tree_util.tree_unflatten(treedef, gathered[pos : pos + len(leaves)]))
        pos += len(leaves)
    return out


def _record_gather_telemetry(
    *,
    bytes_out: int,
    bytes_in: int,
    members: List[int],
    nprocs: int,
    leaves: int,
    desc_bytes: int,
    max_bytes: int,
    error: bool,
    dur_s: float = 0.0,
    t_start: Optional[float] = None,
    descriptor_s: float = 0.0,
    payload_s: float = 0.0,
    span_id: Optional[str] = None,
    transport: str = "gather",
    participants: Optional[List[int]] = None,
) -> None:
    """Record one gather transport into the telemetry registry and the event
    timeline (host-side; the gather itself is already complete).
    ``descriptor_s``/``payload_s`` split the round-trip into its descriptor
    vs payload collective rounds (the span decomposition's raw material);
    ``span_id`` is the transport's collective span id; ``transport`` is the
    histogram/event label (``"gather"`` inline, ``"dcn"`` for the async
    engine's cross-host legs, the backend name for strategy transports —
    see :func:`transport_overrides` and ``metrics_tpu.transport``);
    ``participants`` is the peer set the round PHYSICALLY touched (all
    processes for a global collective, the subgroup for a true subgroup
    round) — what the quorum acceptance tests assert. Never raises."""
    try:
        from metrics_tpu.observability.events import EVENTS
        from metrics_tpu.observability.histogram import (
            observe_gather_payload,
            observe_sync_round_trip,
        )
        from metrics_tpu.observability.registry import TELEMETRY

        payload_rounds = 1 if max_bytes else 0
        transport_bytes = nprocs * desc_bytes + payload_rounds * nprocs * max_bytes
        if TELEMETRY.enabled:
            # fast-path log2 histograms: the transport's full round-trip wall
            # time, its per-round split, and its payload volume (host-side;
            # the gather is complete)
            observe_sync_round_trip(dur_s, transport=transport)
            observe_sync_round_trip(descriptor_s, transport=f"{transport}_descriptor")
            if payload_rounds:
                observe_sync_round_trip(payload_s, transport=f"{transport}_payload")
            observe_gather_payload(transport_bytes)
            TELEMETRY.record_gather(
                bytes_out=int(bytes_out),
                bytes_in=int(bytes_in),
                transport_bytes=transport_bytes,
                descriptor_rounds=1,
                payload_rounds=payload_rounds,
                world=nprocs,
                members=members,
                error=error,
                leaves=leaves,
                descriptor_s=descriptor_s,
                payload_s=payload_s,
                transport=transport,
                participants=participants,
            )
        if EVENTS.enabled:
            # the gather rounds on the global timeline: one interval per
            # transport, with the descriptor/payload round composition (and
            # per-round durations), how many state leaves the packed rounds
            # carried, the collective span id, and the recording process (the
            # fleet export's correlation keys)
            EVENTS.record(
                "sync",
                None,
                dur_s=dur_s,
                t_start=t_start,
                transport=transport,
                leaves=int(leaves),
                bytes_out=int(bytes_out),
                bytes_in=int(bytes_in),
                transport_bytes=transport_bytes,
                descriptor_rounds=1,
                payload_rounds=payload_rounds,
                descriptor_s=round(float(descriptor_s), 9),
                payload_s=round(float(payload_s), 9),
                span_id=span_id,
                process=int(jax.process_index()) if nprocs > 1 else 0,
                world=nprocs,
                members=[int(m) for m in members],
                error=bool(error),
                **(
                    {"participants": [int(p) for p in participants]}
                    if participants is not None
                    else {}
                ),
            )
    except Exception:  # pragma: no cover - telemetry must never break a sync
        pass


# ---------------------------------------------------------------------------
# In-graph (mesh-axis) sync — the TPU-native hot path
# ---------------------------------------------------------------------------

#: reduction spec accepted by ``add_state`` and resolved here
ReduceFx = Optional[Union[str, Callable]]

#: which XLA collective each string reduction lowers to (telemetry labels)
_COLLECTIVE_KIND = {"sum": "psum", "mean": "pmean", "max": "pmax", "min": "pmin", "cat": "all_gather", None: "all_gather"}


def sync_value_in_graph(value: Array, reduce_fx: ReduceFx, axis_name: AxisName) -> Array:
    """Synchronize one state array across the named mesh axis, inside a traced program.

    "sum"/"mean"/"max"/"min" compile to single fused XLA collectives —
    deliberately *not* the reference's gather-then-host-reduce (psum over ICI
    is the TPU-idiomatic fusion). "cat" compiles to a tiled all-gather so the
    result is the cross-shard concatenation. ``None`` gathers with a leading
    participant axis. A custom callable receives the stacked ``(world, ...)``
    gather, mirroring the reference's custom ``dist_reduce_fx`` contract.
    A :class:`Hierarchy` axis lowers over its flat equivalent — per-leaf
    collectives gain nothing from level splitting; the hierarchical mode
    lives in the packed engine (:func:`sync_state_packed`).
    """
    if isinstance(axis_name, Hierarchy):
        axis_name = axis_name.flat
    if reduce_fx == "sum":
        return lax.psum(value, axis_name)
    if reduce_fx == "mean":
        return lax.pmean(value, axis_name)
    if reduce_fx == "max":
        return lax.pmax(value, axis_name)
    if reduce_fx == "min":
        return lax.pmin(value, axis_name)
    if reduce_fx == "cat":
        return lax.all_gather(jnp.atleast_1d(value), axis_name, axis=0, tiled=True)
    stacked = lax.all_gather(value, axis_name, axis=0, tiled=False)
    if reduce_fx is None:
        return stacked
    if callable(reduce_fx):
        return reduce_fx(stacked)
    raise ValueError(f"Unknown dist_reduce_fx: {reduce_fx!r}")


def sync_in_graph(
    state: Dict[str, Union[Array, List[Array]]],
    reductions: Dict[str, ReduceFx],
    axis_name: AxisName,
) -> Dict[str, Union[Array, List[Array]]]:
    """Synchronize a whole state dict across mesh axes inside a traced program.

    List states ("cat"/gather-only accumulators) are pre-concatenated into one
    array so each costs exactly one collective, matching the reference's
    pre-concatenation optimization (``metric.py:203-206``).

    Each lowering records its collective composition (which psum/pmax/
    all_gather kinds, pre-collective payload bytes) into the telemetry
    registry — host-side at trace time, once per compile, never per step.
    """
    from metrics_tpu.utilities.data import dim_zero_cat

    synced: Dict[str, Union[Array, List[Array]]] = {}
    kinds: Dict[str, int] = {}
    bytes_traced = 0
    for name, value in state.items():
        fx = reductions.get(name)
        if isinstance(value, (list, tuple)):
            if len(value) == 0:
                synced[name] = value
                continue
            value = dim_zero_cat(list(value))
            gathered = sync_value_in_graph(value, "cat" if fx in ("cat", None) else fx, axis_name)
            synced[name] = [gathered] if fx in ("cat", None) else gathered
            kind = "all_gather" if fx in ("cat", None) else _COLLECTIVE_KIND.get(fx, "all_gather")
        else:
            synced[name] = sync_value_in_graph(value, fx, axis_name)
            kind = _COLLECTIVE_KIND.get(fx, "all_gather") if not callable(fx) else "all_gather"
        kinds[kind] = kinds.get(kind, 0) + 1
        size = getattr(value, "size", None)
        itemsize = getattr(getattr(value, "dtype", None), "itemsize", None)
        if size is not None and itemsize is not None:
            bytes_traced += int(size) * int(itemsize)
    if kinds:
        n_states = sum(kinds.values())
        _record_in_graph_telemetry(
            axis_name, kinds, bytes_traced, collectives_before=n_states, collectives_after=n_states
        )
    return synced


def _record_in_graph_telemetry(
    axis_name: AxisName,
    kinds: Dict[str, int],
    bytes_traced: int,
    *,
    buckets: Optional[Dict[str, int]] = None,
    collectives_before: int = 0,
    collectives_after: int = 0,
    groups: Optional[Dict[str, int]] = None,
    span_ids: Optional[Dict[str, str]] = None,
    levels: Optional[List[str]] = None,
) -> None:
    """Trace-time record of one in-graph sync lowering (registry + event
    timeline). ``kinds`` counts STATES per collective kind; ``buckets`` maps
    ``"<kind>/<dtype>"`` labels (``"<level>/<kind>/<dtype>"`` for a
    hierarchical lowering) to the leaf count each packed bucket carries;
    before/after are the per-leaf vs actually-issued collective counts;
    ``groups`` maps each deduped bundle (a compute group or shared-update
    class) to the member count it serves — the leaf-set the transport did
    NOT have to carry; ``span_ids`` maps each packed bucket to its collective
    span id (observability/tracing.py); ``levels`` names the hierarchy's
    level labels (e.g. ``["ici", "dcn"]``) when the lowering was two-level.
    Never raises."""
    try:
        from metrics_tpu.observability.events import EVENTS
        from metrics_tpu.observability.registry import TELEMETRY

        TELEMETRY.record_in_graph_sync(
            axis_name,
            kinds,
            bytes_traced,
            buckets=buckets,
            collectives_before=collectives_before,
            collectives_after=collectives_after,
            groups=groups,
            levels=levels,
        )
        if EVENTS.enabled:
            # instant event at TRACE time (once per compile, never per
            # step): which collectives this state bundle lowers to, and the
            # bucket packing that fused them
            payload: Dict[str, Any] = {
                "in_graph": True,
                "axis": repr(axis_name),
                "collectives": dict(kinds),
                "bytes_traced": int(bytes_traced),
                "collectives_before": int(collectives_before),
                "collectives_after": int(collectives_after),
            }
            if buckets is not None:
                payload["buckets"] = dict(buckets)
            if levels:
                payload["levels"] = list(levels)
            if groups:
                payload["compute_groups"] = dict(groups)
            if span_ids:
                payload["span_ids"] = dict(span_ids)
            EVENTS.record("sync", None, **payload)
    except Exception:  # pragma: no cover - telemetry must never break a sync
        pass


#: which packed bucket (collective) each string reduction joins
_PACKED_REDUCE_KIND = {"sum": "psum", "mean": "pmean", "max": "pmax", "min": "pmin"}


def _packed_collective(kind: str, buffer: Array, axis_name: AxisName) -> Array:
    if kind == "psum":
        return lax.psum(buffer, axis_name)
    if kind == "pmean":
        return lax.pmean(buffer, axis_name)
    if kind == "pmax":
        return lax.pmax(buffer, axis_name)
    if kind == "pmin":
        return lax.pmin(buffer, axis_name)
    # gather bucket: one untiled all_gather of the packed buffer; each leaf
    # slices its columns and reshapes to either the stacked (world, ...) form
    # or the tiled concatenation (identical memory layout, see below)
    return lax.all_gather(buffer, axis_name, axis=0, tiled=False)


def _packed_collective_levels(kind: str, buffer: Array, levels: Tuple[Tuple[str, Any], ...]) -> Array:
    """Hierarchical lowering of one packed bucket: one collective per LEVEL,
    innermost (ICI) first, result identical to the flat collective over the
    levels' combined axis tuple.

    * psum/pmax/pmin chain exactly (the level partials re-associate the same
      values; integer and extremal reductions are bit-identical, rounding
      float sums agree to ≤1 ulp of reassociation);
    * pmean runs the psum chain and divides ONCE by the total participant
      count (``lax.psum`` of a literal folds to the static axis size — no
      extra collective), matching the flat ``pmean``'s single division;
    * the gather bucket gathers level by level — each outer level stacks the
      previous level's block — and one reshape flattens the
      (outer, ..., inner) grid into the flat participant axis, which is
      exactly the outer-major order ``lax.all_gather`` over the flat tuple
      produces (bit-identical, pinned in tests).
    """
    if kind in ("psum", "pmean"):
        out = buffer
        for _, axis in levels:
            out = lax.psum(out, axis)
        if kind == "pmean":
            size = 1
            for _, axis in levels:
                size = size * lax.psum(1, axis)  # folds to the static axis size
            out = out / size
        return out
    if kind in ("pmax", "pmin"):
        op = lax.pmax if kind == "pmax" else lax.pmin
        out = buffer
        for _, axis in levels:
            out = op(out, axis)
        return out
    out = lax.all_gather(buffer, levels[0][1], axis=0, tiled=False)
    for _, axis in levels[1:]:
        out = lax.all_gather(out, axis, axis=0, tiled=False)
        out = jnp.reshape(out, (out.shape[0] * out.shape[1],) + out.shape[2:])
    return out


def sync_state_packed(
    state: Dict[str, Union[Array, List[Array]]],
    reductions: Dict[str, ReduceFx],
    axis_name: Any,
    *,
    levels: Optional[Sequence[Tuple[str, Any]]] = None,
    group_composition: Optional[Dict[str, int]] = None,
) -> Dict[str, Union[Array, List[Array]]]:
    """Bucketed in-graph sync: ONE collective per (collective kind, dtype).

    Semantically identical to :func:`sync_in_graph` — bit-identical results
    leaf by leaf — but instead of one XLA collective per state leaf, leaves
    are grouped by the collective they lower to and their dtype, flattened,
    and concatenated into one buffer per bucket:

    * all "sum" leaves of one dtype ride ONE ``psum`` (likewise "mean"/"max"/
      "min" with ``pmean``/``pmax``/``pmin`` — every elementwise reduction
      commutes with concatenation);
    * all "cat" and ``None`` (gather-only) leaves of one dtype ride ONE
      untiled ``all_gather`` of the packed buffer; each leaf's columns are
      sliced back out and reshaped to the tiled concatenation ("cat": the
      row-major reshape of ``(world, n, ...)`` to ``(world*n, ...)`` IS the
      shard-order concatenation) or the stacked ``(world, ...)`` form;
    * callable custom reductions keep the per-leaf path — their contract is
      the stacked per-leaf gather, which packing cannot honor.

    A 10-metric classification collection's epoch sync drops from one
    collective per state (~10-40) to one per bucket (typically <=4: a psum
    per numeric dtype plus at most a pmax/all_gather) — the metric-state
    analogue of DDP gradient bucketing / Horovod tensor fusion. List states
    are pre-concatenated exactly as in :func:`sync_in_graph`.

    **Hierarchical mode** (``levels=[("ici", intra_axis), ("dcn",
    inter_axis)]``, or a :class:`Hierarchy` passed as ``axis_name``): each
    packed bucket lowers to one collective per **(level, kind, dtype)** —
    reduce within-host over ICI first, then across hosts over DCN — so the
    cross-host leg carries one already-reduced buffer per bucket instead of
    every device's contribution (the Horovod-hierarchical-allreduce shape).
    Results are identical to the flat sync over the levels' combined axis
    (bit-identical for integer/extremal reductions and gathers; rounding
    float sums agree to ≤1 ulp of level-partial reassociation — see
    :func:`_packed_collective_levels`). Callable custom reductions keep the
    per-leaf gather over the flat axis (their stacked contract admits no
    level split).

    Telemetry (trace-time, once per compile): bucket composition
    (``"<kind>/<dtype>" -> leaf count``; hierarchical buckets are keyed
    ``"<level>/<kind>/<dtype>"`` per level) and the before/after collective
    counts land in ``snapshot()["sync"]["in_graph"]`` and the sync event.
    ``group_composition`` (``bundle label -> members served``) annotates
    bundles a caller already deduplicated — a ``MetricCollection``'s compute
    groups or shared-update classes syncing ONE leaf-set for several
    members — so the sync event and ``in_graph`` stats carry the group
    composition alongside the bucket packing.

    Dispatches through the ACTIVE transport
    (:func:`metrics_tpu.transport.resolve_transport`); the default
    :class:`~metrics_tpu.transport.in_graph.InGraphTransport` lowering is
    this module's packed engine itself (``_sync_state_packed_impl``), so the
    traced program is byte-identical to a direct engine call.
    """
    from metrics_tpu.transport import resolve_transport

    return resolve_transport().sync_state_packed(
        state, reductions, axis_name, levels=levels, group_composition=group_composition
    )


def _sync_state_packed_impl(
    state: Dict[str, Union[Array, List[Array]]],
    reductions: Dict[str, ReduceFx],
    axis_name: Any,
    *,
    levels: Optional[Sequence[Tuple[str, Any]]] = None,
    group_composition: Optional[Dict[str, int]] = None,
) -> Dict[str, Union[Array, List[Array]]]:
    """The packed in-graph engine behind :func:`sync_state_packed` (what the
    default in-graph backend lowers through)."""
    from metrics_tpu.utilities.data import dim_zero_cat

    if levels is None and isinstance(axis_name, Hierarchy):
        levels = axis_name.levels
    hier: Optional[Tuple[Tuple[str, Any], ...]] = None
    if levels is not None:
        hier = Hierarchy(*levels).levels  # normalize + validate
        # per-leaf fallbacks (callables) and telemetry label the flat axis
        axis_name = Hierarchy(*hier).flat

    synced: Dict[str, Union[Array, List[Array]]] = {}
    kinds: Dict[str, int] = {}
    bytes_traced = 0
    per_leaf_collectives = 0  # what sync_in_graph would have issued
    callable_leaves = 0  # custom reductions stay per-leaf (one gather each)
    # bucket key -> [buffer leaves]; entries: (name, flat, unpack spec)
    buckets: Dict[Tuple[str, Any], List[Tuple[str, Array, Tuple]] ] = {}

    for name, value in state.items():
        fx = reductions.get(name)
        wrap_list = False
        if isinstance(value, (list, tuple)):
            if len(value) == 0:
                synced[name] = value
                continue
            value = dim_zero_cat(list(value))
            fx = "cat" if fx in ("cat", None) else fx
            wrap_list = fx == "cat"

        size = getattr(value, "size", None)
        itemsize = getattr(getattr(value, "dtype", None), "itemsize", None)
        if size is not None and itemsize is not None:
            bytes_traced += int(size) * int(itemsize)
        per_leaf_collectives += 1

        if callable(fx):
            # custom reduction: must see the stacked per-leaf gather
            synced[name] = sync_value_in_graph(value, fx, axis_name)
            kinds["all_gather"] = kinds.get("all_gather", 0) + 1
            callable_leaves += 1
            continue
        if fx in _PACKED_REDUCE_KIND:
            kind = _PACKED_REDUCE_KIND[fx]
            spec = ("reduce", value.shape, wrap_list)
        elif fx == "cat":
            value = jnp.atleast_1d(value)
            kind = "all_gather"
            spec = ("cat", value.shape, wrap_list)
        elif fx is None:
            kind = "all_gather"
            spec = ("stack", value.shape, wrap_list)
        else:
            raise ValueError(f"Unknown dist_reduce_fx: {fx!r}")
        kinds[kind] = kinds.get(kind, 0) + 1
        buckets.setdefault((kind, value.dtype), []).append((name, jnp.reshape(value, (-1,)), spec))

    bucket_compo: Dict[str, int] = {}
    bucket_spans: Dict[str, str] = {}
    tracer = _tracer()
    for (kind, dtype), entries in buckets.items():
        base_label = f"{kind}/{np.dtype(dtype).name}"
        # hierarchical: one issued collective — and one composition entry and
        # one span — per (level, kind, dtype); flat: per (kind, dtype)
        labels = (
            [f"{lvl}/{base_label}" for lvl, _ in hier] if hier else [base_label]
        )
        for label in labels:
            bucket_compo[label] = len(entries)
            if tracer:
                # trace-time instant span: one deterministic id per issued
                # packed collective, keyed by (kind, axis, bucket) — the
                # in-graph analogue of the eager transport's correlation key
                # (this runs once per compile; the lowered program itself
                # carries no tracing ops)
                sid = tracer.instant(
                    "in_graph", group=repr(axis_name), bucket=label, leaves=len(entries)
                )
                if sid is not None:
                    bucket_spans[label] = sid
        buffer = jnp.concatenate([flat for _, flat, _ in entries]) if len(entries) > 1 else entries[0][1]
        if hier:
            out = _packed_collective_levels(kind, buffer, hier)
        else:
            out = _packed_collective(kind, buffer, axis_name)
        offset = 0
        for name, flat, (mode, shape, wrap_list) in entries:
            n = int(flat.shape[0])
            if mode == "reduce":
                piece = jnp.reshape(out[offset : offset + n], shape)
            else:
                # out: (world, bucket_size); this leaf's columns, per shard
                cols = out[:, offset : offset + n]
                world = out.shape[0]
                if mode == "cat":
                    # (world, n0, ...) -> (world*n0, ...): row-major reshape
                    # IS the shard-order concatenation a tiled gather makes
                    piece = jnp.reshape(cols, (world * shape[0],) + tuple(shape[1:]))
                else:  # stack: the (world, ...) leading-axis gather
                    piece = jnp.reshape(cols, (world,) + tuple(shape))
            synced[name] = [piece] if wrap_list else piece
            offset += n

    if kinds:
        _record_in_graph_telemetry(
            axis_name,
            kinds,
            bytes_traced,
            buckets=bucket_compo,
            collectives_before=per_leaf_collectives,
            collectives_after=len(buckets) * (len(hier) if hier else 1) + callable_leaves,
            groups=group_composition,
            span_ids=bucket_spans or None,
            levels=[lvl for lvl, _ in hier] if hier else None,
        )
    return synced


def tenant_axis_sharding(mesh: Any, axis_name: AxisName) -> Any:
    """A sharding that splits the leading (tenant) axis over ``axis_name``.

    The multi-tenant wrappers (``metrics_tpu/wrappers/multitenant.py``) hold
    metric state stacked on a leading tenant axis; pass this as their
    ``tenant_sharding=`` to spread that axis across ``mesh`` — every stacked
    leaf's dim 0 is partitioned on ``axis_name``, all other dims replicated,
    so N tenants' state occupies ``1/len(mesh[axis_name])`` of each device.
    The tenant count must divide the axis size. Cross-PROCESS sync of the
    stacked leaves is orthogonal: elementwise reductions ride the packed
    collective buckets unchanged (one ``psum`` per (kind, dtype) bucket,
    regardless of N or the tenant sharding).
    """
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    return NamedSharding(mesh, P(axis_name))
