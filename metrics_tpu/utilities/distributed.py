"""Distributed communication backend (L0).

Capability parity with the reference's ``torchmetrics/utilities/distributed.py``
(``reduce``/``class_reduce``/``gather_all_tensors`` over torch.distributed),
re-designed TPU-first with two complementary sync paths:

* **In-graph sync** (the TPU-idiomatic hot path): metric state lives inside a
  ``pjit``/``shard_map`` program over a ``jax.sharding.Mesh``; per-state
  reductions compile directly to XLA collectives over named mesh axes —
  ``lax.psum`` for "sum" states (skipping the reference's gather+host-reduce
  dance entirely), ``lax.pmean`` for "mean", ``lax.pmax``/``pmin`` for
  extrema, and a tiled ``lax.all_gather`` for "cat"/gather-only states.
  See :func:`sync_in_graph`.

* **Host (eager) sync** for epoch-boundary ``compute()`` across JAX processes:
  :func:`gather_all_arrays` mirrors the reference's protocol (shape gather ->
  pad to elementwise-max -> all-gather -> trim) on top of
  ``jax.experimental.multihost_utils`` since XLA collectives need static,
  equal shapes across participants.
"""
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

Array = jax.Array

AxisName = Union[str, Tuple[str, ...]]


# ---------------------------------------------------------------------------
# Host-side reducers (parity: utilities/distributed.py:21-89)
# ---------------------------------------------------------------------------


def reduce(to_reduce: Array, reduction: str) -> Array:
    """Reduce an array with ``'elementwise_mean'``, ``'sum'`` or ``'none'``."""
    if reduction == "elementwise_mean":
        return jnp.mean(to_reduce)
    if reduction == "none":
        return to_reduce
    if reduction == "sum":
        return jnp.sum(to_reduce)
    raise ValueError("Reduction parameter unknown.")


def class_reduce(
    num: Array,
    denom: Array,
    weights: Array,
    class_reduction: str = "none",
) -> Array:
    """Reduce per-class fractions ``num / denom`` with micro/macro/weighted/none.

    NaNs arising from empty classes (0/0) are zeroed, matching the reference's
    semantics (``utilities/distributed.py:73-75``); infinities are untouched.
    """
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    if class_reduction == "micro":
        fraction = jnp.sum(num) / jnp.sum(denom)
    else:
        fraction = num / denom

    fraction = jnp.where(jnp.isnan(fraction), jnp.zeros_like(fraction), fraction)

    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        w = weights.astype(fraction.dtype)
        return jnp.sum(fraction * (w / jnp.sum(w)))
    if class_reduction == "none" or class_reduction is None:
        return fraction
    raise ValueError(
        f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}"
    )


# ---------------------------------------------------------------------------
# Process-level (multi-host) eager gather
# ---------------------------------------------------------------------------


def distributed_available() -> bool:
    """True when more than one JAX process participates in the runtime."""
    try:
        return jax.process_count() > 1
    except Exception:  # pragma: no cover
        return False


def world_size() -> int:
    return jax.process_count()


def _process_allgather(x: Array) -> Array:
    """All-gather ``x`` across processes -> stacked ``(num_processes, ...)``."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(np.asarray(x)))


#: descriptor layout for the ragged gather: [ndim, d0..d7, dtype_code]
_MAX_GATHER_NDIM = 8
#: dtypes the ragged gather can align across ranks (code = list index);
#: covers every dtype the library stores in states
_GATHER_DTYPES = (
    np.dtype(np.bool_),
    np.dtype(np.uint8),
    np.dtype(np.int8),
    np.dtype(np.int16),
    np.dtype(np.int32),
    np.dtype(np.int64),
    np.dtype(np.float16),
    np.dtype(np.float32),
    np.dtype(np.float64),
)


def _resolve_group(group: Optional[Any], nprocs: int) -> List[int]:
    """Resolve a ``process_group`` argument to the member process indices.

    ``None`` -> all processes. A collection of ints -> that subgroup (the
    eager analogue of the reference's ``torch.distributed`` group handle,
    ``utilities/distributed.py:113-135``). Mesh-axis names (a str, or a
    collection of strs) are the IN-GRAPH sub-group mechanism; on the eager
    path they cannot name a process subset, so they gather everything —
    the documented fallback for metrics whose ``process_group`` is an axis.
    A collection MIXING axis names and indices (e.g. ``("data", 0)``) is
    ambiguous and raises ``TypeError``.

    Raises eagerly when called directly; :func:`gather_all_arrays` defers
    these raises until after its collective rounds so a bad argument on one
    rank cannot hang peers mid-collective.
    """
    if group is None or isinstance(group, str):
        return list(range(nprocs))
    try:
        items = list(group)
    except TypeError:
        raise TypeError(
            f"group must be None, a mesh-axis name, or a collection of process indices; got {group!r}"
        )
    if any(isinstance(i, str) for i in items):
        if all(isinstance(i, str) for i in items):
            return list(range(nprocs))  # tuple of mesh-axis names
        raise TypeError(
            "group mixes mesh-axis names and process indices; pass either a (tuple of)"
            f" mesh-axis name(s) or a collection of ints, got {group!r}"
        )
    try:
        members = sorted({int(i) for i in items})
    except (TypeError, ValueError):
        raise TypeError(
            f"group must be None, a mesh-axis name, or a collection of process indices; got {group!r}"
        )
    if not members:
        raise ValueError("group must name at least one process index")
    if members[0] < 0 or members[-1] >= nprocs:
        raise ValueError(f"group {group!r} names process indices outside [0, {nprocs})")
    return members


def gather_all_arrays(result: Array, group: Optional[Any] = None) -> List[Array]:
    """Gather one array per group member into a list (eager, epoch-boundary path).

    The analogue of the reference's ``gather_all_tensors``
    (``utilities/distributed.py:113-149``), including its ragged protocol:
    shape descriptors are exchanged first, then payloads, and each member's
    result is restored to its true shape. A member with NO data (a
    never-updated list state — 0 elements, possibly of a different rank and
    placeholder dtype, the reference's 0-length case
    ``tests/bases/test_ddp.py:63-81``) still participates: its contribution
    is a 0-row tensor aligned to the peers' ndim/dtype (a 0-length vector
    when the peers are 0-d scalars, which have no row axis to borrow).

    ``group`` restricts the RESULT to a subset of processes (see
    :func:`_resolve_group`): only members' arrays are returned, in ascending
    process order, and non-members' data never enters the output. Because
    JAX's ``process_allgather`` is a global collective, the underlying
    transport always spans all processes — so disjoint groups sync
    *concurrently*: every process must call ``gather_all_arrays`` the same
    number of times (each with its own group), and one transport round
    serves all groups at once. Payloads ride a byte-level buffer, so
    different groups may hold data of entirely different shapes, ndims and
    dtypes in the same round; consistency is only required *within* a group.
    """
    result = jnp.asarray(result)
    if not distributed_available():
        return [result]

    transport_start = time.perf_counter()
    nprocs = world_size()
    # A bad group ARGUMENT must not desync the transport: peers with valid
    # groups are already committed to the global descriptor/payload
    # collectives below, and a rank that raises before them leaves those
    # peers hung mid-collective. Fall back to the all-process group for the
    # rounds, record the error, and raise it after the last collective —
    # the same discipline as the intra-group alignment `group_error` below.
    arg_error: Optional[Exception] = None
    try:
        members = _resolve_group(group, nprocs)
    except (TypeError, ValueError) as err:
        arg_error = err
        members = list(range(nprocs))

    if result.ndim > _MAX_GATHER_NDIM:
        raise ValueError(f"gather_all_arrays supports up to {_MAX_GATHER_NDIM} dims, got {result.ndim}")
    np_dtype = np.dtype(result.dtype)
    if np_dtype not in _GATHER_DTYPES:
        raise ValueError(f"gather_all_arrays cannot align dtype {np_dtype} across ranks")

    desc = np.zeros(_MAX_GATHER_NDIM + 2, dtype=np.int64)
    desc[0] = result.ndim
    desc[1 : 1 + result.ndim] = result.shape
    desc[-1] = _GATHER_DTYPES.index(np_dtype)
    all_desc = _process_allgather(desc)  # (nprocs, 10)

    ndims = all_desc[:, 0].astype(int)
    # np.prod([]) == 1.0, so a 0-d scalar naturally counts as one element
    counts = np.array([int(np.prod(all_desc[i, 1 : 1 + ndims[i]])) for i in range(nprocs)])
    dtype_codes = all_desc[:, -1].astype(int)
    itemsizes = np.array([_GATHER_DTYPES[c].itemsize for c in dtype_codes])

    # intra-group alignment: consistency is required over the NONEMPTY members
    # of MY group only — other groups may hold anything in the same round. A
    # violation must NOT raise before the payload round below: other (valid)
    # groups are already committed to that global collective, and a rank that
    # bails early would leave them hung. Record the error, keep marching
    # through the transport, raise after.
    group_error: Optional[str] = None
    member_nonempty = [i for i in members if counts[i] > 0]
    if member_nonempty:
        if len({int(ndims[i]) for i in member_nonempty}) > 1:
            group_error = (
                "gather_all_arrays: group members hold data of different ranks"
                f" (ndims {[int(ndims[i]) for i in members]})"
            )
        elif len({int(dtype_codes[i]) for i in member_nonempty}) > 1:
            group_error = "gather_all_arrays: group members hold data of different dtypes"
        ref_ndim = int(ndims[member_nonempty[0]])
        target_dtype = _GATHER_DTYPES[int(dtype_codes[member_nonempty[0]])]
    else:  # every member is empty: any consistent alignment works
        ref_ndim = int(max(ndims[i] for i in members))
        target_dtype = _GATHER_DTYPES[int(dtype_codes[members[0]])]

    # per-member true shapes aligned to ref_ndim; an empty member's
    # contribution becomes 0 rows of the peers' trailing dims (0-d peers
    # have no row axis to borrow, so it degrades to a 0-length vector —
    # never a fabricated scalar)
    shapes = {}
    for i in members:
        s = np.zeros(ref_ndim, dtype=np.int64)
        nd = min(int(ndims[i]), ref_ndim)
        s[:nd] = all_desc[i, 1 : 1 + nd]
        shapes[i] = s
    if member_nonempty:
        max_shape = np.stack([shapes[i] for i in member_nonempty]).max(axis=0)
    else:
        max_shape = np.ones(ref_ndim, dtype=np.int64)
    for i in members:
        if counts[i] == 0:
            shapes[i] = np.concatenate([[0], max_shape[1:]]) if ref_ndim > 0 else np.array([0])

    # byte-level transport: ONE global payload round carries every process's
    # raw data (each group decodes only its own members), padded to the
    # global max byte length — at most the volume of the reference's
    # pad-to-elementwise-max, and shape/dtype-heterogeneous across groups
    nbytes = counts * itemsizes
    max_bytes = int(nbytes.max())
    if max_bytes == 0:
        gathered = None
    else:
        buf = np.zeros(max_bytes, dtype=np.uint8)
        local_bytes = np.frombuffer(np.ascontiguousarray(np.asarray(result)).tobytes(), np.uint8)
        buf[: local_bytes.size] = local_bytes
        gathered = _process_allgather(buf)  # (nprocs, max_bytes)

    _record_gather_telemetry(
        result=result,
        members=members,
        counts=counts,
        itemsizes=itemsizes,
        nprocs=nprocs,
        desc_bytes=int(desc.nbytes),
        max_bytes=max_bytes,
        error=arg_error is not None or group_error is not None,
        dur_s=time.perf_counter() - transport_start,
        t_start=transport_start,
    )

    if arg_error is not None:
        raise arg_error
    if group_error is not None:
        raise ValueError(group_error)

    out = []
    for i in members:
        shape = tuple(int(d) for d in shapes[i])
        if counts[i] == 0:
            out.append(jnp.zeros(shape, target_dtype))
            continue
        raw = np.frombuffer(gathered[i].tobytes(), dtype=target_dtype, count=int(counts[i]))
        out.append(jnp.asarray(raw.reshape(shape)))
    return out


def _record_gather_telemetry(
    *,
    result: Array,
    members: List[int],
    counts: "np.ndarray",
    itemsizes: "np.ndarray",
    nprocs: int,
    desc_bytes: int,
    max_bytes: int,
    error: bool,
    dur_s: float = 0.0,
    t_start: Optional[float] = None,
) -> None:
    """Record one gather transport into the telemetry registry and the event
    timeline (host-side; the gather itself is already complete). Never
    raises."""
    try:
        from metrics_tpu.observability.events import EVENTS
        from metrics_tpu.observability.registry import TELEMETRY

        payload_rounds = 1 if max_bytes else 0
        bytes_in = int(sum(int(counts[i]) * int(itemsizes[i]) for i in members))
        transport_bytes = nprocs * desc_bytes + payload_rounds * nprocs * max_bytes
        if TELEMETRY.enabled:
            TELEMETRY.record_gather(
                bytes_out=int(result.nbytes),
                bytes_in=bytes_in,
                transport_bytes=transport_bytes,
                descriptor_rounds=1,
                payload_rounds=payload_rounds,
                world=nprocs,
                members=members,
                error=error,
            )
        if EVENTS.enabled:
            # the gather rounds on the global timeline: one interval per
            # transport, with the descriptor/payload round composition
            EVENTS.record(
                "sync",
                None,
                dur_s=dur_s,
                t_start=t_start,
                transport="gather",
                bytes_out=int(result.nbytes),
                bytes_in=bytes_in,
                transport_bytes=transport_bytes,
                descriptor_rounds=1,
                payload_rounds=payload_rounds,
                world=nprocs,
                members=[int(m) for m in members],
                error=bool(error),
            )
    except Exception:  # pragma: no cover - telemetry must never break a sync
        pass


# ---------------------------------------------------------------------------
# In-graph (mesh-axis) sync — the TPU-native hot path
# ---------------------------------------------------------------------------

#: reduction spec accepted by ``add_state`` and resolved here
ReduceFx = Optional[Union[str, Callable]]

#: which XLA collective each string reduction lowers to (telemetry labels)
_COLLECTIVE_KIND = {"sum": "psum", "mean": "pmean", "max": "pmax", "min": "pmin", "cat": "all_gather", None: "all_gather"}


def sync_value_in_graph(value: Array, reduce_fx: ReduceFx, axis_name: AxisName) -> Array:
    """Synchronize one state array across the named mesh axis, inside a traced program.

    "sum"/"mean"/"max"/"min" compile to single fused XLA collectives —
    deliberately *not* the reference's gather-then-host-reduce (psum over ICI
    is the TPU-idiomatic fusion). "cat" compiles to a tiled all-gather so the
    result is the cross-shard concatenation. ``None`` gathers with a leading
    participant axis. A custom callable receives the stacked ``(world, ...)``
    gather, mirroring the reference's custom ``dist_reduce_fx`` contract.
    """
    if reduce_fx == "sum":
        return lax.psum(value, axis_name)
    if reduce_fx == "mean":
        return lax.pmean(value, axis_name)
    if reduce_fx == "max":
        return lax.pmax(value, axis_name)
    if reduce_fx == "min":
        return lax.pmin(value, axis_name)
    if reduce_fx == "cat":
        return lax.all_gather(jnp.atleast_1d(value), axis_name, axis=0, tiled=True)
    stacked = lax.all_gather(value, axis_name, axis=0, tiled=False)
    if reduce_fx is None:
        return stacked
    if callable(reduce_fx):
        return reduce_fx(stacked)
    raise ValueError(f"Unknown dist_reduce_fx: {reduce_fx!r}")


def sync_in_graph(
    state: Dict[str, Union[Array, List[Array]]],
    reductions: Dict[str, ReduceFx],
    axis_name: AxisName,
) -> Dict[str, Union[Array, List[Array]]]:
    """Synchronize a whole state dict across mesh axes inside a traced program.

    List states ("cat"/gather-only accumulators) are pre-concatenated into one
    array so each costs exactly one collective, matching the reference's
    pre-concatenation optimization (``metric.py:203-206``).

    Each lowering records its collective composition (which psum/pmax/
    all_gather kinds, pre-collective payload bytes) into the telemetry
    registry — host-side at trace time, once per compile, never per step.
    """
    from metrics_tpu.utilities.data import dim_zero_cat

    synced: Dict[str, Union[Array, List[Array]]] = {}
    kinds: Dict[str, int] = {}
    bytes_traced = 0
    for name, value in state.items():
        fx = reductions.get(name)
        if isinstance(value, (list, tuple)):
            if len(value) == 0:
                synced[name] = value
                continue
            value = dim_zero_cat(list(value))
            gathered = sync_value_in_graph(value, "cat" if fx in ("cat", None) else fx, axis_name)
            synced[name] = [gathered] if fx in ("cat", None) else gathered
            kind = "all_gather" if fx in ("cat", None) else _COLLECTIVE_KIND.get(fx, "all_gather")
        else:
            synced[name] = sync_value_in_graph(value, fx, axis_name)
            kind = _COLLECTIVE_KIND.get(fx, "all_gather") if not callable(fx) else "all_gather"
        kinds[kind] = kinds.get(kind, 0) + 1
        size = getattr(value, "size", None)
        itemsize = getattr(getattr(value, "dtype", None), "itemsize", None)
        if size is not None and itemsize is not None:
            bytes_traced += int(size) * int(itemsize)
    if kinds:
        try:
            from metrics_tpu.observability.events import EVENTS
            from metrics_tpu.observability.registry import TELEMETRY

            TELEMETRY.record_in_graph_sync(axis_name, kinds, bytes_traced)
            if EVENTS.enabled:
                # instant event at TRACE time (once per compile, never per
                # step): which collectives this state bundle lowers to
                EVENTS.record(
                    "sync",
                    None,
                    in_graph=True,
                    axis=repr(axis_name),
                    collectives=dict(kinds),
                    bytes_traced=int(bytes_traced),
                )
        except Exception:  # pragma: no cover - telemetry must never break a sync
            pass
    return synced
