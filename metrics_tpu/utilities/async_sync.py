"""Background async sync engine: epoch-end gathers off the step critical path.

The eager epoch sync (``Metric.compute()`` / ``MetricCollection.compute()``)
is a blocking descriptor+payload transport round-trip — ~100 µs of link RTT
per round on the benched TPU tunnel, and unboundedly worse on a degraded
link. This module moves it onto a worker thread:

* :meth:`Metric.compute_async` / :meth:`MetricCollection.compute_async`
  snapshot the live state into a detached shadow copy on the caller thread
  (jax arrays are immutable, so the snapshot is one state copy — the same
  once-per-epoch cost the donation discipline already pays at ``reset()``;
  the live metric keeps updating, donation intact) and submit the shadow's
  ``compute()`` to the engine. The returned :class:`SyncFuture` resolves to
  exactly what the synchronous ``compute()`` would have returned at the
  snapshot moment, while subsequent ``update()``/``forward()`` steps overlap
  the transfer. ``compute()`` itself is untouched.

* **Degraded-link policies** (``on_degraded=``): before each transport
  attempt the engine consults
  :func:`~metrics_tpu.observability.tracing.degraded_processes` — the PR-8
  straggler trigger — and applies per-round timeouts
  (``round_timeout_s``). On a degraded peer or a timed-out round:

  - ``"retry"`` — bounded exponential backoff (``max_retries``,
    ``backoff_s``), for transient link wobbles;
  - ``"stale"`` — serve the last **completed generation**'s value
    immediately, flagged ``future.stale=True`` and counted
    (``stale_serves``): a dashboard metric a few seconds old beats a step
    loop stalled on a sick link;
  - ``"quorum"`` — reduce over the healthy subgroup. The engine forms a
    TRUE transport subgroup when the active backend supports it
    (``metrics_tpu.transport`` — ``resolve_transport().subgroup(healthy)``
    plus a registered subgroup channel): the gather rounds then span only
    the healthy peers, and a dead process is never contacted. Without a
    subgroup channel the legacy narrowing applies
    (:func:`~metrics_tpu.utilities.distributed.transport_overrides`
    ``quorum=``): the round still spans all processes, but the flagged
    peers' contributions are excluded exactly as an explicit ``group=``
    argument would exclude them.

* **Generation counter.** Every submission under one telemetry key gets a
  monotonically increasing generation; the engine retains the latest
  completed ``(generation, value)`` per key. That is what the stale policy
  serves, what guards a late-arriving superseded round from overwriting a
  newer result, and what ``future.generation`` reports.

**Collective discipline applies across processes**: transport rounds are
global collectives, so every process must submit the same ``compute_async``
calls in the same order (exactly the rule synchronous ``compute()`` already
imposes), and inline gathers must not interleave differently between
processes while a job is in flight. The engine's single FIFO worker
preserves submission order; the per-round timeout exists precisely because a
desynced or dead peer otherwise hangs the round forever.

Everything here is host-side: the engine adds zero traced ops
(``scripts/check_zero_overhead.py`` pins the hot-path jaxprs byte-identical
with the engine constructed and running), and its counters surface in
``observability.snapshot()["async_sync"]`` and the
``metrics_tpu_async_sync_*`` Prometheus family.
"""
import threading
import time
from typing import Any, Callable, Dict, List, Optional

#: default bounded-backoff parameters for the "retry" policy
DEFAULT_MAX_RETRIES = 2
DEFAULT_BACKOFF_S = 0.05

#: the selectable degraded-link policies
POLICIES = ("retry", "stale", "quorum")


class AsyncSyncError(RuntimeError):
    """A background sync exhausted its policy (retries spent, no stale
    generation to serve, quorum round failed)."""


class SyncTimeout(AsyncSyncError):
    """A transport round exceeded its ``round_timeout_s``."""


class SyncFuture:
    """Handle to one in-flight background sync.

    ``result(timeout=None)`` blocks until the engine resolves the job and
    returns the computed value (or raises the job's terminal error);
    ``done()`` polls without blocking. ``stale`` is True when the degraded
    -link policy served the previous completed generation instead of a fresh
    sync; ``generation`` is the submission's per-key generation;
    ``attempts`` counts transport attempts the policy spent.
    """

    def __init__(self, key: str, generation: int, policy: str) -> None:
        self.key = key
        self.generation = generation
        self.policy = policy
        self.stale = False
        self.attempts = 0
        self._event = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"background sync of {self.key} (generation {self.generation}) still"
                f" in flight after {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._value

    def exception(self, timeout: Optional[float] = None) -> Optional[BaseException]:
        """The job's terminal error (None on success); blocks like
        :meth:`result`."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"background sync of {self.key} (generation {self.generation}) still"
                f" in flight after {timeout}s"
            )
        return self._error

    def _resolve(self, value: Any, *, stale: bool = False) -> None:
        self._value = value
        self.stale = stale
        self._event.set()

    def _fail(self, error: BaseException) -> None:
        self._error = error
        self._event.set()

    def __repr__(self) -> str:
        state = "done" if self.done() else "pending"
        return (
            f"SyncFuture({self.key}, generation={self.generation},"
            f" policy={self.policy!r}, {state})"
        )


class _Job:
    __slots__ = ("future", "thunk", "on_degraded", "round_timeout_s", "retry")

    def __init__(self, future, thunk, on_degraded, round_timeout_s, retry):
        self.future = future
        self.thunk = thunk
        self.on_degraded = on_degraded
        self.round_timeout_s = round_timeout_s
        #: the unified RetryPolicy (metrics_tpu/resilience/policies.py) —
        #: what was a hand-rolled ``backoff_s * 2**(k-1)`` loop here
        self.retry = retry


def _degraded() -> List[int]:
    """Peers the engine must treat as sick before an attempt: the union of
    the PR-8 per-attempt straggler hint and the resilience plane's
    versioned membership epoch (a peer the current epoch excludes is dead
    until an explicit rejoin bumps the epoch — the hint can narrow the
    healthy set further, never resurrect a dead peer). Both sources are
    guarded: diagnostics must not break a sync."""
    out: set = set()
    try:
        from metrics_tpu.observability.tracing import degraded_processes

        out.update(int(p) for p in degraded_processes())
    except Exception:  # pragma: no cover - diagnostics must not break a sync
        pass
    try:
        from metrics_tpu.resilience.membership import dead_processes

        out.update(int(p) for p in dead_processes())
    except Exception:  # pragma: no cover - resilience plane optional
        pass
    return sorted(out)


def _membership_epoch() -> int:
    """The current membership epoch (0 when the resilience plane is idle or
    absent) — stamped on every finished job's event."""
    try:
        from metrics_tpu.resilience.membership import current_epoch

        return current_epoch()
    except Exception:  # pragma: no cover - resilience plane optional
        return 0


def _consult_fault_seam(seam: str, **ctx: Any) -> Any:
    """Consult the resilience fault plan (import-guarded only — a raise
    from the plan IS the injected fault, absorbed by the job's policy)."""
    try:
        from metrics_tpu.resilience.faults import maybe_fault
    except Exception:  # pragma: no cover - resilience plane optional
        return None
    return maybe_fault(seam, **ctx)


def _note_round_outcome(peers: List[int], ok: bool) -> None:
    """Feed the failure detector one round outcome (guarded)."""
    try:
        from metrics_tpu.resilience.detector import note_round_outcome

        note_round_outcome(peers, ok)
    except Exception:  # pragma: no cover - diagnostics must not break a sync
        pass


class AsyncSyncEngine:
    """Single-worker FIFO engine running background sync jobs.

    One process-global instance (:func:`get_engine`) backs
    ``compute_async``; private instances are supported for tests. The worker
    thread starts lazily on the first submission and is a daemon — an idle
    engine holds no thread at import, and process exit never blocks on it.
    FIFO matters: it is what keeps engine-issued collectives in the same
    order on every process (the collective-discipline invariant).
    """

    def __init__(
        self,
        *,
        max_retries: int = DEFAULT_MAX_RETRIES,
        backoff_s: float = DEFAULT_BACKOFF_S,
        round_timeout_s: Optional[float] = None,
        retry_policy: Optional[Any] = None,
    ) -> None:
        from metrics_tpu.resilience.policies import retry_policy_for

        # one retry vocabulary across planes: the legacy knobs construct a
        # RetryPolicy from the async_sync plane default; an explicit policy
        # wins outright
        if retry_policy is None:
            retry_policy = retry_policy_for("async_sync").with_overrides(
                max_retries=int(max_retries), backoff_s=float(backoff_s)
            )
        self.retry_policy = retry_policy
        self.max_retries = int(retry_policy.max_retries)
        self.backoff_s = float(retry_policy.backoff_s)
        self.round_timeout_s = round_timeout_s
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: List[_Job] = []
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self._in_flight = 0
        self._generations: Dict[str, int] = {}
        self._last: Dict[str, Any] = {}  # key -> (generation, value)
        self._pending: Dict[str, SyncFuture] = {}  # key -> newest unresolved future
        self._counters: Dict[str, int] = {
            "submitted": 0,
            "completed": 0,
            "failed": 0,
            "retries": 0,
            "timeouts": 0,
            "stale_serves": 0,
            "quorum_syncs": 0,
            "degraded_rounds": 0,
            "coalesced": 0,
        }

    # -- submission ---------------------------------------------------------

    def submit(
        self,
        key: str,
        thunk: Callable[[], Any],
        *,
        on_degraded: str = "retry",
        round_timeout_s: Optional[float] = None,
        max_retries: Optional[int] = None,
        backoff_s: Optional[float] = None,
        coalesce: bool = False,
    ) -> SyncFuture:
        """Queue ``thunk`` (a self-contained sync+compute over a detached
        state snapshot) and return its :class:`SyncFuture`. Per-job
        ``round_timeout_s``/``max_retries``/``backoff_s`` override the engine
        defaults.

        ``coalesce=True`` is the serving-read submission mode: when a job
        for ``key`` is already queued or running, the existing future is
        returned instead of enqueueing a duplicate (counted ``coalesced``,
        no new generation) — N concurrent readers of one metric cost one
        gather, not N. **Collective discipline caveat**: coalescing makes
        the submission count depend on local timing, so only use it for
        single-process or loopback-transport reads (the serving scheduler's
        case), never for jobs whose thunks issue multi-process
        collectives."""
        if on_degraded not in POLICIES:
            raise ValueError(
                f"on_degraded must be one of {POLICIES}, got {on_degraded!r}"
            )
        with self._lock:
            if coalesce:
                pending = self._pending.get(key)
                if pending is not None and not pending.done():
                    self._counters["coalesced"] += 1
                    return pending
            generation = self._generations.get(key, 0) + 1
            self._generations[key] = generation
            future = SyncFuture(key, generation, on_degraded)
            self._pending[key] = future
            self._queue.append(
                _Job(
                    future,
                    thunk,
                    on_degraded,
                    self.round_timeout_s if round_timeout_s is None else round_timeout_s,
                    self.retry_policy.with_overrides(
                        max_retries=max_retries, backoff_s=backoff_s
                    ),
                )
            )
            self._counters["submitted"] += 1
            self._in_flight += 1
            self._ensure_worker()
            self._cv.notify()
        return future

    def _ensure_worker(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stopping = False
            self._thread = threading.Thread(
                target=self._worker, name="metrics-tpu-async-sync", daemon=True
            )
            self._thread.start()

    # -- the worker ---------------------------------------------------------

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stopping:
                    self._cv.wait()
                if self._stopping and not self._queue:
                    return
                job = self._queue.pop(0)
            try:
                self._run_job(job)
            finally:
                with self._lock:
                    self._in_flight -= 1
                    # the coalesce window closes with the job: a LATER
                    # submission must queue fresh work, never adopt a future
                    # that already resolved
                    if self._pending.get(job.future.key) is job.future:
                        del self._pending[job.future.key]

    def _attempt(self, thunk: Callable[[], Any], timeout: Optional[float]) -> Any:
        """One transport attempt under the per-round timeout.

        The timeout runs the thunk on a helper thread and abandons it on
        expiry — a hung collective cannot be cancelled, only orphaned; the
        orphan operates on the job's detached shadow state, so a late
        completion mutates nothing the caller can observe and its result is
        discarded. The helper INHERITS the worker thread's transport context
        and eager overrides (both are thread-local) — without the snapshot a
        quorum/label set on the worker would silently not apply to the
        gather it governs."""
        if timeout is None:
            return thunk()
        box: Dict[str, Any] = {}
        from metrics_tpu.transport import get_transport, use_transport
        from metrics_tpu.utilities.distributed import (
            applied_transport_overrides,
            current_transport_overrides,
        )

        overrides = current_transport_overrides()
        transport = get_transport()

        def run() -> None:
            try:
                with use_transport(transport), applied_transport_overrides(overrides):
                    box["value"] = thunk()
            except BaseException as err:  # noqa: BLE001 - relayed to the policy
                box["error"] = err

        helper = threading.Thread(target=run, daemon=True)
        helper.start()
        helper.join(timeout)
        if helper.is_alive():
            with self._lock:
                self._counters["timeouts"] += 1
            raise SyncTimeout(f"transport round exceeded round_timeout_s={timeout}")
        if "error" in box:
            raise box["error"]
        return box["value"]

    def _serve_stale(self, job: _Job, reason: str) -> bool:
        """Resolve the job from the last completed generation (the "stale"
        policy); False when no generation has ever completed for the key."""
        with self._lock:
            last = self._last.get(job.future.key)
            if last is None:
                return False
            self._counters["stale_serves"] += 1
            self._counters["completed"] += 1
        generation, value = last
        job.future._resolve(value, stale=True)
        self._record_event(
            job, outcome="stale", reason=reason, served_generation=generation
        )
        return True

    def _run_job(self, job: _Job) -> None:
        future = job.future
        attempt = 0
        while True:
            degraded = _degraded()
            quorum: Optional[List[int]] = None
            if degraded:
                with self._lock:
                    self._counters["degraded_rounds"] += 1
                if job.on_degraded == "stale" and self._serve_stale(
                    job, reason=f"degraded peers {degraded}"
                ):
                    return
                if job.on_degraded == "quorum":
                    quorum = self._healthy_subgroup(degraded)
            try:
                future.attempts = attempt + 1
                # the resilience seam: an armed ``async.attempt`` spec
                # raises/delays HERE, inside the policy loop, exactly like a
                # failed transport attempt would
                _consult_fault_seam(
                    "async.attempt", key=future.key, attempt=attempt + 1
                )
                from metrics_tpu.transport import resolve_transport, use_transport
                from metrics_tpu.utilities.distributed import transport_overrides

                if quorum is not None:
                    with self._lock:
                        self._counters["quorum_syncs"] += 1
                    # TRUE subgroup formation when the active transport (and
                    # its channel) supports it: the gather rounds span only
                    # the healthy peers — a dead peer is never contacted.
                    # The decode-narrowing override stays installed either
                    # way (it is the fallback when no subgroup channel is
                    # registered, and it is harmless when one is).
                    subgroup = resolve_transport().subgroup(quorum)
                    with use_transport(subgroup), transport_overrides(
                        quorum=quorum, transport_label="dcn"
                    ):
                        value = self._attempt(job.thunk, job.round_timeout_s)
                else:
                    with transport_overrides(transport_label="dcn"):
                        value = self._attempt(job.thunk, job.round_timeout_s)
            except BaseException as err:  # noqa: BLE001 - the policy decides
                _note_round_outcome(degraded, ok=False)
                if job.on_degraded == "stale" and self._serve_stale(
                    job, reason=f"{type(err).__name__}: {err}"
                ):
                    return
                if job.on_degraded in ("retry", "quorum") and job.retry.should_retry(
                    attempt + 1
                ):
                    attempt += 1
                    with self._lock:
                        self._counters["retries"] += 1
                    job.retry.sleep(attempt)
                    continue
                with self._lock:
                    self._counters["failed"] += 1
                if isinstance(err, AsyncSyncError):
                    future._fail(err)
                else:
                    future._fail(
                        AsyncSyncError(
                            f"background sync of {future.key} failed after"
                            f" {attempt + 1} attempt(s): {type(err).__name__}: {err}"
                        )
                    )
                self._record_event(job, outcome="failed", reason=f"{type(err).__name__}: {err}")
                return
            with self._lock:
                self._counters["completed"] += 1
                prev = self._last.get(future.key)
                # a late round never overwrites a newer completed generation
                if prev is None or prev[0] < future.generation:
                    self._last[future.key] = (future.generation, value)
            # a completed round is a heartbeat for every peer it spanned
            _note_round_outcome(
                quorum if quorum is not None else self._all_processes(), ok=True
            )
            future._resolve(value)
            self._record_event(
                job,
                outcome="quorum" if quorum is not None else "completed",
                quorum=quorum,
            )
            return

    @staticmethod
    def _all_processes() -> List[int]:
        from metrics_tpu.utilities.distributed import world_size

        return list(range(world_size()))

    @staticmethod
    def _healthy_subgroup(degraded: List[int]) -> List[int]:
        from metrics_tpu.utilities.distributed import world_size

        sick = {int(p) for p in degraded}
        healthy = [p for p in range(world_size()) if p not in sick]
        return healthy or list(range(world_size()))  # never an empty quorum

    def _record_event(self, job: _Job, *, outcome: str, **payload: Any) -> None:
        """One ``sync`` event per finished background job (host-side; never
        raises)."""
        try:
            from metrics_tpu.observability.events import EVENTS

            if EVENTS.enabled:
                EVENTS.record(
                    "sync",
                    job.future.key,
                    path="async",
                    policy=job.on_degraded,
                    outcome=outcome,
                    generation=job.future.generation,
                    attempts=job.future.attempts,
                    stale=job.future.stale,
                    membership_epoch=_membership_epoch(),
                    **{k: v for k, v in payload.items() if v is not None},
                )
        except Exception:  # pragma: no cover - telemetry must not break a sync
            pass

    # -- reading / lifecycle ------------------------------------------------

    def last_generation(self, key: str) -> int:
        """The latest completed generation for ``key`` (0 when none)."""
        with self._lock:
            last = self._last.get(key)
            return last[0] if last else 0

    def summary(self) -> Dict[str, Any]:
        """Compact JSON view for ``snapshot()["async_sync"]``."""
        with self._lock:
            return {
                "engine_alive": bool(self._thread is not None and self._thread.is_alive()),
                "in_flight": self._in_flight,
                "generations": {k: g for k, g in self._generations.items()},
                **dict(self._counters),
            }

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every queued job has finished; False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                if self._in_flight == 0:
                    return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.001)

    def reset(self) -> None:
        """Clear counters, generations and retained values (queued jobs keep
        running). Like the span tracker's clear: generations are part of the
        cross-process contract — reset on every process together or on
        none."""
        with self._lock:
            self._generations.clear()
            self._last.clear()
            self._pending.clear()
            for k in self._counters:
                self._counters[k] = 0

    def shutdown(self, timeout: Optional[float] = 1.0) -> None:
        """Stop the worker after the queue drains (mainly for tests)."""
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout)


#: the process-global engine, constructed lazily (import must stay cheap and
#: thread-free for the zero-overhead discipline)
_ENGINE: Optional[AsyncSyncEngine] = None
_ENGINE_LOCK = threading.Lock()
#: named auxiliary engines (lanes): work that must not queue behind the
#: default lane's FIFO — e.g. the durability plane's checkpoint writes,
#: which can take seconds and would otherwise stall every serving-read
#: refresh submitted after them — runs on its own single-worker engine
_NAMED_ENGINES: Dict[str, AsyncSyncEngine] = {}


def get_engine(name: str = "default") -> AsyncSyncEngine:
    """The process-global background sync engine (created on first use).

    ``name`` selects an engine LANE: ``"default"`` is the engine
    ``compute_async`` and the serving scheduler share (its FIFO is the
    collective-discipline guarantee); any other name returns a dedicated
    single-worker engine created on first use — FIFO within the lane,
    independent of the default lane. Named lanes are for host-only work
    (disk writes, serialization); jobs that issue multi-process
    collectives belong on the default lane, where submission order is the
    cross-process contract."""
    global _ENGINE
    if name != "default":
        with _ENGINE_LOCK:
            engine = _NAMED_ENGINES.get(name)
            if engine is None:
                engine = _NAMED_ENGINES[name] = AsyncSyncEngine()
            return engine
    if _ENGINE is None:
        with _ENGINE_LOCK:
            if _ENGINE is None:
                _ENGINE = AsyncSyncEngine()
    return _ENGINE


def staging_lane() -> AsyncSyncEngine:
    """The serving queue's dedicated host-only staging lane.

    The staged-ingest prefetch (``AdmissionQueue(staging=True)``) fills and
    transfers the NEXT cohort while the current dispatch is still on device.
    That fill is pure host work plus a ``device_put``-style transfer — it
    must never queue behind the default lane's FIFO (where a slow refresh
    or checkpoint would serialize exactly the overlap the double-buffer
    exists to create), so it gets its own single-worker lane. FIFO within
    the lane keeps cohort hand-off order deterministic."""
    return get_engine("staging")


def summary() -> Dict[str, Any]:
    """The global engine's compact view — ``{}`` when nothing ever submitted
    (the snapshot stays clean for processes that never used
    ``compute_async``)."""
    if _ENGINE is None:
        return {}
    return _ENGINE.summary()
