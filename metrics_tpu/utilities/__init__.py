from metrics_tpu.utilities.data import apply_to_collection  # noqa: F401
from metrics_tpu.utilities.distributed import (  # noqa: F401
    Hierarchy,
    applied_transport_overrides,
    class_reduce,
    current_transport_overrides,
    hierarchical_axis,
    reduce,
    shard_map_compat,
    transport_overrides,
)
from metrics_tpu.utilities.prints import (  # noqa: F401
    rank_zero_debug,
    rank_zero_info,
    rank_zero_only,
    rank_zero_warn,
)
