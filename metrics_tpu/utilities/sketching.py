"""Sketched-state wiring shared by the ``sketched=True`` metric modes.

:mod:`metrics_tpu.kernels.sketches` holds the pure summaries; this module
holds the *metric-class* plumbing around them:

* :class:`SketchTelemetryMixin` — the observability contract every sketched
  metric honors: a ``sketch_merges`` counter (eager state merges of sketch
  summaries, plus cross-shard merges at compute) and an ``info.sketch`` blob
  in ``observability.snapshot()`` (kind, bins/capacity, overflow counters)
  rendered as the ``metrics_tpu_sketch_*`` Prometheus families.

* :class:`HistogramSketchMixin` — state registration + canonicalized update
  for the binned-label-histogram sketch backing
  AUROC/ROC/PrecisionRecallCurve/AveragePrecision ``sketched=True``: fixed
  ``(C, num_bins)`` ``pos_hist``/``neg_hist`` float32 sum states (plus a
  scalar clipped-score counter), mirroring the capacity mode's binary /
  multiclass one-vs-rest / multilabel input handling.

Because every sketch state is a fixed-shape ``"sum"`` array, sketched
metrics clear the PR-4 compiled-state gate (jit_forward / warmup /
update_many / donation), the PR-5 compute-group tracer, AND the PR-6 keyed
gate — the whole hot-path machinery the ``cat``-list states were excluded
from — and their sync rides the packed (kind, dtype) buckets as one psum
regardless of sample count.
"""
from typing import Optional, Tuple

import jax.numpy as jnp

from metrics_tpu.kernels.binned_counts import label_score_histograms
from metrics_tpu.observability.registry import TELEMETRY
from metrics_tpu.observability.retrace import is_tracing
from metrics_tpu.utilities.data import Array, _is_traced
from metrics_tpu.utilities.enums import DataType

__all__ = ["HistogramSketchMixin", "SketchTelemetryMixin"]


def _check_num_bins(num_bins: int) -> None:
    if not (isinstance(num_bins, int) and num_bins > 1):
        raise ValueError(f"`num_bins` should be an integer > 1, got: {num_bins}")


def _check_range(name: str, rng: Tuple[float, float]) -> Tuple[float, float]:
    try:
        lo, hi = float(rng[0]), float(rng[1])
    except (TypeError, ValueError, IndexError):
        raise ValueError(f"`{name}` should be a (low, high) pair of floats, got: {rng!r}")
    if not lo < hi:
        raise ValueError(f"`{name}` needs low < high, got: {rng!r}")
    return lo, hi


class SketchTelemetryMixin:
    """Observability hooks shared by every ``sketched=True`` metric mode."""

    #: set by the concrete metric's sketched-state init
    sketched: bool = False

    def merge_states(self, a, b):  # type: ignore[override]
        merged = super().merge_states(a, b)
        # host-side accounting only: under tracing this body runs once per
        # compile, and counting there would both miscount and (worse) tempt a
        # traced op — sketched states must stay zero-overhead like the rest
        # of the telemetry plane
        if self.sketched and TELEMETRY.enabled and not is_tracing(a, b):
            TELEMETRY.inc(self.telemetry_key, "sketch_merges")
        return merged

    def _count_sketch_merges(self, n: int) -> None:
        """Cross-shard sketch merges performed at compute (eager sync)."""
        if n > 0 and TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "sketch_merges", n)

    def _publish_sketch_info(self, **info) -> None:
        """Publish the ``info.sketch`` snapshot blob (eager compute only —
        traced values cannot be read and the publish is skipped)."""
        if not TELEMETRY.enabled:
            return
        concrete = {}
        for k, v in info.items():
            if _is_traced(v):
                return
            concrete[k] = float(v) if hasattr(v, "dtype") else v
        TELEMETRY.set_info(self.telemetry_key, "sketch", concrete)


class HistogramSketchMixin(SketchTelemetryMixin):
    """Binned-label-histogram states + canonicalized update for the
    threshold-curve metrics' ``sketched=True`` mode."""

    _sketch_multilabel = False

    def _init_hist_states(
        self,
        num_bins: int,
        score_range: Tuple[float, float],
        num_classes: Optional[int],
        pos_label: Optional[int],
        multilabel: bool = False,
    ) -> None:
        """Validate the sketched configuration and register the histogram
        states: ``pos_hist``/``neg_hist`` of shape ``(C, num_bins)`` (C = 1
        for binary) plus the scalar out-of-range counter, all ``"sum"``."""
        _check_num_bins(num_bins)
        lo, hi = _check_range("score_range", score_range)
        multi = num_classes is not None and num_classes > 1
        if multilabel and not multi:
            raise ValueError(
                f"multilabel `sketched` mode needs `num_classes` > 1 (the label count), got {num_classes}"
            )
        if not multi and pos_label not in (None, 0, 1):
            raise ValueError(f"`sketched` mode expects `pos_label` in (0, 1), got: {pos_label}")
        if multi and pos_label is not None:
            raise ValueError("`pos_label` does not apply to multi-class `sketched` mode")
        self._sketch_multilabel = multilabel
        self._sketch_bins = num_bins
        self._sketch_range = (lo, hi)
        width = num_classes if multi else 1
        for name in ("pos_hist", "neg_hist"):
            self.add_state(name, jnp.zeros((width, num_bins), jnp.float32), dist_reduce_fx="sum")
        self.add_state("sketch_clipped", jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

    @property
    def _sketch_multiclass(self) -> bool:
        num_classes = getattr(self, "num_classes", None)
        return num_classes is not None and num_classes > 1 and not self._sketch_multilabel

    def _hist_update(self, preds: Array, target: Array) -> None:
        """Accumulate one batch into the label histograms — the capacity
        buffer's canonicalization (binary / multiclass one-vs-rest /
        multilabel) over the fixed score grid instead of a sample buffer."""
        from metrics_tpu.functional.classification.auroc import _auroc_update
        from metrics_tpu.utilities.data import to_onehot

        preds, target, mode = _auroc_update(preds, target)
        if self._sketch_multilabel:
            if mode != DataType.MULTILABEL or preds.ndim != 2 or preds.shape[1] != self.num_classes:
                raise ValueError(
                    f"multilabel `sketched` mode with num_classes={self.num_classes} expects"
                    f" (N, C) scores and (N, C) binary labels, got mode {mode} with preds shape {preds.shape}"
                )
            target = (target == 1).astype(jnp.int32)
        elif self._sketch_multiclass:
            if mode != DataType.MULTICLASS or preds.ndim != 2 or preds.shape[1] != self.num_classes:
                raise ValueError(
                    f"`sketched` mode with num_classes={self.num_classes} expects (N, C) class scores"
                    f" and (N,) labels, got mode {mode} with preds shape {preds.shape}"
                )
            target = to_onehot(target.astype(jnp.int32), num_classes=self.num_classes).astype(jnp.int32)
        else:
            if mode != DataType.BINARY:
                raise ValueError(f"`sketched` mode supports binary inputs only, got mode {mode}")
            pos_label = 1 if getattr(self, "pos_label", None) is None else self.pos_label
            preds = preds.reshape(-1, 1)
            target = (target == pos_label).astype(jnp.int32).reshape(-1, 1)
        lo, hi = self._sketch_range
        pos, neg, clipped = label_score_histograms(preds, target, self._sketch_bins, lo, hi)
        self.pos_hist = self.pos_hist + pos
        self.neg_hist = self.neg_hist + neg
        self.sketch_clipped = self.sketch_clipped + clipped

    def _hist_check_degenerate(self) -> Optional[Array]:
        """Eager raise on degenerate (single-label) histograms, mirroring the
        capacity mode's :meth:`_check_degenerate_classes`; returns the
        per-class positive supports for weighted averaging. Inside compiled
        programs raising is impossible — the hist kernels return the same
        0/0 NaN the reference's arithmetic would."""
        if _is_traced(self.pos_hist, self.neg_hist):
            return None
        import numpy as np

        pos = np.asarray(jnp.sum(self.pos_hist, axis=-1))
        neg = np.asarray(jnp.sum(self.neg_hist, axis=-1))
        if (pos + neg).sum() == 0:  # empty stream: compute-before-update already warned
            return None
        for p, n in zip(pos, neg):
            if p > 0 and n == 0:
                raise ValueError("No negative samples in targets, false positive value should be meaningless")
            if n > 0 and p == 0:
                raise ValueError("No positive samples in targets, true positive value should be meaningless")
        return jnp.sum(self.pos_hist, axis=-1)

    def _publish_hist_info(self) -> None:
        self._publish_sketch_info(
            kind="binned_histogram",
            bins=self._sketch_bins,
            range=list(self._sketch_range),
            classes=int(self.pos_hist.shape[0]),
            overflow=self.sketch_clipped,
        )
