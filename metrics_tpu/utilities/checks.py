"""Input canonicalization & validation for classification/retrieval metrics.

Capability parity with the reference's ``torchmetrics/utilities/checks.py``
(case inference at ``checks.py:54-113``, the override matrix of
``multiclass``/``top_k``/``num_classes`` at ``checks.py:312-451``, retrieval
checks at ``checks.py:503-583``) with a TPU-first split:

* **Shape/dtype case inference** uses only static information (ndim, dtype,
  shapes) and is therefore trace-safe.
* **Value-dependent validation** (non-negative targets, label ranges, binary
  targets for float preds) reads data values and cannot run inside a traced
  XLA program; it runs on the host when inputs are concrete and is skipped
  under tracing (``jit``/``vmap``/``shard_map``), where configuration must be
  made explicit (e.g. ``num_classes``).
* **Transforms** (threshold / top-k / one-hot / reshape) are pure static-shape
  jnp ops that fuse into the surrounding XLA program.
"""
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from metrics_tpu.utilities.data import Array, _is_traced, is_floating_point, select_topk, to_onehot
from metrics_tpu.utilities.enums import DataType


def _check_same_shape(preds: Array, target: Array) -> None:
    """Raise if predictions and targets differ in shape."""
    if preds.shape != target.shape:
        raise RuntimeError("Predictions and targets are expected to have the same shape")


def _basic_input_validation(preds: Array, target: Array, threshold: float, multiclass: Optional[bool]) -> None:
    """Value/dtype checks that need no case information. Host-side (eager only)."""
    if is_floating_point(target):
        raise ValueError("The `target` has to be an integer tensor.")

    preds_float = is_floating_point(preds)

    if not _is_traced(preds, target):
        target_np = np.asarray(target)
        if target_np.size and target_np.min() < 0:
            raise ValueError("The `target` has to be a non-negative tensor.")
        preds_np = np.asarray(preds)
        if not preds_float and preds_np.size and preds_np.min() < 0:
            raise ValueError("If `preds` are integers, they have to be non-negative.")
        if multiclass is False and target_np.size and target_np.max() > 1:
            raise ValueError("If you set `multiclass=False`, then `target` should not exceed 1.")
        if multiclass is False and not preds_float and preds_np.size and preds_np.max() > 1:
            raise ValueError(
                "If you set `multiclass=False` and `preds` are integers, then `preds` should not exceed 1."
            )

    if not preds.shape[0] == target.shape[0]:
        raise ValueError("The `preds` and `target` should have the same first dimension.")


def _check_shape_and_type_consistency(preds: Array, target: Array) -> Tuple[DataType, int]:
    """Infer the input case from shapes/dtypes (static info only; trace-safe).

    Returns the case and the implied number of classes (C dim for multi-class,
    flattened extra dims for multi-label).
    """
    preds_float = is_floating_point(preds)

    if preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError(
                "The `preds` and `target` should have the same shape,"
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
        if preds_float and not _is_traced(target) and np.asarray(target).size and np.asarray(target).max() > 1:
            raise ValueError(
                "If `preds` and `target` are of shape (N, ...) and `preds` are floats, `target` should be binary."
            )
        if preds.ndim == 1:
            case = DataType.BINARY if preds_float else DataType.MULTICLASS
        else:
            case = DataType.MULTILABEL if preds_float else DataType.MULTIDIM_MULTICLASS
        implied_classes = int(np.prod(preds.shape[1:])) if preds.ndim > 1 else 1

    elif preds.ndim == target.ndim + 1:
        if not preds_float:
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should be"
                " (N, C, ...), and the shape of `target` should be (N, ...)."
            )
        implied_classes = preds.shape[1]
        case = DataType.MULTICLASS if preds.ndim == 2 else DataType.MULTIDIM_MULTICLASS
    else:
        raise ValueError(
            "Either `preds` and `target` both should have the (same) shape (N, ...), or `target` should be (N, ...)"
            " and `preds` should be (N, C, ...)."
        )

    return case, implied_classes


def _check_num_classes_binary(num_classes: int, multiclass: Optional[bool]) -> None:
    """Consistency of ``num_classes`` with binary data."""
    if num_classes > 2:
        raise ValueError("Your data is binary, but `num_classes` is larger than 2.")
    if num_classes == 2 and not multiclass:
        raise ValueError(
            "Your data is binary and `num_classes=2`, but `multiclass` is not True."
            " Set it to True if you want to transform binary data to multi-class format."
        )
    if num_classes == 1 and multiclass:
        raise ValueError(
            "You have binary data and have set `multiclass=True`, but `num_classes` is 1."
            " Either set `multiclass=None`(default) or set `num_classes=2`"
            " to transform binary data to multi-class format."
        )


def _check_num_classes_mc(
    preds: Array,
    target: Array,
    num_classes: int,
    multiclass: Optional[bool],
    implied_classes: int,
) -> None:
    """Consistency of ``num_classes`` with (multi-dim) multi-class data."""
    if num_classes == 1 and multiclass is not False:
        raise ValueError(
            "You have set `num_classes=1`, but predictions are integers."
            " If you want to convert (multi-dimensional) multi-class data with 2 classes"
            " to binary/multi-label, set `multiclass=False`."
        )
    if num_classes > 1:
        if multiclass is False and implied_classes != num_classes:
            raise ValueError(
                "You have set `multiclass=False`, but the implied number of classes "
                " (from shape of inputs) does not match `num_classes`. If you are trying to"
                " transform multi-dim multi-class data with 2 classes to multi-label, `num_classes`"
                " should be either None or the product of the size of extra dimensions (...)."
                " See Input Types in Metrics documentation."
            )
        if not _is_traced(preds, target):
            if np.asarray(target).size and num_classes <= np.asarray(target).max():
                raise ValueError("The highest label in `target` should be smaller than `num_classes`.")
            if not is_floating_point(preds) and np.asarray(preds).size and num_classes <= np.asarray(preds).max():
                raise ValueError("The highest label in `preds` should be smaller than `num_classes`.")
        if preds.shape != target.shape and num_classes != implied_classes:
            raise ValueError("The size of C dimension of `preds` does not match `num_classes`.")


def _check_num_classes_ml(num_classes: int, multiclass: Optional[bool], implied_classes: int) -> None:
    """Consistency of ``num_classes`` with multi-label data."""
    if multiclass and num_classes != 2:
        raise ValueError(
            "Your have set `multiclass=True`, but `num_classes` is not equal to 2."
            " If you are trying to transform multi-label data to 2 class multi-dimensional"
            " multi-class, you should set `num_classes` to either 2 or None."
        )
    if not multiclass and num_classes != implied_classes:
        raise ValueError("The implied number of classes (from shape of inputs) does not match num_classes.")


def _check_top_k(
    top_k: int, case: DataType, implied_classes: int, multiclass: Optional[bool], preds_float: bool
) -> None:
    if case == DataType.BINARY:
        raise ValueError("You can not use `top_k` parameter with binary data.")
    if not isinstance(top_k, int) or top_k <= 0:
        raise ValueError("The `top_k` has to be an integer larger than 0.")
    if not preds_float:
        raise ValueError("You have set `top_k`, but you do not have probability predictions.")
    if multiclass is False:
        raise ValueError("If you set `multiclass=False`, you can not set `top_k`.")
    if case == DataType.MULTILABEL and multiclass:
        raise ValueError(
            "If you want to transform multi-label data to 2 class multi-dimensional"
            "multi-class data using `multiclass=True`, you can not use `top_k`."
        )
    if top_k >= implied_classes:
        raise ValueError("The `top_k` has to be strictly smaller than the `C` dimension of `preds`.")


def _check_classification_inputs(
    preds: Array,
    target: Array,
    threshold: float,
    num_classes: Optional[int],
    multiclass: Optional[bool],
    top_k: Optional[int],
) -> DataType:
    """Full input validation; returns the inferred case.

    Value-dependent pieces run on the host for concrete inputs and are skipped
    under tracing.
    """
    _basic_input_validation(preds, target, threshold, multiclass)

    case, implied_classes = _check_shape_and_type_consistency(preds, target)

    if preds.shape != target.shape:
        if multiclass is False and implied_classes != 2:
            raise ValueError(
                "You have set `multiclass=False`, but have more than 2 classes in your data,"
                " based on the C dimension of `preds`."
            )
        if not _is_traced(target) and np.asarray(target).size and np.asarray(target).max() >= implied_classes:
            raise ValueError(
                "The highest label in `target` should be smaller than the size of the `C` dimension of `preds`."
            )

    if num_classes:
        if case == DataType.BINARY:
            _check_num_classes_binary(num_classes, multiclass)
        elif case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS):
            _check_num_classes_mc(preds, target, num_classes, multiclass, implied_classes)
        elif case == DataType.MULTILABEL:
            _check_num_classes_ml(num_classes, multiclass, implied_classes)

    if top_k is not None:
        _check_top_k(top_k, case, implied_classes, multiclass, is_floating_point(preds))

    return case


def _input_squeeze(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Drop all size-1 dimensions except the leading sample dimension."""
    if preds.shape[0] == 1:
        preds = jnp.expand_dims(jnp.squeeze(preds), 0)
        target = jnp.expand_dims(jnp.squeeze(target), 0)
    else:
        preds, target = jnp.squeeze(preds), jnp.squeeze(target)
    return preds, target


def _input_format_classification(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Tuple[Array, Array, DataType]:
    """Canonicalize every classification input into binary int tensors.

    Output is always ``(N, C)`` or ``(N, C, X)`` int32 plus the inferred case,
    following the same case/override semantics as the reference
    (``checks.py:312-451``):

    * binary / multi-label: probabilities thresholded (or top-k for
      multi-label); ``multiclass=True`` expands to a 2-class one-hot.
    * (multi-dim) multi-class: targets one-hot; float preds top-k one-hot;
      ``multiclass=False`` squashes 2-class data down to the positive column.
    * all extra dims are flattened into ``X``; size-1 dims (except N) squeezed.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)

    preds, target = _input_squeeze(preds, target)

    # half-precision inputs are canonicalized through f32 (cheap; outputs are int)
    if preds.dtype in (jnp.float16, jnp.bfloat16):
        preds = preds.astype(jnp.float32)

    case = _check_classification_inputs(
        preds, target, threshold=threshold, num_classes=num_classes, multiclass=multiclass, top_k=top_k
    )

    if case in (DataType.BINARY, DataType.MULTILABEL) and not top_k:
        preds = (preds >= threshold).astype(jnp.int32)
        num_classes = num_classes if not multiclass else 2

    if case == DataType.MULTILABEL and top_k:
        preds = select_topk(preds, top_k)

    if case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) or multiclass:
        if is_floating_point(preds):
            num_classes = preds.shape[1]
            preds = select_topk(preds, top_k or 1)
        else:
            if not num_classes:
                if _is_traced(preds, target):
                    raise ValueError(
                        "`num_classes` must be given explicitly when canonicalizing label "
                        "predictions inside a traced (jit/shard_map) program."
                    )
                num_classes = int(max(np.asarray(preds).max(), np.asarray(target).max())) + 1
            preds = to_onehot(preds, max(2, num_classes))

        target = to_onehot(target, max(2, int(num_classes) if num_classes else 2))

        if multiclass is False:
            preds, target = preds[:, 1, ...], target[:, 1, ...]

    if (case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) and multiclass is not False) or multiclass:
        target = target.reshape(target.shape[0], target.shape[1], -1)
        preds = preds.reshape(preds.shape[0], preds.shape[1], -1)
    else:
        target = target.reshape(target.shape[0], -1)
        preds = preds.reshape(preds.shape[0], -1)

    # drop the trailing singleton the reshapes above create for flat MC/binary data
    if preds.ndim > 2 and preds.shape[-1] == 1:
        preds, target = jnp.squeeze(preds, -1), jnp.squeeze(target, -1)

    return preds.astype(jnp.int32), target.astype(jnp.int32), case


def _input_format_classification_one_hot(
    num_classes: int,
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multilabel: bool = False,
) -> Tuple[Array, Array]:
    """Legacy one-hot formatter: returns ``(num_classes, -1)`` binary tensors."""
    if preds.ndim not in (target.ndim, target.ndim + 1):
        raise ValueError("preds and target must have same number of dimensions, or one additional dimension for preds")

    if preds.ndim == target.ndim + 1:
        preds = jnp.argmax(preds, axis=1)

    if preds.ndim == target.ndim and jnp.issubdtype(preds.dtype, jnp.integer) and num_classes > 1 and not multilabel:
        preds = to_onehot(preds, num_classes=num_classes)
        target = to_onehot(target, num_classes=num_classes)
    elif preds.ndim == target.ndim and is_floating_point(preds):
        preds = (preds >= threshold).astype(jnp.int32)

    if preds.ndim > 1:
        preds = jnp.swapaxes(preds, 0, 1)
        target = jnp.swapaxes(target, 0, 1)

    return preds.reshape(num_classes, -1), target.reshape(num_classes, -1)


def _check_retrieval_functional_inputs(
    preds: Array,
    target: Array,
    allow_non_binary_target: bool = False,
) -> Tuple[Array, Array]:
    """Validate and flatten a (preds, target) retrieval pair -> (f32, int32).

    With ``allow_non_binary_target`` (nDCG), targets hold graded relevance:
    float dtypes are accepted and preserved as f32 instead of cast to int.
    """
    if preds.shape != target.shape:
        raise ValueError("`preds` and `target` must be of the same shape")
    if preds.ndim == 0 or preds.size == 0:
        raise ValueError("`preds` and `target` must be non-empty and non-scalar tensors")
    target_is_int = jnp.issubdtype(target.dtype, jnp.integer) or target.dtype == jnp.bool_
    if not target_is_int and not (allow_non_binary_target and is_floating_point(target)):
        raise ValueError("`target` must be a tensor of booleans or integers")
    if not is_floating_point(preds):
        raise ValueError("`preds` must be a tensor of floats")
    if not _is_traced(target):
        t = np.asarray(target)
        if (not allow_non_binary_target and t.max() > 1) or t.min() < 0:
            raise ValueError("`target` must contain `binary` values")
    target = target.astype(jnp.int32) if target_is_int else target.astype(jnp.float32)
    return preds.astype(jnp.float32).reshape(-1), target.reshape(-1)


def _check_retrieval_inputs(
    indexes: Array,
    preds: Array,
    target: Array,
    allow_non_binary_target: bool = False,
) -> Tuple[Array, Array, Array]:
    """Validate and flatten an (indexes, preds, target) triple -> (int32, f32, int32).

    With ``allow_non_binary_target`` (nDCG), float graded-relevance targets are
    accepted and preserved as f32.
    """
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
    if indexes.ndim == 0 or indexes.size == 0:
        raise ValueError("`indexes`, `preds` and `target` must be non-empty and non-scalar tensors")
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")
    if not is_floating_point(preds):
        raise ValueError("`preds` must be a tensor of floats")
    target_is_int = jnp.issubdtype(target.dtype, jnp.integer) or target.dtype == jnp.bool_
    if not target_is_int and not (allow_non_binary_target and is_floating_point(target)):
        raise ValueError("`target` must be a tensor of booleans or integers")
    if not _is_traced(target):
        t = np.asarray(target)
        if (not allow_non_binary_target and t.max() > 1) or t.min() < 0:
            raise ValueError("`target` must contain `binary` values")
    target = target.astype(jnp.int32) if target_is_int else target.astype(jnp.float32)
    return (
        indexes.astype(jnp.int32).reshape(-1),
        preds.astype(jnp.float32).reshape(-1),
        target.reshape(-1),
    )
