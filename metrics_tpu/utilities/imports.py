"""Package-availability and version gates.

Parity with the reference's ``torchmetrics/utilities/imports.py``
(``_module_available``/``_compare_version`` and feature flags) adapted to the
JAX ecosystem: the optional integrations here are flax (NN feature
extractors), scipy/sklearn (test-time oracles) and torch (weight porting).
"""
import operator
from importlib import import_module
from importlib.util import find_spec
from typing import Callable

from packaging.version import Version


def _module_available(module_path: str) -> bool:
    """Return ``True`` if the (possibly nested) module can be imported."""
    parts = module_path.split(".")
    try:
        for i in range(len(parts)):
            if find_spec(".".join(parts[: i + 1])) is None:
                return False
    except (AttributeError, ImportError, ModuleNotFoundError, ValueError):
        return False
    return True


def _compare_version(package: str, op: Callable, version: str) -> bool:
    """Compare an installed package's version against ``version`` with ``op``."""
    if not _module_available(package):
        return False
    try:
        pkg = import_module(package)
        pkg_version = Version(getattr(pkg, "__version__", "0.0.0"))
    except (ModuleNotFoundError, ImportError, TypeError):
        return False
    return op(pkg_version, Version(version))


_JAX_AVAILABLE: bool = _module_available("jax")
_FLAX_AVAILABLE: bool = _module_available("flax")
_OPTAX_AVAILABLE: bool = _module_available("optax")
_SCIPY_AVAILABLE: bool = _module_available("scipy")
_SKLEARN_AVAILABLE: bool = _module_available("sklearn")
_TORCH_AVAILABLE: bool = _module_available("torch")
_TORCHVISION_AVAILABLE: bool = _module_available("torchvision")
_NLTK_AVAILABLE: bool = _module_available("nltk")
_JAX_GREATER_EQUAL_0_4: bool = _compare_version("jax", operator.ge, "0.4.0")
