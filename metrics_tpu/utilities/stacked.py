"""Stacked child states: one pure metric program over a leading replica axis.

Two wrappers hold many logical copies of one metric as a SINGLE state pytree
whose every leaf carries an extra leading axis — ``BootStrapper`` (the axis is
bootstrap replicas) and ``KeyedMetric``/``MultiTenantCollection`` (the axis is
tenants). Both need the same three pieces, extracted here so the pattern is
written once:

* **stack build** — :func:`stack_pytrees` (stack N concrete child states) and
  :func:`broadcast_stack` (N identical fresh copies without N inits);
* **vmapped update** — :func:`vmap_update`, the child's pure ``apply_update``
  mapped over the stack axis, with a pluggable per-replica body (the
  bootstrapper derives a resample from a PRNG key, the multi-tenant router
  updates each stack row with its own event rows);
* **vmapped compute** — :func:`vmap_compute`, the child's pure
  ``apply_compute`` fanned out per stack row.

:func:`row_states` is the multi-tenant router's first half: the child's
update evaluated on every EVENT ROW of a batch independently (a vmap over the
leading event axis, each row kept as a length-1 batch so the child sees the
layout it was written for), producing per-row partial states that a
segment-reduction then routes to their tenants.
"""
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "broadcast_stack",
    "row_states",
    "stack_pytrees",
    "vmap_compute",
    "vmap_update",
]


def stack_pytrees(trees: Sequence[Any]) -> Any:
    """Stack equal-structure pytrees leaf-wise along a new leading axis."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves, axis=0), *trees)


def broadcast_stack(tree: Any, n: int) -> Any:
    """``n`` identical copies of ``tree`` stacked on a new leading axis.

    Value-identical to ``stack_pytrees([tree] * n)`` but materializes one
    broadcast per leaf instead of an ``n``-way stack — the cheap form for
    replicating a fresh ``init_state()`` to thousands of tenants."""
    return jax.tree.map(
        lambda leaf: jnp.broadcast_to(jnp.asarray(leaf), (n,) + jnp.shape(leaf)),
        tree,
    )


def vmap_update(metric: Any, body: Optional[Callable] = None) -> Callable:
    """``jax.vmap`` of one child's pure update over the leading stack axis.

    Returns ``(stacked_state, xs) -> stacked_state`` where ``xs`` carries one
    entry per stack row. ``body(child_state, x)`` defaults to
    ``metric.apply_update(child_state, *x)``; wrappers that derive each
    replica's inputs from ``x`` (the bootstrapper resamples from a per-child
    PRNG key) pass their own body."""
    if body is None:
        body = lambda s, x: metric.apply_update(s, *x)  # noqa: E731
    return jax.vmap(body)


def vmap_compute(metric: Any, axis_name: Any = None) -> Callable:
    """``jax.vmap`` of one child's pure compute over the leading stack axis:
    ``stacked_state -> stacked values``. ``axis_name`` is forwarded to every
    row's ``apply_compute`` (the stack axis itself is never reduced over)."""
    return jax.vmap(lambda s: metric.apply_compute(s, axis_name=axis_name))


def row_states(metric: Any, args: Tuple, kwargs: Dict) -> Dict[str, Any]:
    """The child's update evaluated on every event row independently.

    Every array argument of rank >= 1 must share the same leading event axis
    ``B``; rank-0 and python-scalar leaves broadcast to every row. Each row is
    presented to ``metric.apply_update`` as a length-1 batch (shape
    ``(1, ...)``), so the child runs the exact program it was written for.
    Returns the per-row batch-local states stacked to ``(B, ...)`` leaves —
    the input of a segment reduction routing rows to stacked replicas."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    mapped = [getattr(leaf, "ndim", 0) >= 1 for leaf in leaves]
    lengths = {int(leaf.shape[0]) for leaf, m in zip(leaves, mapped) if m}
    if not lengths:
        raise ValueError(
            "keyed update expects at least one array argument whose leading axis"
            " is the event-row axis (aligned with `tenant_ids`)"
        )
    if len(lengths) > 1:
        raise ValueError(
            "keyed update: array arguments disagree on the event-row axis"
            f" (leading axes {sorted(lengths)}); every array argument must carry"
            " the same leading row count as `tenant_ids`"
        )
    b = lengths.pop()
    # keep a length-1 batch axis per row: (B, ...) -> (B, 1, ...)
    expanded = [
        leaf.reshape((b, 1) + tuple(leaf.shape[1:])) if m else leaf
        for leaf, m in zip(leaves, mapped)
    ]
    init = metric.init_state()

    def one(row_leaves: Tuple) -> Dict[str, Any]:
        merged = list(expanded)
        it = iter(row_leaves)
        for i, m in enumerate(mapped):
            if m:
                merged[i] = next(it)
        row_args, row_kwargs = jax.tree_util.tree_unflatten(treedef, merged)
        return metric.apply_update(init, *row_args, **row_kwargs)

    return jax.vmap(one)(tuple(leaf for leaf, m in zip(expanded, mapped) if m))
