"""String enums used across the library.

Capability parity with the reference's ``torchmetrics/utilities/enums.py``
(``EnumStr``/``DataType``/``AverageMethod``/``MDMCAverageMethod``), re-written
for this framework: comparisons are case-insensitive and tolerate raw strings
or ``None`` so user-facing kwargs stay plain strings.
"""
from enum import Enum
from typing import Optional, Union


class EnumStr(str, Enum):
    """A ``str``-valued Enum with case-insensitive lookup and comparison."""

    @classmethod
    def from_str(cls, value: str) -> Optional["EnumStr"]:
        try:
            return cls[str(value).replace(" ", "_").replace("-", "_").upper()]
        except KeyError:
            return None

    def __eq__(self, other: Union[str, "EnumStr", None]) -> bool:
        if isinstance(other, Enum):
            other = other.value
        # str(None) == "none" intentionally matches the NONE member, so users
        # may spell the no-averaging mode either average=None or average="none"
        return self.value.lower() == str(other).lower()

    def __ne__(self, other) -> bool:
        return not self.__eq__(other)

    def __hash__(self) -> int:
        return hash(self.value.lower())


class DataType(EnumStr):
    """The four canonical classification input cases."""

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"


class AverageMethod(EnumStr):
    """Class-averaging modes for classification metrics."""

    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = "none"
    SAMPLES = "samples"


class MDMCAverageMethod(EnumStr):
    """How the extra sample dimension is handled for multi-dim multi-class inputs."""

    GLOBAL = "global"
    SAMPLEWISE = "samplewise"
