"""Rank-zero-gated logging/warning helpers.

Parity with the reference's ``torchmetrics/utilities/prints.py`` — but rank
detection is JAX-native: ``jax.process_index()`` when the distributed runtime
is initialized, with the ``LOCAL_RANK``/``GLOBAL_RANK`` env vars as fallback
so launchers that pre-set them behave identically.
"""
import logging
import os
import warnings
from functools import partial, wraps
from typing import Any, Callable

log = logging.getLogger("metrics_tpu")


def _detect_rank() -> int:
    for env_key in ("GLOBAL_RANK", "RANK", "LOCAL_RANK"):
        if env_key in os.environ:
            return int(os.environ[env_key])
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover - jax always importable in practice
        return 0


def rank_zero_only(fn: Callable) -> Callable:
    """Decorator: run ``fn`` only on global rank zero."""

    @wraps(fn)
    def wrapped(*args: Any, **kwargs: Any) -> Any:
        if getattr(rank_zero_only, "rank", _detect_rank()) == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped


def _warn(message: str, *args: Any, **kwargs: Any) -> None:
    warnings.warn(message, *args, **kwargs)


def _info(message: str, *args: Any, **kwargs: Any) -> None:
    log.info(message, *args, **kwargs)


def _debug(message: str, *args: Any, **kwargs: Any) -> None:
    log.debug(message, *args, **kwargs)


rank_zero_warn = rank_zero_only(partial(_warn, stacklevel=5))
rank_zero_info = rank_zero_only(_info)
rank_zero_debug = rank_zero_only(_debug)
