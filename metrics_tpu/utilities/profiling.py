"""Profiling hooks.

The reference has no in-repo tracing (only instantiation telemetry,
``torchmetrics/metric.py:83``). Here every metric phase is observable
natively: compiled regions carry ``jax.named_scope`` annotations (visible in
HLO and in ``jax.profiler`` / XProf timelines as ``metrics/<Metric>.<phase>``)
and eager calls carry ``jax.profiler.TraceAnnotation`` spans, so per-metric
step overhead — the BASELINE north-star number — can be read straight off a
profiler trace instead of wall-clock sampling.

Enable a trace with the standard JAX tooling, e.g.::

    with jax.profiler.trace("/tmp/metrics-trace"):
        state = step(state, preds, target)   # annotated regions appear per metric
"""
from contextlib import contextmanager
from typing import Iterator

import jax

_SCOPE_PREFIX = "metrics"


def compiled_scope(name: str):
    """Named scope for trace-time annotation inside jitted programs."""
    return jax.named_scope(f"{_SCOPE_PREFIX}/{name}")


@contextmanager
def eager_span(name: str) -> Iterator[None]:
    """Host-side profiler span for eager (non-compiled) metric phases."""
    try:
        annotation = jax.profiler.TraceAnnotation(f"{_SCOPE_PREFIX}/{name}")
    except Exception:  # pragma: no cover - profiler backend unavailable
        yield
        return
    with annotation:
        yield
