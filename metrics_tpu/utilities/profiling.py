"""Profiling hooks.

The reference has no in-repo tracing (only instantiation telemetry,
``torchmetrics/metric.py:83``). Here every metric phase is observable
natively: compiled regions carry ``jax.named_scope`` annotations (visible in
HLO and in ``jax.profiler`` / XProf timelines as ``metrics/<Metric>.<phase>``)
and eager calls carry ``jax.profiler.TraceAnnotation`` spans, so per-metric
step overhead — the BASELINE north-star number — can be read straight off a
profiler trace instead of wall-clock sampling.

Enable a trace with the standard JAX tooling, e.g.::

    with jax.profiler.trace("/tmp/metrics-trace"):
        state = step(state, preds, target)   # annotated regions appear per metric

These hooks are the TRACE-TIME half of observability: they label compiled
regions for offline profiler inspection. The RUNTIME half — per-metric call
counters, eager wall-time histograms, retrace detection, XLA cost reports,
and collective-sync payload accounting, all scrapeable live via
``metrics_tpu.observability.snapshot()`` — lives in
:mod:`metrics_tpu.observability` (see ``docs/observability.md``). The two
compose: a scanned program measured by :func:`measure_scan_slope` shows up in
the telemetry registry as one ``update_traces`` entry per compiled length,
never as per-step counts, because all counters live host-side.
"""
import time
from contextlib import contextmanager
from typing import Any, Iterator

import jax

_SCOPE_PREFIX = "metrics"


def compiled_scope(name: str):
    """Named scope for trace-time annotation inside jitted programs."""
    return jax.named_scope(f"{_SCOPE_PREFIX}/{name}")


@contextmanager
def eager_span(name: str) -> Iterator[None]:
    """Host-side profiler span for eager (non-compiled) metric phases."""
    try:
        annotation = jax.profiler.TraceAnnotation(f"{_SCOPE_PREFIX}/{name}")
    except Exception:  # pragma: no cover - profiler backend unavailable
        yield
        return
    with annotation:
        yield


def measure_scan_slope(
    all_inputs: Any, init_state: Any, update: Any, rounds: int = 7, stats: Any = None
) -> float:
    """Marginal per-step device time (seconds) of ``update`` scanned over
    ``all_inputs`` (leading axis = steps) — the shared two-length-slope
    harness behind ``bench.py`` / ``scripts/bench_suite.py`` and
    :func:`measure_step_overhead`. The value is the conservative max of two
    median estimators (paired differences and difference-of-medians; see the
    inline comment).

    The same jitted program runs at 1x and 5x the step count; the slope
    ``(t_long - t_short) / (4 * steps)`` cancels fixed dispatch/transfer
    latency, which on remote-device links can exceed the per-step cost by
    orders of magnitude. Outputs fold to one scalar so no state computation
    is dead-code-eliminable, the two lengths are timed back-to-back per
    round (cancels slow latency drift), and the median averages the middle
    pair for even ``rounds``. Returns NaN (with a warning) when noise
    swallows the signal even after retrying with more rounds — never a
    silent zero.

    Pass a dict as ``stats`` to receive compile evidence:
    ``warmup_short_s``/``warmup_long_s`` are the first-call wall times of
    the two program lengths (compile + one run). When the persistent
    compilation cache is warm these sit near the steady-state run time;
    a cold cache shows up as the full XLA compile — which is how a bench
    record proves its warmup actually hit the cache.
    """
    import warnings

    import jax.numpy as jnp

    steps = jax.tree.leaves(all_inputs)[0].shape[0]

    @jax.jit
    def epoch(state, inputs):
        def body(s, xs):
            return update(s, *xs), None

        final = jax.lax.scan(body, state, inputs)[0]
        return jax.tree.reduce(
            lambda a, b: a + b,
            [jnp.sum(jnp.asarray(leaf, jnp.float32)) for leaf in jax.tree.leaves(final)],
        )

    tiled = jax.tree.map(lambda x: jnp.concatenate([x] * 5, axis=0), all_inputs)

    def run(inputs):
        start = time.perf_counter()
        float(epoch(init_state(), inputs))
        return time.perf_counter() - start

    from statistics import median

    warmup_short = run(all_inputs)  # compile both lengths
    warmup_long = run(tiled)
    if stats is not None:
        stats["warmup_short_s"] = round(warmup_short, 3)
        stats["warmup_long_s"] = round(warmup_long, 3)
    for attempt in range(2):
        shorts, longs = [], []
        for _ in range(rounds * (attempt + 1)):
            longs.append(run(tiled))
            shorts.append(run(all_inputs))
        # two estimators: the paired-difference median cancels slow latency
        # drift; the difference-of-medians filters one-sided latency spikes
        # (a spike during a short run shrinks every paired difference and
        # can understate the cost 10x+). Validity is keyed on the paired
        # estimator alone (so below-noise signals still fall through to the
        # NaN warning); when valid, report the LARGER of the two —
        # conservative: a glitch may hide a win, never manufacture one.
        paired = median(lo - sh for lo, sh in zip(longs, shorts))
        of_medians = median(longs) - median(shorts)
        if paired > 0:
            return max(paired, of_medians) / (4 * steps)
    warnings.warn(
        "slope measurement failed (non-positive median): per-step signal is"
        " below the link's timing noise; raise the step count"
    )
    return float("nan")


def measure_step_overhead(metric: Any, *example_batch: Any, steps: int = 256, rounds: int = 5) -> float:
    """Marginal per-step device cost (seconds) of ``metric``'s fused update —
    the BASELINE "µs/step overhead" number, measured natively.

    Builds ``steps`` varied copies of ``example_batch`` and delegates to
    :func:`measure_scan_slope` — exactly how the update rides a jitted train
    step. Works for a single metric or a
    :class:`~metrics_tpu.MetricCollection`. Returns NaN when the signal is
    swallowed by link noise; raise ``steps`` until the slope dominates (the
    per-step signal grows linearly with it).
    """
    import jax.numpy as jnp

    batch = tuple(jnp.asarray(a) for a in example_batch)
    # per-step data must differ or XLA hoists the loop-invariant update delta
    # out of the scan; rolling the sample axis varies it for free (scalars
    # have nothing to roll and broadcast unchanged)
    idx = jnp.arange(steps)
    inputs = tuple(
        jnp.broadcast_to(a, (steps,) + a.shape)
        if a.ndim == 0
        else jax.vmap(lambda i, a=a: jnp.roll(a, i, axis=0))(idx)
        for a in batch
    )
    return measure_scan_slope(
        inputs, metric.init_state, metric.apply_update, rounds=rounds
    )
