"""AOT-compiled stateful dispatch: the executable cache behind ``jit_forward``.

The compiled stateful hot path (``Metric.jit_forward`` / ``update_many`` and
the collection variants) dispatches through ONE of these per program: an
aval-keyed cache of ``jax.stages`` executables built with
``jit(fn).lower(...).compile()`` — the same AOT pipeline
``observability/cost.py`` uses read-only for cost reports, here driving the
serving path. Owning the lower/compile step (instead of letting ``jax.jit``
compile lazily inside a dispatch) buys three things:

* **Donation.** The executable is built with ``donate_argnums=(0,)`` so XLA
  reuses the state pytree's buffers in place — zero-copy state updates. The
  caller owns the discipline (the donated input arrays are invalidated by the
  dispatch); ``donate_state=False`` builds the copying lowering instead.
* **Warmup.** :meth:`warm` lowers and compiles for a given batch shape
  WITHOUT executing, so first-step latency becomes a deliberate, observable
  event (``Metric.warmup``) instead of a surprise inside step 0 — and the
  returned executable exposes ``cost_analysis()`` for the compile-time cost
  report.
* **Exact compile accounting.** A dispatch either hits the cache or compiles
  — :attr:`last_compiled` says which, with no jit-cache-size inference.

Host-side argument handling mirrors the eager call as closely as tracing
allows: python ``bool``/``str`` leaves are STATIC (baked into the executable
and part of the cache key — the ``FID(...)(imgs, real=True)`` flag pattern,
which branches host-side in ``update``), while python ``int``/``float``
leaves are traced as weak-typed scalars (so a stream of varying python
numbers costs one compile, not one per value).
"""
import hashlib
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["CompiledDispatch", "trace_fingerprint"]

#: leaf-layout markers: traced (device data) vs static (baked into the program)
_TRACED = 0
_STATIC = 1


class CompiledDispatch:
    """Aval-keyed cache of AOT-compiled executables for one stateful program.

    ``fn(state, *args, **kwargs)`` is the pure program; ``__call__`` runs it
    through a compiled executable, compiling on the first sight of each
    (state avals, argument avals, static values) signature. With
    ``donate_state=True`` the executable donates the ``state`` argument:
    every dispatch invalidates the state arrays passed in (the caller must
    hand over ownership — see ``Metric._donation_safe_state``).

    Not thread-safe (same contract as the jit cache it replaces).
    """

    def __init__(
        self, fn: Callable, donate_state: bool = True, context_fn: Optional[Callable[[], Any]] = None
    ) -> None:
        self._fn = fn
        self.donate_state = bool(donate_state)
        #: optional hashable-context provider mixed into every cache key —
        #: the compute-group engine passes the collection's group signature
        #: here, so a group rebuild dispatches to a matching executable
        #: (and a rebuild back to a previous layout is a cache HIT, not a
        #: recompile) without dropping the whole dispatch cache
        self._context_fn = context_fn
        self._cache: Dict[Any, Any] = {}
        #: True when the most recent warm()/__call__ compiled a fresh executable
        self.last_compiled = False
        #: lower+compile wall seconds of that fresh executable (0.0 on a hit)
        self.last_compile_s = 0.0
        #: lifetime dispatch accounting (see :meth:`cache_info`)
        self._hits = 0
        self._misses = 0

    # -- argument canonicalization ------------------------------------------

    @staticmethod
    def _split(args: Tuple, kwargs: Dict) -> Tuple[Any, Tuple, List, Tuple]:
        """Flatten ``(args, kwargs)`` and partition the leaves into traced
        (arrays, plus python numbers coerced to weak-typed scalars) and
        static (bools/strings/other host objects, baked into the program)."""
        import jax
        import jax.numpy as jnp

        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        layout: List[int] = []
        traced: List[Any] = []
        static: List[Any] = []
        for leaf in leaves:
            if isinstance(leaf, (jax.Array, np.ndarray, np.generic)):
                layout.append(_TRACED)
                traced.append(leaf)
            elif isinstance(leaf, bool) or isinstance(leaf, str):
                # bool before int (bool is an int subclass): flags like
                # FID's `real=` drive host-side branches in update()
                layout.append(_STATIC)
                static.append(leaf)
            elif isinstance(leaf, (int, float, complex)):
                layout.append(_TRACED)
                traced.append(jnp.asarray(leaf))
            else:
                layout.append(_STATIC)
                static.append(leaf)
        return treedef, tuple(layout), traced, tuple(static)

    @staticmethod
    def _sig(leaf: Any) -> Tuple:
        return (
            tuple(leaf.shape),
            str(leaf.dtype),
            bool(getattr(leaf, "weak_type", False)),
        )

    def _key(self, state: Any, treedef: Any, layout: Tuple, traced: List, static: Tuple) -> Tuple:
        import jax

        state_leaves, state_def = jax.tree_util.tree_flatten(state)
        try:
            hash(static)
            static_key: Tuple = static
        except TypeError:  # unhashable static leaf: degrade to repr identity
            static_key = tuple(repr(s) for s in static)
        return (
            self._context_fn() if self._context_fn is not None else None,
            state_def,
            tuple(self._sig(leaf) for leaf in state_leaves),
            treedef,
            layout,
            static_key,
            tuple(self._sig(leaf) for leaf in traced),
        )

    # -- lowering -----------------------------------------------------------

    def _build_jit(self, treedef: Any, layout: Tuple, static: Tuple) -> Callable:
        """The jit-wrapped program for one (structure, static-values) binding:
        takes ``(state, traced_leaves)`` and reassembles the original call."""
        import jax

        fn = self._fn

        def call(state: Any, traced_leaves: Tuple) -> Any:
            merged: List[Any] = []
            t = iter(traced_leaves)
            s = iter(static)
            for kind in layout:
                merged.append(next(t) if kind == _TRACED else next(s))
            args, kwargs = jax.tree_util.tree_unflatten(treedef, merged)
            return fn(state, *args, **kwargs)

        return jax.jit(call, donate_argnums=(0,) if self.donate_state else ())

    def _lookup(self, state: Any, args: Tuple, kwargs: Dict) -> Tuple[Any, Any, bool, List]:
        treedef, layout, traced, static = self._split(args, kwargs)
        key = self._key(state, treedef, layout, traced, static)
        compiled = self._cache.get(key)
        fresh = compiled is None
        if fresh:
            self._misses += 1
            jitted = self._build_jit(treedef, layout, static)
            start = time.perf_counter()
            compiled = jitted.lower(state, tuple(traced)).compile()
            self.last_compile_s = time.perf_counter() - start
            self._cache[key] = compiled
        else:
            self._hits += 1
            self.last_compile_s = 0.0
        return key, compiled, fresh, traced

    # -- public surface -----------------------------------------------------

    def warm(self, state: Any, *args: Any, **kwargs: Any) -> Tuple[Any, bool]:
        """Lower+compile (without executing) the executable for these
        arguments' avals; returns ``(compiled, fresh)``. A cache hit returns
        the existing executable with ``fresh=False``."""
        _, compiled, fresh, _ = self._lookup(state, args, kwargs)
        self.last_compiled = fresh
        return compiled, fresh

    def lower_text(self, state: Any, *args: Any, **kwargs: Any) -> str:
        """StableHLO text of the lowering for these arguments, WITHOUT
        compiling or caching — the zero-copy gate counts buffer-donation
        aliasing attributes (``tf.aliasing_output``) in it."""
        treedef, layout, traced, static = self._split(args, kwargs)
        jitted = self._build_jit(treedef, layout, static)
        return jitted.lower(state, tuple(traced)).as_text()

    def __call__(self, state: Any, *args: Any, **kwargs: Any) -> Any:
        key, compiled, fresh, traced = self._lookup(state, args, kwargs)
        self.last_compiled = fresh
        try:
            return compiled(state, tuple(traced))
        except TypeError:
            if fresh:
                raise
            # aval drift the host-side key cannot see (a device_put moved the
            # states, a committed-sharding change): drop the stale executable
            # and recompile once, mirroring jit's transparent behavior.
            # The type check precedes execution, so no donated buffer was
            # consumed by the failed attempt.
            del self._cache[key]
            _, compiled, _, traced = self._lookup(state, args, kwargs)
            self.last_compiled = True
            return compiled(state, tuple(traced))

    def _cache_size(self) -> int:
        """Compiled-executable count (the retrace ledger's cache watermark)."""
        return len(self._cache)

    def cache_info(self) -> Dict[str, int]:
        """Lifetime dispatch accounting: ``{"entries", "hits", "misses"}``.

        ``hits``/``misses`` count every ``warm()``/``__call__`` lookup, so a
        serving loop can verify its steady state re-uses one executable
        (``misses`` stops growing) — the evidence the multi-tenant bench and
        ``warmup`` reports attach beside ``executables_cached``."""
        return {"entries": len(self._cache), "hits": self._hits, "misses": self._misses}


def trace_fingerprint(fn: Callable, state: Any, args: Tuple, kwargs: Dict) -> Tuple:
    """Exact trace identity of ``fn(state, *args, **kwargs)`` under the SAME
    traced/static argument partition a :class:`CompiledDispatch` would use.

    Returns a hashable tuple ``(jaxpr_text, const_digest, static_leaves,
    layout, treedef_repr)``. Two calls fingerprint equal **iff** they lower to
    the same program for the same dispatch signature: the canonical jaxpr
    pretty-print captures every traced op and literal (two metrics differing
    only in a baked-in ``threshold`` print different jaxprs), the SHA-256 over
    the closed-over constants catches programs whose text coincides but whose
    captured arrays differ (e.g. different binned-threshold buffers), and the
    static leaves/layout/treedef pin the host-side half of the dispatch key.
    This is what lets ``MetricCollection`` build compute groups *exactly* —
    by program identity — rather than by the reference's runtime heuristics.
    """
    import jax

    treedef, layout, traced, static = CompiledDispatch._split(args, kwargs)

    def call(state: Any, traced_leaves: Tuple) -> Any:
        merged: List[Any] = []
        t = iter(traced_leaves)
        s = iter(static)
        for kind in layout:
            merged.append(next(t) if kind == _TRACED else next(s))
        a, kw = jax.tree_util.tree_unflatten(treedef, merged)
        return fn(state, *a, **kw)

    closed = jax.make_jaxpr(call)(state, tuple(traced))
    digest = hashlib.sha256()
    for const in closed.consts:
        arr = np.asarray(const)
        digest.update(str(arr.dtype).encode())
        digest.update(str(arr.shape).encode())
        digest.update(np.ascontiguousarray(arr).tobytes())
    try:
        hash(static)
        static_key: Tuple = static
    except TypeError:
        static_key = tuple(repr(s) for s in static)
    return (str(closed.jaxpr), digest.hexdigest(), static_key, layout, repr(treedef))
