"""Array helpers shared by all metric kernels.

Capability parity with the reference's ``torchmetrics/utilities/data.py``
(``dim_zero_cat``/``to_onehot``/``select_topk``/``to_categorical``/
``get_num_classes``/``apply_to_collection``/``get_group_indexes``), designed
JAX-first: every transform is trace-safe (pure jnp ops, static shapes) except
the explicitly host-side helpers (``get_num_classes`` infers class counts from
data values and therefore requires concrete arrays).
"""
from typing import Any, Callable, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array

METRIC_EPS = 1e-6


def _is_traced(*arrays: Any) -> bool:
    """True if any input is an abstract tracer (inside jit/vmap/shard_map)."""
    return any(isinstance(a, jax.core.Tracer) for a in arrays)


def is_floating_point(x: Array) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def to_scalar(x: Union[Array, float, int]) -> Union[float, int]:
    """Host-side extraction of a 0-d array value (eager paths only)."""
    return np.asarray(x).item()


def dim_zero_cat(x: Union[Array, List[Array], Tuple[Array, ...]]) -> Array:
    """Concatenate a (list of) array(s) along the leading axis.

    Scalars are promoted to shape ``(1,)`` so appended 0-d states concatenate.
    """
    items = list(x) if isinstance(x, (list, tuple)) else [x]
    if not items:
        raise ValueError("No samples to concatenate")
    items = [jnp.atleast_1d(jnp.asarray(it)) for it in items]
    return jnp.concatenate(items, axis=0)


def tie_group_bounds(changed: Array) -> Tuple[Array, Array]:
    """Per-position tie-group start/end indices from an adjacent-change mask.

    ``changed`` is the ``(n-1,)`` boolean mask ``key[1:] != key[:-1]`` over a
    SORTED key sequence; returns ``(start_idx, end_idx)``, both ``(n,)``,
    where position ``i`` carries the first/last index of its tie group. The
    shared TPU idiom behind the masked curve scalars (zero-width trapezoids
    for duplicates) and the fractional rank kernel (mean of the rank block).
    """
    n = changed.shape[0] + 1
    idx = jnp.arange(n)
    is_start = jnp.concatenate([jnp.ones((1,), bool), changed])
    is_end = jnp.concatenate([changed, jnp.ones((1,), bool)])
    start_idx = jax.lax.cummax(jnp.where(is_start, idx, 0))
    end_idx = jnp.flip(jax.lax.cummin(jnp.flip(jnp.where(is_end, idx, n - 1))))
    return start_idx, end_idx


def dim_zero_sum(x: Array) -> Array:
    return jnp.sum(jnp.asarray(x), axis=0)


def dim_zero_mean(x: Array) -> Array:
    return jnp.mean(jnp.asarray(x), axis=0)


def dim_zero_max(x: Array) -> Array:
    return jnp.max(jnp.asarray(x), axis=0)


def dim_zero_min(x: Array) -> Array:
    return jnp.min(jnp.asarray(x), axis=0)


def _flatten(x: Sequence[Sequence[Any]]) -> List[Any]:
    return [item for sub in x for item in sub]


def to_onehot(label_tensor: Array, num_classes: Optional[int] = None) -> Array:
    """Dense labels ``[N, d1, ...]`` -> one-hot ``[N, C, d1, ...]``.

    Trace-safe when ``num_classes`` is given; otherwise inferred from the max
    label on the host (eager only).
    """
    label_tensor = jnp.asarray(label_tensor)
    if label_tensor.dtype == jnp.bool_:
        label_tensor = label_tensor.astype(jnp.int32)
    if num_classes is None:
        num_classes = int(np.asarray(jnp.max(label_tensor)).item()) + 1
    onehot = jax.nn.one_hot(label_tensor, num_classes, dtype=label_tensor.dtype)
    # one_hot puts the class axis last; the canonical layout is (N, C, ...).
    return jnp.moveaxis(onehot, -1, 1)


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binarize by marking the top-k entries along ``dim`` with 1 (int32 output)."""
    prob_tensor = jnp.asarray(prob_tensor)
    num_entries = prob_tensor.shape[dim]
    moved = jnp.moveaxis(prob_tensor, dim, -1)
    if topk == 1:
        # argmax + broadcast-compare: identical lower-index tie rule as
        # lax.top_k, but ~3x cheaper per step on TPU (no sort network, one
        # fused compare instead of one_hot+sum)
        top_idx = jnp.argmax(moved, axis=-1)[..., None]
        mask = (jnp.arange(num_entries) == top_idx).astype(jnp.int32)
    else:
        _, top_idx = jax.lax.top_k(moved, topk)  # (..., topk), ties -> lower index
        mask = jax.nn.one_hot(top_idx, num_entries, dtype=jnp.int32).sum(axis=-2)
    return jnp.moveaxis(mask, -1, dim).astype(jnp.int32)


def to_categorical(x: Array, argmax_dim: int = 1) -> Array:
    """Probabilities ``[N, C, d2, ...]`` -> dense labels ``[N, d2, ...]``."""
    return jnp.argmax(jnp.asarray(x), axis=argmax_dim)


def get_num_classes(preds: Array, target: Array, num_classes: Optional[int] = None) -> int:
    """Infer the number of classes from data values (host-side, eager only)."""
    num_target_classes = int(np.asarray(jnp.max(target)).item()) + 1
    num_pred_classes = int(np.asarray(jnp.max(preds)).item()) + 1
    num_all_classes = max(num_target_classes, num_pred_classes)
    if num_classes is None:
        return num_all_classes
    if num_classes != num_all_classes:
        rank_zero_warn(
            f"You have set {num_classes} number of classes which is"
            f" different from predicted ({num_pred_classes}) and"
            f" target ({num_target_classes}) number of classes",
            RuntimeWarning,
        )
    return num_classes


def apply_to_collection(
    data: Any,
    dtype: Union[type, tuple],
    function: Callable,
    *args: Any,
    wrong_dtype: Optional[Union[type, tuple]] = None,
    **kwargs: Any,
) -> Any:
    """Recursively apply ``function`` to every element of type ``dtype`` in a pytree-like
    collection (dict / namedtuple / sequence), preserving the container types."""
    elem_type = type(data)

    if isinstance(data, dtype) and (wrong_dtype is None or not isinstance(data, wrong_dtype)):
        return function(data, *args, **kwargs)

    if isinstance(data, Mapping):
        return elem_type(
            {k: apply_to_collection(v, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs) for k, v in data.items()}
        )
    if isinstance(data, tuple) and hasattr(data, "_fields"):  # namedtuple
        return elem_type(
            *(apply_to_collection(d, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs) for d in data)
        )
    if isinstance(data, Sequence) and not isinstance(data, str):
        return elem_type(
            [apply_to_collection(d, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs) for d in data]
        )
    return data


def get_group_indexes(indexes: Array) -> List[Array]:
    """Positions of each distinct value of ``indexes``, grouped, in order of first
    appearance.

    Vectorized (unique + stable argsort) instead of the reference's per-element
    Python dict loop (``utilities/data.py:207-232``); the retrieval metrics use
    fully fused segment ops and only fall back to this for the host path.
    """
    idx = np.asarray(indexes)
    if idx.ndim != 1:
        idx = idx.reshape(-1)
    uniques, first_pos, inverse = np.unique(idx, return_index=True, return_inverse=True)
    order = np.argsort(inverse, kind="stable")  # positions grouped by sorted-unique value
    counts = np.bincount(inverse)
    splits = np.split(order, np.cumsum(counts)[:-1])
    appearance = np.argsort(first_pos, kind="stable")  # sorted-unique -> appearance order
    return [jnp.asarray(splits[g], dtype=jnp.int32) for g in appearance]
