"""Fixed-capacity sample buffer shared by the ``capacity=...`` metric modes.

Backs :class:`~metrics_tpu.AUROC`, :class:`~metrics_tpu.AveragePrecision`
(score/label buffers + masked curve kernels) and
:class:`~metrics_tpu.SpearmanCorrcoef` (raw value buffers + masked ranks): a
preallocated buffer plus a fill counter gives a step-invariant state
structure that lives inside ``jit``/``shard_map`` without retracing, syncs
with one tiled ``all_gather``, and drops (and warns about) samples past the
capacity.

Layout (measured on a real v5e, see git history for the losing variants):
scores and labels ride ONE flat f32 array of ``(capacity + SLACK) * width``
elements — row-major ``(rows, width)`` semantics with ``width`` = score
columns + label columns. Flat matters: a contiguous 1-D
``dynamic_update_slice`` costs ~1 µs/step where the same write into a
``(rows, width)`` array pays ~3-7 µs in sublane-strided addressing (and a
reshape round-trip on a loop-carried buffer copies the whole buffer,
~1.5 ms). The ``SLACK`` rows give exact drop-past-capacity semantics with
no masking or branching: the write offset clamps to ``capacity + SLACK -
n``, so overflow writes land entirely in the slack zone — which
``_buffer_flatten`` never reads — instead of clobbering the tail of the
real data. Batches larger than ``SLACK`` rows append in ``SLACK``-row
chunks (each chunk re-establishes the invariant).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from metrics_tpu.utilities.data import Array, _is_traced, dim_zero_cat
from metrics_tpu.utilities.enums import DataType
from metrics_tpu.utilities.prints import rank_zero_warn

#: upper bound on the overflow landing zone, in rows; the per-instance slack
#: is ``min(capacity, BUF_SLACK_ROWS)`` so tiny or very wide buffers don't
#: pay 4096 rows of allocation and all_gather traffic, and it doubles as the
#: chunk size for oversized batches
BUF_SLACK_ROWS = 4096

#: what a capacity-mode metric does when the stream exceeds the buffer
OVERFLOW_POLICIES = ("warn", "error")


class BufferOverflowError(RuntimeError):
    """An exact-mode ``capacity=`` buffer received more samples than it can
    hold and the metric was built with ``overflow="error"``.

    Raised at the first host boundary where the fill counters are concrete
    (eager ``compute()``, including after compiled ``jit_forward`` /
    ``update_many`` steps — inside a compiled program the counter is traced
    and cannot raise, so the overflow surfaces at the next eager read
    instead of silently truncating the stream)."""


def _check_capacity(capacity: int) -> None:
    if not (isinstance(capacity, int) and capacity > 0):
        raise ValueError(f"`capacity` should be a positive integer, got: {capacity}")


def _check_overflow_policy(overflow: str) -> str:
    if overflow not in OVERFLOW_POLICIES:
        raise ValueError(
            f"`overflow` should be one of {OVERFLOW_POLICIES}, got: {overflow!r}"
        )
    return overflow


def init_feature_buffer(capacity: int, dim: int, dtype=jnp.float32) -> Tuple[Array, int]:
    """Preallocated ``(capacity + slack, dim)`` row buffer for feature metrics.

    The 2-D row layout (unlike the flat classification buffer above) is
    already contiguous for whole-row writes — feature rows are ``dim`` wide,
    so a row-aligned ``dynamic_update_slice`` writes one contiguous span and
    none of the flat layout's sublane-stride pathology applies. The slack
    zone plays the same role: overflow writes clamp into rows the read path
    never touches, giving exact drop-past-capacity semantics with no
    masking. Returns ``(buffer, slack_rows)``.
    """
    _check_capacity(capacity)
    slack = min(capacity, BUF_SLACK_ROWS)
    return jnp.zeros((capacity + slack, dim), dtype), slack


def feature_buffer_write(
    buf: Array, count: Array, feats: Array, capacity: int, slack: int
) -> Tuple[Array, Array]:
    """Append ``(N, dim)`` rows at the fill offset; overflow rows land in the
    slack zone (dropped), the counter keeps the true total."""
    total_rows = capacity + slack
    n = feats.shape[0]
    zero = jnp.zeros((), jnp.int32)
    for i in range(0, n, slack):
        rows = min(slack, n - i)  # static per trace
        chunk = feats[i : i + rows].astype(buf.dtype)
        start = jnp.minimum(count + i, total_rows - rows)
        buf = lax.dynamic_update_slice(buf, chunk, (start, zero))
    return buf, count + n


def feature_buffer_read(buf, count, capacity: int, slack: int, owner: str = "metric") -> Array:
    """Valid rows across however many shards the sync produced — eager only
    (the row count is data-dependent; feature metrics compute at epoch end
    on the host boundary, like the reference). Warns when rows were dropped
    past capacity.

    Accepts every state form the sync paths produce: the local 2-D
    ``(capacity+slack, d)`` buffer with a scalar count, the eager
    multi-process sync's stacked ``(world, capacity+slack, d)`` buffer with
    a ``(world,)`` count vector, a row-concatenated
    ``(world·(capacity+slack), d)`` form (tiled in-graph all_gather), and
    list-of-shards variants.
    """
    import numpy as np

    bufs = buf if isinstance(buf, list) else [buf]
    raw_counts = count if isinstance(count, list) else [count]
    if any(_is_traced(c) for c in raw_counts) or any(_is_traced(b) for b in bufs):
        raise NotImplementedError(
            f"{owner}: `capacity` mode computes on concrete (non-traced) state —"
            " the valid-row count is data-dependent. Call compute()/apply_compute"
            " outside jit (the fixed-shape part is the update path)."
        )
    counts = [int(c) for c in np.concatenate([np.atleast_1d(np.asarray(c)) for c in raw_counts])]
    rows_per_shard = capacity + slack
    # split multi-shard buffers back into (rows_per_shard, d) shards
    shards = []
    for b in bufs:
        b = jnp.asarray(b)
        if b.ndim == 3 and b.shape[1] == rows_per_shard:  # stacked (world, rows, d)
            shards.extend(b)
        elif b.ndim == 2 and b.shape[0] == rows_per_shard:
            shards.append(b)
        elif b.ndim == 2 and b.shape[0] % rows_per_shard == 0:  # row-concatenated
            shards.extend(b.reshape(-1, rows_per_shard, b.shape[-1]))
        else:
            raise ValueError(
                f"{owner}: synced buffer shape {b.shape} does not decompose"
                f" into (capacity+slack={rows_per_shard}, dim) shards"
            )
    if len(shards) != len(counts):
        raise ValueError(
            f"{owner}: {len(shards)} buffer shard(s) but {len(counts)} count(s) after sync"
        )
    dropped = sum(max(c - capacity, 0) for c in counts)
    if dropped > 0:
        rank_zero_warn(
            f"{owner}(capacity={capacity}) dropped {dropped} feature rows past"
            " the buffer capacity; the computed value covers the first"
            " `capacity` rows per shard.",
            UserWarning,
        )
    valid = [b[: min(c, capacity)] for b, c in zip(shards, counts)]
    return jnp.concatenate(valid, axis=0)


class CappedBufferMixin:
    """State/update/mask logic shared by the fixed-capacity metric modes.

    Scores and labels merge into ONE buffer (see the module docstring for
    the flat + slack layout) so every step issues a single contiguous
    ``dynamic_update_slice``. Labels live in the score dtype; exact, since
    class indices and binary flags are far below f32's 2**24 integer range.
    """

    #: set True by _init_capacity_states(multilabel=True); class default keeps
    #: plain attribute access safe for consumers that never set the flag
    _capacity_multilabel = False
    #: classification modes cast the label columns back to int32 at flatten
    _capacity_int_target = True
    #: overflow policy: "warn" drops past-capacity samples with a warning
    #: (the historical behavior), "error" raises BufferOverflowError at the
    #: first concrete read of an overflowed counter
    _buf_overflow_policy = "warn"

    def _init_capacity_states(
        self,
        capacity: int,
        num_classes: Optional[int],
        pos_label: Optional[int],
        multilabel: bool = False,
        overflow: str = "warn",
    ) -> None:
        """Validate the capacity-mode configuration and register the buffer state.

        ``num_classes > 1`` switches to the multi-column layout: ``C`` score
        columns with one integer class-label column (multiclass, one-vs-rest
        at epoch end) or ``C`` per-label binary target columns
        (``multilabel=True``).
        """
        _check_capacity(capacity)
        multi = num_classes is not None and num_classes > 1
        if multilabel and not multi:
            raise ValueError(
                f"multilabel `capacity` mode needs `num_classes` > 1 (the label count), got {num_classes}"
            )
        if not multi and pos_label not in (None, 0, 1):
            raise ValueError(f"`capacity` mode expects `pos_label` in (0, 1), got: {pos_label}")
        if multi and pos_label is not None:
            raise ValueError("`pos_label` does not apply to multi-column `capacity` mode")
        self._capacity_multilabel = multilabel
        self._capacity_int_target = True
        self._buf_overflow_policy = _check_overflow_policy(overflow)
        if multi:
            width = 2 * num_classes if multilabel else num_classes + 1
        else:
            width = 2
        self._buf_width = width
        self._buf_slack = min(capacity, BUF_SLACK_ROWS)
        total = (capacity + self._buf_slack) * width
        self.add_state("buf", jnp.full((total,), -jnp.inf, jnp.float32), dist_reduce_fx="cat")
        self.add_state("count", jnp.zeros((), jnp.int32), dist_reduce_fx="cat")

    @property
    def _capacity_multiclass(self) -> bool:
        num_classes = getattr(self, "num_classes", None)  # raw-mode consumers have none
        return num_classes is not None and num_classes > 1 and not self._capacity_multilabel

    @property
    def _capacity_score_cols(self) -> int:
        """Leading buffer columns holding scores (the rest hold labels)."""
        if self._capacity_multiclass or self._capacity_multilabel:
            return self.num_classes
        return 1

    def _init_raw_buffer_states(self, capacity: int, dtype=jnp.float32, overflow: str = "warn") -> None:
        """Raw-value variant: preds/target kept verbatim (no canonicalization)."""
        _check_capacity(capacity)
        self._buf_overflow_policy = _check_overflow_policy(overflow)
        self._capacity_int_target = False
        self._buf_width = 2
        self._buf_slack = min(capacity, BUF_SLACK_ROWS)
        total = (capacity + self._buf_slack) * 2
        self.add_state("buf", jnp.zeros((total,), dtype), dist_reduce_fx="cat")
        self.add_state("count", jnp.zeros((), jnp.int32), dist_reduce_fx="cat")

    def _buffer_write(self, preds: Array, target: Array) -> None:
        """Append one batch at the fill offset (contiguous flat slice writes);
        positions past capacity drop into the slack zone, the counter keeps
        the true total."""
        dtype = self.buf.dtype
        p = preds if preds.ndim == 2 else preds[:, None]
        t = target if target.ndim == 2 else target[:, None]
        batch = jnp.concatenate([p.astype(dtype), t.astype(dtype)], axis=-1).reshape(-1)
        width = self._buf_width
        slack = self._buf_slack
        total_rows = self.capacity + slack
        n = p.shape[0]
        buf, count = self.buf, self.count
        for i in range(0, n, slack):
            rows = min(slack, n - i)  # static per trace
            chunk = batch[i * width : (i + rows) * width]
            # rows <= SLACK, so a clamped start keeps every overflow write
            # inside the slack zone — exact drop semantics, no masking
            start = jnp.minimum(count + i, total_rows - rows) * width
            buf = lax.dynamic_update_slice_in_dim(buf, chunk, start, axis=0)
        self.buf = buf
        self.count = count + n

    def _raw_buffer_update(self, preds: Array, target: Array) -> None:
        self._buffer_write(jnp.atleast_1d(preds), jnp.atleast_1d(target))

    def _buffer_update(self, preds: Array, target: Array) -> None:
        from metrics_tpu.functional.classification.auroc import _auroc_update

        preds, target, mode = _auroc_update(preds, target)
        if self._capacity_multilabel:
            if mode != DataType.MULTILABEL or preds.ndim != 2 or preds.shape[1] != self.num_classes:
                raise ValueError(
                    f"multilabel `capacity` mode with num_classes={self.num_classes} expects"
                    f" (N, C) scores and (N, C) binary labels, got mode {mode} with preds shape {preds.shape}"
                )
            target = (target == 1).astype(jnp.int32)
        elif self._capacity_multiclass:
            if mode != DataType.MULTICLASS or preds.ndim != 2 or preds.shape[1] != self.num_classes:
                raise ValueError(
                    f"`capacity` mode with num_classes={self.num_classes} expects (N, C) class scores"
                    f" and (N,) labels, got mode {mode} with preds shape {preds.shape}"
                )
            target = target.astype(jnp.int32)
        else:
            if mode != DataType.BINARY:
                raise ValueError(f"`capacity` mode supports binary inputs only, got mode {mode}")
            pos_label = 1 if self.pos_label is None else self.pos_label
            target = (target == pos_label).astype(jnp.int32)
        self._buffer_write(preds.astype(jnp.float32), target)

    def _buffer_flatten(self) -> Tuple[Array, Array, Array]:
        """(flat preds, flat target, valid mask) across however many shards the
        sync produced — scalar count = 1 shard; ``(world,)`` counts = world
        shards of ``capacity`` samples each. Multiclass preds keep their
        trailing class axis: ``(world·capacity, C)``."""
        buf = dim_zero_cat(self.buf) if isinstance(self.buf, list) else self.buf
        count = self.count
        if isinstance(count, list):
            count = jnp.stack([jnp.asarray(c) for c in count])
        counts = jnp.atleast_1d(count)

        if not _is_traced(counts):
            import numpy as np

            overflow = np.asarray(jnp.maximum(counts - self.capacity, 0)).sum()
            if overflow > 0:
                if self._buf_overflow_policy == "error":
                    raise BufferOverflowError(
                        f"{self.__class__.__name__}(capacity={self.capacity}) overflowed:"
                        f" {int(overflow)} sample(s) past the buffer capacity"
                        f" ({int(np.asarray(counts).sum())} received in total). This metric"
                        ' was built with overflow="error", so the truncated stream is an'
                        " error instead of a silently approximate value. Raise `capacity`,"
                        " reset() more often, or switch to the bounded-memory"
                        " `sketched=True` mode if the metric offers one."
                    )
                rank_zero_warn(
                    f"{self.__class__.__name__}(capacity={self.capacity}) dropped {int(overflow)}"
                    " samples past the buffer capacity; the computed value covers the first"
                    " `capacity` samples per shard.",
                    UserWarning,
                )

        valid = (jnp.arange(self.capacity)[None, :] < jnp.clip(counts, 0, self.capacity)[:, None]).reshape(-1)
        width = self._buf_width
        # (shards, rows, width) view; the slack zone past `capacity` is never read
        rows = buf.reshape(-1, self.capacity + self._buf_slack, width)[:, : self.capacity, :]
        flat = rows.reshape(-1, width)
        ncols = self._capacity_score_cols
        preds_flat = flat[:, :ncols]
        target_flat = flat[:, ncols:]
        if preds_flat.shape[-1] == 1:
            preds_flat = preds_flat[:, 0]
        if target_flat.shape[-1] == 1:
            target_flat = target_flat[:, 0]
        if self._capacity_int_target:
            target_flat = target_flat.astype(jnp.int32)
        return preds_flat, target_flat, valid

    def _one_vs_rest(self, kernel, preds: Array, target: Array, valid: Array) -> Array:
        """Apply a masked binary curve kernel per class/label: ``(C,)`` values.

        Takes the already-flattened buffers so callers flatten (and gather,
        in the sharded path) exactly once per compute. ``target`` is either
        ``(M,)`` integer labels (one-vs-rest) or ``(M, C)`` per-label binaries.
        """
        if target.ndim == 2:
            per_label = lambda c: kernel(preds[:, c], target[:, c], valid)  # noqa: E731
        else:
            per_label = lambda c: kernel(preds[:, c], (target == c).astype(jnp.int32), valid)  # noqa: E731
        return jax.vmap(per_label)(jnp.arange(self.num_classes))

    def _check_degenerate_classes(self, target: Array, valid: Array) -> Optional[Array]:
        """Raise on degenerate (single-class) eager buffers; return per-class
        supports for reuse. Mirrors the cat path's single-class raises
        (``roc.py:46,50``) on the eager capacity path. Inside jit/shard_map
        raising is impossible — the masked kernels return the same 0/0 NaN
        the reference's arithmetic would produce instead; callers whose
        reference analogue *returns* NaN rather than raising (average
        precision) skip this check.

        The reductions run on device so only C+1 scalars cross to host (the
        buffers this mode is built for are ~200k samples). An empty buffer is
        NOT a single-class stream — compute-before-update already warns, and
        the kernels return NaN for it.

        Returns the on-device per-class support vector for multiclass/
        multilabel buffers (``None`` otherwise) so a weighted-average caller
        doesn't reduce the buffer a second time."""
        if _is_traced(target, valid):
            return None
        import numpy as np

        n_valid = float(jnp.sum(valid))
        if n_valid == 0:
            return None
        supports = None
        if target.ndim == 2 or getattr(self, "_capacity_multiclass", False):
            supports = self._class_supports(target, valid)
            pos_counts = np.atleast_1d(np.asarray(supports))
        else:
            pos_counts = np.asarray([jnp.sum(jnp.where(valid, (target == 1).astype(jnp.float32), 0.0))])
        for pos in pos_counts:
            if pos == n_valid:  # negatives-first, like the reference
                raise ValueError("No negative samples in targets, false positive value should be meaningless")
            if pos == 0:
                raise ValueError("No positive samples in targets, true positive value should be meaningless")
        return supports

    def _class_supports(self, target: Array, valid: Array) -> Array:
        """Valid positive count per class/label (for weighted averaging)."""
        if target.ndim == 2:
            return jnp.sum(target * valid[:, None], axis=0).astype(jnp.float32)
        onehot = (target[None, :] == jnp.arange(self.num_classes)[:, None]) & valid[None, :]
        return jnp.sum(onehot, axis=1).astype(jnp.float32)
