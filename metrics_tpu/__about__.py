__version__ = "0.20.0"
__author__ = "metrics-tpu contributors"
__license__ = "Apache-2.0"
__docs__ = (
    "TPU-native metrics framework: a distributed metric-state engine on JAX/XLA "
    "with mesh-axis collectives, plus functional metric kernels across "
    "classification, regression, retrieval, image, audio and NLP domains."
)
