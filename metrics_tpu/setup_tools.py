"""Packaging helpers used by ``setup.py`` (parity: ``torchmetrics/setup_tools.py``).

Requirement files may carry inline comments and extra whitespace; loading
through this helper keeps ``setup.py`` free of parsing logic.
"""
import os
from typing import List

_PROJECT_ROOT = os.path.dirname(os.path.dirname(__file__))


def _load_requirements(path_dir: str, file_name: str = "requirements.txt", comment_char: str = "#") -> List[str]:
    """Requirement specs from ``path_dir/file_name``, comments stripped.

    >>> _load_requirements(_PROJECT_ROOT)  # doctest: +ELLIPSIS +NORMALIZE_WHITESPACE
    ['numpy', 'jax...', 'packaging']
    """
    with open(os.path.join(path_dir, file_name)) as file:
        lines = [ln.strip() for ln in file.readlines()]
    reqs = []
    for ln in lines:
        if comment_char in ln:
            ln = ln[: ln.index(comment_char)].strip()
        if ln.startswith("http"):  # directly-installed dependencies
            continue
        if ln:
            reqs.append(ln)
    return reqs
