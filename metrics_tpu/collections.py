"""MetricCollection: many metrics, one call.

Capability parity with the reference's ``torchmetrics/collections.py``
(``MetricCollection(nn.ModuleDict)``: broadcast forward/update with per-metric
kwarg filtering, dict compute, dedup'd construction, clone with
prefix/postfix) — plus the pure-state fan-out API (:meth:`init_state` /
:meth:`apply_update` / :meth:`apply_compute`) so a whole collection updates
and syncs inside one jitted program: XLA then fuses the per-metric psum
collectives into a single staged bundle over the mesh, which is how a
10-metric collection stays at ~one collective of step overhead.

On top of that rides the **compute-group engine** (on by default,
``compute_groups=False`` opts out): members whose per-batch update traces to
the EXACT same program over the same state layout — compared by jaxpr
fingerprint, not runtime heuristics — share one live state, so each compiled
step runs one donated update per group and ``compute()`` fans the shared
state out to every member's own ``compute``. See
``MetricCollection.build_compute_groups`` and ``docs/performance.md``.
"""
import functools
import sys
import time
from collections import OrderedDict
from copy import deepcopy
from typing import Any, Dict, Iterable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.metric import (
    AXIS_UNSET,
    ArrayTypes,
    Metric,
    StateDict,
    _ComputeGroup,
    _microbatch_len,
    _note_compiled_dispatch,
    _observed_forward,
)
from metrics_tpu.observability.events import EVENTS
from metrics_tpu.observability.health import HEALTH, guard_state
from metrics_tpu.observability.histogram import observe_dispatch
from metrics_tpu.observability.profiling import PROFILER
from metrics_tpu.observability.registry import TELEMETRY
from metrics_tpu.observability.retrace import arg_signature
from metrics_tpu.observability.tracing import TRACER
from metrics_tpu.utilities.aot import CompiledDispatch, trace_fingerprint
from metrics_tpu.utilities.prints import rank_zero_warn
from metrics_tpu.utilities.profiling import compiled_scope, eager_span


class MetricCollection:
    """An ordered, dict-like container of metrics sharing one call pattern.

    Args:
        metrics: a single metric, a sequence of metrics (keyed by class name,
            duplicates rejected), or a dict name -> metric (inserted in sorted
            key order for determinism).
        additional_metrics: further metrics when ``metrics`` is not a dict.
        prefix: string prepended to every output key.
        postfix: string appended to every output key.
        compute_groups: deduplicate provably-identical member updates (default
            True). At the first compiled dispatch (``jit_forward`` /
            ``update_many`` / ``warmup``) — or explicitly via
            :meth:`build_compute_groups` — each member's ``apply_update`` is
            traced against the batch avals and members whose (update-jaxpr
            fingerprint, state layout, static dispatch args) match EXACTLY
            are grouped onto one shared state: each step then runs ONE
            donated update per group, and ``compute()`` fans the shared state
            out to every member's own ``compute``. A
            ``MetricCollection([Precision, Recall, F1, Specificity,
            StatScores])`` issues 1 update computation and donates 1 state
            bundle per step instead of 5. Exact jaxpr equality means no
            heuristic false merges; direct writes to a grouped member's state
            copy-on-write detach it (see ``docs/performance.md``). Pass
            ``False`` to keep fully private per-member states.

    Example::

        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MetricCollection, Accuracy, Precision, Recall
        >>> target = jnp.array([0, 2, 0, 2, 0, 1, 0, 2])
        >>> preds = jnp.array([2, 1, 2, 0, 1, 2, 2, 2])
        >>> metrics = MetricCollection([Accuracy(),
        ...                             Precision(num_classes=3, average='macro'),
        ...                             Recall(num_classes=3, average='macro')])
        >>> {k: round(float(v), 4) for k, v in metrics(preds, target).items()}
        {'Accuracy': 0.125, 'Precision': 0.0667, 'Recall': 0.1111}
    """

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
        compute_groups: bool = True,
    ) -> None:
        self._metrics: "OrderedDict[str, Metric]" = OrderedDict()
        self._compute_groups_enabled = bool(compute_groups)
        self._compute_groups_built = False
        self.add_metrics(metrics, *additional_metrics)
        self.prefix = self._check_arg(prefix, "prefix")
        self.postfix = self._check_arg(postfix, "postfix")
        self._jit_forward_enabled = False
        self._jit_forward_fn: Optional[CompiledDispatch] = None
        self._jit_forward_donate = True
        self._jit_forward_copy_fn: Optional[CompiledDispatch] = None
        self._update_many_fn: Optional[CompiledDispatch] = None
        self._update_many_copy_fn: Optional[CompiledDispatch] = None
        self._donation_warned = False

    # ------------------------------------------------------------------
    # stateful interface
    # ------------------------------------------------------------------

    @property
    def telemetry_key(self) -> str:
        """Per-instance telemetry key (see :attr:`Metric.telemetry_key`)."""
        key = self.__dict__.get("_telemetry_key")
        if key is None:
            key = TELEMETRY.register(self)
            self._telemetry_key = key
        return key

    # ------------------------------------------------------------------
    # compute groups: trace-fingerprinted shared-state update dedup
    # ------------------------------------------------------------------

    def build_compute_groups(self, *sample_batch: Any, **kwargs: Any) -> Dict[str, list]:
        """Trace every member's ``apply_update`` against this batch's avals
        and group members whose programs match EXACTLY onto one shared state.

        Grouping is by program identity, not runtime heuristics: the
        fingerprint is the member's update jaxpr text + closed-over constant
        digest + static dispatch args + state layout (tree structure, avals,
        reductions) + ``process_group``
        (:func:`~metrics_tpu.utilities.aot.trace_fingerprint`). Two metrics
        that merely hold equal state VALUES but run different update programs
        never merge — the false-merge class the reference's runtime-heuristic
        compute groups admit. Members whose current states have already
        diverged (e.g. after a partial ``load_state_dict``) are left
        ungrouped even on a fingerprint match, so restored per-member states
        are honored.

        Called automatically at the first compiled dispatch (``jit_forward``
        / ``update_many`` / ``warmup``); call it explicitly to group ahead of
        time or to regroup after mutating members. Returns ``{owner_name:
        [member names]}`` for the multi-member groups formed (empty when
        grouping is disabled or nothing matches).
        """
        self._dissolve_compute_groups()
        if not self._compute_groups_enabled:
            return {}
        self._compute_groups_built = True
        if len(self._metrics) < 2:
            return {}
        buckets: "OrderedDict[Tuple, list]" = OrderedDict()
        for name, m in self.items(keep_base=True):
            fp = self._member_group_fingerprint(m, sample_batch, kwargs)
            if fp is not None:
                buckets.setdefault(fp, []).append(name)
        groups: Dict[str, list] = {}
        for names in buckets.values():
            if len(names) < 2:
                continue
            owner = self._metrics[names[0]]
            members = [names[0]] + [
                n for n in names[1:] if self._states_equal(owner, self._metrics[n])
            ]
            if len(members) < 2:
                continue
            self._form_group(members)
            groups[members[0]] = list(members)
        if TELEMETRY.enabled:
            key = self.telemetry_key
            TELEMETRY.inc(key, "compute_group_count", len(groups))
            TELEMETRY.set_info(
                key,
                "compute_groups",
                {"groups": {o: list(ns) for o, ns in groups.items()}, "members": len(self._metrics)},
            )
        if EVENTS.enabled:
            EVENTS.record(
                "compile",
                self.telemetry_key,
                path="compute_groups",
                groups=[list(ns) for ns in groups.values()],
                members=len(self._metrics),
            )
        return groups

    def _member_group_fingerprint(self, m: Metric, args: Tuple, kwargs: Dict) -> Optional[Tuple]:
        """The member's exact-trace group key, or ``None`` when it cannot
        share a state: custom sync protocols, non-base pure-state layouts
        (wrappers, compositions), or updates that refuse to trace against
        these avals (value-dependent canonicalization) all stay private."""
        if m.dist_sync_on_step or m.dist_sync_fn is not None or not m._defaults:
            return None
        cls = type(m)
        if (
            cls.apply_update is not Metric.apply_update
            or cls.apply_compute is not Metric.apply_compute
            or cls.sync_state is not Metric.sync_state
            or cls.init_state is not Metric.init_state
            or cls._get_states is not Metric._get_states
            or cls._set_states is not Metric._set_states
        ):
            return None
        state = m.init_state()
        if set(state) != set(m._defaults):
            return None
        try:
            fkw = m._filter_kwargs(**kwargs)
            trace_key = trace_fingerprint(m.apply_update, state, args, fkw)
        except Exception:
            return None
        state_spec = tuple(
            (
                k,
                "list"
                if isinstance(m._defaults[k], list)
                else (tuple(m._defaults[k].shape), str(m._defaults[k].dtype)),
                m._reductions[k] if isinstance(m._reductions[k], (str, type(None))) else repr(m._reductions[k]),
            )
            for k in sorted(m._defaults)
        )
        return trace_key + (state_spec, repr(m.process_group))

    @staticmethod
    def _states_equal(a: Metric, b: Metric) -> bool:
        """Element-wise equality of two members' CURRENT states (the group
        precondition: a shared state can only adopt members that agree)."""
        import numpy as np

        for name in a._defaults:
            va, vb = getattr(a, name), getattr(b, name)
            if isinstance(va, list) != isinstance(vb, list):
                return False
            pairs = list(zip(va, vb)) if isinstance(va, list) else [(va, vb)]
            if isinstance(va, list) and len(va) != len(vb):
                return False
            for x, y in pairs:
                x, y = np.asarray(x), np.asarray(y)
                if x.shape != y.shape or x.dtype != y.dtype or not np.array_equal(x, y):
                    return False
        return True

    def _form_group(self, names: list) -> None:
        members = [self._metrics[n] for n in names]
        group = _ComputeGroup(
            owner=members[0], members=members, collection=self, collection_key=self.telemetry_key
        )
        for m in members:
            m.__dict__["_compute_group"] = group
        for m in members[1:]:
            # followers hold NO state attributes: reads delegate to the owner
            for sname in m._defaults:
                m.__dict__.pop(sname, None)
            # a follower's own compiled caches baked its private state
            m._drop_compiled_dispatch()

    def _dissolve_compute_groups(self) -> None:
        """Silently ungroup every member (administrative: member-set change,
        ``load_state_dict``, explicit rebuild). Each member keeps the state
        it currently observes."""
        for _, m in self.items(keep_base=True):
            if m.__dict__.get("_compute_group") is not None:
                m._group_cow_detach(None)
        self._compute_groups_built = False

    def _ensure_compute_groups(self, args: Tuple, kwargs: Dict) -> None:
        if self._compute_groups_enabled and not self._compute_groups_built:
            self.build_compute_groups(*args, **kwargs)

    def _group_layout(self) -> list:
        """``[(owner_name, [member names]), ...]`` in member order: one entry
        per compute group plus one singleton entry per ungrouped member.
        Derived from the live group objects, so copy-on-write detaches are
        reflected immediately. Groups formed by a DIFFERENT collection are
        treated as singletons here (and detached at dispatch time)."""
        layout: list = []
        seen: set = set()
        for name, m in self.items(keep_base=True):
            g = m.__dict__.get("_compute_group")
            if g is None or g.collection_ref() is not self:
                layout.append((name, [name]))
                continue
            if id(g) in seen:
                continue
            seen.add(id(g))
            names = [
                n
                for n, mm in self.items(keep_base=True)
                if mm.__dict__.get("_compute_group") is g
            ]
            owner_name = next((n for n in names if self._metrics[n] is g.owner), None)
            if owner_name is None:  # pragma: no cover - defensive: owner replaced
                layout.extend((n, [n]) for n in names)
            else:
                layout.append((owner_name, [owner_name] + [n for n in names if n != owner_name]))
        return layout

    def _group_signature(self) -> Optional[Tuple]:
        """Hashable group-layout key mixed into every compiled-dispatch cache
        entry (``CompiledDispatch(context_fn=...)``): a group rebuild or CoW
        detach re-keys the executable instead of serving a stale program."""
        if not self.__dict__.get("_compute_groups_built", False):
            return None
        return tuple((owner, tuple(names)) for owner, names in self._group_layout())

    def _has_compute_groups(self) -> bool:
        return self.__dict__.get("_compute_groups_built", False) and any(
            len(names) > 1 for _, names in self._group_layout()
        )

    def compute_group_report(self) -> Dict[str, Any]:
        """The current group composition: ``{"built": bool, "groups":
        {owner: [members]}, "ungrouped": [...]}`` — also attached to
        ``observability.snapshot()`` under the collection's key at build."""
        layout = self._group_layout() if self.__dict__.get("_compute_groups_built", False) else []
        groups = {owner: list(names) for owner, names in layout if len(names) > 1}
        grouped = {n for ns in groups.values() for n in ns}
        return {
            "built": bool(self.__dict__.get("_compute_groups_built", False)),
            "enabled": bool(self.__dict__.get("_compute_groups_enabled", True)),
            "groups": groups,
            "ungrouped": [n for n in self._metrics if n not in grouped],
        }

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Call forward on every metric; positional args broadcast, kwargs are
        filtered per metric signature. Compute groups (once built) run ONE
        update on their shared state; shared-update classes (see
        :meth:`_shared_deltas`) run their partial-statistics pass once."""
        if self._jit_forward_enabled:
            return self._forward_jitted(*args, **kwargs)
        grouped_vals, handled = self._forward_grouped_eager(args, kwargs)
        shared = self._shared_deltas(args, kwargs, exclude=handled)
        out = {}
        for name, m in self.items(keep_base=True):
            if name in handled:
                out[self._set_name(name)] = grouped_vals[name]
                continue
            deltas = shared.get(name)
            if deltas is not None and m._states_mergeable():
                with eager_span(f"{type(m).__name__}.forward"):
                    out[self._set_name(name)] = _observed_forward(
                        m,
                        "forward_fused_calls",
                        lambda m=m, d=deltas: m._forward_fused(
                            *args,
                            _update_thunk=lambda: m._accumulate(*d),
                            **m._filter_kwargs(**kwargs),
                        ),
                    )
            else:
                out[self._set_name(name)] = m(*args, **m._filter_kwargs(**kwargs))
        return out

    def _forward_grouped_eager(self, args: Tuple, kwargs: Dict) -> Tuple[Dict[str, Any], set]:
        """One eager step per multi-member compute group: a single update
        pass advances the shared state, each member's on-step value comes
        from its own ``compute`` over the shared batch state. Returns
        ``(values by base name, handled names)`` — empty until groups are
        built (a compiled dispatch or :meth:`build_compute_groups`)."""
        vals: Dict[str, Any] = {}
        handled: set = set()
        if not self.__dict__.get("_compute_groups_built", False):
            return vals, handled
        for owner_name, names in self._group_layout():
            if len(names) < 2:
                continue
            owner = self._metrics[owner_name]
            fkw = owner._filter_kwargs(**kwargs)
            with eager_span(f"{type(owner).__name__}.forward"):
                start = time.perf_counter() if (TELEMETRY.enabled or EVENTS.enabled) else None
                batch_state = owner.apply_update(owner.init_state(), *args, **fkw)
                if owner._states_mergeable():
                    new_state = owner.merge_states(owner._get_states(), batch_state)
                else:
                    new_state = owner.apply_update(owner._get_states(), *args, **fkw)
                owner._set_states(new_state)
                if HEALTH.enabled:
                    guard_state(owner, new_state, source="forward")
                for n in names:
                    m = self._metrics[n]
                    m._update_called = True
                    m._computed = None
                    value = m.apply_compute(batch_state, axis_name=None) if m.compute_on_step else None
                    m._forward_cache = value
                    vals[n] = value
                handled.update(names)
                if start is not None:
                    dur = time.perf_counter() - start
                    if TELEMETRY.enabled:
                        TELEMETRY.inc(owner.telemetry_key, "update_calls")
                        TELEMETRY.inc(self.telemetry_key, "update_dedup_skipped", len(names) - 1)
                    if EVENTS.enabled:
                        EVENTS.record(
                            "forward",
                            owner.telemetry_key,
                            dur_s=dur,
                            t_start=start,
                            path="compute_group",
                            members=list(names),
                        )
        return vals, handled

    def update(self, *args: Any, **kwargs: Any) -> None:
        handled: set = set()
        if self.__dict__.get("_compute_groups_built", False):
            for owner_name, names in self._group_layout():
                if len(names) < 2:
                    continue
                owner = self._metrics[owner_name]
                # ONE update pass on the shared state serves every member
                owner._set_states(
                    owner.apply_update(owner._get_states(), *args, **owner._filter_kwargs(**kwargs))
                )
                for n in names:
                    m = self._metrics[n]
                    m._update_called = True
                    m._computed = None
                handled.update(names)
                if TELEMETRY.enabled:
                    TELEMETRY.inc(owner.telemetry_key, "update_calls")
                    TELEMETRY.inc(self.telemetry_key, "update_dedup_skipped", len(names) - 1)
                if EVENTS.enabled:
                    EVENTS.record(
                        "update", owner.telemetry_key, path="compute_group", members=list(names)
                    )
        shared = self._shared_deltas(args, kwargs, exclude=handled)
        for name, m in self.items(keep_base=True):
            if name in handled:
                continue
            if name in shared:
                m._update_from_deltas(*shared[name])
            else:
                m.update(*args, **m._filter_kwargs(**kwargs))

    def jit_forward(self, enable: bool = True, donate: bool = True) -> "MetricCollection":
        """Compile the collection's stateful ``forward`` into ONE XLA program.

        Same contract and trades as :meth:`Metric.jit_forward` — including
        **state donation**: the single executable donates the whole
        collection state pytree, so every member's buffers update in place
        (``donate=False`` opts out; an externally-held member state falls
        back to the copying executable for that step, with a one-shot
        warning). The collection-level wins ride on top: the shared-update
        classes canonicalize once inside the single program, and XLA fuses
        across members. Every member must individually satisfy the
        :meth:`Metric.jit_forward` constraints (no unbounded list states, no
        ``dist_sync_on_step``)."""
        if not enable:
            self._jit_forward_enabled = False
            self._drop_compiled_dispatch()
            return self
        for name, m in self.items(keep_base=True):
            try:
                # side-effect-free member validation: a member's OWN
                # jit_forward enablement (and built cache) stays untouched
                m._jit_forward_gate()
            except ValueError as err:
                raise ValueError(f"member {name!r}: {err}") from None
        self._jit_forward_enabled = True
        self._jit_forward_donate = bool(donate)
        self._drop_compiled_dispatch()
        return self

    def _drop_compiled_dispatch(self) -> None:
        """Invalidate every cached compiled-dispatch executable (member set
        or donation flag changed, enablement toggled, unpickled copy)."""
        self._jit_forward_fn = None
        self._jit_forward_copy_fn = None
        self._update_many_fn = None
        self._update_many_copy_fn = None

    def _forward_dispatch(self) -> CompiledDispatch:
        if self._jit_forward_fn is None:
            self._jit_forward_fn = CompiledDispatch(
                functools.partial(self._grouped_apply_forward, axis_name=None),
                donate_state=self._jit_forward_donate,
                context_fn=self._group_signature,
            )
            self._jit_cache_seen = 0
        return self._jit_forward_fn

    def _forward_copy_dispatch(self) -> CompiledDispatch:
        if self._jit_forward_copy_fn is None:
            self._jit_forward_copy_fn = CompiledDispatch(
                functools.partial(self._grouped_apply_forward, axis_name=None),
                donate_state=False,
                context_fn=self._group_signature,
            )
        return self._jit_forward_copy_fn

    def _grouped_apply_forward(
        self, state: Dict[str, StateDict], *args: Any, axis_name: Any = AXIS_UNSET, **kwargs: Any
    ) -> Tuple[Dict[str, StateDict], Dict[str, Any]]:
        """:meth:`apply_forward` over the GROUP-DEDUPED state layout: one
        state bundle (and one update pass) per compute group, keyed by the
        group owner's name; every member still gets its own on-step value,
        computed from the shared batch state. With no multi-member groups
        this IS :meth:`apply_forward` — byte-identical program, per-member
        state keys."""
        layout = self._group_layout()
        if all(len(names) == 1 for _, names in layout):
            return self.apply_forward(state, *args, axis_name=axis_name, **kwargs)
        grouped = {n for _, ns in layout if len(ns) > 1 for n in ns}
        deltas = self._shared_deltas(args, kwargs, exclude=grouped)
        batch: Dict[str, StateDict] = {}
        for owner_name, _ in layout:
            m = self._metrics[owner_name]
            if owner_name in deltas:
                batch[owner_name] = m._apply_accumulate(m.init_state(), deltas[owner_name])
            else:
                batch[owner_name] = m.apply_update(
                    m.init_state(), *args, **m._filter_kwargs(**kwargs)
                )
        new_state: Dict[str, StateDict] = {}
        values: Dict[str, Any] = {}
        for owner_name, names in layout:
            m = self._metrics[owner_name]
            new_state[owner_name], values[self._set_name(owner_name)] = m.apply_forward(
                state[owner_name],
                *args,
                axis_name=axis_name,
                batch_state=batch[owner_name],
                **m._filter_kwargs(**kwargs),
            )
            for n in names[1:]:
                mm = self._metrics[n]
                values[self._set_name(n)] = (
                    mm.apply_compute(batch[owner_name], axis_name=None)
                    if mm.compute_on_step
                    else None
                )
        return new_state, values

    def _grouped_apply_update(
        self, state: Dict[str, StateDict], *args: Any, **kwargs: Any
    ) -> Dict[str, StateDict]:
        """:meth:`apply_update` over the group-deduped state layout (one
        update per group); identical to :meth:`apply_update` when no
        multi-member groups exist."""
        layout = self._group_layout()
        if all(len(names) == 1 for _, names in layout):
            return self.apply_update(state, *args, **kwargs)
        grouped = {n for _, ns in layout if len(ns) > 1 for n in ns}
        deltas = self._shared_deltas(args, kwargs, exclude=grouped)
        out: Dict[str, StateDict] = {}
        for owner_name, _ in layout:
            m = self._metrics[owner_name]
            if owner_name in deltas:
                out[owner_name] = m._apply_accumulate(state[owner_name], deltas[owner_name])
            else:
                out[owner_name] = m.apply_update(
                    state[owner_name], *args, **m._filter_kwargs(**kwargs)
                )
        return out

    def _collect_dispatch_state(self) -> Dict[str, StateDict]:
        """The live state bundles a compiled dispatch threads: ONE per
        compute group (keyed by owner name) plus one per ungrouped member —
        the 5-member stat-scores collection donates 4 leaves, not 20.
        Members grouped by a DIFFERENT collection are detached first (their
        shared state cannot be donated out from under the other group)."""
        state: Dict[str, StateDict] = {}
        for name, m in self.items(keep_base=True):
            g = m.__dict__.get("_compute_group")
            if g is not None and g.collection_ref() is not self:
                m._group_cow_detach("compiled dispatch through another collection")
        for owner_name, names in self._group_layout():
            for n in names:
                m = self._metrics[n]
                m._computed = None
                m._forward_cache = None
            state[owner_name] = self._metrics[owner_name]._get_states()
        return state

    def _writeback_dispatch_state(self, new_state: Dict[str, StateDict]) -> int:
        """Adopt a dispatch's output states (one bundle per layout entry) and
        refresh every member's step flags; returns the number of per-member
        updates the group dedup skipped this dispatch."""
        skipped = 0
        for owner_name, names in self._group_layout():
            self._metrics[owner_name]._set_states(new_state[owner_name])
            skipped += len(names) - 1
            for n in names:
                m = self._metrics[n]
                m._update_called = True
                m._computed = None
        return skipped

    def _donation_safe_state(
        self, state: Dict[str, StateDict]
    ) -> Tuple[Dict[str, StateDict], bool]:
        """Collection-wide :meth:`Metric._donation_safe_state`: default-aliased
        member leaves are defensively copied; ANY externally-held member leaf
        sends the whole dispatch to the copying executable (the executable is
        one program — donation is all-or-nothing per step). ``state`` is
        keyed by layout entry (group owners + ungrouped members)."""
        aliased = None
        for name in state:
            m = self._metrics[name]
            member = state[name]
            for sname in member:
                v = member[sname]
                if not isinstance(v, ArrayTypes):
                    continue
                if v is m._defaults.get(sname):
                    member[sname] = jnp.asarray(v).copy()
                    continue
                # expected references: the member's attribute slot, this
                # member-state dict, the loop variable, getrefcount's argument
                if sys.getrefcount(v) > 4:
                    aliased = f"{name}.{sname}"
                    break
            if aliased is not None:
                break
        if aliased is None:
            return state, True
        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "jit_forward_alias_fallbacks")
        if not self.__dict__.get("_donation_warned", False):
            self._donation_warned = True
            rank_zero_warn(
                f"MetricCollection.jit_forward: member state `{aliased}` is referenced"
                " outside its metric, so this step dispatches through the copying"
                " executable instead of donating the state buffers. Drop external"
                " references to member states to restore zero-copy updates, or call"
                " jit_forward(donate=False) to keep the copying path silently.",
                UserWarning,
            )
        return state, False

    def _forward_jitted(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        self._ensure_compute_groups(args, kwargs)
        fn = self._forward_dispatch()
        # _collect_dispatch_state clears the members' cached compute()/step
        # values BEFORE the alias check (they're invalidated by the incoming
        # batch anyway), so a cached result that aliases a state leaf cannot
        # be donated out from under a caller still holding it
        state = self._collect_dispatch_state()
        if fn.donate_state:
            state, donatable = self._donation_safe_state(state)
            if not donatable:
                fn = self._forward_copy_dispatch()
        prof = PROFILER.begin("compiled", state)
        start = time.perf_counter() if (EVENTS.enabled or TELEMETRY.enabled) else None
        new_state, values = fn(state, *args, **kwargs)
        submitted = time.perf_counter() if (start is not None or prof is not None) else None
        if prof is not None:
            PROFILER.finish(prof, new_state, self.telemetry_key, fn, submit_end=submitted)
        if start is not None:
            dur = submitted - start
            if TELEMETRY.enabled:
                observe_dispatch(dur, "compiled")
            if EVENTS.enabled:
                EVENTS.record(
                    "forward",
                    self.telemetry_key,
                    dur_s=dur,
                    t_start=start,
                    path="compiled",
                    members=len(self._metrics),
                    state_bundles=len(state),
                    compiled_this_call=bool(fn.last_compiled),
                    donated=fn.donate_state,
                )
        record = TELEMETRY.enabled
        if record:
            # one compiled program serves every member: the collection key
            # carries the compile/retrace ledger, members count the dispatch
            _note_compiled_dispatch(self, fn, args, kwargs)
        skipped = self._writeback_dispatch_state(new_state)
        if record and skipped:
            TELEMETRY.inc(self.telemetry_key, "update_dedup_skipped", skipped)
        for name, m in self.items(keep_base=True):
            if record:
                TELEMETRY.inc(m.telemetry_key, "forward_compiled_calls")
            if not m.compute_on_step:
                # eager-contract parity: such members return None on step
                values[self._set_name(name)] = None
            m._forward_cache = values[self._set_name(name)]
        return values

    def warmup(self, *sample_batch: Any, **kwargs: Any) -> Dict[str, Any]:
        """AOT lower+compile the collection's single ``jit_forward``
        executable for this batch shape (see :meth:`Metric.warmup`):
        first-step latency becomes a deliberate, observable ``compile``
        event instead of a surprise inside step 0. Enables
        :meth:`jit_forward` if not already enabled. Returns the cost report
        for the compiled collection program."""
        if not self._jit_forward_enabled:
            self.jit_forward(donate=self._jit_forward_donate)
        self._ensure_compute_groups(sample_batch, kwargs)
        fn = self._forward_dispatch()
        state = self._collect_dispatch_state()
        start = time.perf_counter()
        compiled, fresh = fn.warm(state, *sample_batch, **kwargs)
        key = self.telemetry_key
        if TELEMETRY.enabled:
            TELEMETRY.inc(key, "warmup_calls")
            if fresh:
                TELEMETRY.inc(key, "warmup_compiles")
        if EVENTS.enabled:
            EVENTS.record(
                "compile",
                key,
                dur_s=fn.last_compile_s,
                t_start=start,
                path="warmup",
                fresh=fresh,
                donated=fn.donate_state,
                members=len(self._metrics),
                signature=arg_signature(*sample_batch, **kwargs),
            )
        from metrics_tpu.observability.cost import executable_cost

        return {
            "metric": type(self).__name__,
            "members": len(self._metrics),
            "compiled_this_call": fresh,
            "compile_seconds": round(fn.last_compile_s, 6),
            "donated": fn.donate_state,
            "executables_cached": fn._cache_size(),
            "forward": executable_cost(compiled),
            "state_memory": self.state_memory_report(),
        }

    def _scan_update_many(
        self, state: Dict[str, StateDict], stacked: Tuple, stacked_kwargs: Dict
    ) -> Dict[str, StateDict]:
        """One ``lax.scan`` of the collection's shared :meth:`apply_update`
        over the stacked leading axis (see :meth:`Metric._scan_update_many`);
        compute groups advance ONE shared state per group inside the scan."""
        leaves, treedef = jax.tree_util.tree_flatten((stacked, stacked_kwargs))
        scanned_ix = [i for i, leaf in enumerate(leaves) if getattr(leaf, "ndim", 0) >= 1]

        def body(s: Dict[str, StateDict], xs: Tuple) -> Tuple[Dict[str, StateDict], None]:
            merged = list(leaves)
            for i, x in zip(scanned_ix, xs):
                merged[i] = x
            args, kw = jax.tree_util.tree_unflatten(treedef, merged)
            return self._grouped_apply_update(s, *args, **kw), None

        new_state, _ = jax.lax.scan(body, state, tuple(leaves[i] for i in scanned_ix))
        return new_state

    @staticmethod
    def _microbatch_slice(stacked: Tuple, stacked_kwargs: Dict) -> Tuple[Tuple, Dict]:
        """One micro-batch's avals out of ``update_many``'s stacked arguments
        (rank >= 1 leaves lose their leading K axis; scalars broadcast)."""
        slice0 = lambda x: x[0] if getattr(x, "ndim", 0) >= 1 else x  # noqa: E731
        return (
            jax.tree_util.tree_map(slice0, stacked),
            jax.tree_util.tree_map(slice0, stacked_kwargs),
        )

    def update_many(self, *stacked: Any, **stacked_kwargs: Any) -> None:
        """Accumulate K stacked micro-batches across EVERY member in ONE
        compiled dispatch (see :meth:`Metric.update_many`): a single
        ``lax.scan`` of the collection's shared update — shared-update
        classes canonicalize once per micro-batch inside it, and compute
        groups run one update per group — over the donated collection state.
        One dispatch amortized over K × members updates; works with or
        without :meth:`jit_forward` enabled."""
        for name, m in self.items(keep_base=True):
            try:
                m._compiled_state_gate()
            except ValueError as err:
                raise ValueError(f"member {name!r}: {err}") from None
        k = _microbatch_len(stacked, stacked_kwargs)
        self._ensure_compute_groups(*self._microbatch_slice(stacked, stacked_kwargs))
        state = self._collect_dispatch_state()
        donatable = True
        if self._jit_forward_donate:
            state, donatable = self._donation_safe_state(state)
        if donatable and self._jit_forward_donate:
            if self._update_many_fn is None:
                self._update_many_fn = CompiledDispatch(
                    self._scan_update_many, donate_state=True, context_fn=self._group_signature
                )
            fn = self._update_many_fn
        else:
            if self._update_many_copy_fn is None:
                self._update_many_copy_fn = CompiledDispatch(
                    self._scan_update_many, donate_state=False, context_fn=self._group_signature
                )
            fn = self._update_many_copy_fn
        prof = PROFILER.begin("update_many", state)
        start = time.perf_counter() if (TELEMETRY.enabled or EVENTS.enabled) else None
        new_state = fn(state, stacked, stacked_kwargs)
        submitted = time.perf_counter() if (start is not None or prof is not None) else None
        if prof is not None:
            PROFILER.finish(prof, new_state, self.telemetry_key, fn, submit_end=submitted)
        if start is not None:
            dur = submitted - start
            key = self.telemetry_key
            if TELEMETRY.enabled:
                TELEMETRY.inc(key, "update_many_calls")
                TELEMETRY.inc(key, "update_many_batches", k)
                observe_dispatch(dur, "update_many")
                _note_compiled_dispatch(
                    self, fn, stacked, stacked_kwargs, counter="update_many_dispatches"
                )
            if EVENTS.enabled:
                EVENTS.record(
                    "update",
                    key,
                    dur_s=dur,
                    t_start=start,
                    path="scan_microbatch",
                    batches=k,
                    members=len(self._metrics),
                    state_bundles=len(state),
                    compiled_this_call=bool(fn.last_compiled),
                    donated=fn.donate_state,
                )
        skipped = self._writeback_dispatch_state(new_state)
        if TELEMETRY.enabled and skipped:
            TELEMETRY.inc(self.telemetry_key, "update_dedup_skipped", skipped * k)

    def __getstate__(self) -> dict:
        # group objects never serialize: each member's own __getstate__
        # materializes the shared state (byte-compatible with an ungrouped
        # 0.6.0 checkpoint), and groups rebuild at the next compiled dispatch
        return {
            k: v
            for k, v in self.__dict__.items()
            if k not in ("_jit_forward_fn", "_jit_forward_copy_fn", "_update_many_fn",
                         "_update_many_copy_fn", "_telemetry_key", "_jit_cache_seen",
                         "_donation_warned", "_compute_groups_built")
        }

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)
        # pickles from before the compiled stateful forward (0.4.0) predate
        # this flag; default it off so their first forward() stays eager.
        # Donation (0.6.0) defaults on for enabled pickles — enablement
        # survives, the executable cache is rebuilt on first dispatch.
        # Compute groups (0.7.0): the opt-out survives, the grouping itself
        # is rebuilt (value-checked) at the next compiled dispatch.
        self.__dict__.setdefault("_jit_forward_enabled", False)
        self.__dict__.setdefault("_jit_forward_donate", True)
        self.__dict__.setdefault("_compute_groups_enabled", True)
        self._compute_groups_built = False
        self._donation_warned = False
        self._drop_compiled_dispatch()

    def _class_groups(self) -> Dict[Tuple, list]:
        """Member names per shared-update equivalence key (insertion order)."""
        groups: Dict[Tuple, list] = {}
        for name, m in self.items(keep_base=True):
            key = m._shared_update_key()
            if key is not None:
                groups.setdefault(key, []).append(name)
        return groups

    def _shared_deltas(
        self, args: Tuple, kwargs: Dict, exclude: Optional[set] = None
    ) -> Dict[str, Any]:
        """Per-batch partial statistics computed ONCE per equivalence class.

        Metrics advertising the same :meth:`Metric._shared_update_key` (e.g.
        Precision/Recall/F1 with identical stat-scores settings) get one
        canonicalization + one tp/fp/tn/fn pass instead of one each — the
        collection-level fusion the reference leaves on the table (every
        member keeps private states, SURVEY §3.3). ``exclude`` names members
        a compute group already serves (their shared state advances without
        any per-member deltas at all)."""
        deltas: Dict[str, Any] = {}
        for names in self._class_groups().values():
            if exclude:
                names = [n for n in names if n not in exclude]
            if len(names) < 2:
                continue
            rep = self._metrics[names[0]]
            with compiled_scope(f"{type(rep).__name__}.shared_update"):
                value = rep._batch_deltas(*args, **rep._filter_kwargs(**kwargs))
            for name in names:
                deltas[name] = value
        return deltas

    def compute(self) -> Dict[str, Any]:
        """Compute every metric; the whole collection syncs in ONE transport.

        On the default distributed gather, every member's states (one bundle
        per shared-update equivalence class — class members hold identical
        states by construction, so A+P+R+F1 ship one tp/fp/tn/fn quartet)
        ride a single packed ``gather_all_pytrees`` call: one descriptor
        round + one payload round for the entire collection, instead of two
        transport rounds per state per metric (the reference's ~(1 barrier +
        2 gathers) × states cost model, SURVEY §3.3). Members with injected
        ``dist_sync_fn`` gathers or overridden sync protocols keep syncing
        themselves. Restores every member's local state and sync flag
        afterwards."""
        adopted: list = []
        try:
            # adoption runs INSIDE the try so a failure while syncing a later
            # class still restores members already pointed at synced states.
            # Compute-group followers never sync themselves: their reads
            # delegate to the owner, whose bundle is gathered once — flip
            # their _to_sync off (restored by the finally) so their compute()
            # cannot issue a duplicate gather of the shared state.
            if self.__dict__.get("_compute_groups_built", False):
                for _, names in self._group_layout():
                    for n in names[1:]:
                        mm = self._metrics[n]
                        adopted.append((mm, None, mm._to_sync))
                        mm._to_sync = False
            self._adopt_packed_synced_states(adopted)
            return {k: m.compute() for k, m in self.items()}
        finally:
            for m, cache, prev_to_sync in adopted:
                if cache is not None:
                    m._set_states(cache)
                m._to_sync = prev_to_sync

    def compute_async(
        self,
        *,
        on_degraded: str = "retry",
        round_timeout_s: Optional[float] = None,
        max_retries: Optional[int] = None,
        backoff_s: Optional[float] = None,
    ) -> Any:
        """Epoch-end :meth:`compute` with the packed gather OFF the step path.

        Snapshots the whole collection into a detached shadow clone (compute
        groups, class aliases and the packed ONE-descriptor+ONE-payload
        transport all apply inside the shadow exactly as in :meth:`compute`)
        and runs the transport rounds on the background sync engine,
        overlapped with subsequent ``update()``/``forward()`` steps on the
        live collection. Returns a
        :class:`~metrics_tpu.utilities.async_sync.SyncFuture` resolving to
        the same ``{name: value}`` dict a synchronous :meth:`compute` at the
        snapshot moment would return. ``on_degraded`` /
        ``round_timeout_s`` select the degraded-link policy exactly as in
        :meth:`Metric.compute_async`; the same cross-process collective
        discipline applies. ``compute()`` stays the synchronous path.
        """
        from metrics_tpu.utilities.async_sync import get_engine

        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "compute_async_calls")
        shadow = self.clone()
        # per-attempt clone: an orphaned (timed-out) transport attempt must
        # not race a retry on shared shadow state — see Metric.compute_async
        return get_engine().submit(
            self.telemetry_key,
            lambda: shadow.clone().compute(),
            on_degraded=on_degraded,
            round_timeout_s=round_timeout_s,
            max_retries=max_retries,
            backoff_s=backoff_s,
        )

    def _adopt_packed_synced_states(self, adopted: list) -> None:
        """Sync every packable member's states in ONE packed transport per
        gather group and point the members at the synced values; appends
        restore records to ``adopted`` AS THEY HAPPEN (so a mid-way failure
        is fully restorable).

        Packable means: default ``gather_all_arrays`` transport (no injected
        ``dist_sync_fn``), the base ``Metric._sync_dist`` protocol, at least
        one registered state, and sync not already disabled. Shared-update
        equivalence classes contribute their representative's bundle once;
        the members adopt the synced result, exactly as the per-class
        adoption did. Everything else (custom gathers, overridden sync)
        falls back to the per-class adoption + per-member self-sync."""
        from metrics_tpu.utilities import distributed as _dist

        # compute-group members share ONE live state: the owner's bundle
        # gathers once for the whole group (the followers' _to_sync is
        # already off, see compute()), and the class-alias fan-out below must
        # not point a follower at a private state copy
        cg_members: set = set()
        cg_sizes: Dict[str, int] = {}
        if self.__dict__.get("_compute_groups_built", False):
            for owner_name, names in self._group_layout():
                if len(names) > 1:
                    cg_members.update(names)
                    cg_sizes[owner_name] = len(names)

        if not _dist.distributed_available():
            # no packed transport to save; class adoption still dedups
            # injected-gather classes
            return self._adopt_class_synced_states(adopted, skip=cg_members or None)

        alias: Dict[str, list] = {}  # rep name -> all class member names
        aliased = set()
        for names in self._class_groups().values():
            if len(names) < 2:
                continue
            if cg_members and any(n in cg_members for n in names):
                continue  # served by a compute group's shared state
            if all(self._metrics[n]._computed is not None for n in names):
                continue  # every member returns its cached value; don't re-gather
            rep = self._metrics[names[0]]
            if any(
                self._metrics[n]._reductions != rep._reductions
                or self._metrics[n].process_group != rep.process_group
                or self._metrics[n].dist_sync_fn is not rep.dist_sync_fn
                or self._metrics[n].__dict__.get("_transport")
                is not rep.__dict__.get("_transport")
                for n in names[1:]
            ):
                continue
            alias[names[0]] = names
            aliased.update(names[1:])

        # one bundle per gather group (metrics naming different process
        # subsets cannot share a decode, but each bundle is still one
        # descriptor + one payload round, and rounds across bundles stay
        # aligned rank-to-rank because membership derives from SPMD state)
        bundles: Dict[str, Tuple[Any, list]] = {}
        for name, m in self.items(keep_base=True):
            if name in aliased:
                continue
            if m._computed is not None and name not in alias:
                continue  # cached value; compute() will not sync anyway
            if (
                m.dist_sync_fn is not None
                or type(m)._sync_dist is not Metric._sync_dist
                or m.__dict__.get("_transport") is not None  # pinned backends self-sync
                or not m._defaults
                or not m._to_sync
            ):
                continue
            key = repr(m.process_group)
            bundles.setdefault(key, (m.process_group, []))[1].append(name)

        for group, names in bundles.values():
            pre = [self._metrics[n]._pre_sync_states() for n in names]
            sync_start = time.perf_counter() if EVENTS.enabled else None
            # collective span around the whole collection bundle: one
            # deterministic id per epoch sync, shared by every participating
            # process (the fleet-timeline correlation key)
            tr_span = (
                TRACER.begin("sync", group=repr(group), bucket="collection")
                if TRACER.enabled
                else None
            )
            gathered = _dist.gather_all_pytrees([states for states, _ in pre], group=group)
            span_id = (
                TRACER.end(tr_span, collection=self.telemetry_key, members=list(names))
                if tr_span
                else None
            )
            if sync_start is not None:
                # compute_groups: how many members each gathered bundle
                # serves (owner -> group size) — the transport-dedup evidence
                EVENTS.record(
                    "sync",
                    self.telemetry_key,
                    dur_s=time.perf_counter() - sync_start,
                    t_start=sync_start,
                    members=list(names),
                    packed=True,
                    span_id=span_id,
                    compute_groups={n: cg_sizes[n] for n in names if n in cg_sizes},
                )
            for n, (states, list_dtypes), g in zip(names, pre, gathered):
                m = self._metrics[n]
                m._note_sync_telemetry(states)
                adopted.append((m, m._get_states(), m._to_sync))
                m._apply_gathered_states(g, list_dtypes)
                m._to_sync = False  # already synced; don't re-gather inside compute()
                if n in alias:
                    synced = m._get_states()
                    for member in alias[n][1:]:
                        mm = self._metrics[member]
                        adopted.append((mm, mm._get_states(), mm._to_sync))
                        # fresh list shells so no member can mutate a shared one
                        mm._set_states(
                            {k: (list(v) if isinstance(v, list) else v) for k, v in synced.items()}
                        )
                        mm._to_sync = False

        # anything not packable (injected gathers, overridden sync) still
        # gets the per-class dedup it had before
        remaining: list = []
        self._adopt_class_synced_states(
            remaining,
            skip={n for _, ns in bundles.values() for n in ns} | aliased | cg_members,
        )
        adopted.extend(remaining)

    def _adopt_class_synced_states(self, adopted: list, skip: Optional[set] = None) -> None:
        """Sync one representative per shared-update class and point the
        members at the synced values; appends restore records to ``adopted``
        AS THEY HAPPEN (so a mid-way failure is fully restorable). No-op
        when not distributed — each member then syncs (trivially) itself.
        ``skip`` names members the packed adoption already handled."""
        for names in self._class_groups().values():
            if skip and any(n in skip for n in names):
                continue
            if len(names) < 2:
                continue
            if all(self._metrics[n]._computed is not None for n in names):
                continue  # every member returns its cached value; don't re-gather
            rep = self._metrics[names[0]]
            if any(
                self._metrics[n]._reductions != rep._reductions
                or self._metrics[n].process_group != rep.process_group
                or self._metrics[n].dist_sync_fn is not rep.dist_sync_fn
                for n in names[1:]
            ):
                continue
            rep_cache = rep.sync(dist_sync_fn=rep.dist_sync_fn, process_group=rep.process_group)
            if not rep_cache:  # sync was a no-op (not distributed)
                continue
            synced = rep._get_states()
            adopted.append((rep, rep_cache, rep._to_sync))
            rep._to_sync = False  # already synced; don't re-gather inside compute()
            for n in names[1:]:
                m = self._metrics[n]
                adopted.append((m, m._get_states(), m._to_sync))
                # fresh list shells so no member can mutate a shared one
                m._set_states({k: (list(v) if isinstance(v, list) else v) for k, v in synced.items()})
                m._to_sync = False

    def reset(self) -> None:
        if not self.__dict__.get("_compute_groups_built", False):
            for _, m in self.items(keep_base=True):
                m.reset()
            return
        # group-aware: the shared state resets ONCE per group and the group
        # stays intact (a member-level reset() would CoW-detach itself)
        for owner_name, names in self._group_layout():
            if len(names) == 1:
                self._metrics[owner_name].reset()
                continue
            owner = self._metrics[owner_name]
            owner._set_states(owner.init_state())
            for n in names:
                m = self._metrics[n]
                m._reset_flags()
                if TELEMETRY.enabled:
                    TELEMETRY.inc(m.telemetry_key, "reset_calls")

    def keyed(self, num_tenants: int, **kwargs: Any) -> Any:
        """An N-tenant stacked view of this collection: one
        :class:`~metrics_tpu.wrappers.multitenant.MultiTenantCollection`
        holding one stacked state bundle per compute-group layout entry,
        all bundles advanced by a single donated dispatch per step. State
        starts fresh at the defaults."""
        from metrics_tpu.wrappers.multitenant import MultiTenantCollection

        return MultiTenantCollection(self, num_tenants, **kwargs)

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MetricCollection":
        mc = deepcopy(self)
        if prefix:
            mc.prefix = self._check_arg(prefix, "prefix")
        if postfix:
            mc.postfix = self._check_arg(postfix, "postfix")
        return mc

    def persistent(self, mode: bool = True) -> None:
        for _, m in self.items(keep_base=True):
            m.persistent(mode)

    def state_dict(self, destination: Optional[dict] = None, prefix: str = "") -> dict:
        destination = {} if destination is None else destination
        for name, m in self.items(keep_base=True):
            m.state_dict(destination, prefix=f"{prefix}{name}.")
        return destination

    def load_state_dict(self, state_dict: dict, prefix: str = "") -> None:
        # restored per-member states must be honored: dissolve the groups
        # first (each member materializes, then loads its own values); the
        # next compiled dispatch regroups only members whose restored states
        # still agree (build_compute_groups value-checks)
        self._dissolve_compute_groups()
        for name, m in self.items(keep_base=True):
            m.load_state_dict(state_dict, prefix=f"{prefix}{name}.")

    # ------------------------------------------------------------------
    # pure-state fan-out (jit / shard_map native)
    # ------------------------------------------------------------------

    def init_state(self) -> Dict[str, StateDict]:
        """Fresh state pytrees for every metric, keyed by base name."""
        return {name: m.init_state() for name, m in self.items(keep_base=True)}

    def apply_update(self, state: Dict[str, StateDict], *args: Any, **kwargs: Any) -> Dict[str, StateDict]:
        """Advance every metric's state with this batch in one traceable pass.

        Metrics in the same shared-update equivalence class get their partial
        statistics computed once and fanned out (one canonicalization + one
        stat-scores kernel for e.g. Precision+Recall+F1)."""
        shared = self._shared_deltas(args, kwargs)
        return {
            name: (
                m._apply_accumulate(state[name], shared[name])
                if name in shared
                else m.apply_update(state[name], *args, **m._filter_kwargs(**kwargs))
            )
            for name, m in self.items(keep_base=True)
        }

    def apply_compute(self, state: Dict[str, StateDict], axis_name: Any = AXIS_UNSET) -> Dict[str, Any]:
        """Compute every metric from its state; with ``axis_name`` the whole
        collection's sync lowers to ONE packed collective per (kind, dtype)
        bucket. When omitted, each member falls back to its own declared
        ``process_group``.

        Two fusion layers compose here:

        * **class aliasing** — shared-update equivalence classes sync ONE
          state bundle: the collection's update fans identical deltas to
          every member of a class (:meth:`_shared_deltas` /
          :meth:`apply_update`), so their states are equal by construction
          and syncing each would multiply the collective payload by the
          class size for no information (A+P+R+F1 would ship 4 private
          tp/fp/tn/fn quartets). The representative's synced bundle is
          fanned out to the members instead. This leans on the collection
          state contract — states come from this collection's
          ``init_state``/``apply_update`` chain; hand-divergent states for
          same-class members are outside it.
        * **cross-member bucketing** — every surviving bundle (class
          representatives + unshared members) over the same axis is packed
          into ONE :func:`~metrics_tpu.utilities.distributed.sync_state_packed`
          call, so a 10-metric classification collection lowers to one
          ``psum`` (plus at most a ``pmax``/``all_gather`` bucket) instead
          of one collective per state per metric."""
        presynced = self._presync_in_graph(state, axis_name)
        out = {}
        for name, m in self.items(keep_base=True):
            if name in presynced:
                out[self._set_name(name)] = m.apply_compute(presynced[name], axis_name=None)
            else:
                out[self._set_name(name)] = m.apply_compute(state[name], axis_name=axis_name)
        return out

    def _in_graph_alias(self, axis_name: Any) -> Dict[str, list]:
        """Shared-update classes AND built compute groups whose members may
        alias ONE synced bundle in-graph: rep name -> all member names.
        Class aliases apply only when the members' state specs (and, with
        ``axis_name`` unset, their fallback axes) genuinely coincide;
        compute groups guarantee both by fingerprint, so every built group
        aliases directly — their states are identical by the exact-trace
        construction whenever they come from this collection's
        ``init_state``/``apply_update`` chain."""
        alias: Dict[str, list] = {}
        taken: set = set()
        if self.__dict__.get("_compute_groups_built", False):
            for owner_name, names in self._group_layout():
                if len(names) > 1:
                    alias[owner_name] = names
                    taken.update(names)
        for names in self._class_groups().values():
            names = [n for n in names if n not in taken]
            if len(names) < 2:
                continue
            rep = self._metrics[names[0]]
            if any(self._metrics[n]._reductions != rep._reductions for n in names[1:]):
                continue
            if axis_name is AXIS_UNSET and any(
                self._metrics[n].process_group != rep.process_group for n in names[1:]
            ):
                continue
            alias[names[0]] = names
        return alias

    def _packable_in_graph(self, m: Metric, member_state: StateDict) -> bool:
        """True when the member's state bundle can join a cross-member packed
        sync: base pure-state protocol (custom layouts like BootStrapper's
        sync inside their own ``apply_compute``) and a state dict whose keys
        match the registered reductions."""
        return (
            type(m).apply_compute is Metric.apply_compute
            and type(m).sync_state is Metric.sync_state
            and m.__dict__.get("_transport") is None  # pinned backends self-sync
            and bool(m._reductions)
            and set(member_state) == set(m._reductions)
        )

    def _packed_presync(
        self, state: Dict[str, StateDict], names: list, axis: Any,
        group_sizes: Optional[Dict[str, int]] = None,
    ) -> Dict[str, StateDict]:
        """One packed in-graph sync over ``axis`` for the named members'
        bundles: leaves from EVERY bundle share the (kind, dtype) buckets.
        ``group_sizes`` annotates how many members each bundle serves
        (compute groups / class aliases) for the sync telemetry."""
        from metrics_tpu.utilities.distributed import sync_state_packed

        flat_state: Dict[str, Any] = {}
        flat_reductions: Dict[str, Any] = {}
        for n in names:
            m = self._metrics[n]
            for k, v in state[n].items():
                flat_state[f"{n}\x1f{k}"] = v
                flat_reductions[f"{n}\x1f{k}"] = m._reductions[k]
        try:
            synced_flat = sync_state_packed(
                flat_state, flat_reductions, axis, group_composition=group_sizes
            )
        except NameError as err:  # unbound collective axis — mirror Metric.sync_state
            raise NameError(
                f"{err}. The collection members resolve to mesh axis {axis!r} — collectives"
                " over it only work inside shard_map/pmap binding that axis. To compute"
                " eagerly (single-device, no sync), pass `axis_name=None` explicitly."
            ) from err
        return {n: {k: synced_flat[f"{n}\x1f{k}"] for k in state[n]} for n in names}

    def _presync_in_graph(self, state: Dict[str, StateDict], axis_name: Any) -> Dict[str, StateDict]:
        """The collection-wide packed sync behind :meth:`apply_compute`:
        group class representatives and unshared members by their resolved
        axis, pack each group's bundles into shared buckets, fan class
        results out to the aliased members."""
        alias = self._in_graph_alias(axis_name)
        aliased = {n for names in alias.values() for n in names[1:]}

        bundles: Dict[str, Tuple[Any, list]] = {}
        presynced: Dict[str, StateDict] = {}
        for name, m in self.items(keep_base=True):
            if name in aliased:
                continue
            axis = m.process_group if axis_name is AXIS_UNSET else axis_name
            if axis is None:
                continue
            if self._packable_in_graph(m, state[name]):
                bundles.setdefault(repr(axis), (axis, []))[1].append(name)
            elif name in alias:
                # unpackable class rep: sync its bundle alone, still aliased
                synced = m.sync_state(state[name], axis)
                for n in alias[name]:
                    presynced[n] = synced

        for axis, names in bundles.values():
            synced_bundles = self._packed_presync(
                state,
                names,
                axis,
                group_sizes={n: len(alias[n]) for n in names if len(alias.get(n, ())) > 1},
            )
            for n, synced in synced_bundles.items():
                for member in alias.get(n, [n]):
                    presynced[member] = synced
        return presynced

    def apply_forward(
        self, state: Dict[str, StateDict], *args: Any, axis_name: Any = AXIS_UNSET, **kwargs: Any
    ) -> Tuple[Dict[str, StateDict], Dict[str, Any]]:
        """(accumulated state, per-batch values) — one shared update pass.

        The batch-local states come from a single :meth:`apply_update` (so
        shared-update classes canonicalize once for the whole collection);
        each metric then merges its batch state into the accumulator the same
        way :meth:`Metric.apply_forward` would. EVERY on-step syncer
        (``dist_sync_on_step=True`` over a resolved axis) joins the packed
        batch-bundle sync: shared-update classes contribute one bundle
        (synced once, fanned out), and all bundles over the same axis share
        the (kind, dtype) collective buckets — the third sync path with
        class aliasing AND cross-member bucketing, alongside :meth:`compute`
        and :meth:`apply_compute`."""
        batch_state = self.apply_update(self.init_state(), *args, **kwargs)

        # class aliasing among on-step syncers: a class bundle syncs once
        alias: Dict[str, list] = {}
        aliased: set = set()
        for names in self._class_groups().values():
            syncers = [
                n
                for n in names
                if self._metrics[n].dist_sync_on_step
                and (self._metrics[n].process_group if axis_name is AXIS_UNSET else axis_name)
                is not None
            ]
            if len(syncers) < 2:
                continue
            rep = self._metrics[syncers[0]]
            if any(self._metrics[n]._reductions != rep._reductions for n in syncers[1:]):
                continue
            if axis_name is AXIS_UNSET and any(
                self._metrics[n].process_group != rep.process_group for n in syncers[1:]
            ):
                continue
            alias[syncers[0]] = syncers
            aliased.update(syncers[1:])

        # pack every surviving on-step bundle per resolved axis
        bundles: Dict[str, Tuple[Any, list]] = {}
        presynced: Dict[str, StateDict] = {}
        for name, m in self.items(keep_base=True):
            if name in aliased or not m.dist_sync_on_step:
                continue
            axis = m.process_group if axis_name is AXIS_UNSET else axis_name
            if axis is None:
                continue
            if self._packable_in_graph(m, batch_state[name]):
                bundles.setdefault(repr(axis), (axis, []))[1].append(name)
            elif name in alias:
                synced = m.sync_state(batch_state[name], axis)
                for n in alias[name]:
                    presynced[n] = synced
        for axis, names in bundles.values():
            synced_bundles = self._packed_presync(batch_state, names, axis)
            for n, synced in synced_bundles.items():
                for member in alias.get(n, [n]):
                    presynced[member] = synced

        new_state, values = {}, {}
        for name, m in self.items(keep_base=True):
            new_state[name], values[self._set_name(name)] = m.apply_forward(
                state[name],
                *args,
                axis_name=axis_name,
                batch_state=batch_state[name],
                synced_batch_state=presynced.get(name),
                **m._filter_kwargs(**kwargs),
            )
        return new_state, values

    # ------------------------------------------------------------------
    # observability reports
    # ------------------------------------------------------------------

    def check_health(self, state: Optional[Dict[str, StateDict]] = None) -> Dict[str, Any]:
        """Numerical health report of every member (see
        :meth:`Metric.check_health`), keyed by base name, plus the
        collection-level ``healthy`` conjunction."""
        state = state or {}
        members = {
            name: m.check_health(state.get(name)) for name, m in self.items(keep_base=True)
        }
        return {
            "healthy": all(r["healthy"] for r in members.values()),
            "members": members,
        }

    def state_memory_report(self) -> Dict[str, Any]:
        """Bytes held by every member's states right now (see
        :meth:`Metric.state_memory_report`)."""
        per_metric = {name: m.state_memory_report() for name, m in self.items(keep_base=True)}
        return {
            "per_metric": per_metric,
            "total_bytes": int(sum(r["total_bytes"] for r in per_metric.values())),
        }

    def cost_report(self, *example_batch: Any, **kwargs: Any) -> Dict[str, Any]:
        """XLA cost estimate for the collection on an example batch.

        ``fused_update`` costs the collection's single shared-update program
        (what a scanned/jitted train step actually pays — shared-update
        equivalence classes canonicalize once); ``members`` carries each
        metric's individual :meth:`Metric.cost_report`, whose sum is the cost
        the same metrics would pay UNFUSED. The gap between the two is the
        collection-level fusion win, now measurable per workload.
        """
        from metrics_tpu.observability.cost import program_cost

        members = {
            name: m.cost_report(*example_batch, **m._filter_kwargs(**kwargs))
            for name, m in self.items(keep_base=True)
        }
        return {
            "fused_update": program_cost(self.apply_update, self.init_state(), *example_batch, **kwargs),
            "members": members,
            "state_memory": self.state_memory_report(),
        }

    # ------------------------------------------------------------------
    # container protocol
    # ------------------------------------------------------------------

    def add_metrics(
        self, metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]], *additional_metrics: Metric
    ) -> None:
        before = set(self._metrics) if getattr(self, "_jit_forward_enabled", False) else None
        self._add_metrics(metrics, *additional_metrics)
        # any cached update_many executable baked in the OLD member set too —
        # and it exists independently of jit_forward enablement. Compute
        # groups likewise baked the old member set: dissolve, rebuild at the
        # next compiled dispatch against the grown membership.
        if getattr(self, "_compute_groups_built", False):
            self._dissolve_compute_groups()
        self._update_many_fn = None
        self._update_many_copy_fn = None
        if before is not None:
            # a previously-built jitted forward baked in the OLD member set;
            # keeping it would silently drop the new members from every step.
            # Invalidate the cache and re-run the member eligibility gate —
            # atomically: an ineligible addition is rolled back, so the
            # documented ValueError fires instead of a per-step retrace.
            self._jit_forward_fn = None
            self._jit_forward_copy_fn = None
            new_names = [n for n in self._metrics if n not in before]
            for name in new_names:
                try:
                    self._metrics[name]._jit_forward_gate()
                except ValueError as err:
                    for n in new_names:
                        del self._metrics[n]
                    raise ValueError(f"member {name!r}: {err}") from None
        # new members mean new state bundles: re-note the memory ledger at
        # the same seam that invalidated the executables
        from metrics_tpu.observability.memory import LEDGER

        LEDGER.note(self)

    def _add_metrics(
        self, metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]], *additional_metrics: Metric
    ) -> None:
        if isinstance(metrics, Metric):
            metrics = [metrics]
        if isinstance(metrics, Sequence):
            metrics = list(metrics)
            remain: list = []
            for m in additional_metrics:
                (metrics if isinstance(m, Metric) else remain).append(m)
            if remain:
                rank_zero_warn(
                    f"You have passes extra arguments {remain} which are not `Metric` so they will be ignored."
                )
        elif additional_metrics:
            raise ValueError(
                f"You have passes extra arguments {additional_metrics} which are not compatible"
                f" with first passed dictionary {metrics} so they will be ignored."
            )

        if isinstance(metrics, dict):
            for name in sorted(metrics.keys()):
                metric = metrics[name]
                if not isinstance(metric, Metric):
                    raise ValueError(f"Value {metric} belonging to key {name} is not an instance of `Metric`")
                self._metrics[name] = metric
        elif isinstance(metrics, Sequence):
            for metric in metrics:
                if not isinstance(metric, Metric):
                    raise ValueError(f"Input {metric} to `MetricCollection` is not a instance of `Metric`")
                name = metric.__class__.__name__
                if name in self._metrics:
                    raise ValueError(f"Encountered two metrics both named {name}")
                self._metrics[name] = metric
        else:
            raise ValueError("Unknown input to MetricCollection.")

    def _set_name(self, base: str) -> str:
        name = base if self.prefix is None else self.prefix + base
        return name if self.postfix is None else name + self.postfix

    def _renamed(self) -> "OrderedDict[str, Metric]":
        return OrderedDict((self._set_name(k), v) for k, v in self._metrics.items())

    def keys(self, keep_base: bool = False) -> Iterable[str]:
        return self._metrics.keys() if keep_base else self._renamed().keys()

    def values(self) -> Iterable[Metric]:
        return self._metrics.values()

    def items(self, keep_base: bool = False) -> Iterable[Tuple[str, Metric]]:
        return self._metrics.items() if keep_base else self._renamed().items()

    def __getitem__(self, key: str) -> Metric:
        return self._metrics[key]

    def __setitem__(self, key: str, value: Metric) -> None:
        if not isinstance(value, Metric):
            raise ValueError(f"Value {value} is not an instance of `Metric`")
        if getattr(self, "_jit_forward_enabled", False):
            # same staleness hazard as add_metrics: the cached program bakes
            # in the replaced member's update
            value._jit_forward_gate()
            self._jit_forward_fn = None
            self._jit_forward_copy_fn = None
        if getattr(self, "_compute_groups_built", False):
            # the replaced member may own (or belong to) a group: dissolve
            # all assignments; the next compiled dispatch regroups
            self._dissolve_compute_groups()
        self._update_many_fn = None
        self._update_many_copy_fn = None
        self._metrics[key] = value

    def __contains__(self, key: str) -> bool:
        return key in self._metrics

    def __iter__(self) -> Iterable[str]:
        return iter(self.keys())

    def __len__(self) -> int:
        return len(self._metrics)

    @staticmethod
    def _check_arg(arg: Optional[str], name: str) -> Optional[str]:
        if arg is None or isinstance(arg, str):
            return arg
        raise ValueError(f"Expected input `{name}` to be a string, but got {type(arg)}")

    def __repr__(self) -> str:
        lines = [f"  ({k}): {v!r}" for k, v in self._metrics.items()]
        body = "\n".join(lines)
        out = f"{self.__class__.__name__}(\n{body}"
        if self.prefix:
            out += f",\n  prefix={self.prefix}{',' if self.postfix else ''}"
        if self.postfix:
            out += f"{',' if not self.prefix else ''}\n  postfix={self.postfix}"
        return out + "\n)"
