"""Core metric-state engine (L2).

Capability parity with the reference's ``torchmetrics/metric.py`` (the
``Metric`` base class: ``add_state``/``forward``/``sync``/``reset``/
``state_dict`` lifecycle, ``metric.py:37-592``, and ``CompositionalMetric``,
``metric.py:598-677``) — re-designed for JAX/XLA rather than translated:

* **State is a pytree.** Every metric owns a dict of jnp arrays (or lists of
  arrays for unbounded "cat" accumulators) plus a static reduction spec. The
  stateful class is a thin eager wrapper; the *native* interface is the pure
  one — :meth:`init_state` / :meth:`apply_update` / :meth:`apply_compute` /
  :meth:`apply_forward` — which threads the state pytree through jitted
  programs and expresses cross-device sync as XLA collectives over named mesh
  axes (``axis_name=...`` inside ``shard_map``), the TPU-idiomatic replacement
  for torch.distributed all_gather.

* **forward() is fused.** The reference runs ``update`` twice per step (global
  accumulate + batch-local value, ``metric.py:168-198``). Here a single update
  computes the batch-local state; the batch value is computed from it and the
  global state is advanced by an O(state)-cost merge derived from each state's
  reduction ("sum" -> add, "cat" -> extend, "max"/"min" -> elementwise), so
  the per-step cost is one kernel pass instead of two. Metrics whose states
  are not mergeable (custom reductions) transparently fall back to the
  reference's double-update protocol.

* **Sync skips the gather when it can.** "sum"/"mean"/"max"/"min" states
  compile to single ``psum``-family collectives in-graph; only "cat"/gather
  states pay for an all-gather. The eager multi-process path mirrors the
  reference's pad/trim gather protocol (see ``utilities/distributed.py``).
"""
import functools
import inspect
import os
import sys
import time
import weakref
from abc import ABC, abstractmethod
from contextlib import contextmanager
from copy import deepcopy
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utilities.data import (
    _flatten,
    apply_to_collection,
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
)
from metrics_tpu.observability.events import EVENTS
from metrics_tpu.observability.health import HEALTH, MetricHealthError, guard_state  # noqa: F401
from metrics_tpu.observability.histogram import observe_dispatch
from metrics_tpu.observability.profiling import PROFILER
from metrics_tpu.observability.registry import TELEMETRY
from metrics_tpu.observability.retrace import MONITOR, arg_signature, is_tracing
from metrics_tpu.observability.tracing import TRACER
from metrics_tpu.utilities.aot import CompiledDispatch
from metrics_tpu.utilities.distributed import (
    distributed_available,
    gather_all_arrays,
    gather_all_pytrees,
    sync_in_graph,  # noqa: F401 - re-exported; the per-leaf path tests use it
    sync_state_packed,
)
from metrics_tpu.utilities.profiling import compiled_scope, eager_span
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array
ArrayTypes = (jax.Array, np.ndarray)
StateValue = Union[Array, List[Array]]
StateDict = Dict[str, StateValue]


class _AxisUnset:
    """Sentinel for "``axis_name`` not passed": the pure API then falls back
    to the metric's constructor-declared ``process_group`` mesh axis. Distinct
    from ``None``, which explicitly disables in-graph sync."""

    _instance: Optional["_AxisUnset"] = None

    def __new__(cls) -> "_AxisUnset":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "<axis unset>"


#: pass-through default for ``apply_compute``/``apply_forward`` ``axis_name``
AXIS_UNSET = _AxisUnset()

_STR_REDUCTIONS: Dict[str, Callable] = {
    "sum": dim_zero_sum,
    "mean": dim_zero_mean,
    "cat": dim_zero_cat,
    "max": dim_zero_max,
    "min": dim_zero_min,
}

#: reductions whose per-batch state deltas can be merged into the accumulated
#: state without re-running ``update`` (enables the fused forward path);
#: list-typed states always merge by extension regardless of their reduction
_MERGEABLE_REDUCTIONS = {"sum", "cat", "max", "min"}


def _resolve_reduction(fx: Optional[Union[str, Callable]]) -> Optional[Callable]:
    if isinstance(fx, str):
        return _STR_REDUCTIONS[fx]
    return fx


def jit_distributed_available() -> bool:  # pragma: no cover - thin alias
    return distributed_available()


def _observed_forward(obj: Any, counter: str, thunk: Callable) -> Any:
    """Run one eager forward under telemetry: path counter + wall-time
    histogram + timeline event. Host-side only — the thunk itself is the
    (un-traced) eager dispatch path."""
    if not (TELEMETRY.enabled or EVENTS.enabled):
        return thunk()
    start = time.perf_counter()
    try:
        return thunk()
    finally:
        dur = time.perf_counter() - start
        key = obj.telemetry_key
        if TELEMETRY.enabled:
            TELEMETRY.inc(key, counter)
            TELEMETRY.observe(key, "forward", dur)
        if EVENTS.enabled:
            EVENTS.record("forward", key, dur_s=dur, t_start=start, path=counter)


def _note_compiled_dispatch(
    obj: Any, fn: Any, args: Tuple, kwargs: Dict, counter: str = "forward_compiled_calls"
) -> None:
    """Telemetry for one dispatch of a cached compiled forward: count the
    call and record fresh XLA compiles. The :class:`CompiledDispatch` cache
    reports a compile exactly (``last_compiled``); a plain jit fallback is
    inferred from cache-size deltas. A fresh compile means THIS call's
    signature forced it — it is recorded (and warned about past the
    threshold) with that signature. AOT warmup compiles are deliberate and
    bypass this path entirely (``Metric.warmup`` counts them separately)."""
    key = obj.telemetry_key
    TELEMETRY.inc(key, counter)
    fresh = getattr(fn, "last_compiled", None)
    if fresh is not None:
        if fresh:
            obj._jit_cache_seen = obj.__dict__.get("_jit_cache_seen", 0) + 1
            TELEMETRY.inc(key, "jit_forward_compiles")
            MONITOR.note_compile(key, arg_signature(*args, **kwargs), count=1)
        return
    cache_size = getattr(fn, "_cache_size", None)
    if cache_size is None:  # pragma: no cover - private jit API moved
        return
    try:
        size = int(cache_size())
    except Exception:  # pragma: no cover - private jit API moved
        return
    seen = obj.__dict__.get("_jit_cache_seen", 0)
    if size > seen:
        obj._jit_cache_seen = size
        TELEMETRY.inc(key, "jit_forward_compiles", size - seen)
        MONITOR.note_compile(key, arg_signature(*args, **kwargs), count=size - seen)


def _microbatch_len(args: Tuple, kwargs: Dict) -> int:
    """The micro-batch count K of an ``update_many`` call: the shared leading
    axis of every stacked array argument. Scalar (0-d, python-number, bool)
    leaves broadcast to all K micro-batches and don't vote."""
    import jax

    lengths = set()
    for leaf in jax.tree_util.tree_leaves((args, kwargs)):
        shape = getattr(leaf, "shape", None)
        if shape is None or len(shape) == 0:
            continue
        lengths.add(int(shape[0]))
    if not lengths:
        raise ValueError(
            "update_many expects at least one stacked array argument whose leading"
            " axis is the micro-batch count K"
        )
    if len(lengths) > 1:
        raise ValueError(
            "update_many: stacked arguments disagree on the micro-batch count"
            f" (leading axes {sorted(lengths)}); every array argument must carry"
            " the same leading K"
        )
    return lengths.pop()


#: sentinel for "attribute absent" in the bound-state save/restore protocol
_ABSENT = object()


class _ComputeGroup:
    """One shared live state serving several provably-identical metrics.

    Built by ``MetricCollection.build_compute_groups`` from exact update-trace
    fingerprints (:func:`~metrics_tpu.utilities.aot.trace_fingerprint`):
    every member's per-batch update lowers to the same program over the same
    state layout, so ONE update on ``owner``'s state advances all of them and
    each member's ``compute()`` reads the shared state through attribute
    delegation (``Metric.__getattr__``). Followers hold no state attributes
    of their own; any out-of-band mutation of a member (a direct state write,
    a standalone ``update()``/``forward()``) copy-on-write detaches that
    member (:meth:`Metric._group_cow_detach`) instead of corrupting siblings.
    """

    __slots__ = ("owner", "members", "collection_ref", "collection_key", "warned")

    def __init__(self, owner: "Metric", members: List["Metric"], collection: Any = None,
                 collection_key: Optional[str] = None) -> None:
        self.owner = owner
        self.members = list(members)
        self.collection_ref = weakref.ref(collection) if collection is not None else (lambda: None)
        self.collection_key = collection_key
        self.warned = False


class Metric(ABC):
    """Base class of all metrics.

    Subclasses register states with :meth:`add_state` and implement
    :meth:`update` and :meth:`compute`. The same subclass then works in two
    modes:

    * **eager / stateful** — torch-like UX: ``m(preds, target)`` accumulates
      and returns the batch value, ``m.compute()`` gives the epoch value with
      cross-process sync, ``m.reset()`` clears.
    * **pure / compiled** — ``state = m.init_state()``;
      ``state = m.apply_update(state, preds, target)`` inside ``jit`` /
      ``shard_map``; ``m.apply_compute(state, axis_name="data")`` reduces over
      the mesh axis with XLA collectives and returns the value.

    Args:
        compute_on_step: if True (default) ``forward`` returns the metric value
            on the current batch; otherwise it only accumulates and returns None.
        dist_sync_on_step: synchronize state across processes/mesh axes on every
            ``forward`` before computing the step value.
        process_group: mesh-axis name (or tuple of names) the metric's states
            reduce over in the in-graph path; the analogue of the reference's
            torch.distributed process group (``metric.py:76``). It is the
            default ``axis_name`` of :meth:`apply_compute`/:meth:`apply_forward`
            (an explicit ``axis_name=`` argument wins). ``None`` means "all
            participants" (and no in-graph sync unless a call site passes an
            axis). A collection of process indices (e.g. ``[0, 1]``) instead
            scopes the EAGER ``compute()`` gather to that subset of
            processes — disjoint groups sync independently and concurrently
            (``utilities/distributed.py:gather_all_arrays``), matching the
            reference's sub-group semantics
            (``torchmetrics/utilities/distributed.py:113-135``).
        dist_sync_fn: override for the eager gather used at ``compute()``;
            receives one state array and returns the per-participant list.
    """

    is_differentiable: Optional[bool] = None
    higher_is_better: Optional[bool] = None
    #: set False on subclasses whose forward must use the double-update protocol
    _fusable: bool = True
    #: set on subclasses that offer a bounded-memory ``sketched=True`` mode —
    #: appended to the compiled-state / keyed eligibility-gate errors so the
    #: remediation for an O(samples) `cat`-state refusal is actionable
    _sketch_hint: Optional[str] = None

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
        transport: Optional[Any] = None,
    ) -> None:
        self.compute_on_step = compute_on_step
        self.dist_sync_on_step = dist_sync_on_step
        self.process_group = process_group
        self.dist_sync_fn = dist_sync_fn
        self._transport = None
        if transport is not None:
            self.set_transport(transport)

        self._to_sync = True
        self._restore_cache = True
        self._computed = None
        self._forward_cache = None
        self._update_called = False
        self._jit_forward_enabled = False
        self._jit_forward_fn: Optional[CompiledDispatch] = None
        self._jit_forward_donate = True
        self._jit_forward_copy_fn: Optional[CompiledDispatch] = None
        self._update_many_fn: Optional[CompiledDispatch] = None
        self._update_many_copy_fn: Optional[CompiledDispatch] = None
        self._donation_warned = False
        self._compute_group: Optional[_ComputeGroup] = None

        self._defaults: Dict[str, StateValue] = {}
        self._persistent: Dict[str, bool] = {}
        self._buffers: Dict[str, bool] = {}
        self._reductions: Dict[str, Optional[Union[str, Callable]]] = {}

        self._update_signature = inspect.signature(self.update)
        self.update = self._wrap_update(self.update)  # type: ignore[method-assign]
        self.compute = self._wrap_compute(self.compute)  # type: ignore[method-assign]

    def set_transport(self, transport: Optional[Any]) -> "Metric":
        """Pin THIS metric to a collective transport backend
        (``metrics_tpu.transport``); ``None`` restores the ambient
        resolution (context manager -> process global -> auto). A pinned
        metric syncs itself through its own backend and opts out of
        collection-level bundle packing (the bundle rides the ambient
        transport). Returns ``self`` for chaining."""
        if transport is not None:
            from metrics_tpu.transport import Transport

            if not isinstance(transport, Transport):
                raise TypeError(
                    f"expected a metrics_tpu.transport.Transport, got {transport!r}"
                )
        self.__dict__["_transport"] = transport
        return self

    @property
    def transport(self) -> Optional[Any]:
        """This metric's pinned transport backend (``None`` = ambient)."""
        return self.__dict__.get("_transport")

    def _resolve_transport(self) -> Any:
        from metrics_tpu.transport import resolve_transport

        return resolve_transport(self)

    @property
    def telemetry_key(self) -> str:
        """Stable per-instance telemetry key (``"<Class>#<ordinal>"``), under
        which this metric's counters/timers appear in
        ``observability.snapshot()``. Assigned lazily on first use; clones and
        unpickled copies get fresh keys (their counters start at zero)."""
        key = self.__dict__.get("_telemetry_key")
        if key is None:
            key = TELEMETRY.register(self)
            self._telemetry_key = key
        return key

    # ------------------------------------------------------------------
    # compute-group state sharing (see _ComputeGroup / collections.py)
    # ------------------------------------------------------------------

    def __getattr__(self, name: str) -> Any:
        # fires only when normal lookup fails: a grouped follower holds NO
        # state attributes of its own — reads delegate to the group owner's
        # live state, so five grouped metrics hold ONE state pytree
        d = object.__getattribute__(self, "__dict__")
        group = d.get("_compute_group")
        if group is not None and name in d.get("_defaults", ()):
            owner = group.owner
            if owner is not self:
                return getattr(owner, name)
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __setattr__(self, name: str, value: Any) -> None:
        # copy-on-write guard: a DIRECT write to a grouped member's state
        # (``precision.tp = 0``, including via a collection's items()/values())
        # detaches the member from its group first — siblings keep the
        # pre-write shared state — instead of silently corrupting them.
        # Pure-API calls are exempt (``_bound_state`` swaps a temporary state
        # in and out at dict level and raises the ``_group_bound`` depth);
        # internal machinery writes through ``_set_states``/``__dict__``.
        d = self.__dict__
        if (
            d.get("_compute_group") is not None
            and not d.get("_group_bound", 0)
            and name in d.get("_defaults", ())
        ):
            self._group_cow_detach(f"direct write to state `{name}`")
        object.__setattr__(self, name, value)

    def _group_cow_detach(self, reason: Optional[str]) -> None:
        """Leave the compute group, keeping every party's state intact.

        A detaching FOLLOWER materializes the current shared state into its
        own attributes; a detaching OWNER first hands the live state to the
        next member (ownership transfer), so siblings continue unaffected
        either way. With a ``reason`` this is a user-visible copy-on-write
        event (one-shot warning per group + ``group_cow_detach`` counters);
        ``None`` is the silent administrative form (group dissolution,
        ``load_state_dict``). A group shrunk to one member dissolves.
        """
        group = self.__dict__.get("_compute_group")
        if group is None:
            return
        owner = group.owner
        if owner is self:
            heirs = [m for m in group.members if m is not self]
            if heirs:
                new_owner = heirs[0]
                for name in self._defaults:
                    value = self.__dict__.get(name)
                    new_owner.__dict__[name] = list(value) if isinstance(value, list) else value
                group.owner = new_owner
        else:
            for name in self._defaults:
                value = getattr(owner, name)
                self.__dict__[name] = list(value) if isinstance(value, list) else value
        group.members = [m for m in group.members if m is not self]
        self.__dict__["_compute_group"] = None
        if len(group.members) == 1:
            group.members[0].__dict__["_compute_group"] = None
            group.members = []
        if reason is None:
            return
        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "group_cow_detach")
            if group.collection_key is not None:
                TELEMETRY.inc(group.collection_key, "group_cow_detach")
        if EVENTS.enabled:
            EVENTS.record("update", self.telemetry_key, path="group_cow_detach", reason=reason)
        if not group.warned:
            group.warned = True
            rank_zero_warn(
                f"{type(self).__name__} was detached from its compute group ({reason}):"
                " grouped metrics share ONE state, so out-of-band mutations apply to a"
                " private copy instead of corrupting the sibling metrics. The remaining"
                " members keep sharing their state; pass compute_groups=False to"
                " MetricCollection to disable grouping entirely.",
                UserWarning,
            )

    # ------------------------------------------------------------------
    # state registry
    # ------------------------------------------------------------------

    def add_state(
        self,
        name: str,
        default: StateValue,
        dist_reduce_fx: Optional[Union[str, Callable]] = None,
        persistent: bool = False,
        buffer: bool = False,
    ) -> None:
        """Register a state variable, accessible as ``self.<name>``.

        ``default`` is either an array (fixed-shape state) or an empty list
        (unbounded accumulator of per-batch arrays). ``dist_reduce_fx`` is one
        of ``"sum" | "mean" | "cat" | "max" | "min" | None`` or a custom
        callable receiving the stacked ``(world, ...)`` gather. String specs
        are kept symbolic so the in-graph path can lower them to the matching
        XLA collective (psum/pmean/pmax/pmin/all_gather) directly.

        ``buffer=True`` pins the state's persistence: :meth:`persistent` mode
        flips skip it, mirroring the reference's ``register_buffer`` states
        (e.g. binned-curve thresholds) which stay in ``state_dict`` regardless
        of ``Metric.persistent()``.
        """
        is_empty_list = isinstance(default, list) and not default
        if not (isinstance(default, ArrayTypes) or is_empty_list):
            raise ValueError("state variable must be a tensor or any empty list (where you can append tensors)")
        if isinstance(dist_reduce_fx, str):
            if dist_reduce_fx not in _STR_REDUCTIONS:
                raise ValueError("`dist_reduce_fx` must be callable or one of ['mean', 'sum', 'cat', None]")
        elif dist_reduce_fx is not None and not callable(dist_reduce_fx):
            raise ValueError("`dist_reduce_fx` must be callable or one of ['mean', 'sum', 'cat', None]")

        if isinstance(default, ArrayTypes):
            default = jnp.asarray(default)

        setattr(self, name, default if isinstance(default, ArrayTypes) else [])
        self._defaults[name] = deepcopy(default) if isinstance(default, list) else default
        self._persistent[name] = persistent
        self._buffers[name] = buffer
        self._reductions[name] = dist_reduce_fx

    # ------------------------------------------------------------------
    # pure-functional interface (jit / shard_map native)
    # ------------------------------------------------------------------

    def init_state(self) -> StateDict:
        """A fresh state pytree with every state at its default value."""
        return {
            name: ([] if isinstance(default, list) else default) for name, default in self._defaults.items()
        }

    def _get_states(self) -> StateDict:
        return {name: getattr(self, name) for name in self._defaults}

    def _set_states(self, state: StateDict) -> None:
        # internal write path: bypasses the compute-group copy-on-write guard
        # (library machinery — dispatch writebacks, sync adoption, reset —
        # owns the group discipline; only USER-facing mutations detach)
        for name, value in state.items():
            object.__setattr__(self, name, value)

    @contextmanager
    def _bound_state(self, state: StateDict):
        """Temporarily swap ``state`` in as the live state (pure-call plumbing).

        Operates on ``__dict__`` directly so a grouped member round-trips
        exactly: a follower's saved "state" is the ABSENCE of the attribute
        (reads delegate to the group owner), and restoring re-establishes
        that absence instead of materializing a stale private copy. The
        ``_group_bound`` depth marks update-body writes (``self.tp = ...``)
        as pure-call internals for the copy-on-write guard.
        """
        d = self.__dict__
        names = set(state) | set(self._defaults)
        saved = {name: d.get(name, _ABSENT) for name in names}
        saved_flags = (self._computed, self._update_called, self._forward_cache)
        depth = d.get("_group_bound", 0)
        for name, value in state.items():
            d[name] = value
        d["_group_bound"] = depth + 1
        try:
            yield
        finally:
            for name, value in saved.items():
                if value is _ABSENT:
                    d.pop(name, None)
                else:
                    d[name] = value
            d["_group_bound"] = depth
            self._computed, self._update_called, self._forward_cache = saved_flags

    def apply_update(self, state: StateDict, *args: Any, **kwargs: Any) -> StateDict:
        """Pure update: return the state advanced by this batch. Trace-safe."""
        # trace-entry hook: under jit/scan tracing this body runs once per
        # COMPILE, not per step — counting those entries host-side measures
        # compile churn without adding a single traced op
        if TELEMETRY.enabled and is_tracing(state, args, kwargs):
            TELEMETRY.inc(self.telemetry_key, "update_traces")
            MONITOR.note_trace(self.telemetry_key, arg_signature(*args, **kwargs))
        with compiled_scope(f"{self.__class__.__name__}.update"):
            with self._bound_state({k: (list(v) if isinstance(v, list) else v) for k, v in state.items()}):
                self._unwrapped_update(*args, **kwargs)
                new_state = self._get_states()
        if HEALTH.enabled:
            guard_state(self, new_state, source="apply_update")
        return new_state

    def apply_compute(self, state: StateDict, axis_name: Any = AXIS_UNSET) -> Any:
        """Pure compute: final value from ``state``.

        With ``axis_name`` (inside ``shard_map``/``pmap``) states are first
        synchronized across the named mesh axis with XLA collectives. When the
        argument is omitted it defaults to ``self.process_group`` — the
        constructor's declared mesh axis — so a metric built with
        ``process_group="data"`` syncs over that axis without every call site
        repeating it; passing ``axis_name=None`` explicitly disables sync.
        """
        if axis_name is AXIS_UNSET:
            axis_name = self.process_group
        if TELEMETRY.enabled and is_tracing(state):
            TELEMETRY.inc(self.telemetry_key, "compute_traces")
        with compiled_scope(f"{self.__class__.__name__}.compute"):
            state = self.sync_state(state, axis_name)
            with self._bound_state(state):
                return self._unwrapped_compute()

    def sync_state(self, state: StateDict, axis_name: Any, levels: Any = None) -> StateDict:
        """In-graph sync of a state pytree over ``axis_name`` (no compute);
        ``None`` returns the state untouched. Exposed so a caller holding
        several metrics with IDENTICAL states (a shared-update equivalence
        class in a :class:`MetricCollection`) can sync one bundle and fan it
        out instead of paying the collective payload once per member.

        Lowers through the bucketed engine
        (:func:`~metrics_tpu.utilities.distributed.sync_state_packed`): one
        collective per (kind, dtype) bucket instead of one per state leaf;
        callable custom reductions keep the per-leaf gather. A hierarchical
        spec — ``levels=[("ici", intra_axis), ("dcn", inter_axis)]``, or a
        :class:`~metrics_tpu.utilities.distributed.Hierarchy` passed as
        ``axis_name`` (e.g. the metric's ``process_group``) — lowers each
        bucket two-level instead: reduce within-host over ICI first, then
        across hosts over DCN, one collective per (level, kind, dtype)."""
        if axis_name is None:
            return state
        with compiled_scope(f"{self.__class__.__name__}.sync"):
            try:
                return self._resolve_transport().sync_state_packed(
                    state, self._reductions, axis_name, levels=levels
                )
            except NameError as err:  # unbound collective axis
                raise NameError(
                    f"{err}. This metric declares process_group={self.process_group!r}, which is"
                    " the default `axis_name` of the pure compute/forward API — collectives over"
                    " it only work inside shard_map/pmap binding that axis. To compute eagerly"
                    " (single-device, no sync), pass `axis_name=None` explicitly."
                ) from err

    def apply_forward(
        self,
        state: StateDict,
        *args: Any,
        axis_name: Any = AXIS_UNSET,
        batch_state: Optional[StateDict] = None,
        synced_batch_state: Optional[StateDict] = None,
        **kwargs: Any,
    ) -> Tuple[StateDict, Any]:
        """Pure forward: ``(accumulated_state, batch_value)`` in one update pass.

        The batch value reflects only this batch (synced over ``axis_name``
        when ``dist_sync_on_step``), matching the reference's dual-result
        forward contract (``metric.py:168-198``) at single-update cost.
        ``axis_name`` omitted defaults to ``self.process_group`` (see
        :meth:`apply_compute`). ``batch_state`` lets a caller
        (MetricCollection) supply the batch-local state from a shared update
        pass instead of recomputing it here; ``synced_batch_state``
        additionally supplies the ALREADY-SYNCED batch bundle for the
        on-step value (the collection syncs one bundle per shared-update
        class) — the accumulator still merges the LOCAL ``batch_state``, or
        cross-shard contributions would double-count at epoch sync.
        """
        if axis_name is AXIS_UNSET:
            axis_name = self.process_group
        if batch_state is None:
            batch_state = self.apply_update(self.init_state(), *args, **kwargs)
        if synced_batch_state is not None and self.dist_sync_on_step:
            value = self.apply_compute(synced_batch_state, axis_name=None)
        else:
            value = self.apply_compute(
                batch_state, axis_name=axis_name if (self.dist_sync_on_step and axis_name is not None) else None
            )
        if self._states_mergeable():
            new_state = self.merge_states(state, batch_state)
            # the merged accumulator never passes through apply_update's
            # guard; check it here or a NaN already in `state` (the
            # jit_forward accumulator) would go unwatched
            if HEALTH.enabled:
                guard_state(self, new_state, source="apply_forward")
        else:
            new_state = self.apply_update(state, *args, **kwargs)
        return new_state, value

    def _shared_update_key(self) -> Optional[Tuple]:
        """Hashable key identifying metrics whose per-batch update computes the
        same partial statistics (``None`` = not shareable). MetricCollection
        computes the statistics once per key and fans the deltas out — the
        "shared stat-scores state" staging of the reference's
        Accuracy+Precision+Recall+F1 collection (``collections.py`` keeps
        fully private states; see SURVEY §3.3).

        Opting in (returning a key) requires implementing the companion
        protocol: :meth:`_batch_deltas` (the shareable computation) and
        :meth:`_accumulate` (apply precomputed deltas to the live states)."""
        return None

    def _batch_deltas(self, *args: Any, **kwargs: Any) -> Tuple:
        """This batch's partial statistics — the shareable part of ``update``."""
        raise NotImplementedError(
            f"{self.__class__.__name__} returns a _shared_update_key but does not implement _batch_deltas"
        )

    def _accumulate(self, *deltas: Any) -> None:
        """Apply precomputed :meth:`_batch_deltas` output to the live states."""
        raise NotImplementedError(
            f"{self.__class__.__name__} returns a _shared_update_key but does not implement _accumulate"
        )

    def _update_from_deltas(self, *deltas: Any) -> None:
        """``update`` by precomputed deltas, with the same cache bookkeeping
        as the :meth:`_wrap_update` wrapper."""
        self._computed = None
        self._update_called = True
        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "update_calls")
        if EVENTS.enabled:
            EVENTS.record("update", self.telemetry_key, path="shared_deltas")
        self._accumulate(*deltas)
        if HEALTH.enabled:
            guard_state(self, self._get_states(), source="update")

    def _apply_accumulate(self, state: StateDict, deltas: Tuple) -> StateDict:
        """Pure analogue of :meth:`_accumulate`: state advanced by precomputed deltas."""
        with compiled_scope(f"{self.__class__.__name__}.update"):
            with self._bound_state({k: (list(v) if isinstance(v, list) else v) for k, v in state.items()}):
                self._accumulate(*deltas)
                new_state = self._get_states()
        if HEALTH.enabled:
            guard_state(self, new_state, source="apply_update")
        return new_state

    def _states_mergeable(self) -> bool:
        if not self._fusable:
            return False
        for name, fx in self._reductions.items():
            if isinstance(self._defaults[name], list):
                continue  # list accumulators always merge by extension
            if fx not in _MERGEABLE_REDUCTIONS:
                return False
        return True

    def merge_states(self, a: StateDict, b: StateDict) -> StateDict:
        """Merge two state pytrees according to each state's reduction."""
        merged: StateDict = {}
        for name, fx in self._reductions.items():
            va, vb = a[name], b[name]
            if isinstance(self._defaults[name], list):
                merged[name] = list(va) + list(vb)
            elif fx == "sum":
                merged[name] = va + vb
            elif fx == "max":
                merged[name] = jnp.maximum(va, vb)
            elif fx == "min":
                merged[name] = jnp.minimum(va, vb)
            elif fx == "cat":
                merged[name] = dim_zero_cat([va, vb])
            else:
                raise RuntimeError(f"State `{name}` with reduction {fx!r} is not mergeable")
        return merged

    # ------------------------------------------------------------------
    # stateful (eager) interface
    # ------------------------------------------------------------------

    @property
    def _unwrapped_update(self) -> Callable:
        return self.update.__wrapped__  # type: ignore[attr-defined]

    @property
    def _unwrapped_compute(self) -> Callable:
        return self.compute.__wrapped__  # type: ignore[attr-defined]

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Accumulate this batch and (if ``compute_on_step``) return its value."""
        if self.__dict__.get("_compute_group") is not None:
            # out-of-band accumulation: a standalone forward() on a grouped
            # member would advance the SHARED state for every sibling
            self._group_cow_detach("standalone forward() on a grouped member")
        with eager_span(f"{self.__class__.__name__}.forward"):
            if self._jit_forward_enabled:
                return self._forward_jitted(*args, **kwargs)
            if self._states_mergeable():
                return _observed_forward(
                    self, "forward_fused_calls", lambda: self._forward_fused(*args, **kwargs)
                )
            return _observed_forward(
                self, "forward_double_update_calls", lambda: self._forward_double_update(*args, **kwargs)
            )

    def jit_forward(self, enable: bool = True, donate: bool = True) -> "Metric":
        """Compile the stateful ``forward`` into one XLA program (opt-in).

        The default eager ``m(preds, target)`` dispatches each jnp op to the
        backend individually — convenient and fully validated, but host-bound
        (milliseconds per step of pure dispatch overhead). After
        ``m.jit_forward()`` the same call runs an AOT-compiled executable of
        the pure :meth:`apply_forward`, so update + on-step value execute as
        one compiled program (microseconds per step) behind the unchanged
        stateful API::

            acc = Accuracy().jit_forward()
            acc.warmup(preds0, target0)          # optional: compile NOW
            for preds, target in loader:
                batch_acc = acc(preds, target)   # one compiled step
            acc.compute()                        # epoch sync as usual

        The executable **donates the state argument** (``donate_argnums=(0,)``
        in user terms — the ``docs/performance.md`` guidance, applied to our
        own hot path): XLA reuses the state buffers in place instead of
        copying the full pytree every step, which is megabytes/step for
        ``capacity=N`` curve buffers and ``FID(streaming=True)``'s O(d²)
        moment sums. The metric owns its state arrays afterwards — a state
        leaf still referenced outside the metric (a kept handle to
        ``m.some_state``) is detected per dispatch and that step transparently
        uses the copying executable instead, with a one-shot warning (counted
        under ``jit_forward_alias_fallbacks``). ``donate=False`` opts out of
        donation entirely (always-copying lowering, bit-identical results).

        The trade, inherent to tracing: host-side input *validation* is
        skipped (shape/dtype errors still surface from XLA; value checks
        like out-of-range targets do not), every new input shape pays one
        recompile (see :meth:`warmup` to pay it deliberately), and
        configuration the eager path infers from concrete input VALUES must
        be passed explicitly — e.g. integer label predictions need
        ``num_classes=`` at construction, or the first jitted call raises
        the pure API's documented trace-time error. Python ``bool`` (and
        string) arguments are STATIC — baked into the executable per value,
        the ``FID(...)(imgs, real=True)`` flag pattern. Not available —
        raises ``ValueError`` — for metrics with unbounded list states
        (their state pytree grows per step, forcing a retrace each call; use
        the fixed-shape ``capacity=``/``streaming=`` modes), or with
        ``dist_sync_on_step=True`` (the eager on-step gather is host-side;
        use :meth:`apply_forward` with a mesh axis instead).
        """
        if not enable:
            self._jit_forward_enabled = False
            self._drop_compiled_dispatch()
            return self
        self._jit_forward_gate()
        self._jit_forward_enabled = True
        self._jit_forward_donate = bool(donate)
        self._drop_compiled_dispatch()
        return self

    def _drop_compiled_dispatch(self) -> None:
        """Invalidate every cached compiled-dispatch executable (donation
        flag changed, enablement toggled, unpickled copy)."""
        self._jit_forward_fn = None
        self._jit_forward_copy_fn = None
        self._update_many_fn = None
        self._update_many_copy_fn = None

    def _compiled_state_gate(self) -> None:
        """Raise ``ValueError`` if the state pytree cannot thread a compiled
        stateful dispatch generically — shared by :meth:`jit_forward` and
        :meth:`update_many`; side-effect free, so callers (MetricCollection)
        can validate members without touching their own enablement."""
        if any(isinstance(v, list) for v in self._defaults.values()):
            hint = f" {self._sketch_hint}" if self._sketch_hint else ""
            raise ValueError(
                f"{self.__class__.__name__} holds unbounded list states, whose pytree grows"
                " every step under jit (a retrace per call); use the fixed-shape"
                " `capacity=`/`streaming=` mode of this metric with jit_forward, or keep the"
                f" eager forward.{hint}"
            )
        if set(self.init_state()) != set(self._defaults):
            # wrappers like BootStrapper own a custom pure-state layout the
            # stateful _get_states/_set_states pair does not round-trip
            raise ValueError(
                f"{self.__class__.__name__} overrides the pure-state protocol (its init_state"
                " keys differ from the registered states), so its stateful forward cannot be"
                " jitted generically; jit a function over its pure apply_update/apply_compute"
                " API instead."
            )

    def _jit_forward_gate(self) -> None:
        """The :meth:`_compiled_state_gate` plus the forward-only refusal."""
        self._compiled_state_gate()
        if self.dist_sync_on_step:
            raise ValueError(
                "jit_forward cannot trace the eager on-step gather of dist_sync_on_step=True;"
                " use apply_forward with a mesh axis for compiled on-step sync."
            )

    # -- compiled dispatch plumbing (donation + AOT executable cache) -------

    def _forward_dispatch(self) -> CompiledDispatch:
        if self._jit_forward_fn is None:
            if self.compute_on_step:
                fn: Callable = functools.partial(self.apply_forward, axis_name=None)
            else:
                fn = self.apply_update
            self._jit_forward_fn = CompiledDispatch(fn, donate_state=self._jit_forward_donate)
            self._jit_cache_seen = 0
        return self._jit_forward_fn

    def _forward_copy_dispatch(self) -> CompiledDispatch:
        """The non-donating fallback executable for externally-aliased states."""
        if self._jit_forward_copy_fn is None:
            if self.compute_on_step:
                fn: Callable = functools.partial(self.apply_forward, axis_name=None)
            else:
                fn = self.apply_update
            self._jit_forward_copy_fn = CompiledDispatch(fn, donate_state=False)
        return self._jit_forward_copy_fn

    def _donation_safe_state(self, state: StateDict) -> Tuple[StateDict, bool]:
        """Make ``state`` safe to donate, or report that it is not.

        Two hazards. (1) A leaf that IS the registered default — a fresh or
        just-reset metric — would, donated, invalidate every future
        ``reset()``; such leaves are defensively copied (one copy, once per
        epoch — exactly the copy donation saves on every other step).
        (2) A leaf some caller still holds a handle to: donating it would
        invalidate the caller's array mid-use, so the dispatch must fall
        back to the copying executable. Detection is by reference count —
        beyond the metric's own references (the attribute slot, this
        ``state`` dict, the loop variable, and ``getrefcount``'s argument)
        any extra reference is an external handle.
        """
        aliased = None
        for name in state:
            v = state[name]
            if not isinstance(v, ArrayTypes):
                continue  # list states never reach the compiled path (the gate)
            if v is self._defaults.get(name):
                state[name] = jnp.asarray(v).copy()
                continue
            if sys.getrefcount(v) > 4:
                aliased = name
                break
        if aliased is None:
            return state, True
        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "jit_forward_alias_fallbacks")
        if not self.__dict__.get("_donation_warned", False):
            self._donation_warned = True
            rank_zero_warn(
                f"{self.__class__.__name__}.jit_forward: state `{aliased}` is referenced"
                " outside the metric, so this step dispatches through the copying"
                " executable instead of donating the state buffers (donation would"
                " invalidate the external handle). Drop external references to metric"
                " states to restore zero-copy updates, or call jit_forward(donate=False)"
                " to keep the copying path silently.",
                UserWarning,
            )
        return state, False

    def _forward_jitted(self, *args: Any, **kwargs: Any) -> Any:
        fn = self._forward_dispatch()
        # ownership discipline for donation: these caches are invalidated by
        # the incoming batch anyway; clearing them BEFORE the alias check
        # means a cached compute() result that aliases a state leaf cannot be
        # donated out from under a caller still holding it
        self._computed = None
        self._forward_cache = None
        state = self._get_states()
        if fn.donate_state:
            state, donatable = self._donation_safe_state(state)
            if not donatable:
                fn = self._forward_copy_dispatch()
        prof = PROFILER.begin("compiled", state)
        start = time.perf_counter() if (EVENTS.enabled or TELEMETRY.enabled) else None
        out = fn(state, *args, **kwargs)
        submitted = time.perf_counter() if (start is not None or prof is not None) else None
        if prof is not None:
            PROFILER.finish(prof, out, self.telemetry_key, fn, submit_end=submitted)
        if start is not None:
            # wall time of the (async) dispatch, not the device step — the
            # device cost lives in the profiler trace this timeline rides next to
            dur = submitted - start
            if TELEMETRY.enabled:
                observe_dispatch(dur, "compiled")
            if EVENTS.enabled:
                EVENTS.record(
                    "forward",
                    self.telemetry_key,
                    dur_s=dur,
                    t_start=start,
                    path="compiled",
                    compiled_this_call=bool(fn.last_compiled),
                    donated=fn.donate_state,
                )
        if TELEMETRY.enabled:
            _note_compiled_dispatch(self, fn, args, kwargs)
        new_state, value = out if self.compute_on_step else (out, None)
        self._set_states(new_state)
        self._update_called = True
        self._computed = None
        self._forward_cache = value
        return value

    def warmup(self, *sample_batch: Any, **kwargs: Any) -> Dict[str, Any]:
        """AOT lower+compile the ``jit_forward`` executable for this batch
        shape, ahead of the first step.

        Without warmup the first ``m(preds, target)`` after
        :meth:`jit_forward` pays trace+compile at an uncontrolled moment
        inside the step; ``m.warmup(*sample_batch)`` pays it here — nothing
        executes, no state changes — records a ``compile`` timeline event,
        and caches the executable keyed by the arguments' avals, so the
        first real step is a cache hit. Enables :meth:`jit_forward` if not
        already enabled (same eligibility ``ValueError``\\ s). Idempotent per
        shape: a second warmup on the same avals is a no-op hit.

        Returns the cost report of the compiled program (the
        :meth:`cost_report` structure for the forward executable, from the
        compiler's own ``cost_analysis`` — no extra compile), plus the
        compile bookkeeping::

            {"metric": ..., "compiled_this_call": bool, "compile_seconds": s,
             "donated": bool, "executables_cached": n,
             "forward": {"available": True, "flops": ..., ...},
             "state_memory": {...}}
        """
        if not self._jit_forward_enabled:
            self.jit_forward(donate=self._jit_forward_donate)
        fn = self._forward_dispatch()
        state = self._get_states()
        # lowering only reads avals: no execution, no donation hazard
        start = time.perf_counter()
        compiled, fresh = fn.warm(state, *sample_batch, **kwargs)
        key = self.telemetry_key
        if TELEMETRY.enabled:
            TELEMETRY.inc(key, "warmup_calls")
            if fresh:
                TELEMETRY.inc(key, "warmup_compiles")
        if EVENTS.enabled:
            EVENTS.record(
                "compile",
                key,
                dur_s=fn.last_compile_s,
                t_start=start,
                path="warmup",
                fresh=fresh,
                donated=fn.donate_state,
                signature=arg_signature(*sample_batch, **kwargs),
            )
        from metrics_tpu.observability.cost import executable_cost

        return {
            "metric": type(self).__name__,
            "compiled_this_call": fresh,
            "compile_seconds": round(fn.last_compile_s, 6),
            "donated": fn.donate_state,
            "executables_cached": fn._cache_size(),
            "forward": executable_cost(compiled),
            "state_memory": self.state_memory_report(),
        }

    # -- scan-fused micro-batching ------------------------------------------

    def _scan_update_many(self, state: StateDict, stacked: Tuple, stacked_kwargs: Dict) -> StateDict:
        """Pure K-micro-batch update: one ``lax.scan`` of :meth:`apply_update`
        over the stacked leading axis. Leaves with rank >= 1 are scanned;
        0-d leaves (python numbers, flags) broadcast to every micro-batch."""
        leaves, treedef = jax.tree_util.tree_flatten((stacked, stacked_kwargs))
        scanned_ix = [i for i, leaf in enumerate(leaves) if getattr(leaf, "ndim", 0) >= 1]

        def body(s: StateDict, xs: Tuple) -> Tuple[StateDict, None]:
            merged = list(leaves)
            for i, x in zip(scanned_ix, xs):
                merged[i] = x
            args, kwargs = jax.tree_util.tree_unflatten(treedef, merged)
            return self.apply_update(s, *args, **kwargs), None

        new_state, _ = jax.lax.scan(body, state, tuple(leaves[i] for i in scanned_ix))
        return new_state

    def _update_many_dispatch(self, donatable: bool) -> CompiledDispatch:
        if donatable and self._jit_forward_donate:
            if self._update_many_fn is None:
                self._update_many_fn = CompiledDispatch(self._scan_update_many, donate_state=True)
            return self._update_many_fn
        if self._update_many_copy_fn is None:
            self._update_many_copy_fn = CompiledDispatch(self._scan_update_many, donate_state=False)
        return self._update_many_copy_fn

    def update_many(self, *stacked: Any, **stacked_kwargs: Any) -> None:
        """Accumulate K stacked micro-batches in ONE compiled dispatch.

        Every array argument (positional or keyword) carries a leading axis
        of size K — ``update_many(preds_KBC, target_KB)`` is equivalent to K
        successive ``update(preds, target)`` calls, but runs as a single
        ``lax.scan`` over the donated state: one host dispatch amortized
        over K updates. This is the missing middle ground between the
        per-call compiled step (:meth:`jit_forward`, one dispatch per batch)
        and fusing a whole epoch into your own scanned program
        (``docs/performance.md``) — reach for it when batches arrive in
        chunks (a prefetch queue, a K-step evaluation window) but the epoch
        loop stays host-driven. Scalar python/0-d leaves broadcast to every
        micro-batch; ``bool`` flags are static, so
        ``fid.update_many(imgs_K, real=True)`` works.

        No per-batch values are produced (this is ``update``, not
        ``forward``); ``compute()`` afterwards sees all K batches. Shares
        :meth:`jit_forward`'s state-donation discipline and its
        ``donate=False`` opt-out; the same eligibility rules apply
        (``ValueError`` for unbounded list states).
        """
        if self.__dict__.get("_compute_group") is not None:
            self._group_cow_detach("standalone update_many() on a grouped member")
        self._compiled_state_gate()
        k = _microbatch_len(stacked, stacked_kwargs)
        self._computed = None
        self._forward_cache = None
        state = self._get_states()
        donatable = True
        if self._jit_forward_donate:
            state, donatable = self._donation_safe_state(state)
        fn = self._update_many_dispatch(donatable)
        prof = PROFILER.begin("update_many", state)
        start = time.perf_counter() if (TELEMETRY.enabled or EVENTS.enabled) else None
        new_state = fn(state, stacked, stacked_kwargs)
        submitted = time.perf_counter() if (start is not None or prof is not None) else None
        if prof is not None:
            PROFILER.finish(prof, new_state, self.telemetry_key, fn, submit_end=submitted)
        if start is not None:
            dur = submitted - start
            key = self.telemetry_key
            if TELEMETRY.enabled:
                TELEMETRY.inc(key, "update_many_calls")
                TELEMETRY.inc(key, "update_many_batches", k)
                observe_dispatch(dur, "update_many")
                _note_compiled_dispatch(
                    self, fn, stacked, stacked_kwargs, counter="update_many_dispatches"
                )
            if EVENTS.enabled:
                EVENTS.record(
                    "update",
                    key,
                    dur_s=dur,
                    t_start=start,
                    path="scan_microbatch",
                    batches=k,
                    compiled_this_call=bool(fn.last_compiled),
                    donated=fn.donate_state,
                )
        self._set_states(new_state)
        self._update_called = True
        self._computed = None

    def _forward_fused(self, *args: Any, _update_thunk: Optional[Callable] = None, **kwargs: Any) -> Any:
        accumulated = self._get_states()
        self._set_states(self.init_state())
        # single update pass: batch-local state (the thunk lets MetricCollection
        # substitute precomputed shared deltas for the full update)
        if _update_thunk is None:
            self._unwrapped_update(*args, **kwargs)
        else:
            _update_thunk()
        self._update_called = True
        self._computed = None

        # capture the batch-local state BEFORE compute() may sync it in place:
        # merging a world-reduced state into the local accumulator would
        # double-count across ranks at epoch-end sync
        batch_state = self._get_states()

        result = None
        if self.compute_on_step:
            self._to_sync = self.dist_sync_on_step
            self._restore_cache = False
            self._forward_cache = self.compute()
            result = self._forward_cache

        self._set_states(self.merge_states(accumulated, batch_state))
        self._restore_cache = True
        self._to_sync = True
        self._computed = None
        if HEALTH.enabled:
            # eager accumulator after the merge: concrete values, so policy
            # "raise" surfaces MetricHealthError from this forward call
            guard_state(self, self._get_states(), source="forward")
        return result

    def _forward_double_update(self, *args: Any, **kwargs: Any) -> Any:
        """Reference-faithful fallback (``metric.py:168-198``) for non-mergeable states."""
        self.update(*args, **kwargs)
        if not self.compute_on_step:
            return None

        self._to_sync = self.dist_sync_on_step
        self._restore_cache = False
        cache = self._get_states()

        self.reset()
        self.update(*args, **kwargs)
        self._forward_cache = self.compute()

        self._set_states(cache)
        self._update_called = True
        self._restore_cache = True
        self._to_sync = True
        self._computed = None
        return self._forward_cache

    def _wrap_update(self, update: Callable) -> Callable:
        @functools.wraps(update)
        def wrapped_func(*args: Any, **kwargs: Any) -> Any:
            if self.__dict__.get("_compute_group") is not None and not self.__dict__.get("_group_bound", 0):
                self._group_cow_detach("standalone update() on a grouped member")
            self._computed = None
            self._update_called = True
            observed = TELEMETRY.enabled or EVENTS.enabled
            if not observed and not HEALTH.enabled:
                return update(*args, **kwargs)
            start = time.perf_counter()
            try:
                result = update(*args, **kwargs)
            finally:
                if observed:
                    dur = time.perf_counter() - start
                    key = self.telemetry_key
                    if TELEMETRY.enabled:
                        TELEMETRY.inc(key, "update_calls")
                        TELEMETRY.observe(key, "update", dur)
                    if EVENTS.enabled:
                        EVENTS.record("update", key, dur_s=dur, t_start=start)
            if HEALTH.enabled:
                guard_state(self, self._get_states(), source="update")
            return result

        return wrapped_func

    def _wrap_compute(self, compute: Callable) -> Callable:
        @functools.wraps(compute)
        def wrapped_func(*args: Any, **kwargs: Any) -> Any:
            if not self._update_called:
                rank_zero_warn(
                    f"The ``compute`` method of metric {self.__class__.__name__}"
                    " was called before the ``update`` method which may lead to errors,"
                    " as metric states have not yet been updated.",
                    UserWarning,
                )
            if TELEMETRY.enabled:
                TELEMETRY.inc(self.telemetry_key, "compute_calls")
            if self._computed is not None:
                return self._computed
            start = time.perf_counter() if (TELEMETRY.enabled or EVENTS.enabled) else None
            with self.sync_context(
                dist_sync_fn=self.dist_sync_fn,
                should_sync=self._to_sync,
                restore_cache=self._restore_cache,
            ):
                self._computed = compute(*args, **kwargs)
            if start is not None:
                dur = time.perf_counter() - start
                if TELEMETRY.enabled:
                    TELEMETRY.observe(self.telemetry_key, "compute", dur)
                if EVENTS.enabled:
                    EVENTS.record("compute", self.telemetry_key, dur_s=dur, t_start=start)
            return self._computed

        return wrapped_func

    # ------------------------------------------------------------------
    # cross-process sync (eager / epoch-boundary path)
    # ------------------------------------------------------------------

    def _pre_sync_states(self) -> Tuple[StateDict, Dict[str, Any]]:
        """The gather-ready view of the live states, plus dtype notes.

        Pre-concatenates EVERY list state — regardless of its reduction, as
        the reference does (metric.py:203-206) — so each costs exactly one
        gather. This is also what keeps ranks with different per-rank batch
        counts issuing the same NUMBER of collectives: un-concatenated
        None-reduce lists would gather once per batch and deadlock on the
        rank with fewer batches. A never-updated (empty) list state still
        participates with a 0-length placeholder; the gather protocol
        aligns its ndim/dtype to the peers'. The returned dtype notes record
        each non-empty list state's element dtype so an all-ranks-empty sync
        can restore it (the placeholder is float32 regardless of the data)."""
        states = self._get_states()
        list_dtypes: Dict[str, Any] = {}
        for name in self._reductions:
            value = states[name]
            if isinstance(value, list):
                if value:
                    cat = dim_zero_cat(value)
                    list_dtypes[name] = cat.dtype
                    states[name] = [cat]
                else:
                    states[name] = [jnp.zeros((0,), jnp.float32)]
        return states, list_dtypes

    def _apply_gathered_states(
        self,
        gathered: StateDict,
        list_dtypes: Dict[str, Any],
        presynced: Optional[StateDict] = None,
    ) -> None:
        """Reduce the per-member gather results into the live states
        (stack + reduction for tensor states, flatten + cat for list states,
        empty-shard dropping, all-empty dtype restore). ``presynced`` holds
        leaves the transport ALREADY reduced in place (the sharded backend's
        elementwise states) — set directly, never stacked, so a
        device-sharded giant leaf is not copied through the host protocol."""
        for name, fx in self._reductions.items():
            if presynced is not None and name in presynced:
                setattr(self, name, presynced[name])
                continue
            value = gathered[name]
            if isinstance(value[0], ArrayTypes):
                value = jnp.stack([jnp.asarray(v) for v in value])
            elif isinstance(value[0], list):
                value = _flatten(value)
                # drop empty shards (ranks that never updated) so the cat
                # result keeps the data's dtype/shape; keep one if all empty
                filled = [v for v in value if jnp.asarray(v).size > 0]
                if len(filled) < len(value):
                    value = filled or value[:1]
                if not filled and name in list_dtypes:
                    # every rank was empty: the kept entry is the float32
                    # 0-length placeholder, but THIS rank's (zero-row) data
                    # declared a dtype — restore it so the synced state
                    # cannot silently flip dtype under compute()
                    value = [jnp.asarray(v, list_dtypes[name]) for v in value]
            reduction_fn = _resolve_reduction(fx)
            if not (callable(reduction_fn) or reduction_fn is None):
                raise TypeError("reduction_fn must be callable or None")
            setattr(self, name, reduction_fn(value) if reduction_fn is not None else value)

    def _note_sync_telemetry(self, states: StateDict) -> Optional[int]:
        """Per-metric sync counters; returns the payload byte count (or None
        when nothing records)."""
        if not (TELEMETRY.enabled or EVENTS.enabled):
            return None
        from metrics_tpu.observability.cost import pytree_nbytes

        payload_bytes = pytree_nbytes(states)
        if TELEMETRY.enabled:
            key = self.telemetry_key
            TELEMETRY.inc(key, "sync_calls")
            TELEMETRY.inc(key, "sync_payload_bytes", payload_bytes)
        return payload_bytes

    def _sync_dist(self, dist_sync_fn: Callable = gather_all_arrays, process_group: Optional[Any] = None) -> None:
        states, list_dtypes = self._pre_sync_states()
        payload_bytes = self._note_sync_telemetry(states)

        sync_start = time.perf_counter() if EVENTS.enabled else None
        group = process_group or self.process_group
        # collective span around the epoch sync: a deterministic id shared by
        # every participating process, correlating this metric's gather on the
        # merged fleet timeline (observability/tracing.py)
        tr_span = TRACER.begin("sync", group=repr(group), bucket="metric") if TRACER.enabled else None
        presynced: Optional[StateDict] = None
        if dist_sync_fn is gather_all_arrays:
            # the default path dispatches through the ACTIVE transport
            # (metrics_tpu.transport): device-resident backends reduce the
            # elementwise leaves in place (sharding-preserving — a giant
            # sharded state never materializes on one host), and whatever
            # remains packs into one descriptor round + one payload round
            # (see gather_all_pytrees)
            transport = self._resolve_transport()
            presynced = transport.reduce_states(states, self._reductions, group=group)
            if presynced:
                rest = {k: v for k, v in states.items() if k not in presynced}
                gathered = (
                    transport.gather_pytrees([rest], group=group)[0] if rest else {}
                )
            else:
                presynced = None
                gathered = transport.gather_pytrees([states], group=group)[0]
        else:
            # injected custom gathers keep the documented per-leaf contract
            gathered = apply_to_collection(states, ArrayTypes, dist_sync_fn, group=group)
        span_id = TRACER.end(tr_span, metric=self.telemetry_key) if tr_span else None
        if sync_start is not None:
            EVENTS.record(
                "sync",
                self.telemetry_key,
                dur_s=time.perf_counter() - sync_start,
                t_start=sync_start,
                payload_bytes=payload_bytes,
                span_id=span_id,
            )

        self._apply_gathered_states(gathered, list_dtypes, presynced=presynced)

    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        distributed_available: Callable = distributed_available,
    ) -> StateDict:
        """Synchronize states across processes; returns the pre-sync local cache
        (empty dict when no sync happened)."""
        is_distributed = distributed_available()
        if not should_sync or not (is_distributed or dist_sync_fn is not None):
            return {}
        if dist_sync_fn is None:
            dist_sync_fn = gather_all_arrays
        cache = self._get_states()
        self._sync_dist(dist_sync_fn, process_group=process_group)
        return cache

    @contextmanager
    def sync_context(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        restore_cache: bool = True,
        distributed_available: Callable = distributed_available,
    ):
        """Sync states for the duration of the block, then restore the local
        (unsynced) states so accumulation can continue."""
        cache = self.sync(
            dist_sync_fn=dist_sync_fn,
            process_group=process_group,
            should_sync=should_sync,
            distributed_available=distributed_available,
        )
        yield
        if cache and restore_cache:
            self._set_states(cache)

    def compute_async(
        self,
        *,
        on_degraded: str = "retry",
        round_timeout_s: Optional[float] = None,
        max_retries: Optional[int] = None,
        backoff_s: Optional[float] = None,
    ) -> "Any":
        """Epoch-end compute with the cross-process gather OFF the step path.

        Snapshots the current states into a detached shadow copy (one state
        copy — the same once-per-epoch cost the donation discipline already
        pays at ``reset()``; the live metric is never touched again) and
        hands the descriptor+payload gather rounds to the background sync
        engine (:mod:`metrics_tpu.utilities.async_sync`), overlapped with
        whatever ``update()``/``forward()`` steps follow. Returns a
        :class:`~metrics_tpu.utilities.async_sync.SyncFuture` whose
        ``result()`` is exactly what a synchronous :meth:`compute` at the
        snapshot moment would have returned; ``compute()`` itself is
        untouched and stays the synchronous path.

        ``on_degraded`` picks the degraded-link policy the engine applies
        when :func:`~metrics_tpu.observability.tracing.degraded_processes`
        flags peers or a transport round times out (``round_timeout_s``):
        ``"retry"`` (bounded backoff), ``"stale"`` (serve the last completed
        generation, ``future.stale=True``), or ``"quorum"`` (reduce over the
        healthy subgroup via the existing group plumbing). **Collective
        discipline applies across processes**: every process must submit the
        same ``compute_async`` calls in the same order, exactly as for
        ``compute()`` — the engine's FIFO worker preserves that order.
        """
        from metrics_tpu.utilities.async_sync import get_engine

        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "compute_async_calls")
        shadow = self.clone()
        # each policy ATTEMPT computes on its own clone of the snapshot: a
        # timed-out transport round cannot be cancelled, only orphaned, and
        # the orphan must not race the retry on shared state (the per-attempt
        # clone runs on the worker, off the hot path)
        return get_engine().submit(
            self.telemetry_key,
            lambda: shadow.clone().compute(),
            on_degraded=on_degraded,
            round_timeout_s=round_timeout_s,
            max_retries=max_retries,
            backoff_s=backoff_s,
        )

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    @abstractmethod
    def update(self) -> None:
        """Override to advance the metric states with a batch of inputs."""

    @abstractmethod
    def compute(self) -> Any:
        """Override to produce the final value from (synced) states."""

    def _reset_flags(self) -> None:
        """Clear the per-epoch bookkeeping (shared with wrapper overrides of
        ``reset`` that must not rebuild state through ``init_state``)."""
        self._update_called = False
        self._forward_cache = None
        self._computed = None

    def reset(self) -> None:
        """Restore every state to its default."""
        if self.__dict__.get("_compute_group") is not None:
            # a standalone reset on one grouped member must not wipe the
            # siblings' accumulation; MetricCollection.reset() resets the
            # shared state once per group without detaching anyone
            self._group_cow_detach("standalone reset() on a grouped member")
        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "reset_calls")
        self._reset_flags()
        self._set_states(self.init_state())

    def clone(self) -> "Metric":
        return deepcopy(self)

    def _restore_derived(self, state: StateDict) -> None:
        """Refresh update-derived Python attributes from a restored state.

        Some metrics learn configuration from their first batch (e.g.
        ``Accuracy.mode``) and keep it as a plain attribute alongside a
        synced bookkeeping state. A clone/pickle carries the attribute, but
        a checkpoint restored into a FRESH instance does not — the
        durability plane calls this hook after installing restored states
        so such metrics can decode their derived attributes eagerly
        (``state`` holds the restored leaves, possibly tenant-stacked:
        decode with reductions over the leading axes). Default: no-op."""

    def keyed(self, num_tenants: int, **kwargs: Any) -> "Metric":
        """An N-tenant stacked view of this metric: one
        :class:`~metrics_tpu.wrappers.multitenant.KeyedMetric` holding the
        state for ``num_tenants`` logical streams on a leading tenant axis,
        updated by a single donated segment-scatter dispatch per step. The
        keyed state starts fresh at the defaults (this instance's accumulated
        state is not inherited)."""
        from metrics_tpu.wrappers.multitenant import KeyedMetric

        return KeyedMetric(self, num_tenants, **kwargs)

    def persistent(self, mode: bool = False) -> None:
        for key in self._persistent:
            if not self._buffers.get(key, False):
                self._persistent[key] = mode

    def state_dict(self, destination: Optional[dict] = None, prefix: str = "") -> dict:
        """Serialize persistent states, synced across processes first so the
        saved values are rank-aggregated (parity: ``metric.py:408-424``)."""
        destination = {} if destination is None else destination
        with self.sync_context(dist_sync_fn=self.dist_sync_fn):
            for key in self._defaults:
                if self._persistent[key]:
                    current = getattr(self, key)
                    if isinstance(current, list):
                        destination[prefix + key] = [np.asarray(v) for v in current]
                    else:
                        destination[prefix + key] = np.asarray(current)
        return destination

    def _should_load_from_state_dict(self) -> bool:
        # saved states are already rank-aggregated -> only global rank 0 reloads
        if "GLOBAL_RANK" in os.environ:
            return os.environ["GLOBAL_RANK"] == "0"
        try:
            return jax.process_index() == 0
        except Exception:  # pragma: no cover
            return True

    def load_state_dict(self, state_dict: dict, prefix: str = "") -> None:
        if self.__dict__.get("_compute_group") is not None and any(
            prefix + key in state_dict for key in self._defaults
        ):
            # the restored per-member state must be honored even when it
            # diverges from the group's shared state: silent detach, then
            # load into this member's own attributes. The owning collection's
            # next compiled dispatch rebuilds groups (value-checked).
            self._group_cow_detach(None)
        for key in self._defaults:
            name = prefix + key
            if name in state_dict:
                value = state_dict[name]
                if self._should_load_from_state_dict():
                    if isinstance(value, list):
                        setattr(self, key, [jnp.asarray(v) for v in value])
                    else:
                        setattr(self, key, jnp.asarray(value))

    # ------------------------------------------------------------------
    # observability reports
    # ------------------------------------------------------------------

    def check_health(self, state: Optional[StateDict] = None) -> Dict[str, Any]:
        """Numerical health report of ``state`` (default: the live stateful
        states): per-state NaN/Inf element counts plus the zero total-weight
        flag for mean-style denominators. Works at any health policy — an
        explicit check never raises or warns, but an unhealthy result records
        a ``health`` event and the per-metric ``health_events`` counter.
        Eager only: values are read to the host (pass concrete states).

        The automatic per-update guard — the policy-driven, jit-compatible
        version of this check — is enabled with
        ``observability.set_health_policy("record" | "warn" | "raise")``;
        see :mod:`metrics_tpu.observability.health`.
        """
        from metrics_tpu.observability.health import check_state

        return check_state(self, self._get_states() if state is None else state)

    def state_memory_report(self) -> Dict[str, Any]:
        """Bytes held by each registered state right now.

        Reads array metadata only (shape x itemsize) — no device->host
        transfer. List accumulators report their element count alongside the
        summed bytes, which is how unbounded "cat" states show their growth.
        """
        from metrics_tpu.observability.cost import leaf_nbytes

        per_state: Dict[str, Any] = {}
        total = 0
        for name in self._defaults:
            value = getattr(self, name)
            nbytes = leaf_nbytes(value)
            entry: Dict[str, Any] = {"bytes": int(nbytes)}
            if isinstance(value, list):
                entry["elements"] = len(value)
            per_state[name] = entry
            total += nbytes
        return {"per_state": per_state, "total_bytes": int(total)}

    def cost_report(self, *example_batch: Any, **kwargs: Any) -> Dict[str, Any]:
        """XLA cost estimate of this metric's per-step programs on an example
        batch: FLOPs, bytes accessed, and compiled memory sizes for the
        ``apply_update`` step (and the epoch-end ``apply_compute``), plus the
        current :meth:`state_memory_report`.

        Built on ``jit(...).lower().compile().cost_analysis()`` — nothing is
        executed, only compiled. Metrics that infer configuration from input
        VALUES (the documented jit constraint) report
        ``{"available": False, "error": ...}`` for the affected program
        instead of raising; construct them with explicit config
        (``num_classes=``, ...) to get numbers.
        """
        from metrics_tpu.observability.cost import program_cost

        state = self.init_state()
        report: Dict[str, Any] = {
            "metric": type(self).__name__,
            "update": program_cost(self.apply_update, state, *example_batch, **kwargs),
            "state_memory": self.state_memory_report(),
        }
        try:
            updated = jax.eval_shape(self.apply_update, state, *example_batch, **kwargs)
            report["compute"] = program_cost(
                functools.partial(self.apply_compute, axis_name=None), updated
            )
        except Exception as err:
            report["compute"] = {"available": False, "error": f"{type(err).__name__}: {err}"}
        return report

    # ------------------------------------------------------------------
    # misc protocol
    # ------------------------------------------------------------------

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        """Keep only kwargs accepted by this metric's ``update`` signature."""
        var_kinds = (inspect.Parameter.VAR_POSITIONAL, inspect.Parameter.VAR_KEYWORD)
        params = self._update_signature.parameters
        filtered = {k: v for k, v in kwargs.items() if k in params and params[k].kind not in var_kinds}
        return filtered if filtered else kwargs

    def __getstate__(self) -> dict:
        # the cached compiled executables are rebuilt lazily (unpicklable,
        # device-bound); the telemetry key/cache-watermark/one-shot warning
        # stay with the ORIGINAL instance — clones and unpickled copies
        # register (and, if it comes to it, warn) fresh
        state = {
            k: v
            for k, v in self.__dict__.items()
            if k not in ("update", "compute", "_update_signature", "_jit_forward_fn",
                         "_jit_forward_copy_fn", "_update_many_fn", "_update_many_copy_fn",
                         "_telemetry_key", "_jit_cache_seen", "_donation_warned",
                         "_compute_group", "_group_bound", "_transport")
        }
        if self.__dict__.get("_compute_group") is not None:
            # a grouped member's dict may hold no state attributes at all
            # (follower) — MATERIALIZE the shared values so the serialized
            # form is byte-compatible with an ungrouped 0.6.0 checkpoint and
            # the unpickled copy stands alone
            for name in self._defaults:
                value = getattr(self, name)
                state[name] = list(value) if isinstance(value, list) else value
        # jax arrays serialize as host numpy and are restored on the default device
        return apply_to_collection(state, jax.Array, np.asarray)

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(apply_to_collection(state, np.ndarray, jnp.asarray))
        # pickles from before the compiled stateful forward (0.4.0) predate
        # this flag; default it off so their first forward() stays eager.
        # Donation (0.6.0) defaults on for enabled pickles — enablement
        # survives, the executable cache is rebuilt on first dispatch.
        self.__dict__.setdefault("_jit_forward_enabled", False)
        self.__dict__.setdefault("_jit_forward_donate", True)
        # compute groups (0.7.0) never serialize: the unpickled copy stands
        # alone with materialized states, and 0.6.0-and-earlier pickles
        # predate the attribute entirely
        self.__dict__.setdefault("_compute_group", None)
        # transport pins never serialize (a backend may hold a device mesh);
        # the unpickled copy resolves the ambient transport until re-pinned
        self.__dict__.setdefault("_transport", None)
        self._donation_warned = False
        self._drop_compiled_dispatch()
        self._update_signature = inspect.signature(self.update)
        self.update = self._wrap_update(self.update)  # type: ignore[method-assign]
        self.compute = self._wrap_compute(self.compute)  # type: ignore[method-assign]

    def __hash__(self) -> int:
        # identity-based per state object, matching the reference's tensor-hash
        # semantics (fresh instances hash differently; empty-list states don't)
        hash_vals: List[Any] = [self.__class__.__name__]
        for key in self._defaults:
            value = getattr(self, key)
            if isinstance(value, list):
                hash_vals.extend(id(v) for v in value)
            else:
                hash_vals.append(id(value))
        return hash(tuple(hash_vals))

    def __repr__(self) -> str:
        return f"{self.__class__.__name__}()"

    def device_put(self, device: Any = None) -> "Metric":
        """Move all states (and defaults) onto ``device`` / a sharding."""
        for key, default in self._defaults.items():
            if isinstance(default, ArrayTypes):
                self._defaults[key] = jax.device_put(default, device)
            current = getattr(self, key)
            if isinstance(current, ArrayTypes):
                setattr(self, key, jax.device_put(current, device))
            else:
                setattr(self, key, [jax.device_put(v, device) for v in current])
        return self


def _neg(value: Array) -> Array:
    return -jnp.abs(value)


def _fmod(a: Any, b: Any) -> Array:
    a, b = jnp.asarray(a), jnp.asarray(b)
    if not jnp.issubdtype(jnp.result_type(a, b), jnp.floating):
        return jnp.fmod(a, b)
    # XLA's rem gives NaN for fmod(finite, ±inf); IEEE (and the reference's
    # torch.fmod, metric.py:511-512) keeps the dividend, signed zero intact.
    return jnp.where(jnp.isinf(b) & jnp.isfinite(a), a, jnp.fmod(a, b))


def _floor_divide(a: Any, b: Any) -> Array:
    a, b = jnp.asarray(a), jnp.asarray(b)
    if not jnp.issubdtype(jnp.result_type(a, b), jnp.floating):
        return jnp.floor_divide(a, b)
    # Float floor division with torch/numpy semantics (the reference
    # composes torch.floor_divide, metric.py:493-494): x//0.0 is ±inf
    # where jnp.floor_divide gives NaN, and the fmod-based fixup (ATen's
    # div_floor / numpy's npy_divmod) recovers the true floor when the
    # rounded quotient lands just across an integer — plain floor(a/b)
    # is off by one there. 0/450k random cases diverge from torch; the
    # residual is inputs where XLA's rem is itself inexact (1.0 // 0.1).
    mod = _fmod(a, b)  # its inf-divisor guard makes finite // ±inf land at 0/-1
    div = (a - mod) / b
    div = div - jnp.where((mod != 0) & ((b < 0) != (mod < 0)), 1, 0).astype(div.dtype)
    floordiv = jnp.floor(div)
    floordiv = floordiv + (div - floordiv > 0.5).astype(div.dtype)
    floordiv = jnp.where(div != 0, floordiv, jnp.copysign(jnp.zeros_like(div), a / b))
    return jnp.where(b == 0, a / b, floordiv)


class CompositionalMetric(Metric):
    """Lazy composition of two metrics under an operator, evaluated at compute().

    Parity: reference ``metric.py:598-677``. ``update`` fans out to both
    children with per-child kwarg filtering; ``compute`` applies ``op`` to the
    child results; sync is a no-op here because each child syncs itself.
    """

    _fusable = False  # children own the state; use the reference forward protocol

    def __init__(
        self,
        operator: Callable,
        metric_a: Union[Metric, int, float, Array],
        metric_b: Union[Metric, int, float, Array, None],
    ) -> None:
        super().__init__()
        self.op = operator
        self.metric_a = metric_a
        self.metric_b = metric_b

    def _sync_dist(self, dist_sync_fn: Optional[Callable] = None, process_group: Optional[Any] = None) -> None:
        pass  # children sync themselves

    def jit_forward(self, enable: bool = True, donate: bool = True) -> "Metric":
        if not enable:  # disabling is a safe no-op everywhere, here included
            return self
        self._jit_forward_gate()
        return self  # pragma: no cover - the gate always raises

    def _compiled_state_gate(self) -> None:
        # also refuses update_many: the children own the states, so the
        # generic stateful scan cannot thread them either
        raise ValueError(
            "CompositionalMetric cannot jit its forward (children own the state); call"
            " jit_forward() on the child metrics, or jit a function over their pure API."
        )

    def update(self, *args: Any, **kwargs: Any) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.update(*args, **self.metric_a._filter_kwargs(**kwargs))
        if isinstance(self.metric_b, Metric):
            self.metric_b.update(*args, **self.metric_b._filter_kwargs(**kwargs))

    def compute(self) -> Any:
        val_a = self.metric_a.compute() if isinstance(self.metric_a, Metric) else self.metric_a
        val_b = self.metric_b.compute() if isinstance(self.metric_b, Metric) else self.metric_b
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def reset(self) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.reset()
        if isinstance(self.metric_b, Metric):
            self.metric_b.reset()

    def persistent(self, mode: bool = False) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.persistent(mode=mode)
        if isinstance(self.metric_b, Metric):
            self.metric_b.persistent(mode=mode)

    def check_health(self, state: Optional[StateDict] = None) -> Dict[str, Any]:
        # the composition owns no states; fan the check to the children
        # (keyed like the pure-state layout, aliased child checked once)
        state = state or {}
        children: Dict[str, Any] = {}
        if isinstance(self.metric_a, Metric):
            children["a"] = self.metric_a.check_health(state.get("a"))
        if isinstance(self.metric_b, Metric) and self.metric_b is not self.metric_a:
            children["b"] = self.metric_b.check_health(state.get("b"))
        return {
            "metric": self.telemetry_key,
            "healthy": all(c["healthy"] for c in children.values()),
            "children": children,
        }

    def state_memory_report(self) -> Dict[str, Any]:
        # the composition owns no states; report the children's (keyed like
        # the pure-state layout, aliased child counted once)
        report: Dict[str, Any] = {"per_state": {}, "total_bytes": 0}
        if isinstance(self.metric_a, Metric):
            sub = self.metric_a.state_memory_report()
            report["per_state"]["a"] = sub
            report["total_bytes"] += sub["total_bytes"]
        if isinstance(self.metric_b, Metric) and self.metric_b is not self.metric_a:
            sub = self.metric_b.state_memory_report()
            report["per_state"]["b"] = sub
            report["total_bytes"] += sub["total_bytes"]
        return report

    # ------------------------------------------------------------------
    # pure (jit-native) API: child states keyed "a"/"b" — without this the
    # base implementation would return an empty state and apply_compute
    # would silently read the children's mutable (untracked) states
    # ------------------------------------------------------------------
    def init_state(self) -> StateDict:
        state: StateDict = {}
        if isinstance(self.metric_a, Metric):
            state["a"] = self.metric_a.init_state()
        if isinstance(self.metric_b, Metric) and self.metric_b is not self.metric_a:
            state["b"] = self.metric_b.init_state()
        return state

    def apply_update(self, state: StateDict, *args: Any, **kwargs: Any) -> StateDict:
        new_state: StateDict = {}
        if isinstance(self.metric_a, Metric):
            new_state["a"] = self.metric_a.apply_update(
                state["a"], *args, **self.metric_a._filter_kwargs(**kwargs)
            )
        if isinstance(self.metric_b, Metric):
            if self.metric_b is self.metric_a:
                # aliased composition (m + m): eager update hits the shared
                # object twice per step, so the pure state advances twice too
                new_state["a"] = self.metric_a.apply_update(
                    new_state["a"], *args, **self.metric_a._filter_kwargs(**kwargs)
                )
            else:
                new_state["b"] = self.metric_b.apply_update(
                    state["b"], *args, **self.metric_b._filter_kwargs(**kwargs)
                )
        return new_state

    def apply_compute(self, state: StateDict, axis_name: Any = AXIS_UNSET) -> Any:
        # forwarded verbatim: when unset, each child falls back to its own
        # declared process_group; an explicit axis (or None) overrides all
        val_a = (
            self.metric_a.apply_compute(state["a"], axis_name=axis_name)
            if isinstance(self.metric_a, Metric)
            else self.metric_a
        )
        if isinstance(self.metric_b, Metric):
            val_b = val_a if self.metric_b is self.metric_a else self.metric_b.apply_compute(
                state["b"], axis_name=axis_name
            )
        else:
            val_b = self.metric_b
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def __repr__(self) -> str:
        _op_name = getattr(self.op, "__name__", repr(self.op))
        return f"{self.__class__.__name__}(\n  {_op_name}(\n    {self.metric_a!r},\n    {self.metric_b!r}\n  )\n)"


def _install_operators() -> None:
    """Attach the 36 arithmetic/comparison dunders that build lazy compositions."""

    def binary(op: Callable, swap: bool = False) -> Callable:
        def method(self: Metric, other: Any) -> CompositionalMetric:
            if swap:
                return CompositionalMetric(op, other, self)
            return CompositionalMetric(op, self, other)

        return method

    def unary(op: Callable) -> Callable:
        def method(self: Metric) -> CompositionalMetric:
            return CompositionalMetric(op, self, None)

        return method

    binary_table = {
        "add": jnp.add,
        "sub": jnp.subtract,
        "mul": jnp.multiply,
        "truediv": jnp.true_divide,
        "floordiv": _floor_divide,
        "mod": _fmod,
        "pow": jnp.power,
        "matmul": jnp.matmul,
        "and": jnp.bitwise_and,
        "or": jnp.bitwise_or,
        "xor": jnp.bitwise_xor,
    }
    for name, op in binary_table.items():
        setattr(Metric, f"__{name}__", binary(op))
        setattr(Metric, f"__r{name}__", binary(op, swap=True))

    for name, op in {
        "eq": jnp.equal,
        "ne": jnp.not_equal,
        "lt": jnp.less,
        "le": jnp.less_equal,
        "gt": jnp.greater,
        "ge": jnp.greater_equal,
    }.items():
        setattr(Metric, f"__{name}__", binary(op))

    Metric.__abs__ = unary(jnp.abs)  # type: ignore[attr-defined]
    Metric.__pos__ = unary(jnp.abs)  # type: ignore[attr-defined]
    Metric.__neg__ = unary(_neg)  # type: ignore[attr-defined]
    Metric.__invert__ = unary(jnp.invert)  # type: ignore[attr-defined]
    Metric.__inv__ = Metric.__invert__  # type: ignore[attr-defined]

    def getitem(self: Metric, idx: Any) -> CompositionalMetric:
        return CompositionalMetric(lambda x: x[idx], self, None)

    Metric.__getitem__ = getitem  # type: ignore[attr-defined]


_install_operators()
