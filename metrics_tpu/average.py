"""Weighted running mean of a stream of values.

Parity: reference ``torchmetrics/average.py`` (``AverageMeter`` with
sum-reduced ``value``/``weight`` states and broadcasted weights).
"""
from typing import Any, Callable, Optional, Union

import jax.numpy as jnp

from metrics_tpu.metric import Array, Metric


class AverageMeter(Metric):
    """Computes the (weighted) average of a stream of values.

    Example::

        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AverageMeter
        >>> avg = AverageMeter()
        >>> avg.update(3)
        >>> avg.update(1)
        >>> float(avg.compute())
        2.0

        >>> avg = AverageMeter()
        >>> values = jnp.array([1., 2.])
        >>> weights = jnp.array([3., 1.])
        >>> float(avg(values, weights))
        1.25
    """

    is_differentiable = True

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("value", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("weight", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, value: Union[Array, float], weight: Union[Array, float] = 1.0) -> None:
        """Accumulate observations ``value`` with per-observation ``weight``
        (broadcast to ``value``'s shape)."""
        value = jnp.asarray(value, dtype=jnp.float32)
        weight = jnp.broadcast_to(jnp.asarray(weight, dtype=jnp.float32), value.shape)
        self.value = self.value + jnp.sum(value * weight)
        self.weight = self.weight + jnp.sum(weight)

    def compute(self) -> Array:
        return self.value / self.weight
