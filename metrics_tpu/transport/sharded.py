"""ShardedTransport: device-sharded giant metric states.

Every prior backend assumes a state leaf fits on one device. That caps the
workloads: a 100k-class confusion matrix is a ``(100_000, 100_000)`` count
grid (~40 GB at int32), a streaming-FID feature bank or a PR-10 sketch grid
at pod scale can exceed a single HBM, and a million-tenant keyed axis
replicated per device wastes ``devices×`` memory. This backend lets the
*state itself* live sharded across the devices of a ``jax.sharding.Mesh``:

* :meth:`ShardedTransport.shard_state` places a state dict onto the mesh —
  each array leaf's leading axis partitioned over ``shard_axis`` (leaves
  whose leading dim does not divide stay replicated), so N devices each
  hold ``1/N`` of every giant leaf;
* **updates** run through ordinary jit/pjit against the sharded buffers
  (donation keeps them in place — XLA routes a scatter-add to the owning
  shard);
* **sync** lowers to *in-place sharded reductions*: elementwise-reduced
  leaves ("sum"/"mean"/"max"/"min") are reduced across the transport's
  replica dimension by a cached, donated, sharding-preserving compiled
  program — one ``shard_map`` collective bucket per (kind, dtype), never a
  host gather, never the full array on one device. With
  ``replica_axis=None`` (one global sharded array, the common case) the
  cross-replica reduction is the identity and sync is zero-copy.
* the **final subgroup combine**: leaves the in-place path cannot express
  (list/"cat"/``None``/callable reductions — protocol-shaped, typically
  tiny) ride the eager gather backend, inheriting its subgroup formation.

``Metric._sync_dist`` consults :meth:`reduce_states` before falling back to
the gather protocol, so ``metric.set_transport(ShardedTransport(mesh,
"shard"))`` is all it takes to run a giant-state metric end to end.
"""
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from metrics_tpu.transport.base import Transport

#: reductions the in-place sharded path can reduce elementwise
_ELEMENTWISE = ("sum", "mean", "max", "min")


class ShardedTransport(Transport):
    """Transport whose state leaves live sharded across mesh devices.

    ``mesh`` is the device mesh the state occupies; ``shard_axis`` names
    the mesh axis the leading (class/tenant/feature-row) dimension is
    partitioned over. ``replica_axis`` optionally names a mesh axis holding
    per-replica PARTIAL states (data-parallel accumulation); sync then
    psum/pmax/pmin-reduces across it in place. ``eager`` overrides the
    fallback transport for non-elementwise leaves (default: the auto
    loopback/byte-gather pair).
    """

    name = "sharded"

    def __init__(
        self,
        mesh: Any,
        shard_axis: str,
        *,
        replica_axis: Optional[str] = None,
        eager: Optional[Transport] = None,
    ) -> None:
        names = tuple(getattr(mesh, "axis_names", ()))
        if shard_axis not in names:
            raise ValueError(f"mesh {names} has no axis {shard_axis!r}")
        if replica_axis is not None and replica_axis not in names:
            raise ValueError(f"mesh {names} has no axis {replica_axis!r}")
        if eager is not None and not isinstance(eager, Transport):
            raise TypeError(f"eager must be a Transport, got {eager!r}")
        self.mesh = mesh
        self.shard_axis = shard_axis
        self.replica_axis = replica_axis
        self._eager_override = eager
        #: compiled in-place reduction programs, keyed by the state bundle's
        #: (names, avals, shardings) signature — the aval-keyed dispatch
        #: discipline of utilities/aot.py applied to the sync path
        self._programs: Dict[Tuple, Any] = {}

    # -- placement ---------------------------------------------------------

    def sharding_for(self, leaf: Any) -> Any:
        """The :class:`~jax.sharding.NamedSharding` this transport gives
        ``leaf``: leading axis split over ``shard_axis`` when it divides the
        axis size, fully replicated otherwise."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        shape = getattr(leaf, "shape", ())
        axis_size = self.mesh.shape[self.shard_axis]
        if len(shape) >= 1 and shape[0] % axis_size == 0 and shape[0] > 0:
            return NamedSharding(self.mesh, P(self.shard_axis))
        return NamedSharding(self.mesh, P())

    def shard_state(self, state: Dict[str, Any]) -> Dict[str, Any]:
        """Place every array leaf of ``state`` onto the mesh (list states
        keep their host-list structure; their elements are placed
        replicated — the gather fallback owns them)."""
        import jax

        out: Dict[str, Any] = {}
        for name, value in state.items():
            if isinstance(value, (list, tuple)):
                out[name] = [jax.device_put(v, self.sharding_for(v)) for v in value]
            else:
                out[name] = jax.device_put(value, self.sharding_for(value))
        return out

    def adopt(self, metric: Any) -> Any:
        """Point ``metric`` at this transport and move its live states onto
        the mesh. Returns the metric."""
        metric.set_transport(self)
        metric._set_states(self.shard_state(metric._get_states()))
        return metric

    def place_state(self, state: Dict[str, Any]) -> Dict[str, Any]:
        """Restore-time placement (``Transport.place_state``): shard every
        leaf's leading axis over the mesh — a replicated-saved checkpoint
        restores device-sharded without the snapshot knowing the topology."""
        return self.shard_state(state)

    # -- eager sync: in-place sharded reduction ----------------------------

    def reduce_states(
        self,
        states: Dict[str, Any],
        reductions: Dict[str, Any],
        group: Optional[Any] = None,
    ) -> Optional[Dict[str, Any]]:
        """Reduce every elementwise leaf across the replica dimension in
        place (donated, sharding-preserving); the caller gathers the rest.

        With ``replica_axis=None`` each leaf is one *global* sharded array —
        already the fleet-wide state by construction — so the reduction is
        the identity and the leaves ride back zero-copy.
        """
        import jax

        handled_names = [
            name
            for name, value in states.items()
            if not isinstance(value, (list, tuple))
            and reductions.get(name) in _ELEMENTWISE
        ]
        if not handled_names:
            return None
        sub = {name: states[name] for name in handled_names}
        if self.replica_axis is None:
            self._note_reduce(sub, identity=True)
            return sub
        program = self._reduce_program(sub, {n: reductions[n] for n in handled_names})
        out = program(sub)
        self._note_reduce(out, identity=False)
        return dict(out)

    def _reduce_program(self, sub: Dict[str, Any], reductions: Dict[str, Any]):
        """The cached donated compiled reduction for this bundle layout:
        ``shard_map`` over the mesh, the packed (bucketed) engine reducing
        each leaf across ``replica_axis`` — one collective per (kind, dtype)
        bucket, outputs sharded exactly as the inputs."""
        import jax

        key = tuple(
            (name, str(v.dtype), tuple(v.shape), str(getattr(v, "sharding", None)))
            for name, v in sorted(sub.items())
        )
        program = self._programs.get(key)
        if program is not None:
            return program

        from jax.sharding import PartitionSpec as P

        from metrics_tpu.utilities.distributed import _sync_state_packed_impl

        axis_size = self.mesh.shape[self.shard_axis]
        # per-leaf specs: sharded leaves split dim 0 over shard_axis; all
        # leaves are REPLICATED over replica_axis (each replica holds a full
        # partial copy that the psum folds)
        specs = {}
        for name, v in sub.items():
            if v.ndim >= 1 and v.shape[0] % axis_size == 0 and v.shape[0] > 0:
                specs[name] = P(self.shard_axis)
            else:
                specs[name] = P()

        body_in_specs = ({name: specs[name] for name in sub},)
        body_out_specs = {name: specs[name] for name in sub}

        def body(state):
            return _sync_state_packed_impl(state, reductions, self.replica_axis)

        mapped = _shard_map(body, self.mesh, body_in_specs, body_out_specs)
        program = jax.jit(mapped, donate_argnums=(0,))
        self._programs[key] = program
        return program

    def _note_reduce(self, sub: Dict[str, Any], *, identity: bool) -> None:
        """Telemetry for one in-place sharded sync (host-side, never
        raises): a zero-byte transport round labeled ``sharded`` — nothing
        crosses the process boundary on this path. The in-place reduction
        covers the FULL replica dimension, so the round spans every
        process: participants is the whole world, never a proper subset —
        it must not count toward ``subgroup_rounds`` (the quorum-acceptance
        telemetry)."""
        try:
            from metrics_tpu.utilities.distributed import (
                _record_gather_telemetry,
                world_size,
            )

            nprocs = max(world_size(), 1)
            everyone = list(range(nprocs))
            _record_gather_telemetry(
                bytes_out=0,
                bytes_in=0,
                members=everyone,
                nprocs=nprocs,
                leaves=len(sub),
                desc_bytes=0,
                max_bytes=0,
                error=False,
                transport=self.name if identity else f"{self.name}_reduce",
                participants=everyone,
            )
        except Exception:  # pragma: no cover - telemetry must not break sync
            pass

    # -- delegation for everything else ------------------------------------

    def gather_pytrees(self, trees: List[Any], group: Optional[Any] = None) -> List[Any]:
        return self._eager().gather_pytrees(trees, group=group)

    def gather_array(self, result: Any, group: Optional[Any] = None) -> List[Any]:
        return self._eager().gather_array(result, group=group)

    def subgroup(self, members: Sequence[int]) -> Transport:
        sub = self._eager().subgroup(members)
        if sub is self._eager():
            return self
        return ShardedTransport(
            self.mesh, self.shard_axis, replica_axis=self.replica_axis, eager=sub
        )

    def _eager(self) -> Transport:
        if self._eager_override is not None:
            return self._eager_override
        from metrics_tpu.transport.base import _AUTO

        return _AUTO._eager()

    def max_shard_fraction(self, leaf: Any) -> float:
        """Diagnostics: the largest single-device fraction of ``leaf``'s
        bytes — ``1/num_shards`` for a properly sharded giant state, 1.0 if
        anything materialized a full copy on one device."""
        shards = getattr(leaf, "addressable_shards", None)
        total = int(np.prod(getattr(leaf, "shape", ()) or (1,))) * leaf.dtype.itemsize
        if not shards or total == 0:
            return 1.0
        biggest = max(int(np.prod(s.data.shape or (1,))) * s.data.dtype.itemsize for s in shards)
        return biggest / total


def _shard_map(fn, mesh, in_specs, out_specs):
    import jax

    if hasattr(jax, "shard_map"):  # pragma: no cover - newer jax
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map

    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False)
