"""InGraphTransport: the ``jax.lax`` packed-bucket collective backend."""
from typing import Any, Dict, List, Optional, Sequence

from metrics_tpu.transport.base import Transport


class InGraphTransport(Transport):
    """The TPU-native in-graph backend: packed (bucketed) ``jax.lax``
    collectives, one per (kind, dtype) bucket — hierarchical
    (``Hierarchy``/two-level) lowering included.

    This IS the engine every traced sync already lowers through; installing
    it explicitly changes nothing about the compiled programs (pinned
    byte-identical by ``scripts/check_zero_overhead.py``) — it exists so the
    in-graph path is nameable, testable and composable like every other
    backend. Epoch-boundary eager gathers delegate to ``eager`` (default:
    the auto loopback/byte-gather pair), since an in-graph collective cannot
    run outside a traced program.
    """

    name = "in_graph"

    def __init__(self, eager: Optional[Transport] = None) -> None:
        if eager is not None and not isinstance(eager, Transport):
            raise TypeError(f"eager must be a Transport, got {eager!r}")
        self._eager_override = eager

    # sync_state_packed: inherited — the base class already routes to the
    # packed jax.lax engine, which is this backend's native path.

    def gather_pytrees(self, trees: List[Any], group: Optional[Any] = None) -> List[Any]:
        return self._eager().gather_pytrees(trees, group=group)

    def gather_array(self, result: Any, group: Optional[Any] = None) -> List[Any]:
        return self._eager().gather_array(result, group=group)

    def reduce_states(
        self,
        states: Dict[str, Any],
        reductions: Dict[str, Any],
        group: Optional[Any] = None,
    ) -> Optional[Dict[str, Any]]:
        return self._eager().reduce_states(states, reductions, group=group)

    def subgroup(self, members: Sequence[int]) -> Transport:
        sub = self._eager().subgroup(members)
        return InGraphTransport(eager=sub) if sub is not self._eager() else self

    def _eager(self) -> Transport:
        if self._eager_override is not None:
            return self._eager_override
        from metrics_tpu.transport.base import _AUTO

        return _AUTO._eager()
