"""LoopbackTransport: the zero-copy single-process identity backend.

Single-process runs (every ``jax.process_count() == 1`` deployment, and —
in this repo's CI — the whole CPU test environment, where jax 0.4.37 has no
multiprocess collectives) previously exercised the multiprocess code paths
only as an incidental degenerate case. The loopback backend makes the
single-participant world a first-class, testable transport:

* the eager gather is the exact world-1 protocol result — every leaf
  becomes a one-member list holding the local array, **zero-copy** (the
  same ``jax.Array`` object rides through; no descriptor/payload rounds,
  no padding, no byte marshalling);
* the in-graph lowering issues **zero collectives** and returns what the
  packed engine produces over a size-1 axis: elementwise reductions are the
  identity, ``cat`` states pre-concatenate, gather-only states gain the
  ``(1, ...)`` participant axis, callable reductions see the stacked
  world-1 gather.

It is the default eager backend whenever ``jax.process_count() == 1``
(via :class:`~metrics_tpu.transport.base.AutoTransport`), which turns the
multiprocess-assuming test surface into runnable single-process signal.
"""
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from metrics_tpu.observability.events import EVENTS
from metrics_tpu.observability.registry import TELEMETRY
from metrics_tpu.transport.base import Transport


class LoopbackTransport(Transport):
    """Identity transport for a world of one participant."""

    name = "loopback"

    # -- eager path --------------------------------------------------------

    def gather_pytrees(self, trees: List[Any], group: Optional[Any] = None) -> List[Any]:
        from metrics_tpu.utilities import distributed as _dist

        # validate the group argument eagerly: with no peers to desync there
        # is nothing to defer for
        if group is not None:
            _dist._resolve_group(group, max(_dist.world_size(), 1))
        record = TELEMETRY.enabled or EVENTS.enabled
        t_start = time.perf_counter() if record else 0.0
        flat = [jax.tree_util.tree_flatten(t) for t in trees]
        out = []
        leaves_total = 0
        for leaves, treedef in flat:
            leaves_total += len(leaves)
            out.append(
                jax.tree_util.tree_unflatten(
                    treedef, [[jnp.asarray(leaf)] for leaf in leaves]
                )
            )
        if record:
            _dist._record_gather_telemetry(
                bytes_out=0,
                bytes_in=0,
                members=[0],
                nprocs=1,
                leaves=leaves_total,
                desc_bytes=0,
                max_bytes=0,
                error=False,
                dur_s=time.perf_counter() - t_start,
                t_start=t_start,
                span_id=None,
                transport=self.name,
                participants=[0],
            )
        return out

    def gather_array(self, result: Any, group: Optional[Any] = None) -> List[Any]:
        return self.gather_pytrees([result], group=group)[0]

    def reduce_states(
        self,
        states: Dict[str, Any],
        reductions: Dict[str, Any],
        group: Optional[Any] = None,
    ) -> Optional[Dict[str, Any]]:
        # every non-list elementwise-reduced leaf is already its own synced
        # value in a world of one: hand the SAME buffers back (zero-copy) and
        # let the caller gather the rest (list/cat/None/callable leaves,
        # which have protocol shape changes even at world 1)
        handled = {
            name: value
            for name, value in states.items()
            if not isinstance(value, (list, tuple))
            and reductions.get(name) in ("sum", "mean", "max", "min")
        }
        return handled or None

    # -- in-graph path -----------------------------------------------------

    def sync_state_packed(
        self,
        state: Dict[str, Any],
        reductions: Dict[str, Any],
        axis_name: Any,
        *,
        levels: Optional[Sequence] = None,
        group_composition: Optional[Dict[str, int]] = None,
    ) -> Dict[str, Any]:
        """The packed engine's world-1 semantics with ZERO collectives.

        Valid only when the named axis has a single participant (the
        loopback contract); results are then bit-identical to
        ``sync_state_packed`` over that axis — pinned by the transport
        -equivalence suite.
        """
        from metrics_tpu.utilities.data import dim_zero_cat
        from metrics_tpu.utilities.distributed import _record_in_graph_telemetry

        synced: Dict[str, Any] = {}
        kinds: Dict[str, int] = {}
        n_states = 0
        for name, value in state.items():
            fx = reductions.get(name)
            wrap_list = False
            if isinstance(value, (list, tuple)):
                if len(value) == 0:
                    synced[name] = value
                    continue
                value = dim_zero_cat(list(value))
                fx = "cat" if fx in ("cat", None) else fx
                wrap_list = fx == "cat"
            n_states += 1
            if callable(fx):
                synced[name] = fx(value[None])
                kinds["loopback"] = kinds.get("loopback", 0) + 1
            elif fx in ("sum", "mean", "max", "min"):
                synced[name] = [value] if wrap_list else value
                kinds["loopback"] = kinds.get("loopback", 0) + 1
            elif fx == "cat":
                value = jnp.atleast_1d(value)
                synced[name] = [value] if wrap_list else value
                kinds["loopback"] = kinds.get("loopback", 0) + 1
            elif fx is None:
                synced[name] = value[None]
                kinds["loopback"] = kinds.get("loopback", 0) + 1
            else:
                raise ValueError(f"Unknown dist_reduce_fx: {fx!r}")
        if kinds:
            _record_in_graph_telemetry(
                axis_name,
                kinds,
                0,
                collectives_before=n_states,
                collectives_after=0,
                groups=group_composition,
            )
        return synced

    # -- topology ----------------------------------------------------------

    @property
    def participants(self) -> Optional[List[int]]:
        return [0]

    def subgroup(self, members: Sequence[int]) -> Transport:
        return self

    def distributed(self) -> bool:
        return False
