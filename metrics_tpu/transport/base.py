"""Transport strategy interface + active-transport resolution.

A :class:`Transport` packages the three ways metric state crosses device or
process boundaries:

* **in-graph** (:meth:`Transport.sync_state_packed`) — called inside a
  traced program (``shard_map``/``pmap``/``pjit``); must lower to XLA
  collectives (or to nothing, for the loopback backend);
* **eager gather** (:meth:`Transport.gather_pytrees` /
  :meth:`Transport.gather_array`) — the epoch-boundary path; returns each
  group member's contribution so the caller applies the declared
  reductions host-side;
* **eager in-place reduction** (:meth:`Transport.reduce_states`) — an
  optional fast path for device-resident (possibly sharded) states: the
  transport reduces elementwise states across processes *without* handing
  full per-member copies to the host. ``None`` (the default) means "use the
  gather protocol".

Resolution order for the **active** transport: per-metric override ->
innermost :func:`use_transport` context (thread-local) -> process-global
:func:`set_transport` -> the :class:`AutoTransport` default (in-graph
packed collectives for traced code; loopback when
``jax.process_count() == 1``, the byte gather otherwise).

Everything here is host-side bookkeeping: resolving a transport never adds
a traced op, and with the default backends active the lowered programs are
byte-identical to the pre-seam engine (``scripts/check_zero_overhead.py``
pins this).
"""
import threading
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence


class Transport:
    """Strategy object for metric-state collectives (the L0 seam).

    Subclasses override the paths they implement natively; the base class
    routes everything to the default engines so a backend only has to
    express what it changes. Transports are cheap, immutable-ish value
    objects — :meth:`subgroup` returns a NEW transport bound to a
    participant subset rather than mutating the receiver.
    """

    #: telemetry label (histogram ``transport=`` label values, sync events,
    #: per-backend round counters)
    name: str = "base"

    # -- in-graph (traced) path -------------------------------------------

    def sync_state_packed(
        self,
        state: Dict[str, Any],
        reductions: Dict[str, Any],
        axis_name: Any,
        *,
        levels: Optional[Sequence] = None,
        group_composition: Optional[Dict[str, int]] = None,
    ) -> Dict[str, Any]:
        """Packed in-graph sync of a state dict over ``axis_name`` — one
        collective per (kind, dtype) bucket. Default: the ``jax.lax``
        packed-bucket engine (hierarchical levels included)."""
        from metrics_tpu.utilities.distributed import _sync_state_packed_impl

        return _sync_state_packed_impl(
            state, reductions, axis_name, levels=levels, group_composition=group_composition
        )

    # -- eager (epoch-boundary) path --------------------------------------

    def gather_pytrees(self, trees: List[Any], group: Optional[Any] = None) -> List[Any]:
        """Gather every array leaf of ``trees`` across the transport's
        participants; each leaf becomes the list of group members' arrays in
        ascending process order. Default: the packed descriptor+payload byte
        rounds (loopback identity when not distributed)."""
        from metrics_tpu.utilities.distributed import _gather_pytrees_impl

        return _gather_pytrees_impl(
            trees, group, participants=self.participants, label=self.name
        )

    def gather_array(self, result: Any, group: Optional[Any] = None) -> List[Any]:
        """Per-array form of :meth:`gather_pytrees` (the
        ``gather_all_arrays`` contract)."""
        return self.gather_pytrees([result], group=group)[0]

    def reduce_states(
        self,
        states: Dict[str, Any],
        reductions: Dict[str, Any],
        group: Optional[Any] = None,
    ) -> Optional[Dict[str, Any]]:
        """Eagerly reduce the elementwise-reducible subset of ``states``
        across processes IN PLACE (device-resident, sharding-preserving) and
        return ``{name: synced_leaf}`` for the leaves handled — or ``None``
        to route everything through the gather protocol (the default).

        Backends for device-sharded giant states override this so a
        100k-class confusion matrix syncs without one host ever holding the
        full array; the caller gathers only the leaves this method did not
        handle."""
        return None

    # -- placement (the durability plane's restore seam) -------------------

    def place_state(self, state: Dict[str, Any]) -> Dict[str, Any]:
        """Place a restored (host-assembled) state dict for THIS
        transport's topology. The base transports hold state replicated, so
        the default is the identity; :class:`ShardedTransport` overrides it
        to shard each leaf's leading axis across its mesh — which is what
        makes a checkpoint saved replicated restorable device-sharded (and
        vice versa) without the snapshot knowing either topology."""
        return state

    # -- capability / topology --------------------------------------------

    @property
    def participants(self) -> Optional[List[int]]:
        """The process indices this transport's rounds span (``None`` = all
        processes)."""
        return None

    def subgroup(self, members: Sequence[int]) -> "Transport":
        """A transport whose rounds span only ``members`` — the degraded
        -link quorum hook. Backends without true subgroup formation return
        ``self`` (callers then narrow decode membership via
        ``transport_overrides(quorum=...)``, the legacy behavior)."""
        return self

    def distributed(self) -> bool:
        """Whether this transport spans more than one participant."""
        from metrics_tpu.utilities.distributed import distributed_available

        return distributed_available()

    def __repr__(self) -> str:
        extra = ""
        if self.participants is not None:
            extra = f", participants={self.participants}"
        return f"{type(self).__name__}(name={self.name!r}{extra})"


class AutoTransport(Transport):
    """The default pair: in-graph packed collectives for traced code, and —
    eagerly — :class:`~metrics_tpu.transport.loopback.LoopbackTransport`
    when ``jax.process_count() == 1``, the descriptor+payload byte gather
    otherwise. Byte-identical to the pre-seam direct engine calls."""

    name = "auto"

    def gather_pytrees(self, trees: List[Any], group: Optional[Any] = None) -> List[Any]:
        return self._eager().gather_pytrees(trees, group=group)

    def gather_array(self, result: Any, group: Optional[Any] = None) -> List[Any]:
        return self._eager().gather_array(result, group=group)

    def subgroup(self, members: Sequence[int]) -> Transport:
        return self._eager().subgroup(members)

    def _eager(self) -> Transport:
        # hot path (every dispatched eager gather): the module reference is
        # resolved once and cached — a per-call import would dominate the
        # loopback backend's zero-copy cost. The attribute lookup stays
        # per-call so test harnesses (and a late-initialized
        # jax.distributed) that swap ``distributed_available`` are honored.
        global _DIST_MODULE
        if _DIST_MODULE is None:
            from metrics_tpu.utilities import distributed

            _DIST_MODULE = distributed
        if _DIST_MODULE.distributed_available():
            if _GATHER_SINGLETON is not None:
                return _GATHER_SINGLETON
            from metrics_tpu.transport.gather import GatherTransport

            return GatherTransport()
        if _LOOPBACK_SINGLETON is not None:
            return _LOOPBACK_SINGLETON
        from metrics_tpu.transport.loopback import LoopbackTransport

        return LoopbackTransport()


#: lazily-filled default instances (avoid an import cycle at module load)
_GATHER_SINGLETON: Optional[Transport] = None
_LOOPBACK_SINGLETON: Optional[Transport] = None
#: cached reference to the distributed engine module (resolved on first
#: dispatch; the availability ATTRIBUTE is looked up per call)
_DIST_MODULE = None

#: the auto default — what ``get_transport()`` returns when nothing is set
_AUTO = AutoTransport()

#: process-global active transport (``None`` = auto)
_GLOBAL: Optional[Transport] = None
_GLOBAL_LOCK = threading.Lock()

#: thread-local context-manager stack (innermost wins)
_CONTEXT = threading.local()


def _register_singletons(gather: Transport, loopback: Transport) -> None:
    """Called by the backend modules at import so :class:`AutoTransport`
    reuses one instance per default backend (stable telemetry identity)."""
    global _GATHER_SINGLETON, _LOOPBACK_SINGLETON
    if _GATHER_SINGLETON is None:
        _GATHER_SINGLETON = gather
    if _LOOPBACK_SINGLETON is None:
        _LOOPBACK_SINGLETON = loopback


def _check(transport: Any) -> Transport:
    if not isinstance(transport, Transport):
        raise TypeError(
            f"expected a metrics_tpu.transport.Transport instance, got {transport!r}"
        )
    return transport


def set_transport(transport: Optional[Transport]) -> Optional[Transport]:
    """Install ``transport`` as the process-global active transport
    (``None`` restores the auto default). Returns the previous global so a
    caller can restore it. **Collective discipline**: like any sync
    configuration, install the same transport on every participating
    process."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        previous = _GLOBAL
        _GLOBAL = _check(transport) if transport is not None else None
    return previous


def get_transport() -> Transport:
    """The active transport for this thread: innermost
    :func:`use_transport` context, else the process global, else the auto
    default."""
    stack = getattr(_CONTEXT, "stack", None)
    if stack:
        return stack[-1]
    return _GLOBAL if _GLOBAL is not None else _AUTO


def resolve_transport(metric: Any = None) -> Transport:
    """Resolution used by every dispatch site: the metric's own override
    (when one is set) wins over the ambient :func:`get_transport`."""
    if metric is not None:
        override = getattr(metric, "_transport", None)
        if override is not None:
            return override
    return get_transport()


def active_transport_name() -> str:
    """Telemetry helper: the active transport's label."""
    return get_transport().name


@contextmanager
def use_transport(transport: Transport):
    """Scope ``transport`` as the active transport for this thread.

    Reentrant and exception-safe: contexts nest (innermost wins) and every
    exit — normal or raising — restores the previous state, so a transport
    round failing mid-sync can never leave a stale backend installed."""
    _check(transport)
    stack = getattr(_CONTEXT, "stack", None)
    if stack is None:
        stack = _CONTEXT.stack = []
    stack.append(transport)
    try:
        yield transport
    finally:
        # pop OUR entry specifically: a mis-nested exit (generator closed
        # out of order) must not strip someone else's context
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is transport:
                del stack[i]
                break
