"""GatherTransport: eager descriptor+payload byte rounds with TRUE subgroups.

The eager gather engine (``utilities/distributed.py::_gather_all_leaves``)
historically had exactly one transport primitive — the global
``process_allgather`` — so every round spanned ALL processes even when the
caller only wanted a subset: PR-9's quorum policy could *narrow the decode*
(drop sick peers' contributions) but still paid a full all-process round per
attempt, and a genuinely dead peer hung the round until its timeout.

This backend adds **real subgroup formation**: a transport bound to a
participant subset (:meth:`GatherTransport.subgroup`) runs its descriptor
and payload rounds over those processes only, through a registered
*subgroup channel* — a primitive that exchanges equal-length byte buffers
among an explicit peer set without involving anyone else:

* :func:`set_subgroup_allgather` installs a channel (the test harness
  installs a barrier-based in-process one; deployments with a JAX
  coordination service get :func:`kvstore_subgroup_allgather` — the
  distributed KV store is point-readable, so healthy members exchange
  payloads without the dead peer ever being contacted);
* the KV-store channel is the **auto default**: when a coordination-service
  client is reachable at transport creation (an initialized
  ``jax.distributed`` runtime), it registers itself automatically — an
  explicit :func:`set_subgroup_allgather` (including ``None``) and the
  ``METRICS_TPU_NO_KVSTORE_SUBGROUP=1`` env opt-out both win over the
  auto-registration;
* with no channel registered, a subgrouped round falls back to the legacy
  behavior — one global round, subgroup members decoded — and the round
  telemetry records the participant set that was actually touched, so the
  degradation is observable rather than silent.

Round telemetry (``sync`` events, ``snapshot()["sync"]``) now carries
``participants`` — the peer set the transport round physically touched —
which is what the acceptance tests assert for quorum syncs.
"""
import base64
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from metrics_tpu.transport.base import Transport


def _consult_fault_seam(seam: str, **ctx: Any) -> Any:
    """Consult the resilience fault plan at ``seam`` (import-guarded only —
    a raise from the plan IS the injected fault and must propagate)."""
    try:
        from metrics_tpu.resilience.faults import maybe_fault
    except Exception:  # pragma: no cover - resilience plane optional
        return None
    return maybe_fault(seam, **ctx)

#: the registered subgroup channel: ``fn(buf: np.ndarray, participants) ->
#: (len(participants), ...) stacked array``, executed by every participant
#: with identical arguments; non-participants never call it.
_SUBGROUP_ALLGATHER: Optional[Callable[[np.ndarray, List[int]], np.ndarray]] = None
#: True once a caller registered (or cleared) the channel EXPLICITLY — an
#: explicit choice, including "no channel", always wins over auto-default
_CHANNEL_EXPLICIT = False
_CHANNEL_LOCK = threading.Lock()

#: env opt-out for the KV-store auto default (set to anything but 0/empty)
NO_KVSTORE_ENV = "METRICS_TPU_NO_KVSTORE_SUBGROUP"


def set_subgroup_allgather(
    fn: Optional[Callable[[np.ndarray, List[int]], np.ndarray]],
) -> Optional[Callable]:
    """Register (or clear, with ``None``) the subgroup exchange channel.
    Returns the previously registered channel. An explicit registration —
    including an explicit ``None`` — disables the KV-store auto-default
    (:func:`maybe_register_kvstore_channel`) for the rest of the process."""
    global _SUBGROUP_ALLGATHER, _CHANNEL_EXPLICIT
    with _CHANNEL_LOCK:
        previous = _SUBGROUP_ALLGATHER
        _SUBGROUP_ALLGATHER = fn
        _CHANNEL_EXPLICIT = True
    return previous


def subgroup_allgather() -> Optional[Callable]:
    """The registered subgroup channel, or ``None``."""
    return _SUBGROUP_ALLGATHER


def maybe_register_kvstore_channel() -> bool:
    """Auto-default the production subgroup channel: when a JAX
    coordination-service client is reachable (an initialized
    ``jax.distributed`` runtime) and nothing was registered explicitly,
    install :func:`kvstore_subgroup_allgather` as the subgroup channel.

    Runs at every :class:`GatherTransport` creation (cheap: two attribute
    reads once registered or opted out). Explicit
    :func:`set_subgroup_allgather` calls — including an explicit ``None`` —
    and the ``METRICS_TPU_NO_KVSTORE_SUBGROUP=1`` env opt-out always win.
    Returns True when the KV-store channel is the registered channel after
    the call."""
    global _SUBGROUP_ALLGATHER
    if _CHANNEL_EXPLICIT:
        return _SUBGROUP_ALLGATHER is kvstore_subgroup_allgather
    if _SUBGROUP_ALLGATHER is not None:
        return _SUBGROUP_ALLGATHER is kvstore_subgroup_allgather
    if os.environ.get(NO_KVSTORE_ENV, "").strip() not in ("", "0"):
        return False
    try:
        from jax._src import distributed as _jax_distributed

        client = getattr(_jax_distributed.global_state, "client", None)
    except Exception:  # pragma: no cover - exotic jax builds
        client = None
    if client is None:
        return False
    with _CHANNEL_LOCK:
        if _SUBGROUP_ALLGATHER is None and not _CHANNEL_EXPLICIT:
            _SUBGROUP_ALLGATHER = kvstore_subgroup_allgather
    return _SUBGROUP_ALLGATHER is kvstore_subgroup_allgather


#: per-participant-set monotonic round counters for the KV-store channel —
#: the same determinism rule as collective span ids: every participant
#: issues subgroup rounds in the same order, so the N-th round over one
#: peer set names the same exchange on every member.
_KV_ROUNDS: Dict[Any, int] = {}
_KV_LOCK = threading.Lock()


def consume_subgroup_round(participants: Sequence[int]) -> bool:
    """Advance the registered subgroup channel's round counter WITHOUT
    running an exchange — the consistency hook for a process that must skip
    a round its peers still run (an injected payload fault, a hard error
    between the descriptor and payload rounds; see
    ``utilities/distributed.py::_gather_all_leaves``).

    A channel object exposing ``consume_round(participants)`` gets it
    called (the test harness's in-process channel); the KV-store channel's
    module-level counter is bumped directly. Returns True when a counter
    was advanced, False when no channel (or an uncounted one) is
    registered. Without this, a channel whose per-peer-set sequence lags
    by one round rendezvouses every subsequent exchange over that peer set
    under mismatched keys — a permanent desync from one transient fault."""
    channel = _SUBGROUP_ALLGATHER
    if channel is None:
        return False
    consume = getattr(channel, "consume_round", None)
    if consume is not None:
        consume(list(participants))
        return True
    if channel is kvstore_subgroup_allgather:
        key_set = tuple(sorted(int(p) for p in participants))
        with _KV_LOCK:
            _KV_ROUNDS[key_set] = _KV_ROUNDS.get(key_set, 0) + 1
        return True
    return False


def kvstore_subgroup_allgather(
    buf: np.ndarray, participants: List[int], *, timeout_ms: int = 60_000
) -> np.ndarray:
    """Subgroup byte exchange over the JAX coordination-service KV store.

    Each participant publishes its buffer under a deterministic
    ``(round, rank)`` key and point-reads only its co-participants' keys —
    a dead non-participant is never contacted, which is exactly the
    property the global ``process_allgather`` cannot offer.

    The channel contract is shape- and dtype-preserving: the raw BYTES of
    ``buf`` ride the store (a byte view, never a value cast — an int64
    descriptor survives intact) and the result is the
    ``(len(participants),) + buf.shape`` stack in ascending rank order
    with ``buf``'s dtype. Every participant must present an
    identically-shaped buffer, which the packed gather protocol
    guarantees (descriptor rounds share one layout; payload rounds pad to
    the round's max byte length); a peer violating it raises.

    Cleanup is deferred one round: a peer publishes round ``N`` only
    after its round-``N-1`` reads completed, so entering round ``N``
    proves every co-participant is done with round ``N-1`` — each rank
    therefore deletes its own round-``N-1`` key after finishing round
    ``N``'s reads. (Deleting the round-``N`` key eagerly would race a
    slower peer into a spurious ``blocking_key_value_get`` timeout.)

    Requires an initialized ``jax.distributed`` runtime; raises
    ``RuntimeError`` otherwise (callers treat that as "no channel").
    """
    from jax._src import distributed as _jax_distributed

    client = getattr(_jax_distributed.global_state, "client", None)
    if client is None:
        raise RuntimeError(
            "kvstore_subgroup_allgather needs an initialized jax.distributed runtime"
        )
    import jax

    rank = jax.process_index()
    key_set = tuple(sorted(int(p) for p in participants))
    with _KV_LOCK:
        seq = _KV_ROUNDS.get(key_set, 0)
        _KV_ROUNDS[key_set] = seq + 1
    # the resilience seam: a consult is one attribute read with no plan
    # installed; an armed ``subgroup.exchange`` spec may sleep here (the
    # hung-channel-get chaos case — the DeadlineBudget below still bounds
    # the whole round) or raise the injected failure. Fired only AFTER the
    # round counter advanced, so an injected error never desyncs the
    # sequence this process shares with its peers.
    from metrics_tpu.resilience.policies import DeadlineBudget

    _consult_fault_seam("subgroup.exchange", process=int(rank), peers=len(key_set))
    peers = "-".join(map(str, key_set))
    prefix = f"mtpu_subgroup/{peers}/{seq}"
    payload = np.ascontiguousarray(buf)
    client.key_value_set(f"{prefix}/{rank}", base64.b64encode(payload.tobytes()).decode())
    # ONE wall-clock budget for the whole round: the legacy behavior
    # charged ``timeout_ms`` PER peer read, so a round over N peers could
    # wait N x the budget before surfacing the failure
    budget = DeadlineBudget(timeout_ms / 1e3)
    rows = []
    for peer in key_set:
        raw = base64.b64decode(
            client.blocking_key_value_get(
                f"{prefix}/{peer}", budget.remaining_ms(floor_ms=1.0)
            )
        )
        if len(raw) != payload.nbytes:
            raise RuntimeError(
                f"kvstore_subgroup_allgather: peer {peer} published {len(raw)} bytes"
                f" where this rank holds {payload.nbytes}; the subgroup channel"
                " contract requires identically-shaped buffers per round"
            )
        rows.append(np.frombuffer(raw, dtype=payload.dtype).reshape(payload.shape))
    if seq > 0:  # deferred cleanup (see docstring); absent on older runtimes
        try:
            client.key_value_delete(f"mtpu_subgroup/{peers}/{seq - 1}/{rank}")
        except Exception:  # pragma: no cover - cleanup is optional
            pass
    return np.stack(rows)


class GatherTransport(Transport):
    """The eager byte-transport backend (descriptor+payload packed rounds).

    ``participants=None`` spans all processes — byte-for-byte the engine
    the default path always ran. A participant-bound instance (from
    :meth:`subgroup`) runs true subgroup rounds when a subgroup channel is
    registered and falls back to global-round + narrowed decode otherwise.
    ``label`` overrides the telemetry ``transport=`` label (the async
    engine labels its legs ``"dcn"``).
    """

    name = "gather"

    def __init__(
        self,
        *,
        participants: Optional[Sequence[int]] = None,
        label: Optional[str] = None,
    ) -> None:
        # transport creation is the auto-default hook: a reachable
        # coordination-service client registers the KV-store subgroup
        # channel unless an explicit registration or env opt-out won
        maybe_register_kvstore_channel()
        self._participants = (
            sorted({int(p) for p in participants}) if participants is not None else None
        )
        if self._participants is not None and not self._participants:
            raise ValueError("participants must name at least one process index")
        if label is not None:
            self.name = str(label)

    @property
    def participants(self) -> Optional[List[int]]:
        return list(self._participants) if self._participants is not None else None

    def subgroup(self, members: Sequence[int]) -> Transport:
        requested = sorted({int(m) for m in members})
        narrowed = (
            [m for m in requested if m in self._participants]
            if self._participants is not None
            else requested
        )
        if not narrowed:
            # a subgroup NEVER widens: an empty request (or one disjoint
            # from this transport's participants) must not silently fall
            # back to the full parent set — a quorum round would then span
            # more peers than the caller asked for
            raise ValueError(
                f"subgroup members {requested} do not intersect this transport's"
                " participants"
                f" {self._participants if self._participants is not None else '(all processes)'}"
            )
        if self._participants is not None and narrowed == self._participants:
            return self
        return GatherTransport(
            participants=narrowed,
            label=self.name if self.name != "gather" else None,
        )

    # gather_pytrees / gather_array: inherited — the base class routes to
    # ``_gather_pytrees_impl`` with this transport's participants + label,
    # which is the native engine for this backend.
