"""Pluggable collective transport (L0 strategy layer).

The library's two hardwired sync paths — the in-graph ``jax.lax`` packed
collectives and the eager descriptor+payload byte gather — become
first-class, swappable **strategy objects** behind one interface:

* :class:`~metrics_tpu.transport.base.Transport` — the strategy interface:
  an in-graph packed lowering (:meth:`~Transport.sync_state_packed`), an
  eager bundle gather (:meth:`~Transport.gather_pytrees`), an eager
  in-place reduction hook for device-resident states
  (:meth:`~Transport.reduce_states`), and subgroup formation
  (:meth:`~Transport.subgroup`).
* :class:`InGraphTransport` — the ``jax.lax`` packed-bucket collectives
  (hierarchical levels included); the TPU-native default for traced
  programs.
* :class:`GatherTransport` — the eager descriptor+payload byte rounds,
  extended with **true subgroup formation**: a transport bound to a
  participant subset runs its rounds over those processes only (via the
  registered subgroup channel), so quorum/degraded syncs never touch a dead
  peer.
* :class:`LoopbackTransport` — the zero-copy single-process identity
  backend; the default eager transport when ``jax.process_count() == 1``.
* :class:`ShardedTransport` — a ``shard_map``/pjit path for states too
  large for one device: state leaves live sharded across mesh devices, and
  sync lowers to in-place sharded reductions plus a final subgroup combine
  for the non-elementwise leaves.

The **active transport** is settable globally (:func:`set_transport`),
per-metric (``Metric(transport=...)`` / :meth:`Metric.set_transport`), and
via context manager (:func:`use_transport`); resolution is
per-metric -> context -> global -> auto default. ``Metric.sync_state``,
``sync_state_packed``, ``Metric._sync_dist``, ``gather_all_pytrees``, the
background async engine and ``aggregate_snapshots`` all dispatch through it.
Dispatch happens host-side at trace/call time: with the default
:class:`InGraphTransport`/:class:`GatherTransport` pair active, every
compiled hot-path jaxpr is byte-identical to the direct-call engine
(pinned by ``scripts/check_zero_overhead.py``).
"""
from metrics_tpu.transport.base import (  # noqa: F401
    AutoTransport,
    Transport,
    active_transport_name,
    get_transport,
    resolve_transport,
    set_transport,
    use_transport,
)
from metrics_tpu.transport.in_graph import InGraphTransport  # noqa: F401
from metrics_tpu.transport.gather import (  # noqa: F401
    GatherTransport,
    kvstore_subgroup_allgather,
    maybe_register_kvstore_channel,
    set_subgroup_allgather,
    subgroup_allgather,
)
from metrics_tpu.transport.loopback import LoopbackTransport  # noqa: F401
from metrics_tpu.transport.sharded import ShardedTransport  # noqa: F401

from metrics_tpu.transport.base import _register_singletons as __register

__register(GatherTransport(), LoopbackTransport())
del __register

