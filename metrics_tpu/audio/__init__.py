from metrics_tpu.audio.si_sdr import SI_SDR  # noqa: F401
from metrics_tpu.audio.si_snr import SI_SNR  # noqa: F401
from metrics_tpu.audio.snr import SNR  # noqa: F401
