"""SI_SNR module metric (parity: ``torchmetrics/audio/si_snr.py:22``)."""
from typing import Any, Callable, Optional

import jax.numpy as jnp

from metrics_tpu.functional.audio.si_snr import si_snr
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import Array


class SI_SNR(Metric):
    """Scale-invariant signal-to-noise ratio, averaged over all samples.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SI_SNR
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> si_snr = SI_SNR()
        >>> print(f"{si_snr(preds, target):.2f}")
        15.09
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("sum_si_snr", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate per-sample SI-SNR values."""
        si_snr_batch = si_snr(preds=preds, target=target)
        self.sum_si_snr = self.sum_si_snr + jnp.sum(si_snr_batch)
        self.total = self.total + si_snr_batch.size

    def compute(self) -> Array:
        """Average SI-SNR over everything seen so far."""
        return self.sum_si_snr / self.total
