"""SNR module metric (parity: ``torchmetrics/audio/snr.py:22``)."""
from typing import Any, Callable, Optional

import jax.numpy as jnp

from metrics_tpu.functional.audio.snr import snr
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import Array


class SNR(Metric):
    """Signal-to-noise ratio, averaged over all samples.

    Args:
        zero_mean: if True, mean-center ``preds``/``target`` before the ratio

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SNR
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> snr = SNR()
        >>> print(f"{snr(preds, target):.2f}")
        16.18
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        zero_mean: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.zero_mean = zero_mean
        self.add_state("sum_snr", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate per-sample SNR values."""
        snr_batch = snr(preds=preds, target=target, zero_mean=self.zero_mean)
        self.sum_snr = self.sum_snr + jnp.sum(snr_batch)
        self.total = self.total + snr_batch.size

    def compute(self) -> Array:
        """Average SNR over everything seen so far."""
        return self.sum_snr / self.total
