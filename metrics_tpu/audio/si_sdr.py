"""SI_SDR module metric (parity: ``torchmetrics/audio/si_sdr.py:22``)."""
from typing import Any, Callable, Optional

import jax.numpy as jnp

from metrics_tpu.functional.audio.si_sdr import si_sdr
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import Array


class SI_SDR(Metric):
    """Scale-invariant signal-to-distortion ratio, averaged over all samples.

    States are two psum-able scalars (``sum_si_sdr``, ``total``) so the
    per-batch update fuses into the training step and epoch sync is a single
    collective.

    Args:
        zero_mean: if True, mean-center ``preds``/``target`` before scaling

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SI_SDR
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> si_sdr = SI_SDR()
        >>> print(f"{si_sdr(preds, target):.2f}")
        18.40
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        zero_mean: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.zero_mean = zero_mean
        self.add_state("sum_si_sdr", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate per-sample SI-SDR values."""
        si_sdr_batch = si_sdr(preds=preds, target=target, zero_mean=self.zero_mean)
        self.sum_si_sdr = self.sum_si_sdr + jnp.sum(si_sdr_batch)
        self.total = self.total + si_sdr_batch.size

    def compute(self) -> Array:
        """Average SI-SDR over everything seen so far."""
        return self.sum_si_sdr / self.total
