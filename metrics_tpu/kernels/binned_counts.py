"""Fused binned precision-recall count kernel.

Computes the per-threshold confusion counts behind
:class:`~metrics_tpu.classification.binned_precision_recall.BinnedPrecisionRecallCurve`:
``TP(c,t) = Σ_n target(n,c)·[pred(n,c) ≥ thr(t)]`` plus FP/FN (the streaming
state the reference fills with a Python loop over thresholds,
``classification/binned_precision_recall.py:135-153``).

* **XLA formulation (the default)** — one broadcast compare
  ``(N, C, 1) >= (T,)`` reduced over N. XLA fuses the compare-and-reduce
  without materializing the ``(N, C, T)`` boolean, and on a real v5e chip
  this beats the Pallas histogram at every measured size (see
  :func:`binned_tp_fp_fn`) — the compiler's fusion is the right tool here.
* **Pallas kernel (explicit only)** — histogram formulation. With sorted thresholds,
  ``[pred ≥ thr_t] ⇔ t < bucket`` where ``bucket = #{thr ≤ pred}``
  (a cheap ``O(N·C·log T)`` searchsorted in XLA). The counts then reduce to a
  **weighted bincount** over flat ``(class, bucket)`` bins — one Pallas pass
  building the one-hot in VMEM and contracting it against the weight column on
  the MXU (``(1, TILE) @ (TILE, K̃)``) — followed by a tiny suffix-cumsum over
  the bucket axis. Per-sample work is ``O(K̃)`` independent of ``T·C``
  materialization, and bins are K-blocked so large ``C·T`` stays in VMEM.
"""
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from metrics_tpu.kernels._common import (
    _PALLAS_TPU_AVAILABLE,
    _round_up,
    pltpu,
)

_TILE = 512
_KBLOCK = 2048  # bins per grid block: one-hot tile is TILE x KBLOCK f32 = 4 MB VMEM


def binned_tp_fp_fn_xla(
    preds: jax.Array, target: jax.Array, thresholds: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Broadcast-compare formulation: three ``(C, T)`` float32 count tensors."""
    t = (target == 1)[:, :, None]  # (N, C, 1)
    p = preds[:, :, None] >= thresholds[None, None, :]  # (N, C, T)
    tps = jnp.sum(t & p, axis=0).astype(jnp.float32)
    fps = jnp.sum(~t & p, axis=0).astype(jnp.float32)
    fns = jnp.sum(t & ~p, axis=0).astype(jnp.float32)
    return tps, fps, fns


def _wbincount_kernel(idx_ref, w_ref, out_ref):
    n_step = pl.program_id(1)

    @pl.when(n_step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    kblock = out_ref.shape[1]
    num_weight_cols = w_ref.shape[1]
    base = pl.program_id(0) * kblock
    bins = base + jax.lax.broadcasted_iota(jnp.int32, (1, kblock), 1)
    onehot = (idx_ref[:] == bins).astype(jnp.float32)  # (TILE, K̃)
    # one contraction yields every weight column's histogram: (W, TILE)@(TILE, K̃)
    out_ref[0:num_weight_cols, :] += jax.lax.dot_general(
        w_ref[:], onehot, dimension_numbers=(((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


@functools.partial(jax.jit, static_argnames=("num_bins", "interpret"))
def weighted_bincount_pallas(
    indices: jax.Array, weights: jax.Array, num_bins: int, interpret: bool = False
) -> jax.Array:
    """``out[w, b] = Σ_i weights[i, w]·[indices[i] == b]`` via MXU one-hot contraction.

    ``weights`` is ``(M,)`` (returns ``(num_bins,)``) or ``(M, W)`` with
    ``W <= 8`` weight columns histogrammed in one pass (returns
    ``(W, num_bins)``). Counts are f32-accumulated: integer-exact while every
    bin stays below 2^24.
    """
    squeeze = weights.ndim == 1
    if indices.size == 0:  # reshape(-1) below cannot infer a dim from 0 elements
        zeros = jnp.zeros(num_bins, jnp.float32)
        return zeros if squeeze else jnp.zeros((weights.shape[-1], num_bins), jnp.float32)
    weights = weights.reshape(weights.shape[0], -1)
    m, num_weight_cols = weights.shape
    if num_weight_cols > 8:
        raise ValueError(f"weighted_bincount_pallas supports at most 8 weight columns, got {num_weight_cols}")
    mpad = _round_up(max(m, _TILE), _TILE)
    kpad = _round_up(num_bins, _KBLOCK if num_bins > _KBLOCK else 128)
    kblock = min(kpad, _KBLOCK)

    idx = jnp.pad(indices.reshape(-1).astype(jnp.int32), (0, mpad - m), constant_values=-1).reshape(mpad, 1)
    w = jnp.pad(weights.astype(jnp.float32), ((0, mpad - m), (0, 0)))

    vmem = pltpu.VMEM if _PALLAS_TPU_AVAILABLE else None
    out = pl.pallas_call(
        _wbincount_kernel,
        grid=(kpad // kblock, mpad // _TILE),
        in_specs=[
            pl.BlockSpec((_TILE, 1), lambda k, i: (i, 0), memory_space=vmem),
            pl.BlockSpec((_TILE, num_weight_cols), lambda k, i: (i, 0), memory_space=vmem),
        ],
        out_specs=pl.BlockSpec((8, kblock), lambda k, i: (0, k), memory_space=vmem),
        out_shape=jax.ShapeDtypeStruct((8, kpad), jnp.float32),
        interpret=interpret,
    )(idx, w)
    return out[0, :num_bins] if squeeze else out[:num_weight_cols, :num_bins]


def _check_sorted_thresholds(thresholds: jax.Array) -> None:
    """Host-side guard: searchsorted silently miscounts on unsorted thresholds."""
    import numpy as np

    if isinstance(thresholds, jax.core.Tracer):
        return  # can't inspect values under tracing; precondition is documented
    t = np.asarray(thresholds)
    if t.size > 1 and not np.all(np.diff(t) >= 0):
        raise ValueError("`thresholds` must be sorted ascending for the Pallas histogram path")


@functools.partial(jax.jit, static_argnames=("interpret",))
def _binned_tp_fp_fn_pallas_impl(
    preds: jax.Array, target: jax.Array, thresholds: jax.Array, interpret: bool = False
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    n, num_classes = preds.shape
    num_thresholds = thresholds.shape[0]
    if n == 0:  # empty shard/batch: zero counts, like the XLA path
        zeros = jnp.zeros((num_classes, num_thresholds), jnp.float32)
        return zeros, zeros, zeros
    num_buckets = num_thresholds + 1  # bucket b = number of thresholds <= pred

    # NaN preds must never fire at any threshold (XLA-path parity: nan >= thr
    # is False), but searchsorted would place them in the top bucket
    preds = jnp.where(jnp.isnan(preds), -jnp.inf, preds.astype(jnp.float32))
    bucket = jnp.searchsorted(thresholds.astype(jnp.float32), preds, side="right")
    class_id = jax.lax.broadcasted_iota(jnp.int32, (n, num_classes), 1)
    flat = class_id * num_buckets + bucket.astype(jnp.int32)

    is_pos = (target == 1).astype(jnp.float32)
    # both histograms (target-weighted and unweighted) in one kernel pass
    weights = jnp.stack([is_pos.reshape(-1), jnp.ones(is_pos.size, jnp.float32)], axis=1)
    hists = weighted_bincount_pallas(flat, weights, num_classes * num_buckets, interpret=interpret)
    tp_hist = hists[0].reshape(num_classes, num_buckets)
    cnt_hist = hists[1].reshape(num_classes, num_buckets)

    # TP(c,t) = Σ_{b >= t+1} hist(c,b): reverse-cumsum, drop bucket 0
    suffix = lambda h: jnp.cumsum(h[:, ::-1], axis=1)[:, ::-1][:, 1:]  # noqa: E731
    tps = suffix(tp_hist)
    cnts = suffix(cnt_hist)
    pos = jnp.sum(is_pos, axis=0)[:, None]
    return tps, cnts - tps, pos - tps


def binned_tp_fp_fn_pallas(
    preds: jax.Array, target: jax.Array, thresholds: jax.Array, interpret: bool = False
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Histogram + suffix-cumsum formulation: three ``(C, T)`` float32 tensors.

    Requires ``thresholds`` sorted ascending (validated eagerly; documented
    precondition under tracing).
    """
    _check_sorted_thresholds(thresholds)
    return _binned_tp_fp_fn_pallas_impl(preds, target, thresholds, interpret=interpret)


def binned_tp_fp_fn(
    preds: jax.Array, target: jax.Array, thresholds: jax.Array, use_pallas: Optional[bool] = None
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Binned TP/FP/FN counts with automatic backend dispatch.

    Auto-dispatch always selects the XLA formulation: measured on a real
    v5e chip the Pallas histogram loses at every size (5x at best,
    n=8192/C=5/T=4000; 1000x at small sizes — its weighted bincount is a
    rank-1 contraction the MXU cannot tile, while XLA fuses the broadcast
    compare-and-reduce without materializing ``(N, C, T)``). The kernel
    stays available via ``use_pallas=True`` for explicit use/benchmarks
    (``scripts/bench_suite.py::bench_pallas_binned`` tracks the numbers).
    """
    if use_pallas is None:
        use_pallas = False
    if use_pallas:
        return binned_tp_fp_fn_pallas(preds, target, thresholds)
    return binned_tp_fp_fn_xla(preds, target, thresholds)
