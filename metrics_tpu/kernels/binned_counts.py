"""Fused binned precision-recall counts.

Computes the per-threshold confusion counts behind
:class:`~metrics_tpu.classification.binned_precision_recall.BinnedPrecisionRecallCurve`:
``TP(c,t) = Σ_n target(n,c)·[pred(n,c) ≥ thr(t)]`` plus FP/FN (the streaming
state the reference fills with a Python loop over thresholds,
``classification/binned_precision_recall.py:135-153``).

The formulation is one broadcast compare ``(N, C, 1) >= (T,)`` reduced over
N. XLA fuses the compare-and-reduce without materializing the ``(N, C, T)``
boolean — on a real v5e chip this beat a hand-written Pallas histogram
kernel at every measured size (5x at best, 1000x at small sizes; the
histogram's one-hot-contraction bincount does ``N·C²·T`` work, a factor C
more than the fused compare, so it can never win). The kernel was removed;
the compiler's fusion is the right tool here.
"""
from typing import Tuple

import jax
import jax.numpy as jnp


def label_score_histograms(
    preds: jax.Array,
    target: jax.Array,
    num_bins: int,
    lo: float = 0.0,
    hi: float = 1.0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-bin score counts split by label: two ``(C, B)`` float32 histograms.

    The bounded-memory dual of :func:`binned_tp_fp_fn`: instead of comparing
    every score against every threshold (O(N·C·T) per update), bucket each
    score once (O(N·C)) and recover the per-threshold counts at compute time
    by a cumulative sum over the fixed grid — the update cost no longer
    scales with the threshold resolution, so sketches can afford thousands
    of bins. Backs the ``sketched=True`` modes via
    :mod:`metrics_tpu.kernels.sketches`.

    ``preds`` is ``(N, C)`` scores on an ascending ``num_bins`` grid over
    ``[lo, hi]`` (out-of-range scores clip into the edge bins and are
    counted in the returned scalar); ``target`` is ``(N, C)`` binary
    {0, 1}. Returns ``(pos_hist, neg_hist, clipped)``. Counts are float32 —
    exact integers far below 2**24, and psum/merge-reducible by ``+``.
    """
    span = hi - lo
    x = preds.astype(jnp.float32)
    idx = jnp.clip(
        jnp.floor((x - lo) / span * num_bins), 0, num_bins - 1
    ).astype(jnp.int32)
    pos = (target == 1).astype(jnp.float32)
    clipped = jnp.sum((x < lo) | (x > hi)).astype(jnp.float32)

    def one_column(ix: jax.Array, p: jax.Array) -> Tuple[jax.Array, jax.Array]:
        zeros = jnp.zeros((num_bins,), jnp.float32)
        return zeros.at[ix].add(p), zeros.at[ix].add(1.0 - p)

    pos_hist, neg_hist = jax.vmap(one_column, in_axes=(1, 1), out_axes=0)(idx, pos)
    return pos_hist, neg_hist, clipped


def binned_tp_fp_fn(
    preds: jax.Array,
    target: jax.Array,
    thresholds: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Binned TP/FP/FN counts: three ``(C, T)`` float32 count tensors.

    (The 0.3.x ``use_pallas`` kwarg was deprecated in 0.4.0 and removed in
    0.5.0 as its deprecation warning promised — see the module docstring for
    why the Pallas histogram kernel lost.)
    """
    t = (target == 1)[:, :, None]  # (N, C, 1)
    p = preds[:, :, None] >= thresholds[None, None, :]  # (N, C, T)
    tps = jnp.sum(t & p, axis=0).astype(jnp.float32)
    fps = jnp.sum(~t & p, axis=0).astype(jnp.float32)
    fns = jnp.sum(t & ~p, axis=0).astype(jnp.float32)
    return tps, fps, fns


#: alias kept for callers that referenced the formulation explicitly
binned_tp_fp_fn_xla = binned_tp_fp_fn
