"""Fused binned precision-recall counts.

Computes the per-threshold confusion counts behind
:class:`~metrics_tpu.classification.binned_precision_recall.BinnedPrecisionRecallCurve`:
``TP(c,t) = Σ_n target(n,c)·[pred(n,c) ≥ thr(t)]`` plus FP/FN (the streaming
state the reference fills with a Python loop over thresholds,
``classification/binned_precision_recall.py:135-153``).

The formulation is one broadcast compare ``(N, C, 1) >= (T,)`` reduced over
N. XLA fuses the compare-and-reduce without materializing the ``(N, C, T)``
boolean — on a real v5e chip this beat a hand-written Pallas histogram
kernel at every measured size (5x at best, 1000x at small sizes; the
histogram's one-hot-contraction bincount does ``N·C²·T`` work, a factor C
more than the fused compare, so it can never win). The kernel was removed;
the compiler's fusion is the right tool here.
"""
from typing import Tuple

import jax
import jax.numpy as jnp


def binned_tp_fp_fn(
    preds: jax.Array,
    target: jax.Array,
    thresholds: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Binned TP/FP/FN counts: three ``(C, T)`` float32 count tensors.

    (The 0.3.x ``use_pallas`` kwarg was deprecated in 0.4.0 and removed in
    0.5.0 as its deprecation warning promised — see the module docstring for
    why the Pallas histogram kernel lost.)
    """
    t = (target == 1)[:, :, None]  # (N, C, 1)
    p = preds[:, :, None] >= thresholds[None, None, :]  # (N, C, T)
    tps = jnp.sum(t & p, axis=0).astype(jnp.float32)
    fps = jnp.sum(~t & p, axis=0).astype(jnp.float32)
    fns = jnp.sum(t & ~p, axis=0).astype(jnp.float32)
    return tps, fps, fns


#: alias kept for callers that referenced the formulation explicitly
binned_tp_fp_fn_xla = binned_tp_fp_fn
