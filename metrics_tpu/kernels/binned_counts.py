"""Fused binned precision-recall counts and label/score sketch histograms.

Computes the per-threshold confusion counts behind
:class:`~metrics_tpu.classification.binned_precision_recall.BinnedPrecisionRecallCurve`:
``TP(c,t) = Σ_n target(n,c)·[pred(n,c) ≥ thr(t)]`` plus FP/FN (the streaming
state the reference fills with a Python loop over thresholds,
``classification/binned_precision_recall.py:135-153``).

The per-threshold formulation is one broadcast compare ``(N, C, 1) >= (T,)``
reduced over N. XLA fuses the compare-and-reduce without materializing the
``(N, C, T)`` boolean — on a real v5e chip this beat a hand-written Pallas
histogram kernel at every measured size (5x at best, 1000x at small sizes;
the histogram's one-hot-contraction bincount does ``N·C²·T`` work, a factor
C more than the fused compare, so it can never win). That kernel was removed;
the compiler's fusion is the right tool there.

:func:`label_score_histograms` — the bounded-memory O(N·C) sketch build that
feeds every ``sketched=True`` state — is a different economy: its cost does
NOT scale with the threshold resolution, so a hand-fused bucketize +
per-class segment-sum in one VMEM-resident pass wins where the per-threshold
kernel lost. It follows the kernels dispatch contract
(:mod:`metrics_tpu.kernels`): ``label_score_histograms`` auto-dispatches,
``label_score_histograms_pallas`` takes ``interpret=`` for CPU testing,
``label_score_histograms_xla`` is the portable scatter-add formulation.
"""
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from metrics_tpu.kernels._common import (
    _PALLAS_TPU_AVAILABLE,
    _round_up,
    note_kernel_dispatch,
    pallas_auto_ok,
    pltpu,
)

#: largest histogram resolution the Pallas path handles: VMEM must hold the
#: (TILE, B̃) one-hot tile (B̃=4096 at TILE=256 -> 4 MB, in budget)
_MAX_PALLAS_BINS = 4096
_TILE = 256


def label_score_pallas_ok(num_rows: int, num_classes: int, num_bins: int) -> bool:
    """True when the auto dispatch would select the Pallas sketch kernel for
    this shape: TPU backend plus the per-kernel VMEM shape limits."""
    return (
        pallas_auto_ok(num_rows * max(num_classes, 1))
        and num_classes >= 1
        and 1 <= num_bins <= _MAX_PALLAS_BINS
    )


def label_score_histograms_xla(
    preds: jax.Array,
    target: jax.Array,
    num_bins: int,
    lo: float = 0.0,
    hi: float = 1.0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Scatter-add formulation of the label/score sketch histograms."""
    span = hi - lo
    x = preds.astype(jnp.float32)
    idx = jnp.clip(
        jnp.floor((x - lo) / span * num_bins), 0, num_bins - 1
    ).astype(jnp.int32)
    pos = (target == 1).astype(jnp.float32)
    clipped = jnp.sum((x < lo) | (x > hi)).astype(jnp.float32)

    def one_column(ix: jax.Array, p: jax.Array) -> Tuple[jax.Array, jax.Array]:
        zeros = jnp.zeros((num_bins,), jnp.float32)
        return zeros.at[ix].add(p), zeros.at[ix].add(1.0 - p)

    pos_hist, neg_hist = jax.vmap(one_column, in_axes=(1, 1), out_axes=0)(idx, pos)
    return pos_hist, neg_hist, clipped


def _hist_kernel(x_ref, pos_ref, neg_ref, pos_out, neg_out, clip_out, *, num_bins, lo, hi):
    col, step = pl.program_id(0), pl.program_id(1)

    @pl.when(step == 0)
    def _():
        pos_out[:] = jnp.zeros_like(pos_out)
        neg_out[:] = jnp.zeros_like(neg_out)

    @pl.when((col == 0) & (step == 0))
    def _():
        clip_out[:] = jnp.zeros_like(clip_out)

    bpad = pos_out.shape[1]
    x = x_ref[:]  # (TILE, 1) scores; padded rows carry lo (in-range, zero label mass)
    span = hi - lo
    idx = jnp.clip(jnp.floor((x - lo) / span * num_bins), 0, num_bins - 1).astype(jnp.int32)
    bins = jax.lax.broadcasted_iota(jnp.int32, (1, bpad), 1)
    onehot = (idx == bins).astype(jnp.float32)  # (TILE, B̃) built in VMEM
    contract = (((0,), (0,)), ((), ()))  # over the tile axis
    pos_out[:] += jax.lax.dot_general(
        pos_ref[:], onehot, dimension_numbers=contract, preferred_element_type=jnp.float32
    )
    neg_out[:] += jax.lax.dot_general(
        neg_ref[:], onehot, dimension_numbers=contract, preferred_element_type=jnp.float32
    )
    clip_out[:] += jnp.sum(((x < lo) | (x > hi)).astype(jnp.float32)).reshape(1, 1)


@functools.partial(jax.jit, static_argnames=("num_bins", "lo", "hi", "interpret"))
def label_score_histograms_pallas(
    preds: jax.Array,
    target: jax.Array,
    num_bins: int,
    lo: float = 0.0,
    hi: float = 1.0,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """VMEM-fused formulation: bucketize + per-class segment-sum in one pass.

    Per grid step one ``(TILE,)`` score column bucketizes in VMEM (iota
    compare — no materialized index array in HBM) and both label histograms
    accumulate by one MXU contraction each into the resident ``(1, B̃)``
    output rows. ``interpret=True`` runs the Pallas interpreter (CPU
    testing). Requires ``num_classes >= 1``.
    """
    n, c = preds.shape
    x = preds.astype(jnp.float32)
    pos = (target == 1).astype(jnp.float32)
    neg = 1.0 - pos
    npad = _round_up(max(n, _TILE), _TILE)
    bpad = _round_up(num_bins, 128)
    pad_rows = lambda a, v: jnp.pad(  # noqa: E731
        a, ((0, npad - n), (0, 0)), constant_values=v
    )

    grid = (c, npad // _TILE)
    vmem = pltpu.VMEM if _PALLAS_TPU_AVAILABLE else None
    col_block = lambda: pl.BlockSpec(  # noqa: E731
        (_TILE, 1), lambda col, step: (step, col), memory_space=vmem
    )
    hist_block = lambda: pl.BlockSpec(  # noqa: E731
        (1, bpad), lambda col, step: (col, 0), memory_space=vmem
    )
    pos_hist, neg_hist, clipped = pl.pallas_call(
        functools.partial(_hist_kernel, num_bins=num_bins, lo=lo, hi=hi),
        grid=grid,
        in_specs=[col_block(), col_block(), col_block()],
        out_specs=[
            hist_block(),
            hist_block(),
            pl.BlockSpec((1, 1), lambda col, step: (0, 0), memory_space=vmem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((c, bpad), jnp.float32),
            jax.ShapeDtypeStruct((c, bpad), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(pad_rows(x, lo), pad_rows(pos, 0.0), pad_rows(neg, 0.0))
    return pos_hist[:, :num_bins], neg_hist[:, :num_bins], clipped[0, 0]


def label_score_histograms(
    preds: jax.Array,
    target: jax.Array,
    num_bins: int,
    lo: float = 0.0,
    hi: float = 1.0,
    use_pallas: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-bin score counts split by label: two ``(C, B)`` float32 histograms.

    The bounded-memory dual of :func:`binned_tp_fp_fn`: instead of comparing
    every score against every threshold (O(N·C·T) per update), bucket each
    score once (O(N·C)) and recover the per-threshold counts at compute time
    by a cumulative sum over the fixed grid — the update cost no longer
    scales with the threshold resolution, so sketches can afford thousands
    of bins. Backs the ``sketched=True`` modes via
    :mod:`metrics_tpu.kernels.sketches`.

    ``preds`` is ``(N, C)`` scores on an ascending ``num_bins`` grid over
    ``[lo, hi]`` (out-of-range scores clip into the edge bins and are
    counted in the returned scalar); ``target`` is ``(N, C)`` binary
    {0, 1}. Returns ``(pos_hist, neg_hist, clipped)``. Counts are float32 —
    exact integers far below 2**24, and psum/merge-reducible by ``+``.

    ``use_pallas=None`` selects the fused Pallas kernel on a TPU backend
    when the shape fits the VMEM gates and the XLA scatter otherwise; the
    decision lands on the ``kernel.dispatch`` telemetry counter either way.
    """
    if use_pallas is None:
        use_pallas = label_score_pallas_ok(preds.shape[0], preds.shape[1], num_bins)
    note_kernel_dispatch("label_score_histograms", "pallas" if use_pallas else "xla")
    if use_pallas:
        return label_score_histograms_pallas(preds, target, num_bins, lo, hi)
    return label_score_histograms_xla(preds, target, num_bins, lo, hi)


def binned_tp_fp_fn(
    preds: jax.Array,
    target: jax.Array,
    thresholds: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Binned TP/FP/FN counts: three ``(C, T)`` float32 count tensors.

    (The 0.3.x ``use_pallas`` kwarg was deprecated in 0.4.0 and removed in
    0.5.0 as its deprecation warning promised — see the module docstring for
    why the Pallas histogram kernel lost.)
    """
    t = (target == 1)[:, :, None]  # (N, C, 1)
    p = preds[:, :, None] >= thresholds[None, None, :]  # (N, C, T)
    tps = jnp.sum(t & p, axis=0).astype(jnp.float32)
    fps = jnp.sum(~t & p, axis=0).astype(jnp.float32)
    fns = jnp.sum(t & ~p, axis=0).astype(jnp.float32)
    return tps, fps, fns


#: alias kept for callers that referenced the formulation explicitly
binned_tp_fp_fn_xla = binned_tp_fp_fn
