"""Fused confusion-matrix count kernel.

The counting step ``confmat[t, p] += 1`` (the reference's ``torch.bincount``
over flat ``target*C + preds`` indices,
``functional/classification/confusion_matrix.py:291-310``) has two TPU-native
formulations:

* **XLA fallback** — a static-shape ``scatter-add`` (``zeros.at[idx].add(1)``).
  Portable, but scatters serialize poorly on TPU.
* **Pallas kernel** — the MXU formulation ``onehot(target)ᵀ @ onehot(preds)``
  with the one-hots *built inside the kernel* (iota-compare in VMEM), so HBM
  traffic is just the two ``(N,)`` int vectors instead of two materialized
  ``(N, C)`` float matrices, and the contraction runs on the systolic array.
  Per grid step one ``(TILE, C̃)ᵀ @ (TILE, C̃)`` accumulates into the ``(C̃, C̃)``
  output block kept resident in VMEM.
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from metrics_tpu.kernels._common import (
    _PALLAS_TPU_AVAILABLE,
    _round_up,
    note_kernel_dispatch,
    pallas_auto_ok,
    pltpu,
)

#: largest C the Pallas path handles: VMEM must hold two (TILE, C̃) one-hot
#: tiles plus the (C̃, C̃) f32 accumulator (C̃=512 -> 1 MB + 2 MB, well in budget)
_MAX_PALLAS_CLASSES = 512
_TILE = 512


def confmat_counts_xla(preds: jax.Array, target: jax.Array, num_classes: int) -> jax.Array:
    """Scatter-add formulation: ``(C, C)`` int32 counts."""
    flat = target.reshape(-1) * num_classes + preds.reshape(-1)
    bins = jnp.zeros(num_classes * num_classes, dtype=jnp.int32).at[flat].add(1)
    return bins.reshape(num_classes, num_classes)


def _confmat_kernel(t_ref, p_ref, out_ref):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    cpad = out_ref.shape[0]
    classes = jax.lax.broadcasted_iota(jnp.int32, (1, cpad), 1)
    # build both one-hots in VMEM; padded rows carry index -1 -> all-zero rows
    onehot_t = (t_ref[:] == classes).astype(jnp.float32)  # (TILE, C̃)
    onehot_p = (p_ref[:] == classes).astype(jnp.float32)  # (TILE, C̃)
    out_ref[:] += jax.lax.dot_general(
        onehot_t,
        onehot_p,
        dimension_numbers=(((0,), (0,)), ((), ())),  # contract over the tile axis
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("num_classes", "interpret"))
def confmat_counts_pallas(
    preds: jax.Array, target: jax.Array, num_classes: int, interpret: bool = False
) -> jax.Array:
    """MXU one-hot-matmul formulation: ``(C, C)`` int32 counts.

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU testing).
    """
    cpad = _round_up(num_classes, 128)
    n = preds.size
    npad = _round_up(max(n, _TILE), _TILE)

    def pad(idx: jax.Array) -> jax.Array:
        idx = idx.reshape(-1).astype(jnp.int32)
        return jnp.pad(idx, (0, npad - n), constant_values=-1).reshape(npad, 1)

    grid = npad // _TILE
    vmem = pltpu.VMEM if _PALLAS_TPU_AVAILABLE else None
    block = lambda: pl.BlockSpec((_TILE, 1), lambda i: (i, 0), memory_space=vmem)  # noqa: E731
    out = pl.pallas_call(
        _confmat_kernel,
        grid=(grid,),
        in_specs=[block(), block()],
        out_specs=pl.BlockSpec((cpad, cpad), lambda i: (0, 0), memory_space=vmem),
        out_shape=jax.ShapeDtypeStruct((cpad, cpad), jnp.float32),
        interpret=interpret,
    )(pad(target), pad(preds))
    return out[:num_classes, :num_classes].astype(jnp.int32)


def confmat_counts(
    preds: jax.Array, target: jax.Array, num_classes: int, use_pallas: Optional[bool] = None
) -> jax.Array:
    """Confusion-matrix counts with automatic backend dispatch.

    ``use_pallas=None`` selects the Pallas kernel on a TPU backend for
    ``num_classes <= 512`` and the XLA scatter otherwise.
    """
    if use_pallas is None:
        use_pallas = pallas_auto_ok(preds.size) and num_classes <= _MAX_PALLAS_CLASSES
    note_kernel_dispatch("confmat_counts", "pallas" if use_pallas else "xla")
    if use_pallas:
        return confmat_counts_pallas(preds, target, num_classes)
    return confmat_counts_xla(preds, target, num_classes)
