"""Pallas TPU kernels for the hot metric ops.

The reference leans on torch's tuned CPU/CUDA primitives for its inner loops
(``torch.bincount`` for the confusion matrix,
``functional/classification/confusion_matrix.py:291-310``; a Python threshold
loop for binned PR counts, ``classification/binned_precision_recall.py:147-152``).
Here the equivalents are hand-fused Pallas kernels that keep the per-batch
pass in VMEM and feed the MXU directly, with the plain-XLA formulations as
the portable fallback used on CPU and for any shape the kernel does not cover.

Dispatch contract: every kernel module exposes ``<op>(...)`` (auto: Pallas on
TPU when the shape qualifies, XLA otherwise) plus ``<op>_pallas`` /
``<op>_xla`` for explicit selection and testing (``interpret=True`` runs the
Pallas path on CPU). Every auto-dispatch decision lands on the
``kernel.dispatch`` telemetry counter (``snapshot()["kernels"]`` /
``metrics_tpu_kernel_dispatch_total{op=...,path=...}``), and with the Pallas
paths gated off the traced hot programs are byte-identical to the
pre-kernel lowerings (pinned by ``scripts/check_zero_overhead.py``).

The suite (gates documented in ``docs/performance.md#pallas-kernels``):

* ``confmat_counts`` — confusion-matrix counting via MXU one-hot matmul;
* ``segment_scatter_add`` — the multi-tenant segment-scatter: bucketing,
  clip-and-drop, and scatter-accumulate fused into one VMEM pass;
* ``segment_scatter_max`` / ``segment_scatter_min`` — the extremal keyed
  leaves: per-feature masked VPU reductions against the segment iota,
  bit-identical to the XLA ``segment_max``/``segment_min`` lowering;
* ``label_score_histograms`` — the ``sketched=True`` histogram build:
  bucketize + per-class segment-sum in one VMEM pass;
* ``stat_scores_counts`` — fused tp/fp/tn/fn counting for the stat-scores
  quintet.
"""
from metrics_tpu.kernels.confusion_matrix import (  # noqa: F401
    confmat_counts,
    confmat_counts_pallas,
    confmat_counts_xla,
)
from metrics_tpu.kernels.binned_counts import (  # noqa: F401
    binned_tp_fp_fn,
    binned_tp_fp_fn_xla,
    label_score_histograms,
    label_score_histograms_pallas,
    label_score_histograms_xla,
)
from metrics_tpu.kernels.segment_scatter import (  # noqa: F401
    segment_scatter_add,
    segment_scatter_add_pallas,
    segment_scatter_add_xla,
    segment_scatter_max,
    segment_scatter_max_pallas,
    segment_scatter_max_xla,
    segment_scatter_min,
    segment_scatter_min_pallas,
    segment_scatter_min_xla,
)
from metrics_tpu.kernels.stat_scores import (  # noqa: F401
    stat_scores_counts,
    stat_scores_counts_pallas,
    stat_scores_counts_xla,
)
from metrics_tpu.kernels.sketches import (  # noqa: F401
    bounded_priority_keep,
    cdf_sketch_cdf,
    cdf_sketch_quantile,
    hist_auroc,
    hist_average_precision,
    hist_precision_recall_curve,
    hist_roc,
    joint_grid_update,
    spearman_from_grid,
    uniform_hash,
    weighted_priority,
)
