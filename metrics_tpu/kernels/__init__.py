"""Pallas TPU kernels for the hot metric ops.

The reference leans on torch's tuned CPU/CUDA primitives for its inner loops
(``torch.bincount`` for the confusion matrix,
``functional/classification/confusion_matrix.py:291-310``; a Python threshold
loop for binned PR counts, ``classification/binned_precision_recall.py:147-152``).
Here the equivalents are hand-fused Pallas kernels that keep the per-batch
pass in VMEM and feed the MXU directly, with the plain-XLA formulations as
the portable fallback used on CPU and for any shape the kernel does not cover.

Dispatch contract: every kernel module exposes ``<op>(...)`` (auto: Pallas on
TPU when the shape qualifies, XLA otherwise) plus ``<op>_pallas`` /
``<op>_xla`` for explicit selection and testing (``interpret=True`` runs the
Pallas path on CPU).
"""
from metrics_tpu.kernels.confusion_matrix import (  # noqa: F401
    confmat_counts,
    confmat_counts_pallas,
    confmat_counts_xla,
)
from metrics_tpu.kernels.binned_counts import (  # noqa: F401
    binned_tp_fp_fn,
    binned_tp_fp_fn_xla,
    label_score_histograms,
)
from metrics_tpu.kernels.sketches import (  # noqa: F401
    bounded_priority_keep,
    cdf_sketch_cdf,
    cdf_sketch_quantile,
    hist_auroc,
    hist_average_precision,
    hist_precision_recall_curve,
    hist_roc,
    joint_grid_update,
    spearman_from_grid,
    uniform_hash,
    weighted_priority,
)
