"""Shared plumbing for the Pallas kernel modules: availability probe,
alignment helper, the common part of the auto-dispatch predicate, and the
``kernel.dispatch`` decision counters.

The dispatch counters are host-side bookkeeping in the health-guard style:
recording happens where the auto-dispatch decision is made (eagerly, or once
per trace when the ``<op>`` wrapper runs under ``jit``), never inside the
compiled program — the zero-overhead gate's byte-identical-jaxpr discipline
is untouched. They surface as ``observability.snapshot()["kernels"]`` and
the ``metrics_tpu_kernel_dispatch_total{op=...,path=...}`` Prometheus
family.
"""
import threading
from typing import Any, Dict

import jax

try:  # pltpu import fails on builds without TPU support compiled in
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_TPU_AVAILABLE = True
except ImportError:  # pragma: no cover
    pltpu = None
    _PALLAS_TPU_AVAILABLE = False

#: kernels accumulate counts in f32 (MXU output); counts stay integer-exact
#: up to 2^24, so auto-dispatch caps the element count there
_MAX_PALLAS_SAMPLES = 1 << 24


def _round_up(value: int, multiple: int) -> int:
    return ((value + multiple - 1) // multiple) * multiple


def pallas_auto_ok(num_elems: int) -> bool:
    """Common auto-dispatch gate: TPU backend, non-empty input, f32-exact counts."""
    return (
        _PALLAS_TPU_AVAILABLE
        and jax.default_backend() == "tpu"
        and 0 < num_elems <= _MAX_PALLAS_SAMPLES
    )


# --------------------------------------------------------------------------
# kernel.dispatch decision counters
# --------------------------------------------------------------------------

_DISPATCH_LOCK = threading.Lock()
#: ``{op: {"pallas": n, "xla": n}}`` — auto-dispatch decisions per kernel op
_DISPATCH_COUNTS: Dict[str, Dict[str, int]] = {}


def note_kernel_dispatch(op: str, path: str) -> None:
    """Record one auto-dispatch decision (``path`` ∈ ``pallas``/``xla``).

    Gated on the lock-free telemetry-enabled read like every other call
    site; a disabled stack pays one attribute read. Host-side only — when
    the ``<op>`` wrapper runs inside a trace this records once per trace,
    which is exactly when the decision is made (the compiled program replays
    it for free).
    """
    from metrics_tpu.observability.registry import TELEMETRY

    if not TELEMETRY.enabled:
        return
    with _DISPATCH_LOCK:
        by_path = _DISPATCH_COUNTS.setdefault(op, {})
        by_path[path] = by_path.get(path, 0) + 1


def dispatch_summary() -> Dict[str, Any]:
    """The ``snapshot()["kernels"]`` section: per-op dispatch-path counts."""
    with _DISPATCH_LOCK:
        return {"dispatch": {op: dict(paths) for op, paths in _DISPATCH_COUNTS.items()}}


def dispatch_count(op: str, path: str) -> int:
    """Point read of one decision counter (test/assert helper)."""
    with _DISPATCH_LOCK:
        return _DISPATCH_COUNTS.get(op, {}).get(path, 0)


def reset_dispatch_counters() -> None:
    """Zero the decision counters (tests; production counters are monotonic)."""
    with _DISPATCH_LOCK:
        _DISPATCH_COUNTS.clear()
