"""Shared plumbing for the Pallas kernel modules: availability probe,
alignment helper, and the common part of the auto-dispatch predicate."""
import jax

try:  # pltpu import fails on builds without TPU support compiled in
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_TPU_AVAILABLE = True
except ImportError:  # pragma: no cover
    pltpu = None
    _PALLAS_TPU_AVAILABLE = False

#: kernels accumulate counts in f32 (MXU output); counts stay integer-exact
#: up to 2^24, so auto-dispatch caps the element count there
_MAX_PALLAS_SAMPLES = 1 << 24


def _round_up(value: int, multiple: int) -> int:
    return ((value + multiple - 1) // multiple) * multiple


def pallas_auto_ok(num_elems: int) -> bool:
    """Common auto-dispatch gate: TPU backend, non-empty input, f32-exact counts."""
    return (
        _PALLAS_TPU_AVAILABLE
        and jax.default_backend() == "tpu"
        and 0 < num_elems <= _MAX_PALLAS_SAMPLES
    )
