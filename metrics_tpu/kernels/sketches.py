"""Mergeable fixed-size sketch summaries for the O(samples) metrics.

The reference (TorchMetrics v0.4.0) carries ``dist_reduce_fx="cat"`` list
states for every rank/threshold metric — AUROC, ROC, PrecisionRecallCurve,
AveragePrecision, Spearman, the retrieval family — so state size, memory,
and sync payloads all grow O(samples) with traffic. This module provides the
three bounded-memory summaries behind their ``sketched=True`` modes, each a
plain fixed-shape array state that merges by a cheap elementwise reduction
(``psum``-able across the mesh, ``+``-mergeable across batches):

1. **binned label histograms** — per-bin score counts split by label
   (:func:`~metrics_tpu.kernels.binned_counts.label_score_histograms`); the
   curve functions here (:func:`hist_auroc`, :func:`hist_roc`,
   :func:`hist_precision_recall_curve`, :func:`hist_average_precision`)
   reconstruct threshold metrics from the counts, treating each bin as one
   prediction tie group — exactly the tie handling of the masked curve
   kernels, so the result equals the exact computation whenever no two
   samples share a bin and degrades smoothly (O(1/num_bins)) otherwise.

2. **fixed-grid CDF sketch** — a value histogram over a static grid,
   supporting interpolated :func:`cdf_sketch_quantile` / :func:`cdf_sketch_cdf`
   queries, and its 2-D form :func:`joint_grid_update` /
   :func:`spearman_from_grid` computing Spearman's rho from joint bin counts
   with midrank tie correction (equal to the exact rho of the discretized
   stream).

3. **weighted reservoir sampling** — Efraimidis–Spirakis priorities
   (:func:`weighted_priority`) over deterministic per-id uniforms
   (:func:`uniform_hash`): keeping the ``capacity`` smallest keys draws a
   weighted sample without replacement, and because the key is a pure
   function of the id, independently-built reservoirs merge exactly
   (:func:`bounded_priority_keep`) — the generic fallback for metrics (the
   retrieval family) whose value is not a function of any fixed summary.

All functions are pure jnp (jit/vmap/scan-safe, zero host ops); counts are
float32 — exact integers far below 2**24, and directly ``psum``-reducible in
the packed (kind, dtype) sync buckets.
"""
from typing import Tuple

import jax.numpy as jnp
from jax import lax

from metrics_tpu.utilities.data import METRIC_EPS, Array

__all__ = [
    "bounded_priority_keep",
    "cdf_sketch_cdf",
    "cdf_sketch_quantile",
    "grid_index",
    "hist_auroc",
    "hist_average_precision",
    "hist_precision_recall_curve",
    "hist_roc",
    "joint_grid_update",
    "spearman_from_grid",
    "uniform_hash",
    "weighted_priority",
]


# ---------------------------------------------------------------------------
# binned label histograms -> threshold metrics
# ---------------------------------------------------------------------------
#
# Convention shared by all hist_* functions: ``pos_hist``/``neg_hist`` hold
# per-bin counts over the LAST axis (leading axes = classes/labels), bin b
# covering scores in [edge_b, edge_{b+1}) over an ascending grid.


def _rev_cumsum(x: Array) -> Array:
    """Inclusive cumulative sum from the top bin down, along the last axis."""
    return jnp.cumsum(x[..., ::-1], axis=-1)[..., ::-1]


def hist_auroc(pos_hist: Array, neg_hist: Array) -> Array:
    """AUROC from label histograms: the Mann-Whitney U with half credit for
    within-bin ties (== the trapezoid over the per-bin ROC segments).

    Degenerate single-label streams divide 0/0 -> NaN, matching the masked
    curve kernels and the reference's arithmetic.
    """
    pos = pos_hist.astype(jnp.float32)
    neg = neg_hist.astype(jnp.float32)
    p_total = jnp.sum(pos, axis=-1)
    n_total = jnp.sum(neg, axis=-1)
    pos_above = _rev_cumsum(pos) - pos  # positives in strictly higher bins
    u = jnp.sum(neg * (pos_above + 0.5 * pos), axis=-1)
    return u / (p_total * n_total)


def _desc_counts(pos_hist: Array, neg_hist: Array) -> Tuple[Array, Array]:
    """(tps, fps) cumulative counts walking thresholds DOWN the bin grid:
    position k holds the counts at threshold = lower edge of the k-th bin
    from the top (every sample in that bin and above)."""
    tps = jnp.cumsum(pos_hist[..., ::-1].astype(jnp.float32), axis=-1)
    fps = jnp.cumsum(neg_hist[..., ::-1].astype(jnp.float32), axis=-1)
    return tps, fps


def _bin_edges(num_bins: int, lo: float, hi: float) -> Array:
    """Ascending lower bin edges (``num_bins`` values in [lo, hi))."""
    return lo + (hi - lo) * jnp.arange(num_bins, dtype=jnp.float32) / num_bins


def hist_roc(pos_hist: Array, neg_hist: Array, lo: float = 0.0, hi: float = 1.0):
    """(fpr, tpr, thresholds) from label histograms — ``num_bins + 1`` curve
    points at descending thresholds (the exact ROC's orientation), starting
    from the (0, 0) point at threshold ``hi``."""
    num_bins = pos_hist.shape[-1]
    tps, fps = _desc_counts(pos_hist, neg_hist)
    p_total = tps[..., -1:]
    n_total = fps[..., -1:]
    zero = jnp.zeros(tps.shape[:-1] + (1,), jnp.float32)
    tpr = jnp.concatenate([zero, tps / p_total], axis=-1)
    fpr = jnp.concatenate([zero, fps / n_total], axis=-1)
    edges = _bin_edges(num_bins, lo, hi)
    thresholds = jnp.concatenate([jnp.asarray([hi], jnp.float32), edges[::-1]])
    return fpr, tpr, thresholds


def hist_precision_recall_curve(
    pos_hist: Array, neg_hist: Array, lo: float = 0.0, hi: float = 1.0
):
    """(precision, recall, thresholds) at the ascending bin edges, with the
    (1, 0) endpoint appended — the :class:`BinnedPrecisionRecallCurve` output
    convention (``num_bins + 1`` curve values over ``num_bins`` thresholds).
    """
    tps_desc, fps_desc = _desc_counts(pos_hist, neg_hist)
    tps = tps_desc[..., ::-1]  # ascending thresholds
    fps = fps_desc[..., ::-1]
    p_total = tps_desc[..., -1:]
    precision = (tps + METRIC_EPS) / (tps + fps + METRIC_EPS)
    recall = tps / jnp.maximum(p_total, METRIC_EPS)
    one = jnp.ones(precision.shape[:-1] + (1,), precision.dtype)
    zero = jnp.zeros(recall.shape[:-1] + (1,), recall.dtype)
    precision = jnp.concatenate([precision, one], axis=-1)
    recall = jnp.concatenate([recall, zero], axis=-1)
    return precision, recall, _bin_edges(pos_hist.shape[-1], lo, hi)


def hist_average_precision(pos_hist: Array, neg_hist: Array) -> Array:
    """AP = Σ Δrecall · precision over descending thresholds, each bin one
    tie group (the masked kernel's group-end tie handling). No-positive
    streams divide 0/0 -> NaN like the reference's recall."""
    tps, fps = _desc_counts(pos_hist, neg_hist)
    p_total = tps[..., -1:]
    precision = tps / jnp.maximum(tps + fps, METRIC_EPS)
    recall = tps / p_total
    recall_prev = jnp.concatenate(
        [jnp.zeros(recall.shape[:-1] + (1,), recall.dtype), recall[..., :-1]], axis=-1
    )
    return jnp.sum((recall - recall_prev) * precision, axis=-1)


# ---------------------------------------------------------------------------
# fixed-grid CDF sketch (quantiles / rank statistics)
# ---------------------------------------------------------------------------


def grid_index(x: Array, num_bins: int, lo: float, hi: float) -> Array:
    """Bin index of each value on the static ascending grid; out-of-range
    values clip into the edge bins (count them via :func:`clipped_count`)."""
    span = hi - lo
    raw = jnp.floor((x.astype(jnp.float32) - lo) / span * num_bins)
    return jnp.clip(raw, 0, num_bins - 1).astype(jnp.int32)


def clipped_count(x: Array, lo: float, hi: float) -> Array:
    """How many values fell outside [lo, hi] (clipped into an edge bin)."""
    out = (x < lo) | (x > hi)
    return jnp.sum(out).astype(jnp.float32)


def cdf_sketch_update(counts: Array, x: Array, lo: float, hi: float) -> Array:
    """Accumulate a batch into a ``(num_bins,)`` CDF sketch (merge = ``+``)."""
    idx = grid_index(jnp.ravel(x), counts.shape[-1], lo, hi)
    return counts.at[idx].add(1.0)


def cdf_sketch_cdf(counts: Array, v: Array, lo: float, hi: float) -> Array:
    """P(X <= v) under the sketch (bin mass attributed to the bin midpoint)."""
    num_bins = counts.shape[-1]
    total = jnp.maximum(jnp.sum(counts), 1.0)
    idx = grid_index(v, num_bins, lo, hi)
    cum = jnp.cumsum(counts)
    below = jnp.where(idx > 0, cum[jnp.maximum(idx - 1, 0)], 0.0)
    return (below + counts[idx] * 0.5) / total


def cdf_sketch_quantile(counts: Array, q: Array, lo: float, hi: float) -> Array:
    """Interpolated quantile(s): walk the cumulative mass to the target rank
    and interpolate linearly inside the crossing bin."""
    num_bins = counts.shape[-1]
    total = jnp.maximum(jnp.sum(counts), 1.0)
    cum = jnp.cumsum(counts)
    rank = jnp.asarray(q, jnp.float32) * total
    idx = jnp.clip(jnp.searchsorted(cum, rank, side="left"), 0, num_bins - 1)
    prev = jnp.where(idx > 0, cum[jnp.maximum(idx - 1, 0)], 0.0)
    in_bin = jnp.maximum(counts[idx], METRIC_EPS)
    frac = jnp.clip((rank - prev) / in_bin, 0.0, 1.0)
    width = (hi - lo) / num_bins
    return lo + (idx.astype(jnp.float32) + frac) * width


def joint_grid_update(
    grid: Array,
    x: Array,
    y: Array,
    x_range: Tuple[float, float],
    y_range: Tuple[float, float],
) -> Tuple[Array, Array]:
    """Accumulate (x, y) pairs into a ``(Bx, By)`` joint grid; returns the
    advanced grid and this batch's out-of-range (clipped) pair count."""
    bx, by = grid.shape
    x = jnp.ravel(x)
    y = jnp.ravel(y)
    ix = grid_index(x, bx, *x_range)
    iy = grid_index(y, by, *y_range)
    clipped = jnp.sum(
        (x < x_range[0]) | (x > x_range[1]) | (y < y_range[0]) | (y > y_range[1])
    ).astype(jnp.float32)
    return grid.at[ix, iy].add(1.0), clipped


def spearman_from_grid(grid: Array) -> Array:
    """Spearman's rho from joint bin counts with midrank tie correction —
    exactly the rho of the stream discretized onto the grid (error -> 0 as
    the grid refines for continuous in-range data). Empty grids divide
    0/0 -> NaN like the exact formula on an empty stream."""
    g = grid.astype(jnp.float32)
    nx = jnp.sum(g, axis=1)
    ny = jnp.sum(g, axis=0)
    n = jnp.sum(nx)
    # midrank of every bin: ranks 1..n, ties averaged within a bin
    rx = jnp.cumsum(nx) - nx + (nx + 1.0) / 2.0
    ry = jnp.cumsum(ny) - ny + (ny + 1.0) / 2.0
    rbar = (n + 1.0) / 2.0
    dx = rx - rbar
    dy = ry - rbar
    cov = dx @ (g @ dy)
    var_x = jnp.sum(nx * dx * dx)
    var_y = jnp.sum(ny * dy * dy)
    return cov / jnp.sqrt(var_x * var_y)


# ---------------------------------------------------------------------------
# weighted reservoir sampling (bounded-priority sample)
# ---------------------------------------------------------------------------


def uniform_hash(ids: Array) -> Array:
    """Deterministic uniform in [0, 1) per integer id (murmur3 finalizer).

    The same id hashes identically on every process and at every step, so
    independently-built reservoirs agree on priorities and merge exactly —
    no coordination, no PRNG state.
    """
    x = jnp.asarray(ids).astype(jnp.uint32) + jnp.uint32(0x9E3779B9)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x.astype(jnp.float32) / jnp.float32(4294967296.0)


def weighted_priority(uniform: Array, weight: Array = 1.0) -> Array:
    """Efraimidis–Spirakis priority: an Exp(weight) variate from a uniform.

    Keeping the ``capacity`` SMALLEST priorities draws a weighted sample
    without replacement (an item of weight w survives with probability
    proportional to w); ``weight=1`` degrades to uniform sampling.
    """
    u = jnp.clip(jnp.asarray(uniform, jnp.float32), 1e-12, 1.0)
    return -jnp.log(u) / jnp.asarray(weight, jnp.float32)


def bounded_priority_keep(
    keys: Array, tiebreak: Array, values: Tuple[Array, ...], capacity: int
) -> Tuple[Array, Array, Tuple[Array, ...]]:
    """Keep the ``capacity`` rows with the smallest ``(key, tiebreak)``.

    The two-key stable variadic sort carries the payload columns through the
    sort (no argsort+gather) and canonicalizes the row order, so repeated
    pushes and merges of the same row population produce identical buffers —
    the property the merge-associativity suite pins. Empty slots use
    ``key = +inf`` and naturally sort (and fall) off the end.
    """
    out = lax.sort((keys, tiebreak) + tuple(values), num_keys=2, is_stable=True)
    return out[0][:capacity], out[1][:capacity], tuple(v[:capacity] for v in out[2:])
