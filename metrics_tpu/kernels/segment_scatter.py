"""Fused segment-scatter accumulation kernel — the multi-tenant hot path.

The keyed tenant update (:mod:`metrics_tpu.wrappers.multitenant`) routes one
mixed event batch to N tenants' stacked states: bucket each row by tenant id,
clip-and-drop invalid ids, and scatter-accumulate the per-row state deltas
into the ``(N, ...)`` bundle. Two TPU-native formulations:

* **XLA fallback** — ``jax.ops.segment_sum`` over ids clipped to a discard
  bucket (row ``N`` of an ``N+1``-segment reduction that is sliced away).
  Portable, but the scatter serializes on TPU and each state leaf pays its
  own gather/scatter round-trip through HBM.
* **Pallas kernel** — the MXU formulation ``onehot(ids)ᵀ @ rows`` with the
  one-hot built inside the kernel (iota-compare in VMEM), the whole packed
  row-delta bundle contracted in ONE kernel: per grid step one
  ``(TILE, Ñ)ᵀ @ (TILE, D̃)`` accumulates into the ``(Ñ, D̃)`` output block
  kept resident in VMEM. Bucketing, clip-and-drop (invalid ids build an
  all-zero one-hot row — they can never scatter into a real segment), and
  the scatter-accumulate fuse into one VMEM-resident pass; a ones column
  smuggled into the padded row matrix yields the per-segment row counts from
  the same contraction.

Dispatch contract (see :mod:`metrics_tpu.kernels`): ``segment_scatter_add``
auto-dispatches, ``segment_scatter_add_pallas`` takes ``interpret=`` for CPU
testing, ``segment_scatter_add_xla`` is the portable formulation. Sums are
float32 — bit-identical to the XLA path for integer-valued data below 2^24
(the auto gate's sample cap), last-ulp reassociation tolerance for arbitrary
floats.

The **extremal leaves** (``"max"``/``"min"`` keyed reductions) get the same
three-way contract: ``segment_scatter_max`` / ``segment_scatter_min`` with
``_pallas`` / ``_xla`` variants. Max/min is not a contraction, so the Pallas
formulation is the VPU transpose: data arrives feature-major ``(D̃, R̃)``,
the per-tile one-hot masks each feature row against the segment iota, and a
lane-wise ``max``/``min`` reduction folds the ``(TILE, S̃)`` masked tile into
the VMEM-resident ``(D̃, S̃)`` extremum block — empty segments keep the
∓inf identity, exactly what ``jax.ops.segment_max``/``segment_min`` emit, so
results are bit-identical (extrema pick, they never reassociate).
"""
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from metrics_tpu.kernels._common import (
    _PALLAS_TPU_AVAILABLE,
    _round_up,
    note_kernel_dispatch,
    pallas_auto_ok,
    pltpu,
)

#: largest segment count the Pallas path handles: VMEM must hold the
#: (TILE, Ñ) one-hot tile plus the (Ñ, D̃) f32 accumulator
_MAX_PALLAS_SEGMENTS = 1024
#: largest packed feature width (D̃ = D + 1 for the smuggled counts column,
#: rounded to the 128-lane boundary)
_MAX_PALLAS_FEATURES = 511
_TILE = 256


def segment_scatter_pallas_ok(num_rows: int, num_segments: int, num_features: int) -> bool:
    """True when the auto dispatch would select the Pallas kernel for this
    shape: TPU backend plus the per-kernel VMEM shape limits."""
    return (
        pallas_auto_ok(num_rows * max(num_features, 1))
        and 1 <= num_segments <= _MAX_PALLAS_SEGMENTS
        and 1 <= num_features <= _MAX_PALLAS_FEATURES
    )


def segment_scatter_add_xla(
    rows: jax.Array, segment_ids: jax.Array, num_segments: int
) -> Tuple[jax.Array, jax.Array]:
    """Scatter-add formulation: ``((S, D) float32 sums, (S,) int32 counts)``.

    Invalid ids (negative or ``>= num_segments``) clip to a discard bucket
    and contribute to neither output.
    """
    ids = segment_ids.reshape(-1).astype(jnp.int32)
    valid = (ids >= 0) & (ids < num_segments)
    safe = jnp.where(valid, ids, num_segments)
    sums = jax.ops.segment_sum(
        rows.astype(jnp.float32), safe, num_segments=num_segments + 1
    )[:num_segments]
    counts = jax.ops.segment_sum(
        valid.astype(jnp.int32), safe, num_segments=num_segments + 1
    )[:num_segments]
    return sums, counts


def _scatter_kernel(ids_ref, data_ref, out_ref):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    segs = jax.lax.broadcasted_iota(jnp.int32, (1, out_ref.shape[0]), 1)
    # invalid / padded ids (-1, or >= the real segment count) either match no
    # column or match a padding row sliced away by the caller: clip-and-drop
    onehot = (ids_ref[:] == segs).astype(jnp.float32)  # (TILE, Ñ)
    out_ref[:] += jax.lax.dot_general(
        onehot,
        data_ref[:],
        dimension_numbers=(((0,), (0,)), ((), ())),  # contract over the tile axis
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("num_segments", "interpret"))
def segment_scatter_add_pallas(
    rows: jax.Array, segment_ids: jax.Array, num_segments: int, interpret: bool = False
) -> Tuple[jax.Array, jax.Array]:
    """MXU one-hot-contraction formulation of :func:`segment_scatter_add_xla`.

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU testing).
    """
    r, d = rows.shape
    spad = _round_up(num_segments, 128)
    dpad = _round_up(d + 1, 128)  # +1: the smuggled per-segment counts column
    npad = _round_up(max(r, _TILE), _TILE)

    ids = segment_ids.reshape(-1).astype(jnp.int32)
    ids_p = jnp.pad(ids, (0, npad - r), constant_values=-1).reshape(npad, 1)
    data = jnp.zeros((npad, dpad), jnp.float32)
    data = data.at[:r, :d].set(rows.astype(jnp.float32))
    data = data.at[:r, d].set(1.0)

    grid = npad // _TILE
    vmem = pltpu.VMEM if _PALLAS_TPU_AVAILABLE else None
    out = pl.pallas_call(
        _scatter_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_TILE, 1), lambda i: (i, 0), memory_space=vmem),
            pl.BlockSpec((_TILE, dpad), lambda i: (i, 0), memory_space=vmem),
        ],
        out_specs=pl.BlockSpec((spad, dpad), lambda i: (0, 0), memory_space=vmem),
        out_shape=jax.ShapeDtypeStruct((spad, dpad), jnp.float32),
        interpret=interpret,
    )(ids_p, data)
    return out[:num_segments, :d], out[:num_segments, d].astype(jnp.int32)


def segment_scatter_add(
    rows: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    use_pallas: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Segment-scatter accumulation with automatic backend dispatch.

    ``rows`` is ``(R, D)`` per-row values, ``segment_ids`` the rank-1 routing
    vector; returns ``((S, D) float32 sums, (S,) int32 valid-row counts)``.
    ``use_pallas=None`` selects the Pallas kernel on a TPU backend when the
    shape fits the VMEM gates and the XLA scatter otherwise; the decision
    lands on the ``kernel.dispatch`` telemetry counter either way.
    """
    if use_pallas is None:
        use_pallas = segment_scatter_pallas_ok(rows.shape[0], num_segments, rows.shape[1])
    note_kernel_dispatch("segment_scatter_add", "pallas" if use_pallas else "xla")
    if use_pallas:
        return segment_scatter_add_pallas(rows, segment_ids, num_segments)
    return segment_scatter_add_xla(rows, segment_ids, num_segments)


# ---------------------------------------------------------------------------
# extremal leaves: masked segment max / min
# ---------------------------------------------------------------------------

#: widest feature bundle the extremal kernel unrolls (the VPU formulation
#: statically unrolls one masked reduction per feature row — extremal keyed
#: leaves are narrow scalars/small vectors, so a tight cap keeps compile
#: time and VMEM traffic bounded)
_MAX_EXTREMAL_FEATURES = 16


def segment_scatter_extremal_ok(
    num_rows: int, num_segments: int, num_features: int
) -> bool:
    """True when the auto dispatch would select the Pallas extremal kernel:
    TPU backend plus the per-feature unroll and segment-lane shape gates."""
    return (
        pallas_auto_ok(num_rows * max(num_features, 1))
        and 1 <= num_segments <= _MAX_PALLAS_SEGMENTS
        and 1 <= num_features <= _MAX_EXTREMAL_FEATURES
    )


def _segment_scatter_extremal_xla(
    rows: jax.Array, segment_ids: jax.Array, num_segments: int, op: str
) -> Tuple[jax.Array, jax.Array]:
    ids = segment_ids.reshape(-1).astype(jnp.int32)
    valid = (ids >= 0) & (ids < num_segments)
    safe = jnp.where(valid, ids, num_segments)
    seg_fn = jax.ops.segment_max if op == "max" else jax.ops.segment_min
    ext = seg_fn(
        rows.astype(jnp.float32), safe, num_segments=num_segments + 1
    )[:num_segments]
    counts = jax.ops.segment_sum(
        valid.astype(jnp.int32), safe, num_segments=num_segments + 1
    )[:num_segments]
    return ext, counts


def segment_scatter_max_xla(
    rows: jax.Array, segment_ids: jax.Array, num_segments: int
) -> Tuple[jax.Array, jax.Array]:
    """Masked segment max: ``((S, D) float32 extrema, (S,) int32 counts)``.

    Invalid ids clip to the discard bucket; segments with no valid rows hold
    the ``-inf`` identity, so callers mask with ``counts > 0``.
    """
    return _segment_scatter_extremal_xla(rows, segment_ids, num_segments, "max")


def segment_scatter_min_xla(
    rows: jax.Array, segment_ids: jax.Array, num_segments: int
) -> Tuple[jax.Array, jax.Array]:
    """Masked segment min — :func:`segment_scatter_max_xla` with the ``+inf``
    identity for empty segments."""
    return _segment_scatter_extremal_xla(rows, segment_ids, num_segments, "min")


def _extremal_kernel(op: str, d: int):
    """Kernel factory: ``op`` and the true feature count are trace-static.

    Row ``d`` of the output block smuggles the per-segment valid-row counts
    (f32 accumulation — exact below 2^24 rows), mirroring the add kernel's
    ones column.
    """
    fill = float("-inf") if op == "max" else float("inf")
    combine = jnp.maximum if op == "max" else jnp.minimum
    reduce_fn = jnp.max if op == "max" else jnp.min

    def kernel(ids_ref, data_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            out_ref[:] = jnp.full(out_ref.shape, fill, out_ref.dtype)
            out_ref[d, :] = jnp.zeros((out_ref.shape[1],), out_ref.dtype)

        segs = jax.lax.broadcasted_iota(jnp.int32, (1, out_ref.shape[1]), 1)
        # padded ids (-1) match no lane; ids in the padding band land on a
        # lane the caller slices away — clip-and-drop, same as the add kernel
        onehot = ids_ref[:] == segs  # (TILE, S̃) bool
        out_ref[d, :] += jnp.sum(onehot.astype(jnp.float32), axis=0)
        for j in range(d):  # static unroll — gated by _MAX_EXTREMAL_FEATURES
            col = data_ref[j, :].reshape(-1, 1)
            masked = jnp.where(onehot, col, fill)
            out_ref[j, :] = combine(out_ref[j, :], reduce_fn(masked, axis=0))

    return kernel


@functools.partial(jax.jit, static_argnames=("num_segments", "op", "interpret"))
def _segment_scatter_extremal_pallas(
    rows: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    op: str,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    r, d = rows.shape
    spad = _round_up(num_segments, 128)
    kpad = _round_up(d + 1, 8)  # +1: the smuggled counts row; 8 = f32 sublane
    npad = _round_up(max(r, _TILE), _TILE)

    ids = segment_ids.reshape(-1).astype(jnp.int32)
    ids_p = jnp.pad(ids, (0, npad - r), constant_values=-1).reshape(npad, 1)
    data_t = jnp.zeros((kpad, npad), jnp.float32)
    data_t = data_t.at[:d, :r].set(rows.astype(jnp.float32).T)

    grid = npad // _TILE
    vmem = pltpu.VMEM if _PALLAS_TPU_AVAILABLE else None
    out = pl.pallas_call(
        _extremal_kernel(op, d),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_TILE, 1), lambda i: (i, 0), memory_space=vmem),
            pl.BlockSpec((kpad, _TILE), lambda i: (0, i), memory_space=vmem),
        ],
        out_specs=pl.BlockSpec((kpad, spad), lambda i: (0, 0), memory_space=vmem),
        out_shape=jax.ShapeDtypeStruct((kpad, spad), jnp.float32),
        interpret=interpret,
    )(ids_p, data_t)
    return out[:d, :num_segments].T, out[d, :num_segments].astype(jnp.int32)


def segment_scatter_max_pallas(
    rows: jax.Array, segment_ids: jax.Array, num_segments: int, interpret: bool = False
) -> Tuple[jax.Array, jax.Array]:
    """VPU masked-reduction formulation of :func:`segment_scatter_max_xla`.

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU testing).
    """
    return _segment_scatter_extremal_pallas(
        rows, segment_ids, num_segments, "max", interpret=interpret
    )


def segment_scatter_min_pallas(
    rows: jax.Array, segment_ids: jax.Array, num_segments: int, interpret: bool = False
) -> Tuple[jax.Array, jax.Array]:
    """VPU masked-reduction formulation of :func:`segment_scatter_min_xla`."""
    return _segment_scatter_extremal_pallas(
        rows, segment_ids, num_segments, "min", interpret=interpret
    )


def _segment_scatter_extremal(
    rows: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    op: str,
    use_pallas: Optional[bool],
) -> Tuple[jax.Array, jax.Array]:
    if use_pallas is None:
        use_pallas = segment_scatter_extremal_ok(
            rows.shape[0], num_segments, rows.shape[1]
        )
    note_kernel_dispatch(f"segment_scatter_{op}", "pallas" if use_pallas else "xla")
    if use_pallas:
        return _segment_scatter_extremal_pallas(rows, segment_ids, num_segments, op)
    return _segment_scatter_extremal_xla(rows, segment_ids, num_segments, op)


def segment_scatter_max(
    rows: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    use_pallas: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Masked segment max with automatic backend dispatch.

    Same contract as :func:`segment_scatter_add`: ``(R, D)`` rows, rank-1
    routing ids, ``((S, D) float32 extrema, (S,) int32 valid-row counts)``;
    the dispatch decision lands on ``kernel.dispatch`` telemetry either way.
    Extrema pick — results are bit-identical across backends, not just for
    integer data.
    """
    return _segment_scatter_extremal(rows, segment_ids, num_segments, "max", use_pallas)


def segment_scatter_min(
    rows: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    use_pallas: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Masked segment min with automatic backend dispatch — see
    :func:`segment_scatter_max`."""
    return _segment_scatter_extremal(rows, segment_ids, num_segments, "min", use_pallas)
