"""Fused segment-scatter accumulation kernel — the multi-tenant hot path.

The keyed tenant update (:mod:`metrics_tpu.wrappers.multitenant`) routes one
mixed event batch to N tenants' stacked states: bucket each row by tenant id,
clip-and-drop invalid ids, and scatter-accumulate the per-row state deltas
into the ``(N, ...)`` bundle. Two TPU-native formulations:

* **XLA fallback** — ``jax.ops.segment_sum`` over ids clipped to a discard
  bucket (row ``N`` of an ``N+1``-segment reduction that is sliced away).
  Portable, but the scatter serializes on TPU and each state leaf pays its
  own gather/scatter round-trip through HBM.
* **Pallas kernel** — the MXU formulation ``onehot(ids)ᵀ @ rows`` with the
  one-hot built inside the kernel (iota-compare in VMEM), the whole packed
  row-delta bundle contracted in ONE kernel: per grid step one
  ``(TILE, Ñ)ᵀ @ (TILE, D̃)`` accumulates into the ``(Ñ, D̃)`` output block
  kept resident in VMEM. Bucketing, clip-and-drop (invalid ids build an
  all-zero one-hot row — they can never scatter into a real segment), and
  the scatter-accumulate fuse into one VMEM-resident pass; a ones column
  smuggled into the padded row matrix yields the per-segment row counts from
  the same contraction.

Dispatch contract (see :mod:`metrics_tpu.kernels`): ``segment_scatter_add``
auto-dispatches, ``segment_scatter_add_pallas`` takes ``interpret=`` for CPU
testing, ``segment_scatter_add_xla`` is the portable formulation. Sums are
float32 — bit-identical to the XLA path for integer-valued data below 2^24
(the auto gate's sample cap), last-ulp reassociation tolerance for arbitrary
floats.
"""
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from metrics_tpu.kernels._common import (
    _PALLAS_TPU_AVAILABLE,
    _round_up,
    note_kernel_dispatch,
    pallas_auto_ok,
    pltpu,
)

#: largest segment count the Pallas path handles: VMEM must hold the
#: (TILE, Ñ) one-hot tile plus the (Ñ, D̃) f32 accumulator
_MAX_PALLAS_SEGMENTS = 1024
#: largest packed feature width (D̃ = D + 1 for the smuggled counts column,
#: rounded to the 128-lane boundary)
_MAX_PALLAS_FEATURES = 511
_TILE = 256


def segment_scatter_pallas_ok(num_rows: int, num_segments: int, num_features: int) -> bool:
    """True when the auto dispatch would select the Pallas kernel for this
    shape: TPU backend plus the per-kernel VMEM shape limits."""
    return (
        pallas_auto_ok(num_rows * max(num_features, 1))
        and 1 <= num_segments <= _MAX_PALLAS_SEGMENTS
        and 1 <= num_features <= _MAX_PALLAS_FEATURES
    )


def segment_scatter_add_xla(
    rows: jax.Array, segment_ids: jax.Array, num_segments: int
) -> Tuple[jax.Array, jax.Array]:
    """Scatter-add formulation: ``((S, D) float32 sums, (S,) int32 counts)``.

    Invalid ids (negative or ``>= num_segments``) clip to a discard bucket
    and contribute to neither output.
    """
    ids = segment_ids.reshape(-1).astype(jnp.int32)
    valid = (ids >= 0) & (ids < num_segments)
    safe = jnp.where(valid, ids, num_segments)
    sums = jax.ops.segment_sum(
        rows.astype(jnp.float32), safe, num_segments=num_segments + 1
    )[:num_segments]
    counts = jax.ops.segment_sum(
        valid.astype(jnp.int32), safe, num_segments=num_segments + 1
    )[:num_segments]
    return sums, counts


def _scatter_kernel(ids_ref, data_ref, out_ref):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    segs = jax.lax.broadcasted_iota(jnp.int32, (1, out_ref.shape[0]), 1)
    # invalid / padded ids (-1, or >= the real segment count) either match no
    # column or match a padding row sliced away by the caller: clip-and-drop
    onehot = (ids_ref[:] == segs).astype(jnp.float32)  # (TILE, Ñ)
    out_ref[:] += jax.lax.dot_general(
        onehot,
        data_ref[:],
        dimension_numbers=(((0,), (0,)), ((), ())),  # contract over the tile axis
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("num_segments", "interpret"))
def segment_scatter_add_pallas(
    rows: jax.Array, segment_ids: jax.Array, num_segments: int, interpret: bool = False
) -> Tuple[jax.Array, jax.Array]:
    """MXU one-hot-contraction formulation of :func:`segment_scatter_add_xla`.

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU testing).
    """
    r, d = rows.shape
    spad = _round_up(num_segments, 128)
    dpad = _round_up(d + 1, 128)  # +1: the smuggled per-segment counts column
    npad = _round_up(max(r, _TILE), _TILE)

    ids = segment_ids.reshape(-1).astype(jnp.int32)
    ids_p = jnp.pad(ids, (0, npad - r), constant_values=-1).reshape(npad, 1)
    data = jnp.zeros((npad, dpad), jnp.float32)
    data = data.at[:r, :d].set(rows.astype(jnp.float32))
    data = data.at[:r, d].set(1.0)

    grid = npad // _TILE
    vmem = pltpu.VMEM if _PALLAS_TPU_AVAILABLE else None
    out = pl.pallas_call(
        _scatter_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_TILE, 1), lambda i: (i, 0), memory_space=vmem),
            pl.BlockSpec((_TILE, dpad), lambda i: (i, 0), memory_space=vmem),
        ],
        out_specs=pl.BlockSpec((spad, dpad), lambda i: (0, 0), memory_space=vmem),
        out_shape=jax.ShapeDtypeStruct((spad, dpad), jnp.float32),
        interpret=interpret,
    )(ids_p, data)
    return out[:num_segments, :d], out[:num_segments, d].astype(jnp.int32)


def segment_scatter_add(
    rows: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    use_pallas: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Segment-scatter accumulation with automatic backend dispatch.

    ``rows`` is ``(R, D)`` per-row values, ``segment_ids`` the rank-1 routing
    vector; returns ``((S, D) float32 sums, (S,) int32 valid-row counts)``.
    ``use_pallas=None`` selects the Pallas kernel on a TPU backend when the
    shape fits the VMEM gates and the XLA scatter otherwise; the decision
    lands on the ``kernel.dispatch`` telemetry counter either way.
    """
    if use_pallas is None:
        use_pallas = segment_scatter_pallas_ok(rows.shape[0], num_segments, rows.shape[1])
    note_kernel_dispatch("segment_scatter_add", "pallas" if use_pallas else "xla")
    if use_pallas:
        return segment_scatter_add_pallas(rows, segment_ids, num_segments)
    return segment_scatter_add_xla(rows, segment_ids, num_segments)
