"""Fused tp/fp/tn/fn counting kernel — the stat-scores family's inner loop.

The per-class confusion counts behind the Precision/Recall/F1/Specificity/
StatScores quintet (the compute-group flagship) reduce canonical binary
``(N, C)`` inputs with four masked sums
(``functional/classification/stat_scores.py::_stat_scores``, parity with the
reference's ``stat_scores.py:29-75``). Two TPU-native formulations:

* **XLA fallback** — the one-hot compare chain: four boolean masks, four
  reductions. XLA fuses them, but each mask/reduce pair walks the ``(N, C)``
  operands again.
* **Pallas kernel** — all four counts in ONE VMEM-resident pass: per grid
  step one ``(TILE, C̃)`` block of preds/target builds the four masks in
  VMEM and accumulates four rows of the resident ``(8, C̃)`` output block
  (rows 4–7 are sublane padding). Padded rows carry the sentinel pair
  ``preds=-1, target=-2``, which satisfies none of the four masks — they can
  never count.

Dispatch contract (see :mod:`metrics_tpu.kernels`): ``stat_scores_counts``
auto-dispatches, ``stat_scores_counts_pallas`` takes ``interpret=`` for CPU
testing, ``stat_scores_counts_xla`` is the portable formulation. Counts are
int32 and bit-identical between the two paths (f32 accumulation is exact
below 2^24, the auto gate's sample cap).
"""
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from metrics_tpu.kernels._common import (
    _PALLAS_TPU_AVAILABLE,
    _round_up,
    note_kernel_dispatch,
    pallas_auto_ok,
    pltpu,
)

#: largest C the Pallas path handles: VMEM holds two (TILE, C̃) int blocks
#: plus the (8, C̃) f32 accumulator
_MAX_PALLAS_CLASSES = 2048
_TILE = 256


def stat_scores_pallas_ok(num_rows: int, num_classes: int) -> bool:
    """True when the auto dispatch would select the Pallas kernel for this
    shape: TPU backend plus the per-kernel VMEM shape limits."""
    return (
        pallas_auto_ok(num_rows * max(num_classes, 1))
        and 1 <= num_classes <= _MAX_PALLAS_CLASSES
    )


def stat_scores_counts_xla(
    preds: jax.Array, target: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """One-hot compare-chain formulation: four ``(C,)`` int32 count vectors
    over canonical binary ``(N, C)`` inputs (the ``reduce="macro"`` sums of
    ``functional/classification/stat_scores.py::_stat_scores``)."""
    true_pred = target == preds
    false_pred = target != preds
    pos_pred = preds == 1
    neg_pred = preds == 0
    tp = jnp.sum(true_pred & pos_pred, axis=0)
    fp = jnp.sum(false_pred & pos_pred, axis=0)
    tn = jnp.sum(true_pred & neg_pred, axis=0)
    fn = jnp.sum(false_pred & neg_pred, axis=0)
    dtype = jnp.int32
    return tp.astype(dtype), fp.astype(dtype), tn.astype(dtype), fn.astype(dtype)


def _stat_scores_kernel(p_ref, t_ref, out_ref):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    p = p_ref[:]
    t = t_ref[:]
    eq = t == p
    pos = p == 1
    neg = p == 0

    def count(mask):  # (TILE, C̃) -> (1, C̃) f32 partial sums
        return jnp.sum(mask.astype(jnp.float32), axis=0, keepdims=True)

    tp, fp = count(eq & pos), count(jnp.logical_not(eq) & pos)
    tn, fn = count(eq & neg), count(jnp.logical_not(eq) & neg)
    pad = jnp.zeros((4, tp.shape[1]), jnp.float32)  # sublane-align to 8 rows
    out_ref[:] += jnp.concatenate([tp, fp, tn, fn, pad], axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def stat_scores_counts_pallas(
    preds: jax.Array, target: jax.Array, interpret: bool = False
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fused four-mask formulation of :func:`stat_scores_counts_xla`.

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU testing).
    """
    n, c = preds.shape
    cpad = _round_up(c, 128)
    npad = _round_up(max(n, _TILE), _TILE)

    def pad(a: jax.Array, sentinel: int) -> jax.Array:
        a = a.astype(jnp.int32)
        return jnp.pad(
            a, ((0, npad - n), (0, cpad - c)), constant_values=sentinel
        )

    grid = npad // _TILE
    vmem = pltpu.VMEM if _PALLAS_TPU_AVAILABLE else None
    block = lambda: pl.BlockSpec((_TILE, cpad), lambda i: (i, 0), memory_space=vmem)  # noqa: E731
    out = pl.pallas_call(
        _stat_scores_kernel,
        grid=(grid,),
        in_specs=[block(), block()],
        out_specs=pl.BlockSpec((8, cpad), lambda i: (0, 0), memory_space=vmem),
        out_shape=jax.ShapeDtypeStruct((8, cpad), jnp.float32),
        interpret=interpret,
    )(pad(preds, -1), pad(target, -2))
    counts = out[:4, :c].astype(jnp.int32)
    return counts[0], counts[1], counts[2], counts[3]


def stat_scores_counts(
    preds: jax.Array, target: jax.Array, use_pallas: Optional[bool] = None
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Per-class tp/fp/tn/fn counts with automatic backend dispatch.

    Inputs are canonical binary ``(N, C)`` arrays (the
    ``_input_format_classification`` output); returns four ``(C,)`` int32
    vectors. ``use_pallas=None`` selects the Pallas kernel on a TPU backend
    when the shape fits the VMEM gates and the XLA compare chain otherwise;
    the decision lands on the ``kernel.dispatch`` telemetry counter either
    way.
    """
    if use_pallas is None:
        use_pallas = stat_scores_pallas_ok(preds.shape[0], preds.shape[1])
    note_kernel_dispatch("stat_scores_counts", "pallas" if use_pallas else "xla")
    if use_pallas:
        return stat_scores_counts_pallas(preds, target)
    return stat_scores_counts_xla(preds, target)


def stat_scores_counts_auto(
    preds: jax.Array, target: jax.Array
) -> Optional[Tuple[jax.Array, jax.Array, jax.Array, jax.Array]]:
    """The seam :func:`~metrics_tpu.functional.classification.stat_scores._stat_scores`
    consults on its macro 2-D path: the fused kernel's counts when the auto
    gate selects Pallas, ``None`` otherwise — the caller then runs its own
    (pre-existing) XLA lowering, byte-identical to the kernels-off program
    (the zero-overhead discipline). The decision is recorded either way.
    """
    if stat_scores_pallas_ok(preds.shape[0], preds.shape[1]):
        note_kernel_dispatch("stat_scores_counts", "pallas")
        return stat_scores_counts_pallas(preds, target)
    note_kernel_dispatch("stat_scores_counts", "xla")
    return None
