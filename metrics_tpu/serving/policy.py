"""Backpressure + load-shedding policies for the admission queue.

A long-running evaluation service cannot assume the dispatch side keeps up
with ingest forever: a recompile storm, a sick endpoint, or a traffic spike
can push the admission queue to capacity. What happens next is a *policy*
decision, and every outcome must be **exactly accounted** — a shed row that
is not counted is indistinguishable from a lost update, which breaks the
soak harness's zero-lost-updates invariant (rows admitted − rows shed ==
rows ingested into tenant state).

Three policies, selected by name (``AdmissionQueue(policy=...)``):

* ``"block"`` — classic backpressure: the producer thread waits (bounded by
  ``block_timeout_s``) until the flusher drains room. Nothing is ever shed;
  ingest latency absorbs the pressure. Rows still unplaceable at the
  timeout are rejected and counted (``shed_rows{reason="block_timeout"}``).
* ``"shed_oldest"`` — bounded-latency ingest: the oldest *queued* rows are
  dropped to admit the new ones (``reason="shed_oldest"``). The freshest
  data wins — the right trade for dashboard-shaped metrics where a stale
  sample is worth less than a current one.
* ``"shed_tenant_over_quota"`` — noisy-neighbor isolation: an incoming row
  whose tenant already holds ``tenant_quota_rows`` queued rows is rejected
  (``reason="tenant_over_quota"``); tenants under quota are admitted even
  at the same instant. A single hot tenant cannot evict everyone else's
  rows. When the queue is full of *under-quota* rows the policy falls back
  to shedding the incoming row (``reason="queue_full"``) rather than
  blocking the producer.

Every decision is host-side Python (zero traced ops) and is recorded in the
``serving.*`` telemetry family (:mod:`metrics_tpu.serving.telemetry`).
"""
from typing import Optional

__all__ = ["POLICIES", "resolve_policy", "AdmissionPolicy"]

#: the selectable admission policies
POLICIES = ("block", "shed_oldest", "shed_tenant_over_quota")

#: shed-accounting reasons each policy can emit (docs + tests pin these)
SHED_REASONS = ("block_timeout", "shed_oldest", "tenant_over_quota", "queue_full")


class AdmissionPolicy:
    """Value object naming one admission policy and its knobs.

    The queue consults :attr:`name` at admission time; the policy itself
    holds only configuration (it is shareable across queues and threads).
    """

    __slots__ = ("name", "block_timeout_s", "tenant_quota_rows")

    def __init__(
        self,
        name: str,
        *,
        block_timeout_s: Optional[float] = None,
        tenant_quota_rows: Optional[int] = None,
    ) -> None:
        if name not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}, got {name!r}")
        if block_timeout_s is not None and block_timeout_s < 0:
            raise ValueError(f"block_timeout_s must be >= 0, got {block_timeout_s}")
        if tenant_quota_rows is not None and int(tenant_quota_rows) < 1:
            raise ValueError(
                f"tenant_quota_rows must be >= 1, got {tenant_quota_rows}"
            )
        self.name = name
        self.block_timeout_s = block_timeout_s
        self.tenant_quota_rows = (
            int(tenant_quota_rows) if tenant_quota_rows is not None else None
        )

    def __repr__(self) -> str:
        extra = ""
        if self.block_timeout_s is not None:
            extra += f", block_timeout_s={self.block_timeout_s}"
        if self.tenant_quota_rows is not None:
            extra += f", tenant_quota_rows={self.tenant_quota_rows}"
        return f"AdmissionPolicy({self.name!r}{extra})"


def resolve_policy(policy, **kwargs) -> AdmissionPolicy:
    """``AdmissionPolicy`` from a name or a ready-made instance (the queue's
    constructor seam). Keyword knobs apply only to the name form."""
    if isinstance(policy, AdmissionPolicy):
        if kwargs:
            raise ValueError(
                "pass policy knobs inside the AdmissionPolicy instance, not"
                f" alongside it: {sorted(kwargs)}"
            )
        return policy
    return AdmissionPolicy(str(policy), **kwargs)
