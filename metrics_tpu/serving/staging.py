"""Columnar staging for the admission queue — device-resident ingest.

The unstaged :class:`~metrics_tpu.serving.queue.AdmissionQueue` keeps every
resident row as a Python tuple and pays the cohort-formation bill inside the
flush: a per-row ``np.stack`` per column, a fresh pad block per bucket, and
the H2D conversion inside the compiled dispatch — all of it serialized under
the dispatch lock, all of it host-queue latency. The staged path moves that
work to where it is cheap:

* **submit time** writes rows straight into a :class:`StagingRing` — one
  preallocated pow2 circular buffer per update-argument column (plus the id,
  submit-timestamp, and trace-cohort columns). Admission order IS ring
  order: the queue pops contiguous sequence ranges, so cohort formation is
  one or two slice copies per column into a reusable :class:`slot
  <StagingSlotPool>`, never a per-row pass.
* **stage time** (a prefetch job on the PR-9 async ``staging`` lane, or the
  flush thread when nothing was prefetched) runs the vectorized quarantine
  scan over the slot columns, folds the pow2 pad in place (ids ``-1``,
  zeroed columns — the compiled program's ``validate_ids=False`` discard
  bucket drops them), and transfers the cohort to the device ahead of the
  dispatch (``jnp.array`` — an owning copy, so slot reuse can never alias a
  live device buffer).
* **dispatch time** hands the target :class:`StagedColumn` views — ndarray
  views over the slot carrying their already-transferred ``jax_array``
  twin. The wrapper layer (duck-typed on the attribute, see
  ``KeyedMetric.update``) dispatches the twin, so the serialized section
  pays no H2D conversion; host-side consumers (validation, traffic ledgers,
  the scheduler's ``np.unique``) read the view without a device sync.

Ring-span safety: sequence numbers are monotonic and the pending window is
always a contiguous range (admissions append at the head; sheds and pops
only ever remove from the front), so a live row is overwritten only if the
span head − oldest-uncopied exceeds the ring capacity. The queue sizes the
ring at ``pow2(capacity_rows + slots * max_batch)`` and acquires a slot
*before* popping, which bounds popped-but-uncopied rows at
``slots * max_batch`` — the span cannot outrun the ring.

Pickle/clone drops every buffer (a staged queue's ring and slots are scratch
tied to this process's threads and device); the rebuilt object re-binds its
layout lazily on the first row it sees.
"""
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "StagedColumn",
    "StagedCohort",
    "StagingRing",
    "StagingSlotPool",
    "as_staged",
    "stage_layout",
]

#: layout entry per staged column: (dtype string, trailing shape)
Layout = Tuple[Tuple[str, Tuple[int, ...]], ...]


def _pow2_at_least(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


class StagedColumn(np.ndarray):
    """An ndarray view over a staging slot carrying its device twin.

    ``jax_array`` is the already-transferred device copy (``None`` when
    staging transfer is off or the twin was dropped). Any derived view,
    copy, or unpickle drops the twin — it is only valid for the exact view
    the stager attached it to.
    """

    jax_array: Optional[Any] = None

    def __array_finalize__(self, obj: Optional[np.ndarray]) -> None:
        # never propagate the twin through slicing/ufuncs/pickle: a derived
        # array no longer matches the transferred buffer
        self.jax_array = None


def as_staged(host: np.ndarray, device: Optional[Any]) -> np.ndarray:
    """Wrap ``host`` as a :class:`StagedColumn` carrying ``device``.

    With ``device=None`` the plain host array is returned untouched — the
    unstaged-transfer path hands the target ordinary numpy and the wrapper
    layer behaves exactly as before.
    """
    if device is None:
        return host
    view = host.view(StagedColumn)
    view.jax_array = device
    return view


def stage_layout(cols: Sequence[np.ndarray]) -> Layout:
    """The schema key a ring/slot binds to: per-column dtype + trailing
    (per-row) shape. Rows are compared on this, never on batch length."""
    return tuple((str(c.dtype), tuple(c.shape[1:])) for c in cols)


class StagingRing:
    """Pow2 columnar ring buffer: one circular array per staged column.

    The caller (the queue, under its admission lock) owns all
    synchronization of ``alloc``; block writes to disjoint index ranges are
    plain numpy slice stores and may race with reads of *other* ranges.
    Layout binds lazily on the first write and re-binds only through
    :meth:`bind` (the queue allows it only with zero live rows).
    """

    def __init__(self, capacity_rows: int) -> None:
        if int(capacity_rows) < 1:
            raise ValueError(f"capacity_rows must be >= 1, got {capacity_rows}")
        self.capacity = _pow2_at_least(int(capacity_rows))
        self._mask = self.capacity - 1
        self.head = 0  # next sequence number to allocate
        self.layout: Optional[Layout] = None
        self.ids: Optional[np.ndarray] = None
        self.t_submit: Optional[np.ndarray] = None
        self.cohorts: Optional[np.ndarray] = None
        self.cols: List[np.ndarray] = []

    @property
    def bound(self) -> bool:
        return self.layout is not None

    def bind(self, layout: Layout) -> None:
        """(Re)allocate every column buffer for ``layout``."""
        self.layout = layout
        self.ids = np.empty(self.capacity, dtype=np.int32)
        self.t_submit = np.empty(self.capacity, dtype=np.float64)
        self.cohorts = np.empty(self.capacity, dtype=object)
        self.cols = [
            np.zeros((self.capacity,) + shape, dtype=dtype) for dtype, shape in layout
        ]

    def alloc(self, n: int = 1) -> int:
        """Reserve ``n`` consecutive sequence numbers; returns the first."""
        seq0 = self.head
        self.head += n
        return seq0

    def write_row(
        self, seq: int, tenant: int, t: float, cohort: Optional[str], values: Sequence[Any]
    ) -> None:
        i = seq & self._mask
        self.ids[i] = tenant
        self.t_submit[i] = t
        self.cohorts[i] = cohort
        for buf, v in zip(self.cols, values):
            buf[i] = v

    def write_rows(
        self,
        seq0: int,
        tenants: np.ndarray,
        t: float,
        cohort: Optional[str],
        columns: Sequence[np.ndarray],
    ) -> None:
        """Bulk write ``len(tenants)`` rows at ``[seq0, seq0 + n)`` — at most
        two slice stores per column (wraparound split)."""
        n = int(tenants.shape[0])
        if n == 0:
            return
        i = seq0 & self._mask
        k = min(n, self.capacity - i)
        self.ids[i : i + k] = tenants[:k]
        self.t_submit[i : i + k] = t
        self.cohorts[i : i + k] = cohort
        for buf, col in zip(self.cols, columns):
            buf[i : i + k] = col[:k]
        if k < n:
            rest = n - k
            self.ids[:rest] = tenants[k:]
            self.t_submit[:rest] = t
            self.cohorts[:rest] = cohort
            for buf, col in zip(self.cols, columns):
                buf[:rest] = col[k:]

    def read_ids(self, seq0: int, n: int) -> np.ndarray:
        """The id column for ``[seq0, seq0 + n)`` (a copy — callers use it
        for per-tenant accounting while producers keep writing)."""
        out = np.empty(n, dtype=np.int32)
        i = seq0 & self._mask
        k = min(n, self.capacity - i)
        out[:k] = self.ids[i : i + k]
        if k < n:
            out[k:] = self.ids[: n - k]
        return out

    def copy_out(self, seq0: int, n: int, slot: "StagingSlot") -> None:
        """Copy rows ``[seq0, seq0 + n)`` into ``slot``'s leading rows —
        one or two contiguous slice copies per column."""
        i = seq0 & self._mask
        k = min(n, self.capacity - i)
        slot.ids[:k] = self.ids[i : i + k]
        slot.t_submit[:k] = self.t_submit[i : i + k]
        slot.cohorts[:k] = self.cohorts[i : i + k]
        for dst, src in zip(slot.cols, self.cols):
            dst[:k] = src[i : i + k]
        if k < n:
            rest = n - k
            slot.ids[k:n] = self.ids[:rest]
            slot.t_submit[k:n] = self.t_submit[:rest]
            slot.cohorts[k:n] = self.cohorts[:rest]
            for dst, src in zip(slot.cols, self.cols):
                dst[k:n] = src[:rest]

    # -- pickle: buffers are process-local scratch --------------------------

    def __getstate__(self) -> Dict[str, Any]:
        return {"capacity": self.capacity}

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__init__(state["capacity"])


class StagingSlot:
    """One reusable cohort-sized buffer set (``max_batch`` rows per column)."""

    __slots__ = ("index", "generation", "rows", "ids", "t_submit", "cohorts", "cols")

    def __init__(self, index: int, generation: int, rows: int, layout: Layout) -> None:
        self.index = index
        self.generation = generation
        self.rows = rows
        self.ids = np.empty(rows, dtype=np.int32)
        self.t_submit = np.empty(rows, dtype=np.float64)
        self.cohorts = np.empty(rows, dtype=object)
        self.cols = [np.zeros((rows,) + shape, dtype=dtype) for dtype, shape in layout]


class StagingSlotPool:
    """A bounded pool of :class:`StagingSlot` — the double-buffer depth.

    ``acquire`` blocks until a slot frees (``try_acquire`` never blocks —
    the prefetcher skips a cycle rather than stall the flusher). Slots
    materialize lazily against the currently bound layout; a re-bind bumps
    the generation so stale slots reallocate on next acquire.
    """

    def __init__(self, num_slots: int, rows: int) -> None:
        if int(num_slots) < 2:
            raise ValueError(
                f"staging needs >= 2 slots to double-buffer, got {num_slots}"
            )
        self.num_slots = int(num_slots)
        self.rows = int(rows)
        self._cv = threading.Condition()
        self._free: List[int] = list(range(self.num_slots))
        self._slots: List[Optional[StagingSlot]] = [None] * self.num_slots
        self._layout: Optional[Layout] = None
        self._generation = 0

    def bind(self, layout: Layout) -> None:
        with self._cv:
            self._layout = layout
            self._generation += 1

    def _take_locked(self) -> StagingSlot:
        idx = self._free.pop()
        slot = self._slots[idx]
        if slot is None or slot.generation != self._generation:
            slot = StagingSlot(idx, self._generation, self.rows, self._layout or ())
            self._slots[idx] = slot
        return slot

    def acquire(self, timeout: Optional[float] = None) -> Optional[StagingSlot]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while not self._free:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cv.wait(remaining)
            return self._take_locked()

    def try_acquire(self) -> Optional[StagingSlot]:
        with self._cv:
            if not self._free:
                return None
            return self._take_locked()

    def refresh(self, slot: StagingSlot) -> StagingSlot:
        """Re-materialize a CHECKED-OUT slot against the current layout
        when a bind raced its acquire. A flusher acquires its slot before
        popping (the ring-span safety ordering), so the very first
        submit's bind can land between the two — the slot would carry the
        pre-bind layout (zero columns) into a real cohort. No-op when the
        slot is current."""
        with self._cv:
            if slot.generation == self._generation:
                return slot
            fresh = StagingSlot(
                slot.index, self._generation, self.rows, self._layout or ()
            )
            self._slots[slot.index] = fresh
            return fresh

    def release(self, slot: StagingSlot) -> None:
        with self._cv:
            self._free.append(slot.index)
            self._cv.notify()

    def in_use(self) -> int:
        with self._cv:
            return self.num_slots - len(self._free)

    # -- pickle: slots are process-local scratch ----------------------------

    def __getstate__(self) -> Dict[str, Any]:
        return {"num_slots": self.num_slots, "rows": self.rows}

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__init__(state["num_slots"], state["rows"])


class StagedCohort:
    """One staged-and-ready dispatch: slot-backed views plus the device twin.

    ``ids``/``cols`` are what the target receives (``StagedColumn`` views
    when the transfer ran, plain slot views otherwise); ``n`` is the
    post-quarantine row count, ``bucket`` the padded hand-off length.
    ``stage_window`` is the ``(t0, t1)`` perf-counter interval the staging
    work occupied — the overlap ledger intersects it with the concurrent
    dispatch window.
    """

    __slots__ = (
        "slot",
        "n",
        "bucket",
        "ids",
        "cols",
        "t_submits",
        "cohorts",
        "stage_window",
    )

    def __init__(
        self,
        slot: StagingSlot,
        n: int,
        bucket: int,
        ids: np.ndarray,
        cols: List[np.ndarray],
        t_submits: np.ndarray,
        cohorts: Sequence[Optional[str]],
        stage_window: Tuple[float, float],
    ) -> None:
        self.slot = slot
        self.n = n
        self.bucket = bucket
        self.ids = ids
        self.cols = cols
        self.t_submits = t_submits
        self.cohorts = cohorts
        self.stage_window = stage_window
