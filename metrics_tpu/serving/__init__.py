"""Online serving layer: metrics-as-a-service on top of the keyed machinery.

Everything below this package is library-shaped — the caller owns the step
loop. Production traffic is service-shaped: many threads submit per-tenant
event rows continuously, and dashboards read per-tenant values against a
latency/staleness SLO. This package is that service plane, built entirely
host-side on the existing machinery (PR-6 keyed tenant scatter, PR-7 tenant
reports, PR-9 ``compute_async`` background engine) with **zero traced
ops** — every hot-path jaxpr digest stays byte-identical
(``scripts/check_zero_overhead.py``).

* :class:`~metrics_tpu.serving.queue.AdmissionQueue` — many-threaded
  ingest coalesced into ONE keyed segment-scatter dispatch per flush, with
  size- AND deadline-triggered micro-batching (``max_batch`` rows or
  ``max_delay_ms``, whichever first).
* :mod:`~metrics_tpu.serving.policy` — backpressure + load-shedding at
  capacity (``block`` / ``shed_oldest`` / ``shed_tenant_over_quota``),
  every shed row exactly accounted.
* :class:`~metrics_tpu.serving.scheduler.SLOScheduler` — arbitration
  between update dispatch and epoch reads: a hot per-tenant ``compute()``
  result cache invalidated by write-generation counters, stale-serving
  within a ``max_staleness_s`` budget, refreshes coalesced onto the PR-9
  background engine.
* :mod:`~metrics_tpu.serving.staging` — the device-resident ingest plane
  (``AdmissionQueue(staging=True)``): a columnar staging ring written at
  submit time plus a double-buffered slot pool so the next cohort's host
  fill + H2D overlaps the current dispatch
  (``docs/performance.md#device-resident-ingest``).
* :mod:`~metrics_tpu.serving.telemetry` — the ``serving.*`` family:
  counters + queue-depth/flush-latency/ingest-latency log2 histograms in
  ``observability.snapshot()["serving"]``, ``metrics_tpu_serving_*``
  Prometheus series, and ``serving`` events on the Perfetto timeline —
  mergeable across the fleet day one (declared ``MERGE_RULES``).

Quickstart::

    from metrics_tpu import Accuracy, KeyedMetric
    from metrics_tpu.serving import SLOScheduler

    svc = SLOScheduler(
        KeyedMetric(Accuracy(), num_tenants=10_000),
        max_batch=4096, max_delay_ms=5.0, policy="shed_oldest",
        max_staleness_s=1.0,
    )
    svc.submit(tenant_id, preds_row, target_row)   # any thread, any rate
    values = svc.read([tenant_id])                 # SLO-governed
    svc.close()

The soak harness (``scripts/soak.py``, bench config ``serving_soak_step``)
drives this stack at sustained synthetic QPS over 10k+ tenants and pins the
zero-lost-updates invariant: rows admitted − rows shed == rows ingested
into tenant state (``tenant_report()["rows_routed"]``), with every shed row
visible in the ``serving.*`` counters. See ``docs/serving.md``.
"""
from metrics_tpu.serving.policy import POLICIES, AdmissionPolicy, resolve_policy  # noqa: F401
from metrics_tpu.serving.queue import AdmissionQueue, QueueClosedError  # noqa: F401
from metrics_tpu.serving.scheduler import SLOScheduler  # noqa: F401
from metrics_tpu.serving.staging import (  # noqa: F401
    StagedCohort,
    StagedColumn,
    StagingRing,
    StagingSlotPool,
)
from metrics_tpu.serving.telemetry import SERVING_STATS, ServingStats, summary  # noqa: F401

__all__ = [
    "POLICIES",
    "AdmissionPolicy",
    "AdmissionQueue",
    "QueueClosedError",
    "SERVING_STATS",
    "SLOScheduler",
    "ServingStats",
    "StagedCohort",
    "StagedColumn",
    "StagingRing",
    "StagingSlotPool",
    "resolve_policy",
    "summary",
]
