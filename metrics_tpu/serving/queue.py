"""Admission queue: many-threaded event ingest coalesced into keyed dispatches.

The library's hot path wants few, large, compiled dispatches (PR-4 donation,
PR-6 segment scatter); a service's ingest side is many threads submitting
single event rows. :class:`AdmissionQueue` is the seam between the two:

* **submit side** — any number of producer threads call
  :meth:`AdmissionQueue.submit` (one event row: a tenant id plus the
  metric's positional update arguments for that row) or
  :meth:`submit_many` (a pre-batched cohort). Admission is host-side
  Python under one condition variable; the configured
  :mod:`policy <metrics_tpu.serving.policy>` decides what happens at
  capacity (block / shed oldest / shed over-quota tenants), and every shed
  row is exactly accounted (``serving.*`` counters, per-reason split).
* **dispatch side** — a single flusher thread coalesces pending rows into
  ONE ``target(tenant_ids, *stacked_args)`` call — the
  :meth:`KeyedMetric.update <metrics_tpu.wrappers.KeyedMetric.update>` /
  :meth:`MultiTenantCollection.update` segment-scatter — with **size- AND
  deadline-triggered micro-batching**: a flush fires at ``max_batch``
  resident rows or ``max_delay_ms`` after the oldest resident row,
  whichever comes first. Dispatches are serialized on one lock (metric
  updates are a read-modify-write), so a manual :meth:`flush` or a
  scheduler epoch read can never interleave with the flusher mid-dispatch.

Exact accounting is load-bearing: the queue maintains
``admitted − shed == dispatched (+ resident)`` as an internal invariant
independent of telemetry enablement, which is what the soak harness's
zero-lost-updates acceptance reads. Zero traced ops: everything here runs
on the host; the compiled update programs are byte-identical with the queue
running (``scripts/check_zero_overhead.py``).

With ``staging=True`` the flush path goes device-resident
(:mod:`metrics_tpu.serving.staging`): submit writes rows into a columnar
ring, cohort formation is a slice hand-off into a reusable slot, the H2D
transfer runs ahead of the dispatch on the async ``staging`` lane, and a
prefetched second slot overlaps cohort ``k+1``'s staging with cohort ``k``'s
compute. The conservation laws hold unchanged — staged rows move through
exactly the same ledger transitions; only WHERE the bytes live differs.
"""
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from metrics_tpu.observability.events import EVENTS
from metrics_tpu.observability.profiling import PROFILER
from metrics_tpu.observability.registry import TELEMETRY
from metrics_tpu.observability.tracing import TRACER
from metrics_tpu.serving.policy import AdmissionPolicy, resolve_policy
from metrics_tpu.serving.staging import (
    StagedCohort,
    StagingRing,
    StagingSlotPool,
    as_staged,
    stage_layout,
)
from metrics_tpu.serving.telemetry import (
    SERVING_STATS,
    observe_dispatch_latency,
    observe_flush,
    observe_ingest,
    observe_queue_depth,
    observe_queue_wait,
    observe_staging_fill,
    observe_staging_occupancy,
    observe_staging_overlap,
)
from metrics_tpu.utilities.prints import rank_zero_warn

__all__ = ["AdmissionQueue", "QueueClosedError"]

#: default micro-batch size (rows per coalesced dispatch)
DEFAULT_MAX_BATCH = 4096
#: default flush deadline: a row waits at most this long before dispatch
DEFAULT_MAX_DELAY_MS = 5.0
#: retained poisoned rows (the dead-letter sample an operator inspects);
#: the COUNT is exact regardless — it rides the shed ledger
DEAD_LETTER_CAP = 32
#: distinct submit-cohort ids carried on one dispatch span's payload (a
#: flush can coalesce thousands of rows; the trace stays bounded)
SPAN_COHORT_CAP = 64


def _consult_fault_seam(seam: str, **ctx: Any) -> Any:
    """Consult the resilience fault plan (import-guarded only — a raise
    from the plan IS the injected dispatch failure, absorbed by the exact
    shed accounting below)."""
    try:
        from metrics_tpu.resilience.faults import maybe_fault
    except Exception:  # pragma: no cover - resilience plane optional
        return None
    return maybe_fault(seam, **ctx)


class QueueClosedError(RuntimeError):
    """Submission against a closed queue."""


class AdmissionQueue:
    """Coalesce per-tenant event submissions into keyed update dispatches.

    Args:
        target: the dispatch callable — ``target(tenant_ids, *cols)`` with
            ``tenant_ids`` a ``(rows,)`` int array and each ``cols[j]`` the
            j-th positional update argument stacked on a leading row axis.
            Typically ``KeyedMetric.update`` or
            ``MultiTenantCollection.update`` (one segment-scatter dispatch
            per flush).
        max_batch: flush when this many rows are resident.
        max_delay_ms: flush when the OLDEST resident row has waited this
            long — the deadline trigger that bounds ingest latency at low
            traffic.
        capacity_rows: admission bound (default ``8 * max_batch``); the
            policy governs what happens past it.
        policy: ``"block"`` / ``"shed_oldest"`` / ``"shed_tenant_over_quota"``
            or an :class:`~metrics_tpu.serving.policy.AdmissionPolicy`.
        block_timeout_s: bound on a blocked producer's wait (``block``
            policy; ``None`` waits until room or close).
        tenant_quota_rows: resident-row quota per tenant
            (``shed_tenant_over_quota``; default ``capacity_rows // 8``).
        pad_to_bucket: pad every dispatched cohort to the next power-of-two
            row count (capped at ``max_batch``) with discard rows —
            tenant id ``-1``, zero-filled columns. Deadline flushes
            otherwise dispatch arbitrary row counts, and each distinct
            count is a fresh executable in the aval-keyed dispatch cache (a
            recompile storm under bursty traffic); with padding at most
            ``log2(max_batch)+1`` executables ever exist. The target must
            clip-and-drop invalid ids — construct the
            :class:`~metrics_tpu.wrappers.KeyedMetric` with
            ``validate_ids=False`` (the discard-bucket path; dropped
            padding rows are counted under ``invalid_tenant_ids``).
        quarantine: poisoned-row quarantine mode. A single NaN/Inf event
            row poisons every float "sum" state its flush touches — one bad
            producer corrupts a whole cohort's tenants. ``"auto"`` (default)
            quarantines whenever the PR-2 health policy is armed
            (``observability.set_health_policy`` != ``"off"`` — the policy
            that already declares NaN/Inf an error); ``"on"``/``"off"``
            force it. Quarantined rows are SHED with the exact reason
            ``"poisoned"`` (the conservation laws extend to it), counted as
            dead letters, and a bounded sample is retained for inspection
            (:meth:`dead_letters`); the rest of the cohort dispatches
            clean.
        breaker: optional
            :class:`~metrics_tpu.resilience.policies.CircuitBreaker`
            fronting the dispatch: while open, cohorts shed immediately
            under the exact reason ``"breaker_open"`` instead of burning a
            doomed dispatch per flush; a half-open probe dispatch closes it
            again on success.
        staging: device-resident ingest (default off — the unstaged path
            is byte-identical to the pre-staging queue). Rows are written
            at submit time into a preallocated columnar
            :class:`~metrics_tpu.serving.staging.StagingRing`, cohort
            formation becomes a slice hand-off into a reusable staging
            slot, and when a full cohort is already resident the next
            cohort's host fill + H2D transfer runs on the async
            ``staging`` lane, overlapping the current dispatch
            (double-buffering). See docs/performance.md
            "Device-resident ingest".
        staging_slots: staging-slot pool depth (>= 2; 2 double-buffers).
        staging_transfer: transfer staged cohorts to the device on the
            staging lane (``jnp.array`` owning copies) so the serialized
            dispatch pays no H2D conversion; ``False`` stages host-side
            only (cohorts hand off as fresh numpy copies).
        start: start the flusher thread immediately (tests pass ``False``
            to drive flushes by hand).
    """

    def __init__(
        self,
        target: Callable[..., Any],
        *,
        max_batch: int = DEFAULT_MAX_BATCH,
        max_delay_ms: float = DEFAULT_MAX_DELAY_MS,
        capacity_rows: Optional[int] = None,
        policy: Any = "block",
        block_timeout_s: Optional[float] = None,
        tenant_quota_rows: Optional[int] = None,
        pad_to_bucket: bool = False,
        quarantine: str = "auto",
        breaker: Optional[Any] = None,
        staging: bool = False,
        staging_slots: int = 2,
        staging_transfer: bool = True,
        start: bool = True,
    ) -> None:
        if not callable(target):
            raise TypeError(f"target must be callable, got {target!r}")
        if quarantine not in ("auto", "on", "off"):
            raise ValueError(
                f"quarantine must be 'auto', 'on' or 'off', got {quarantine!r}"
            )
        self.quarantine = quarantine
        self.breaker = breaker
        if int(max_batch) < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if float(max_delay_ms) <= 0:
            raise ValueError(f"max_delay_ms must be > 0, got {max_delay_ms}")
        self._target = target
        self.pad_to_bucket = bool(pad_to_bucket)
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1e3
        self.capacity_rows = (
            int(capacity_rows) if capacity_rows is not None else 8 * self.max_batch
        )
        if self.capacity_rows < self.max_batch:
            raise ValueError(
                f"capacity_rows ({self.capacity_rows}) must be >= max_batch"
                f" ({self.max_batch}) or no size-triggered flush can ever fill"
            )
        if isinstance(policy, AdmissionPolicy):
            self.policy = resolve_policy(policy)
        else:
            knobs: Dict[str, Any] = {}
            if block_timeout_s is not None:
                knobs["block_timeout_s"] = block_timeout_s
            if tenant_quota_rows is not None:
                knobs["tenant_quota_rows"] = tenant_quota_rows
            self.policy = resolve_policy(policy, **knobs)
        if (
            self.policy.name == "shed_tenant_over_quota"
            and self.policy.tenant_quota_rows is None
        ):
            self.policy = AdmissionPolicy(
                "shed_tenant_over_quota",
                tenant_quota_rows=max(1, self.capacity_rows // 8),
            )

        self._cv = threading.Condition()
        #: resident rows, oldest first: (tenant, args, t_submit, cohort) —
        #: cohort is the submit span id joining this row's serving trace
        #: (None while the tracer is disabled). Under staging the second
        #: element is the row's RING SEQUENCE instead of an args tuple (the
        #: data lives in the columnar ring); pending seqs are always one
        #: contiguous range, the slice-hand-off invariant.
        self._pending: List[Tuple[int, Any, float, Optional[str]]] = []
        self._per_tenant: Dict[int, int] = {}
        self._closed = False
        self._flush_now = False
        self._flusher: Optional[threading.Thread] = None
        #: serializes every target() call (metric updates are not reentrant)
        self._dispatch_lock = threading.Lock()
        self._in_dispatch = 0
        self._last_error: Optional[BaseException] = None
        self._error_warned = False
        # exact accounting, independent of telemetry enablement — the
        # zero-lost-updates invariant reads these
        self._submitted = 0
        self._admitted = 0
        self._shed = 0
        self._shed_by_reason: Dict[str, int] = {}
        self._dispatched = 0
        self._flushes = 0
        #: bounded sample of quarantined rows (tenant, args); the exact
        #: dead-letter COUNT rides shed_by_reason["poisoned"]
        self._dead_letters: deque = deque(maxlen=DEAD_LETTER_CAP)
        #: newest successful dispatch span id — the scheduler stamps it on
        #: the cache it installs so read spans can point at the flush that
        #: produced the values they serve
        self._last_dispatch_span: Optional[str] = None
        # -- device-resident ingest (staging ring + double buffer) ---------
        self.staging = bool(staging)
        self.staging_transfer = bool(staging_transfer)
        if self.staging:
            # ring span bound: resident rows plus every popped-but-uncopied
            # cohort (a slot is acquired BEFORE the pop, so at most
            # slots * max_batch rows sit between pop and copy-out)
            self._ring: Optional[StagingRing] = StagingRing(
                self.capacity_rows + int(staging_slots) * self.max_batch
            )
            self._slots: Optional[StagingSlotPool] = StagingSlotPool(
                int(staging_slots), self.max_batch
            )
        else:
            self._ring = None
            self._slots = None
        #: the prefetched cohort (dict: slot/seq0/n/depth_before/trigger and
        #: a staging-lane future or an already-staged cohort) — at most one
        #: outstanding; holds ``_in_dispatch`` elevated from pop to dispatch
        self._staged_next: Optional[Dict[str, Any]] = None
        #: (start, end) of the newest dispatch — the overlap ledger
        #: intersects a prefetched cohort's stage window with it
        self._last_dispatch_window: Optional[Tuple[float, float]] = None
        self._stage_seconds = 0.0
        self._prefetched_stage_seconds = 0.0
        self._overlap_seconds = 0.0
        self._staged_cohorts = 0
        self._prefetched_cohorts = 0
        self.telemetry_key = TELEMETRY.register(self)
        SERVING_STATS.register_queue(self)
        if start:
            self._ensure_flusher()

    # ------------------------------------------------------------------
    # submit side
    # ------------------------------------------------------------------

    def submit(self, tenant_id: int, *args: Any) -> bool:
        """Admit one event row; ``True`` when admitted, ``False`` when the
        policy shed it. Thread-safe; raises :class:`QueueClosedError` after
        :meth:`close`."""
        return self.submit_many([tenant_id], *[[a] for a in args]) == 1

    def submit_many(self, tenant_ids: Any, *cols: Any) -> int:
        """Admit a cohort of rows (``tenant_ids`` plus one equal-length
        column per update argument); returns how many rows were admitted.
        Rows are admitted individually, oldest-policy semantics per row, so
        a partial shed is possible (and exactly counted)."""
        ids = np.asarray(tenant_ids).reshape(-1)
        ncols = [np.asarray(c) for c in cols]
        for c in ncols:
            if c.shape[:1] != ids.shape:
                raise ValueError(
                    f"every column must carry one entry per row: ids {ids.shape}"
                    f" vs column {c.shape}"
                )
        n = int(ids.shape[0])
        if n == 0:
            return 0
        # the submit span: one per cohort (this call), its deterministic id
        # carried on every admitted row as the trace-correlation key the
        # dispatch span's flow arrow points back to
        span = TRACER.begin("serving", group=self.telemetry_key, bucket="submit")
        cohort = span.span_id if span is not None else None
        now = time.perf_counter()
        admitted = 0
        shed: Dict[str, int] = {}
        with self._cv:
            if self._closed:
                TRACER.end(span, rows=n, error="queue_closed")
                raise QueueClosedError("AdmissionQueue is closed")
            if self.staging:
                # schema check raises BEFORE any accounting so a rejected
                # cohort never skews the conservation ledger
                self._ensure_staging_layout_locked(ncols)
            self._note_submitted(n)
            if self.staging:
                admitted, shed = self._submit_staged_locked(ids, ncols, now, cohort)
            else:
                for i in range(n):
                    tenant = int(ids[i])
                    row = (tenant, tuple(c[i] for c in ncols), now, cohort)
                    reason = self._admit_locked(row)
                    if reason is None:
                        admitted += 1
                    else:
                        shed[reason] = shed.get(reason, 0) + 1
            self._cv.notify_all()
        if shed:
            self._account_shed(shed)
        TRACER.end(span, rows=n, admitted=admitted, shed=n - admitted)
        return admitted

    def _note_submitted(self, n: int) -> None:
        self._submitted += n  # caller holds the cv
        SERVING_STATS.inc("submitted_rows", n)
        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "submitted_rows", n)

    def _ensure_staging_layout_locked(self, ncols: List[np.ndarray]) -> None:
        """Bind (or validate) the staging ring/slot layout for this cohort's
        column schema. A schema change is only accepted with zero live rows
        — resident, popped-in-flight, or prefetched rows are all views over
        the old buffers."""
        layout = stage_layout(ncols)
        if self._ring.layout == layout:
            return
        if self._ring.layout is not None and (
            self._pending or self._in_dispatch or self._staged_next is not None
        ):
            raise ValueError(
                "staged submit column schema changed while rows are live —"
                f" ring layout {self._ring.layout} vs cohort {layout}. Drain"
                " the queue before submitting a different argument schema,"
                " or run with staging=False for heterogeneous cohorts."
            )
        self._ring.bind(layout)
        self._slots.bind(layout)

    def _submit_staged_locked(
        self,
        ids: np.ndarray,
        ncols: List[np.ndarray],
        now: float,
        cohort: Optional[str],
    ) -> Tuple[int, Dict[str, int]]:
        """The staged admission loop (caller holds the cv): policy decision
        per row, then the row's data lands in the ring — deferred to one
        bulk columnar write per cohort when the policy never releases the
        lock (every non-``block`` policy), per row otherwise (a ``block``
        wait lets a concurrent flush pop rows admitted earlier in this very
        cohort, so their data must already be resident)."""
        ring = self._ring
        can_defer = self.policy.name != "block"
        admitted = 0
        first_seq: Optional[int] = None
        adm_idx: List[int] = []
        shed: Dict[str, int] = {}
        n = int(ids.shape[0])
        for i in range(n):
            tenant = int(ids[i])
            reason = self._admission_decision_locked(tenant)
            if reason is not None:
                shed[reason] = shed.get(reason, 0) + 1
                continue
            seq = ring.alloc()
            if first_seq is None:
                first_seq = seq
            self._append_locked((tenant, seq, now, cohort))
            if can_defer:
                adm_idx.append(i)
            else:
                ring.write_row(seq, tenant, now, cohort, [c[i] for c in ncols])
            admitted += 1
        if can_defer and admitted:
            # seqs are contiguous (the cv never dropped): 1–2 slice stores
            # per column, or a single gather when some rows were shed
            if admitted == n:
                ring.write_rows(
                    first_seq, ids.astype(np.int32, copy=False), now, cohort, ncols
                )
            else:
                sel = np.asarray(adm_idx, dtype=np.intp)
                ring.write_rows(
                    first_seq,
                    ids[sel].astype(np.int32, copy=False),
                    now,
                    cohort,
                    [c[sel] for c in ncols],
                )
        return admitted, shed

    def _admit_locked(self, row: Tuple[int, Tuple, float, Optional[str]]) -> Optional[str]:
        """Admit ``row`` under the lock, or return the shed reason."""
        reason = self._admission_decision_locked(row[0])
        if reason is None:
            self._append_locked(row)
        return reason

    def _admission_decision_locked(self, tenant: int) -> Optional[str]:
        """The policy's verdict for one row (caller holds the cv): ``None``
        admits, else the exact shed reason. ``shed_oldest`` evictions and
        ``block`` waits happen here."""
        policy = self.policy
        if policy.name == "shed_tenant_over_quota":
            if self._per_tenant.get(tenant, 0) >= policy.tenant_quota_rows:
                return "tenant_over_quota"
            if len(self._pending) >= self.capacity_rows:
                return "queue_full"
        elif policy.name == "shed_oldest":
            while len(self._pending) >= self.capacity_rows:
                old = self._pending.pop(0)
                self._per_tenant[old[0]] -= 1
                # shed accounting happens in the caller's aggregate pass —
                # but the eviction itself must be counted HERE, per row
                self._shed += 1
                self._shed_by_reason["shed_oldest"] = (
                    self._shed_by_reason.get("shed_oldest", 0) + 1
                )
                SERVING_STATS.shed("shed_oldest", 1)
        elif policy.name == "block":
            deadline = (
                None
                if policy.block_timeout_s is None
                else time.perf_counter() + policy.block_timeout_s
            )
            while len(self._pending) >= self.capacity_rows and not self._closed:
                remaining = None if deadline is None else deadline - time.perf_counter()
                if remaining is not None and remaining <= 0:
                    return "block_timeout"
                self._cv.wait(remaining)
            if self._closed:
                return "block_timeout"
        return None

    def _append_locked(self, row: Tuple[int, Any, float, Optional[str]]) -> None:
        """Admission bookkeeping for one accepted row (caller holds the cv).
        ``row[1]`` is the args tuple (unstaged) or the ring sequence number
        (staged) — nothing here looks inside it."""
        self._pending.append(row)
        self._per_tenant[row[0]] = self._per_tenant.get(row[0], 0) + 1
        self._admitted += 1
        SERVING_STATS.inc("admitted_rows")
        # wake the flusher the moment there is work to time (first resident
        # row starts the deadline clock) or a full batch to dispatch — a
        # producer that goes on to BLOCK for room in this same cohort would
        # otherwise sleep holding an unnotified flusher (missed wakeup)
        n_pending = len(self._pending)
        if n_pending == 1 or n_pending >= self.max_batch:
            self._cv.notify_all()

    def _account_shed(self, shed: Dict[str, int]) -> None:
        with self._cv:
            for reason, n in shed.items():
                self._shed += n
                self._shed_by_reason[reason] = self._shed_by_reason.get(reason, 0) + n
        for reason, n in shed.items():
            SERVING_STATS.shed(reason, n)
            if TELEMETRY.enabled:
                TELEMETRY.inc(self.telemetry_key, f"shed_{reason}", n)
        if EVENTS.enabled:
            EVENTS.record(
                "serving", self.telemetry_key, path="shed", policy=self.policy.name,
                **{f"shed_{r}": n for r, n in shed.items()},
            )

    # ------------------------------------------------------------------
    # dispatch side
    # ------------------------------------------------------------------

    def _ensure_flusher(self) -> None:
        if self._flusher is None or not self._flusher.is_alive():
            self._flusher = threading.Thread(
                target=self._flusher_loop, name="metrics-tpu-serving-flusher", daemon=True
            )
            self._flusher.start()

    def _flusher_loop(self) -> None:
        while True:
            with self._cv:
                while (
                    not self._pending
                    and not self._closed
                    and self._staged_next is None
                ):
                    self._cv.wait()
                if self._closed and not self._pending and self._staged_next is None:
                    return
                if self._pending:
                    deadline = self._pending[0][2] + self.max_delay_s
                    while (
                        len(self._pending) < self.max_batch
                        and self._pending
                        and not self._closed
                        and not self._flush_now
                        # a prefetched cohort is staged and waiting — do not
                        # sit out a deadline on top of it
                        and self._staged_next is None
                    ):
                        remaining = deadline - time.perf_counter()
                        if remaining <= 0:
                            break
                        self._cv.wait(remaining)
                if not self._pending and self._staged_next is None:
                    continue
                trigger = (
                    "size"
                    if len(self._pending) >= self.max_batch
                    else ("close" if self._closed else "deadline")
                )
            self._flush_once(trigger)

    def _flush_once(self, trigger: str) -> int:
        """Pop up to ``max_batch`` oldest rows and dispatch them as ONE
        target call; returns rows dispatched (0 when nothing was resident)."""
        if self.staging:
            return self._flush_once_staged(trigger)
        with self._dispatch_lock:
            with self._cv:
                if not self._pending:
                    return 0
                depth_before = len(self._pending)
                rows = self._pending[: self.max_batch]
                del self._pending[: self.max_batch]
                if not self._pending:
                    self._flush_now = False
                for tenant, _, _, _ in rows:
                    left = self._per_tenant.get(tenant, 0) - 1
                    if left > 0:
                        self._per_tenant[tenant] = left
                    else:
                        self._per_tenant.pop(tenant, None)
                self._in_dispatch += 1
                self._cv.notify_all()  # room freed: wake blocked producers
            popped = len(rows)
            # sampled profiling brackets the WHOLE flush-side host work —
            # cohort formation (the per-flush np.stack coalescing), the
            # quarantine scan, the pad block, and the target submit — so
            # the serving_flush host-queue series prices exactly what the
            # staged path moves off the flush (its bracket covers only the
            # slice hand-off; formation is serving_stage's window). The
            # owner's state bundles stand in for submit/ready sync (the
            # target call itself returns nothing).
            owner = getattr(self._target, "__self__", None)
            states = getattr(owner, "_get_states", None)
            prof = PROFILER.begin(
                "serving_flush", states() if states is not None else None
            )
            try:
                t0 = time.perf_counter()
                ids = np.asarray([r[0] for r in rows], dtype=np.int32)
                ncols = len(rows[0][1])
                cols = [np.stack([r[1][j] for r in rows]) for j in range(ncols)]
                # poisoned-row quarantine: one NaN/Inf event row would
                # corrupt every float "sum" state the whole flush touches —
                # quarantined rows are shed under the EXACT reason
                # "poisoned" (a dead-letter, sampled for inspection) and
                # the rest of the cohort dispatches clean. The mode resolves
                # ONCE per flush and the scan allocates nothing until a
                # float column exists to scan.
                if self._quarantine_active():
                    mask: Optional[np.ndarray] = None
                    for c in cols:
                        if np.issubdtype(c.dtype, np.floating):
                            bad = ~np.isfinite(c).reshape(popped, -1).all(axis=1)
                            mask = bad if mask is None else (mask | bad)
                    if mask is not None and mask.any():
                        keep = np.nonzero(~mask)[0]
                        bad_rows = [rows[i] for i in np.nonzero(mask)[0]]
                        self._shed_rows(
                            "poisoned",
                            len(bad_rows),
                            dead_letter_samples=[
                                (r[0], r[1]) for r in bad_rows[-DEAD_LETTER_CAP:]
                            ],
                        )
                        rows = [rows[i] for i in keep]
                        ids = ids[~mask]
                        cols = [c[~mask] for c in cols]
                # circuit breaker: while open, a doomed dispatch is not
                # even attempted — the cohort sheds under "breaker_open"
                if rows and self.breaker is not None and not self.breaker.allow():
                    self._shed_rows("breaker_open", len(rows))
                    rows = []
                error: Optional[BaseException] = None
                if rows:
                    if self.pad_to_bucket and len(rows) < self.max_batch:
                        bucket = min(1 << max(0, len(rows) - 1).bit_length(), self.max_batch)
                        pad = bucket - len(rows)
                        if pad > 0:
                            ids = np.concatenate([ids, np.full(pad, -1, ids.dtype)])
                            cols = [
                                np.concatenate(
                                    [c, np.zeros((pad,) + c.shape[1:], c.dtype)]
                                )
                                for c in cols
                            ]
                    try:
                        _consult_fault_seam("serving.dispatch", rows=len(rows))
                        self._target(ids, *cols)
                        if self.breaker is not None:
                            self.breaker.record_success()
                    except Exception as err:  # noqa: BLE001 - accounted below
                        error = err
                        if self.breaker is not None:
                            self.breaker.record_failure()
                if prof is not None:
                    # an all-shed flush still closes its bracket (host-only
                    # sample: formation + scan, no device window)
                    PROFILER.finish(
                        prof,
                        states() if (states is not None and rows) else None,
                        self.telemetry_key,
                    )
                    prof = None
                dur = time.perf_counter() - t0
                end = time.perf_counter()
                kept = rows
                self._note_flush(
                    trigger,
                    len(kept),
                    lambda: ((r[2], r[3]) for r in kept),
                    depth_before,
                    dur,
                    end,
                    error,
                )
            finally:
                if prof is not None:  # formation raised: close the bracket
                    PROFILER.finish(prof, None, self.telemetry_key)
                with self._cv:
                    self._in_dispatch -= 1
                    self._cv.notify_all()
        return popped

    # ------------------------------------------------------------------
    # staged dispatch side (staging=True)
    # ------------------------------------------------------------------

    def _staged_next_rows_locked(self) -> int:
        """Rows parked in the prefetched cohort (caller holds the cv).
        They left ``_pending`` at prefetch time but are still resident in
        the ledger sense until the flush that consumes them dispatches or
        sheds — ``depth()``/``stats()`` must count them or the conservation
        laws show a phantom gap of up to ``max_batch`` rows at quiescence."""
        entry = self._staged_next
        return int(entry["n"]) if entry is not None else 0

    def _pop_staged_locked(self) -> Optional[Tuple[int, int, int]]:
        """Pop up to ``max_batch`` rows off the staged pending window
        (caller holds the cv AND a staging slot): ``(seq0, n,
        depth_before)``, or ``None`` when nothing is resident. Marks the
        dispatch in flight — the rows leave ``resident`` here and reach
        ``dispatched``/``shed`` in the flush that consumes them."""
        if not self._pending:
            return None
        depth_before = len(self._pending)
        take = min(depth_before, self.max_batch)
        seq0 = self._pending[0][1]
        del self._pending[:take]
        if not self._pending:
            self._flush_now = False
        # pending seqs are contiguous, so the popped ids are exactly the
        # ring span [seq0, seq0+take): one vectorized unique instead of a
        # per-row dict pass
        uniq, counts = np.unique(self._ring.read_ids(seq0, take), return_counts=True)
        for tenant, cnt in zip(uniq.tolist(), counts.tolist()):
            left = self._per_tenant.get(tenant, 0) - int(cnt)
            if left > 0:
                self._per_tenant[tenant] = left
            else:
                self._per_tenant.pop(tenant, None)
        self._in_dispatch += 1
        self._cv.notify_all()  # room freed: wake blocked producers
        return seq0, take, depth_before

    def _stage_cohort(self, slot: Any, seq0: int, n: int) -> StagedCohort:
        """Ring → slot hand-off: copy the popped span, run the vectorized
        quarantine scan over the slot columns, fold the pow2 pad in place,
        and transfer the cohort to the device. Runs on the staging lane
        (prefetch) or the flushing thread (sync); touches only the slot and
        the protected ring span, so it races nothing."""
        t0 = time.perf_counter()
        prof = PROFILER.begin("serving_stage", None)
        self._ring.copy_out(seq0, n, slot)
        m = n
        if self._quarantine_active():
            mask: Optional[np.ndarray] = None
            for buf in slot.cols:
                if np.issubdtype(buf.dtype, np.floating):
                    bad = ~np.isfinite(buf[:n]).reshape(n, -1).all(axis=1)
                    mask = bad if mask is None else (mask | bad)
            if mask is not None and mask.any():
                bad_idx = np.nonzero(mask)[0]
                samples = [
                    (int(slot.ids[i]), tuple(np.copy(buf[i]) for buf in slot.cols))
                    for i in bad_idx[-DEAD_LETTER_CAP:]
                ]
                self._shed_rows(
                    "poisoned", int(bad_idx.shape[0]), dead_letter_samples=samples
                )
                keep = ~mask
                m = int(keep.sum())
                # in-place compaction: fancy-index gathers copy first, so
                # the overlapping store is safe
                slot.ids[:m] = slot.ids[:n][keep]
                slot.t_submit[:m] = slot.t_submit[:n][keep]
                slot.cohorts[:m] = slot.cohorts[:n][keep]
                for buf in slot.cols:
                    buf[:m] = buf[:n][keep]
        bucket = m
        if m and self.pad_to_bucket and m < self.max_batch:
            bucket = min(1 << max(0, m - 1).bit_length(), self.max_batch)
            if bucket > m:
                # the pad folds into the preallocated slot (no fresh
                # blocks): discard ids + zeroed columns, dropped by the
                # compiled program's validate_ids=False discard bucket
                slot.ids[m:bucket] = -1
                for buf in slot.cols:
                    buf[m:bucket] = 0
        ids_view: np.ndarray = slot.ids[:bucket]
        col_views: List[np.ndarray] = [buf[:bucket] for buf in slot.cols]
        fill_end = time.perf_counter()
        device = None
        if m and self.staging_transfer:
            device = self._transfer_cohort(ids_view, col_views)
        if device is not None:
            ids_view = as_staged(ids_view, device[0])
            col_views = [as_staged(v, d) for v, d in zip(col_views, device[1:])]
        elif m:
            # no device twin: hand the target OWNING copies — the slot is
            # reused the moment the dispatch returns, and a zero-copy
            # jnp.asarray inside the target could still alias it then
            ids_view = np.array(ids_view)
            col_views = [np.array(v) for v in col_views]
        if prof is not None:
            # host half = slot fill (submit_end), device half = transfer
            # completion — the serving_stage split mirrors serving_flush
            PROFILER.finish(prof, device, self.telemetry_key, submit_end=fill_end)
        t1 = time.perf_counter()
        return StagedCohort(
            slot,
            m,
            bucket,
            ids_view,
            col_views,
            slot.t_submit[:m],
            slot.cohorts[:m],
            (t0, t1),
        )

    def _transfer_cohort(
        self, ids: np.ndarray, cols: List[np.ndarray]
    ) -> Optional[List[Any]]:
        """H2D: owning device copies of the cohort (``jnp.array`` always
        copies, so slot reuse can never alias a live device buffer).
        Import-guarded with a silent host fallback — staging must degrade,
        not fail, without jax."""
        try:
            import jax.numpy as jnp

            return [jnp.array(ids)] + [jnp.array(c) for c in cols]
        except Exception:  # pragma: no cover - jax is a hard dep in-repo
            return None

    def _submit_stage_job(self, slot: Any, seq0: int, n: int) -> Any:
        from metrics_tpu.utilities.async_sync import staging_lane

        return staging_lane().submit(
            f"{self.telemetry_key}.stage",
            lambda: self._stage_cohort(slot, seq0, n),
            max_retries=0,  # a re-run would double-count quarantine sheds
        )

    def _maybe_prefetch(self) -> None:
        """Double-buffer: when a FULL cohort is already resident, pop it now
        and stage it on the async ``staging`` lane so its host fill + H2D
        runs under the dispatch this flush is about to start. Popping only
        at ``max_batch`` preserves batching semantics exactly — these rows
        would flush on the ``size`` trigger immediately anyway."""
        with self._cv:
            if (
                self._staged_next is not None
                or self._closed
                or len(self._pending) < self.max_batch
            ):
                return
        slot = self._slots.try_acquire()
        if slot is None:
            return
        entry: Optional[Dict[str, Any]] = None
        with self._cv:
            if self._staged_next is None and len(self._pending) >= self.max_batch:
                popped = self._pop_staged_locked()
                if popped is not None:
                    # a bind racing the try_acquire above leaves a stale
                    # zero-column slot — re-materialize before staging
                    slot = self._slots.refresh(slot)
                    seq0, n, depth_before = popped
                    entry = {
                        "slot": slot,
                        "seq0": seq0,
                        "n": n,
                        "depth_before": depth_before,
                        "trigger": "size",
                    }
        if entry is None:
            self._slots.release(slot)
            return
        try:
            entry["future"] = self._submit_stage_job(slot, entry["seq0"], entry["n"])
        except Exception:  # pragma: no cover - lane submit is in-process
            entry["cohort"] = self._stage_cohort(slot, entry["seq0"], entry["n"])
        with self._cv:
            self._staged_next = entry
            self._cv.notify_all()

    def _note_staged(
        self,
        cohort: StagedCohort,
        prefetched: bool,
        prev_window: Optional[Tuple[float, float]],
    ) -> None:
        """The overlap ledger: a prefetched cohort's stage window
        intersected with the dispatch that ran while it staged."""
        s0, s1 = cohort.stage_window
        stage_s = max(0.0, s1 - s0)
        overlap = 0.0
        if prefetched and prev_window is not None:
            d0, d1 = prev_window
            overlap = max(0.0, min(s1, d1) - max(s0, d0))
        with self._cv:
            self._staged_cohorts += 1
            self._stage_seconds += stage_s
            if prefetched:
                self._prefetched_cohorts += 1
                self._prefetched_stage_seconds += stage_s
                self._overlap_seconds += overlap
        SERVING_STATS.inc("staged_cohorts")
        if prefetched:
            SERVING_STATS.inc("prefetched_cohorts")
        if TELEMETRY.enabled:
            observe_staging_fill(stage_s)
            if prefetched:
                observe_staging_overlap(overlap)
            observe_staging_occupancy(self._slots.in_use())

    def _flush_once_staged(self, trigger: str) -> int:
        """The staged flush: consume the prefetched cohort when one is
        waiting, else stage synchronously; kick the NEXT cohort's prefetch;
        dispatch. The serialized section holds only the device-side
        hand-off — cohort formation left it entirely."""
        with self._dispatch_lock:
            entry: Optional[Dict[str, Any]] = None
            with self._cv:
                if self._staged_next is not None:
                    entry = self._staged_next
                    self._staged_next = None
            prefetched = entry is not None
            if entry is None:
                # slot BEFORE pop: bounds popped-but-uncopied rows at
                # slots * max_batch, the ring-span safety argument
                slot = self._slots.acquire()
                with self._cv:
                    popped = self._pop_staged_locked()
                if popped is None:
                    self._slots.release(slot)
                    return 0
                # the first submit's bind may have raced the acquire above
                # (slot-before-pop is the ring-span safety ordering) — a
                # stale slot would stage this cohort with zero columns
                slot = self._slots.refresh(slot)
                seq0, n, depth_before = popped
                entry = {
                    "slot": slot,
                    "seq0": seq0,
                    "n": n,
                    "depth_before": depth_before,
                    "trigger": trigger,
                }
            popped_n = int(entry["n"])
            depth_before = int(entry["depth_before"])
            trigger = entry["trigger"]
            prev_window = self._last_dispatch_window
            cohort: Optional[StagedCohort] = None
            try:
                t0 = time.perf_counter()
                error: Optional[BaseException] = None
                try:
                    future = entry.get("future")
                    if future is not None:
                        cohort = future.result()
                    elif "cohort" in entry:
                        cohort = entry["cohort"]
                    else:
                        cohort = self._stage_cohort(
                            entry["slot"], entry["seq0"], entry["n"]
                        )
                except Exception as err:  # noqa: BLE001 - accounted below
                    error = err
                # kick the next cohort's stage BEFORE dispatching this one —
                # the overlap the double buffer exists for
                self._maybe_prefetch()
                if cohort is not None:
                    self._note_staged(cohort, prefetched, prev_window)
                rows_n = cohort.n if cohort is not None else 0
                if (
                    rows_n
                    and self.breaker is not None
                    and not self.breaker.allow()
                ):
                    self._shed_rows("breaker_open", rows_n)
                    rows_n = 0
                if rows_n:
                    owner = getattr(self._target, "__self__", None)
                    states = getattr(owner, "_get_states", None)
                    prof = PROFILER.begin(
                        "serving_flush", states() if states is not None else None
                    )
                    try:
                        _consult_fault_seam("serving.dispatch", rows=rows_n)
                        self._target(cohort.ids, *cohort.cols)
                        if self.breaker is not None:
                            self.breaker.record_success()
                    except Exception as err:  # noqa: BLE001 - accounted below
                        error = err
                        if self.breaker is not None:
                            self.breaker.record_failure()
                    finally:
                        if prof is not None:
                            PROFILER.finish(
                                prof,
                                states() if states is not None else None,
                                self.telemetry_key,
                            )
                dur = time.perf_counter() - t0
                end = time.perf_counter()
                self._last_dispatch_window = (t0, end)
                if cohort is None:
                    # the stage itself failed: the whole popped span sheds
                    # as a dispatch error (no per-row meta survives)
                    self._note_flush(
                        trigger, popped_n, lambda: (), depth_before, dur, end, error
                    )
                else:
                    noted = cohort if rows_n else None
                    self._note_flush(
                        trigger,
                        rows_n,
                        (
                            (lambda: zip(noted.t_submits, noted.cohorts))
                            if noted is not None
                            else (lambda: ())
                        ),
                        depth_before,
                        dur,
                        end,
                        error,
                    )
            finally:
                self._slots.release(entry["slot"])
                with self._cv:
                    self._in_dispatch -= 1
                    self._cv.notify_all()
        return popped_n

    def _quarantine_active(self) -> bool:
        """Quarantine is armed explicitly (``"on"``) or — the ``"auto"``
        default — whenever the PR-2 health policy declares NaN/Inf an
        anomaly (``set_health_policy`` != ``"off"``): the same switch that
        arms the on-device guard arms the ingest-side quarantine."""
        if self.quarantine == "on":
            return True
        if self.quarantine == "off":
            return False
        try:
            from metrics_tpu.observability.health import get_health_policy

            return get_health_policy() != "off"
        except Exception:  # pragma: no cover - health plane optional
            return False

    def _shed_rows(
        self,
        reason: str,
        n: int,
        *,
        dead_letter_samples: Optional[List[Tuple[int, Tuple]]] = None,
    ) -> None:
        """Shed ``n`` already-admitted rows at dispatch time under an exact
        ``reason`` (quarantine, open breaker) — the conservation laws keep
        holding because every such row moves from resident to shed.
        ``dead_letter_samples`` is the bounded ``(tenant, args)`` sample
        retained for inspection (callers pass the NEWEST rows — the deque
        keeps newest-last either way)."""
        if n == 0:
            return
        with self._cv:
            self._shed += n
            self._shed_by_reason[reason] = self._shed_by_reason.get(reason, 0) + n
            if dead_letter_samples:
                self._dead_letters.extend(dead_letter_samples)
        SERVING_STATS.shed(reason, n)
        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, f"shed_{reason}", n)
        if EVENTS.enabled:
            EVENTS.record(
                "serving", self.telemetry_key, path="shed", policy=self.policy.name,
                **{f"shed_{reason}": n},
            )

    def dead_letters(self) -> List[Tuple[int, Tuple]]:
        """The retained sample of quarantined ``(tenant_id, args)`` rows
        (newest last, bounded at ``DEAD_LETTER_CAP``); the exact total is
        ``stats()["shed_by_reason"]["poisoned"]``."""
        with self._cv:
            return list(self._dead_letters)

    def _note_flush(
        self,
        trigger: str,
        n: int,
        row_meta: Callable[[], Iterable[Tuple[float, Optional[str]]]],
        depth_before: int,
        dur: float,
        end: float,
        error: Optional[BaseException],
    ) -> None:
        """Ledger + telemetry for one flush of ``n`` rows. ``row_meta`` is a
        zero-cost factory yielding ``(t_submit, cohort)`` per dispatched row
        — only iterated under the telemetry/tracer gates, so the hot path
        never materializes per-row lists for disabled planes."""
        with self._cv:
            self._flushes += 1
            if error is None:
                self._dispatched += n
            else:
                # a failed dispatch never ingested: the rows are ACCOUNTED
                # shed so the zero-lost invariant keeps holding exactly
                self._shed += n
                self._shed_by_reason["dispatch_error"] = (
                    self._shed_by_reason.get("dispatch_error", 0) + n
                )
                self._last_error = error
        if error is not None:
            SERVING_STATS.inc("dispatch_errors")
            SERVING_STATS.shed("dispatch_error", n)
            if not self._error_warned:
                self._error_warned = True
                rank_zero_warn(
                    f"AdmissionQueue dispatch failed ({type(error).__name__}:"
                    f" {error}); the cohort's {n} rows are counted shed under"
                    " reason 'dispatch_error'. Subsequent failures are counted"
                    " silently — watch serving.dispatch_errors.",
                    UserWarning,
                )
        SERVING_STATS.flush(trigger, n if error is None else 0, depth_before)
        t_start = end - dur  # flush start on the same perf_counter clock
        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "flushes")
            if error is None:
                TELEMETRY.inc(self.telemetry_key, "dispatched_rows", n)
            observe_flush(dur, trigger)
            observe_queue_depth(depth_before)
            for t_submit, _ in row_meta():
                observe_ingest(end - t_submit, self.policy.name)
                # the two components of ingest: host-queue wait (submit →
                # flush start) and device dispatch (flush start → complete,
                # row-weighted so counts line up across the three series)
                observe_queue_wait(max(0.0, t_start - t_submit), self.policy.name)
                observe_dispatch_latency(dur, self.policy.name)
        if n and TRACER.enabled:
            # retro-dated serving spans: the enqueue-wait interval (oldest
            # submit → flush start) and the dispatch interval (flush start →
            # complete) are only known now, but their endpoints were stamped
            # on the perf_counter clock as they happened
            pc_now = time.perf_counter()
            cohorts: List[str] = []
            oldest_submit: Optional[float] = None
            for t_submit, cohort in row_meta():
                if oldest_submit is None or t_submit < oldest_submit:
                    oldest_submit = float(t_submit)
                if cohort is not None and cohort not in cohorts:
                    cohorts.append(cohort)
            dropped_cohorts = max(0, len(cohorts) - SPAN_COHORT_CAP)
            cohorts = cohorts[:SPAN_COHORT_CAP]
            if oldest_submit is not None:
                TRACER.record_span(
                    "serving",
                    group=self.telemetry_key,
                    bucket="wait",
                    enter_ago_s=pc_now - oldest_submit,
                    exit_ago_s=pc_now - t_start,
                    rows=n,
                    trigger=trigger,
                )
            dispatch_span = TRACER.record_span(
                "serving",
                group=self.telemetry_key,
                bucket="dispatch",
                enter_ago_s=pc_now - t_start,
                exit_ago_s=pc_now - end,
                rows=n,
                trigger=trigger,
                cohorts=cohorts,
                dropped_cohorts=dropped_cohorts,
                error=(f"{type(error).__name__}: {error}" if error else None),
            )
            if error is None and dispatch_span is not None:
                with self._cv:
                    self._last_dispatch_span = dispatch_span
        if EVENTS.enabled:
            EVENTS.record(
                "serving",
                self.telemetry_key,
                dur_s=dur,
                t_start=end - dur,
                path="flush",
                trigger=trigger,
                rows=n,
                depth_before=depth_before,
                policy=self.policy.name,
                error=(f"{type(error).__name__}: {error}" if error else None),
            )

    # ------------------------------------------------------------------
    # control plane
    # ------------------------------------------------------------------

    def flush(self) -> int:
        """Dispatch everything resident NOW (caller thread, ``manual``
        trigger); returns rows dispatched. Serialized against the flusher."""
        total = 0
        while True:
            n = self._flush_once("manual")
            if n == 0:
                return total
            total += n

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until no rows are resident and no dispatch is in flight;
        ``False`` on timeout. With a live flusher the drain asks it to
        flush immediately (no waiting out the deadline timer); without one
        (``start=False``) the residue is dispatched on the caller thread.
        The ``timeout`` bounds the WHOLE drain, in-flight dispatch
        included."""
        if self._flusher is None or not self._flusher.is_alive():
            self.flush()
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            self._flush_now = bool(self._pending)
            self._cv.notify_all()
            while self._pending or self._in_dispatch:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining)
        return True

    def close(self, timeout: Optional[float] = 5.0) -> None:
        """Stop admitting, flush the residue, and join the flusher."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self.flush()
        thread = self._flusher
        if thread is not None and thread.is_alive():
            thread.join(timeout)

    def depth(self) -> int:
        """Rows currently resident (point-in-time). A prefetched cohort
        parked in the second staging slot is still resident — it has left
        ``_pending`` but not reached ``dispatched``/``shed``, so without it
        a manual ``while q.depth(): q._flush_once(...)`` drain loop would
        strand up to ``max_batch`` rows."""
        with self._cv:
            return len(self._pending) + self._staged_next_rows_locked()

    def last_dispatch_span(self) -> Optional[str]:
        """The newest successful dispatch span id (``None`` before the
        first traced flush) — the scheduler stamps it on installed caches
        so read spans can reference the flush they serve from."""
        with self._cv:
            return self._last_dispatch_span

    def stats(self) -> Dict[str, Any]:
        """The queue's exact ledger: submitted/admitted/shed (by reason)/
        dispatched/flushes/resident.

        Two conservation laws hold at every quiescent point — the
        zero-lost-updates invariant's left-hand side:

        * ``admitted == dispatched + resident + shed(shed_oldest) +
          shed(dispatch_error) + shed(poisoned) + shed(breaker_open)``
          (rows shed AFTER admission — the quarantine and the open
          breaker shed exactly like a failed dispatch does);
        * ``submitted − shed(total) == dispatched + resident`` — so at
          drain, submitted − shed equals exactly what the keyed state
          ingested (``tenant_report()["rows_routed"]``)."""
        with self._cv:
            staging_block: Dict[str, Any] = {"enabled": self.staging}
            if self.staging:
                staging_block.update(
                    {
                        "slots": self._slots.num_slots,
                        "ring_capacity": self._ring.capacity,
                        "transfer": self.staging_transfer,
                        "staged_cohorts": self._staged_cohorts,
                        "prefetched_cohorts": self._prefetched_cohorts,
                        "stage_seconds": self._stage_seconds,
                        "overlap_seconds": self._overlap_seconds,
                        # fraction of PREFETCHED stage time spent under a
                        # concurrent dispatch — the double-buffer's yield
                        "overlap_fraction": (
                            self._overlap_seconds / self._prefetched_stage_seconds
                            if self._prefetched_stage_seconds > 0
                            else 0.0
                        ),
                    }
                )
            return {
                "policy": self.policy.name,
                "max_batch": self.max_batch,
                "max_delay_ms": round(self.max_delay_s * 1e3, 6),
                "capacity_rows": self.capacity_rows,
                "staging": staging_block,
                "submitted": self._submitted,
                "admitted": self._admitted,
                "shed": self._shed,
                "shed_by_reason": dict(self._shed_by_reason),
                "dispatched": self._dispatched,
                "flushes": self._flushes,
                "resident": len(self._pending) + self._staged_next_rows_locked(),
                "dead_letter_rows": self._shed_by_reason.get("poisoned", 0),
                "closed": self._closed,
                "last_error": (
                    f"{type(self._last_error).__name__}: {self._last_error}"
                    if self._last_error
                    else None
                ),
            }
