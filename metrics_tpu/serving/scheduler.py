"""SLO-aware scheduling between update dispatch and epoch reads.

A serving loop has two competing consumers of the keyed metric state: the
**write path** (admission-queue flushes — segment-scatter dispatches that
must keep absorbing traffic) and the **read path** (per-tenant ``compute()``
values for dashboards and rollups — an epoch-shaped fan-out that is orders
of magnitude more expensive than one update). :class:`SLOScheduler` owns
both and arbitrates by one explicit contract, the **staleness SLO**:

* **updates always win the dispatch path.** Flushes run on the queue's
  flusher thread; an epoch read never blocks them — the read snapshots the
  state (one clone, the PR-9 ``compute_async`` discipline) and runs its
  gather+compute on the background
  :class:`~metrics_tpu.utilities.async_sync.AsyncSyncEngine`, overlapped
  with whatever traffic follows.
* **reads are served from a hot result cache** keyed by the scheduler's
  **write generation** — a counter bumped once per dispatched flush (the
  per-key generation discipline the async engine already applies to its
  retained values). Generations are additionally tracked **per tenant id**
  (each flush stamps only the tenants it actually touched), so a cache
  entry serves a tenant-scoped read (``read([ids])``) whenever NONE of the
  requested tenants changed since it was computed — a flush touching
  tenants {A, B} no longer fans a refresh out to every hot reader of
  tenant C (counted ``tenant_cache_hits``). A cache entry is *fresh*
  globally when its generation matches and nothing is resident in the
  queue; *servable* when younger than the read's ``max_staleness_s``
  budget (served immediately, counted ``stale_serves``, with a background
  refresh scheduled); otherwise the read flushes the queue
  (read-your-writes), submits a refresh, and blocks on the future.
  ``max_staleness_s=0`` therefore guarantees a read NEVER observes a value
  older than the requested tenants' latest write — the
  no-stale-cache-after-a-generation-bump invariant the concurrency tests
  pin.
* **refreshes coalesce.** Any number of concurrent stale reads share one
  in-flight refresh per scheduler (counted ``coalesced_refreshes``); the
  engine-level ``coalesce=`` submission option provides the same guarantee
  for callers talking to the engine directly.

Everything is host-side (zero traced ops); the counters surface under
``snapshot()["serving"]`` next to the queue's, and each refresh rides the
engine's existing ``async_sync.*`` family and ``sync`` events.
"""
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from metrics_tpu.observability.events import EVENTS
from metrics_tpu.observability.registry import TELEMETRY
from metrics_tpu.observability.tracing import TRACER
from metrics_tpu.serving.queue import AdmissionQueue
from metrics_tpu.serving.telemetry import SERVING_STATS, observe_read_staleness

__all__ = ["SLOScheduler"]

#: default read staleness budget (seconds): a cached per-tenant value this
#: young is served without touching the state
def _membership_epoch() -> int:
    """The resilience plane's current membership epoch (0 while idle or
    absent) — the scheduler's fleet-level cache-invalidation edge."""
    try:
        from metrics_tpu.resilience.membership import current_epoch

        return current_epoch()
    except Exception:  # pragma: no cover - resilience plane optional
        return 0


DEFAULT_MAX_STALENESS_S = 1.0
#: default bound on a blocking (cache-miss) read
DEFAULT_READ_TIMEOUT_S = 30.0


class SLOScheduler:
    """Serve one keyed metric: queued updates in, SLO-governed reads out.

    Args:
        metric: a :class:`~metrics_tpu.wrappers.KeyedMetric` or
            :class:`~metrics_tpu.wrappers.MultiTenantCollection` (anything
            with ``update(tenant_ids, *cols)``, ``compute()`` and
            ``clone()``).
        max_staleness_s: default read budget (overridable per read).
        read_timeout_s: bound on a blocking cache-miss read.
        on_degraded: degraded-link policy for the refresh gathers
            (``"retry"`` / ``"stale"`` / ``"quorum"`` — PR-9 semantics).
        queue kwargs (``max_batch``, ``max_delay_ms``, ``capacity_rows``,
            ``policy``, ``block_timeout_s``, ``tenant_quota_rows``,
            ``start``) configure the owned
            :class:`~metrics_tpu.serving.queue.AdmissionQueue`.
    """

    def __init__(
        self,
        metric: Any,
        *,
        max_staleness_s: float = DEFAULT_MAX_STALENESS_S,
        read_timeout_s: float = DEFAULT_READ_TIMEOUT_S,
        on_degraded: str = "retry",
        round_timeout_s: Optional[float] = None,
        **queue_kwargs: Any,
    ) -> None:
        for attr in ("update", "compute"):
            if not callable(getattr(metric, attr, None)):
                raise TypeError(
                    f"metric must provide {attr}(); got {type(metric).__name__}"
                )
        if max_staleness_s < 0:
            raise ValueError(f"max_staleness_s must be >= 0, got {max_staleness_s}")
        self._metric = metric
        self.max_staleness_s = float(max_staleness_s)
        self.read_timeout_s = float(read_timeout_s)
        self.on_degraded = on_degraded
        self.round_timeout_s = round_timeout_s
        self._lock = threading.Lock()
        self._generation = 0
        #: tenant id -> generation of its last dispatched write (only touched
        #: tenants present; an absent tenant has never been written, i.e.
        #: generation 0) — the per-tenant cache-invalidation ledger
        self._tenant_gen: Dict[int, int] = {}
        #: the metric's tenant count the ledger was last pruned against —
        #: an elastic shrink/compaction changes it, and entries for tenants
        #: that no longer exist must not leak in a weeks-long service
        self._pruned_for_tenants: Optional[int] = getattr(
            metric, "num_tenants", None
        )
        #: {"generation", "values", "at"} — the hot per-tenant result cache
        self._cache: Optional[Dict[str, Any]] = None
        self._refresh_future: Optional[Any] = None
        self._refresh_generation = -1
        self.telemetry_key = TELEMETRY.register(self)
        self.queue = AdmissionQueue(self._dispatch, **queue_kwargs)

    # ------------------------------------------------------------------
    # write path
    # ------------------------------------------------------------------

    def _dispatch(self, tenant_ids: Any, *cols: Any) -> None:
        """The queue's flush target: ONE keyed update dispatch, then a
        generation bump — the cache-invalidation edge. Only the tenants the
        flush actually touched are stamped in the per-tenant ledger, so an
        untouched tenant's cached value stays servable."""
        self._metric.update(tenant_ids, *cols)
        touched = np.unique(np.asarray(tenant_ids).reshape(-1))
        with self._lock:
            self._generation += 1
            for t in touched:
                self._tenant_gen[int(t)] = self._generation
        SERVING_STATS.inc("generation_bumps")
        self.prune_tenant_generations()

    def prune_tenant_generations(self) -> int:
        """Drop ledger entries for tenants past the metric's CURRENT tenant
        count; returns entries dropped.

        The per-tenant generation map only ever gained entries — after an
        elastic shrink/compaction (``KeyedMetric.compact``) the dropped
        tenants' entries would sit there forever, a slow leak in a
        weeks-long service, and a stale entry could even mark a FUTURE
        tenant reusing the id as already-written. Runs opportunistically
        after every dispatched flush, but only does work when the metric's
        tenant count actually changed since the last prune (O(1) steady
        state, O(ledger) once per resize)."""
        n = getattr(self._metric, "num_tenants", None)
        if n is None:
            return 0
        with self._lock:
            if n == self._pruned_for_tenants:
                return 0
            stale = [t for t in self._tenant_gen if t >= n]
            for t in stale:
                del self._tenant_gen[t]
            self._pruned_for_tenants = n
        if stale and TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "tenant_generations_pruned", len(stale))
        return len(stale)

    def tenant_generations(self) -> Dict[int, int]:
        """One consistent copy of the per-tenant write-generation ledger —
        the durability plane's preferred delta-checkpoint dirty-set source
        (``CheckpointManager``)."""
        self.prune_tenant_generations()
        with self._lock:
            return dict(self._tenant_gen)

    def submit(self, tenant_id: int, *args: Any) -> bool:
        """Admit one event row (see :meth:`AdmissionQueue.submit`)."""
        return self.queue.submit(tenant_id, *args)

    def submit_many(self, tenant_ids: Any, *cols: Any) -> int:
        """Admit a row cohort (see :meth:`AdmissionQueue.submit_many`)."""
        return self.queue.submit_many(tenant_ids, *cols)

    @property
    def generation(self) -> int:
        """Write generation: dispatched flushes so far (cache entries are
        stamped with the generation they computed at)."""
        with self._lock:
            return self._generation

    # ------------------------------------------------------------------
    # read path
    # ------------------------------------------------------------------

    def read(
        self,
        tenant_ids: Optional[Any] = None,
        *,
        max_staleness_s: Optional[float] = None,
    ) -> Any:
        """Per-tenant computed values under the staleness SLO.

        ``tenant_ids=None`` returns the full per-tenant vector (or
        ``{member: vector}`` for a collection); an index array selects
        rows — and scopes freshness to those tenants: the cache serves the
        read (``tenant_cache_hits``) when none of them changed since it was
        computed, even if OTHER tenants' flushes moved the global
        generation. ``max_staleness_s`` overrides the scheduler default for
        this read; ``0`` forces read-your-writes freshness for the
        requested tenants (flush + recompute when any of them changed).

        Every read records a ``serving`` read span (outcome, staleness, and
        cache-generation evidence; ``flush_span`` references the dispatch
        span whose flush produced the served cache) and feeds the
        ``serving_read_staleness_seconds`` histogram the staleness SLO
        evaluates."""
        SERVING_STATS.inc("reads")
        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "reads")
        span = TRACER.begin("serving", group=self.telemetry_key, bucket="read")
        try:
            values, outcome, evidence = self._read_once(tenant_ids, max_staleness_s)
        except BaseException as err:
            TRACER.end(span, outcome="error", error=f"{type(err).__name__}: {err}")
            raise
        if TELEMETRY.enabled:
            observe_read_staleness(evidence.get("staleness_s", 0.0), outcome)
        TRACER.end(span, outcome=outcome, **evidence)
        return values

    def _read_once(
        self, tenant_ids: Optional[Any], max_staleness_s: Optional[float]
    ) -> Any:
        """One read's control flow; returns ``(selected values, outcome,
        evidence)`` where evidence is the JSON payload the read span and the
        staleness histogram share. ``staleness_s`` is the served cache's age
        for stale serves and 0 otherwise — a fresh (generation-matched)
        value is current no matter how old, so an idle service does not
        false-breach its staleness SLO."""
        budget = self.max_staleness_s if max_staleness_s is None else float(max_staleness_s)
        now = time.monotonic()
        ids = None if tenant_ids is None else np.asarray(tenant_ids).reshape(-1)
        # the membership epoch is a cache-invalidation edge like a write
        # generation: a value computed under an older epoch's peer set (a
        # since-failed peer contributing, a rejoined peer missing) must not
        # be served as current — it expires outright and the next read
        # refreshes under the new epoch
        epoch = _membership_epoch()
        with self._lock:
            cache = self._cache
            if cache is not None and cache.get("epoch", 0) != epoch:
                cache = None
            generation = self._generation
            tenant_scoped_fresh = (
                cache is not None
                and cache["generation"] != generation
                and ids is not None
                and all(
                    self._tenant_gen.get(int(t), 0) <= cache["generation"]
                    for t in ids
                )
            )

        def _evidence(entry: Optional[Dict[str, Any]], staleness: float) -> Dict[str, Any]:
            return {
                "staleness_s": round(max(0.0, staleness), 9),
                "generation": generation,
                "cache_generation": entry["generation"] if entry else None,
                "flush_span": entry.get("span") if entry else None,
            }

        if cache is not None and self.queue.depth() == 0:
            if cache["generation"] == generation:
                SERVING_STATS.inc("cache_hits")
                return _select(cache["values"], tenant_ids), "cache_hit", _evidence(cache, 0.0)
            if tenant_scoped_fresh:
                # other tenants' flushes moved the generation, but every
                # requested tenant is unchanged since the cache computed —
                # their cached values ARE the latest, no refresh fan-out
                SERVING_STATS.inc("cache_hits")
                SERVING_STATS.inc("tenant_cache_hits")
                if TELEMETRY.enabled:
                    TELEMETRY.inc(self.telemetry_key, "tenant_cache_hits")
                return (
                    _select(cache["values"], tenant_ids),
                    "tenant_cache_hit",
                    _evidence(cache, 0.0),
                )
        if cache is not None and (now - cache["at"]) <= budget:
            # within the SLO: serve the stale generation immediately and
            # refresh in the background — a dashboard value a moment old
            # beats a read stalled behind an epoch fan-out (the PR-9
            # stale-serving trade, applied to the result cache)
            SERVING_STATS.inc("stale_serves")
            self._ensure_refresh()
            return (
                _select(cache["values"], tenant_ids),
                "stale_serve",
                _evidence(cache, now - cache["at"]),
            )
        SERVING_STATS.inc("cache_misses")
        future, target = self._ensure_refresh()
        values = future.result(timeout=self.read_timeout_s)
        self._install_cache(target, values)
        with self._lock:
            installed = self._cache
        return _select(values, tenant_ids), "cache_miss", _evidence(installed, 0.0)

    def refresh(self, wait: bool = False) -> Any:
        """Schedule (or join) a cache refresh; returns the refresh's
        :class:`~metrics_tpu.utilities.async_sync.SyncFuture`. ``wait=True``
        blocks until it resolves and installs the cache."""
        future, target = self._ensure_refresh()
        if wait:
            self._install_cache(target, future.result(timeout=self.read_timeout_s))
        return future

    def _ensure_refresh(self):
        """One in-flight refresh per scheduler: concurrent stale reads share
        it (``coalesced_refreshes``); the refresh flushes resident rows
        first so the snapshot covers everything admitted before the read."""
        with self._lock:
            future = self._refresh_future
            if (
                future is not None
                and not future.done()
                and self._refresh_generation >= self._generation
                and self.queue.depth() == 0
            ):
                SERVING_STATS.inc("coalesced_refreshes")
                return future, self._refresh_generation
        # read-your-writes: everything admitted before this read reaches the
        # state before the snapshot (serialized on the queue's dispatch lock)
        self.queue.flush()
        with self._lock:
            future = self._refresh_future
            if (
                future is not None
                and not future.done()
                and self._refresh_generation >= self._generation
            ):
                SERVING_STATS.inc("coalesced_refreshes")
                return future, self._refresh_generation
            target = self._generation
            shadow = _clone(self._metric)

            def thunk(shadow=shadow, target=target):
                # per-attempt clone: an orphaned timed-out attempt must not
                # race a retry on shared state (Metric.compute_async's rule)
                values = _clone(shadow).compute()
                self._install_cache(target, values)
                return values

            from metrics_tpu.utilities.async_sync import get_engine

            key = getattr(self._metric, "telemetry_key", None) or self.telemetry_key
            future = get_engine().submit(
                key,
                thunk,
                on_degraded=self.on_degraded,
                round_timeout_s=self.round_timeout_s,
            )
            self._refresh_future = future
            self._refresh_generation = target
        SERVING_STATS.inc("refreshes")
        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "refreshes")
        if EVENTS.enabled:
            EVENTS.record(
                "serving",
                self.telemetry_key,
                path="refresh",
                generation=target,
                engine_generation=future.generation,
            )
        return future, target

    def _install_cache(self, generation: int, values: Any) -> None:
        # the newest successful dispatch span joins the cache entry so read
        # spans can point a flow arrow at the flush that fed their values
        flush_span = self.queue.last_dispatch_span()
        with self._lock:
            if self._cache is None or self._cache["generation"] <= generation:
                self._cache = {
                    "generation": generation,
                    "values": values,
                    "at": time.monotonic(),
                    "epoch": _membership_epoch(),
                    "span": flush_span,
                }

    # ------------------------------------------------------------------
    # introspection / lifecycle
    # ------------------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        """Host-side drill-down: generation/cache state plus the queue's
        exact ledger (and the metric's ``tenant_report`` when it has one)."""
        with self._lock:
            cache = self._cache
            out: Dict[str, Any] = {
                "generation": self._generation,
                "cache_generation": cache["generation"] if cache else None,
                "cache_age_s": (
                    round(time.monotonic() - cache["at"], 6) if cache else None
                ),
                "cache_fresh": bool(cache and cache["generation"] == self._generation),
                "tenant_generations_tracked": len(self._tenant_gen),
                "max_staleness_s": self.max_staleness_s,
                "on_degraded": self.on_degraded,
                "membership_epoch": _membership_epoch(),
                "cache_epoch": cache.get("epoch", 0) if cache else None,
            }
        out["queue"] = self.queue.stats()
        tenant_report = getattr(self._metric, "tenant_report", None)
        if callable(tenant_report):
            out["tenants"] = tenant_report()
        return out

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Flush and wait out every resident row (see
        :meth:`AdmissionQueue.drain`)."""
        return self.queue.drain(timeout)

    def close(self, timeout: Optional[float] = 5.0) -> None:
        """Close the queue (flushes the residue first)."""
        self.queue.close(timeout)

    def __repr__(self) -> str:
        return (
            f"SLOScheduler({type(self._metric).__name__},"
            f" policy={self.queue.policy.name!r},"
            f" max_staleness_s={self.max_staleness_s})"
        )


def _clone(metric: Any) -> Any:
    """Detached snapshot of ``metric``: its own ``clone()`` when it has one
    (:class:`Metric` subclasses), ``deepcopy`` otherwise
    (:class:`MultiTenantCollection` and metric-shaped doubles)."""
    clone = getattr(metric, "clone", None)
    if callable(clone):
        return clone()
    import copy

    return copy.deepcopy(metric)


def _select(values: Any, tenant_ids: Optional[Any]) -> Any:
    """Index per-tenant values (array or {member: array}) by tenant ids."""
    if tenant_ids is None:
        return values
    ids = np.asarray(tenant_ids).reshape(-1)
    if isinstance(values, dict):
        return {k: np.asarray(v)[ids] for k, v in values.items()}
    return np.asarray(values)[ids]
