"""The ``serving.*`` telemetry family: exact accounting for the service plane.

One process-global :class:`ServingStats` ledger records every admission
outcome (admitted / shed, by reason), every flush (by trigger), every
dispatched row, and every scheduler read outcome (cache hit / miss / stale
serve / refresh). The ledger surfaces in three places, mirroring the
async-sync engine's family:

* ``observability.snapshot()["serving"]`` — the JSON view below, ``{}``
  until the first queue is constructed (processes that never serve keep a
  clean snapshot). Fleet aggregation works day one: the
  :data:`~metrics_tpu.observability.aggregate.MERGE_RULES` table declares
  counters sum, depth/queues sum, and high-water gauges max.
* the ``metrics_tpu_serving_*`` Prometheus series
  (:func:`~metrics_tpu.observability.export.render_prometheus`).
* fast-path log2 histograms: ``serving_ingest_seconds`` (admission →
  dispatch-complete wall time per row batch) and its two components —
  ``serving_queue_wait_seconds`` (submit → flush start, host-queue time) and
  ``serving_dispatch_seconds`` (flush start → dispatch complete, device
  time) — so a p99 regression attributes to queueing vs dispatch;
  ``serving_flush_seconds`` (one coalesced dispatch),
  ``serving_queue_depth`` (rows resident at flush time, unit ``count``),
  and ``serving_read_staleness_seconds`` (age of the cache generation a
  stale read served) — mergeable bucket tables like every other histogram
  family, each with sliding-window percentiles the SLO plane
  (:mod:`~metrics_tpu.observability.slo`) evaluates burn rates over.

Everything here is host-side bookkeeping behind the same lock-free
``TELEMETRY.enabled`` gate the rest of the observability stack uses; the
compiled metric programs are untouched (the zero-overhead gate pins it).
"""
import threading
import weakref
from typing import Any, Dict

from metrics_tpu.observability.histogram import HISTOGRAMS
from metrics_tpu.observability.registry import TELEMETRY

__all__ = [
    "SERVING_STATS",
    "ServingStats",
    "observe_dispatch_latency",
    "observe_flush",
    "observe_ingest",
    "observe_queue_depth",
    "observe_queue_wait",
    "observe_read_staleness",
    "observe_staging_fill",
    "observe_staging_occupancy",
    "observe_staging_overlap",
    "summary",
]

#: canonical fast-path histogram series of the serving plane
INGEST_SECONDS = "serving_ingest_seconds"
QUEUE_WAIT_SECONDS = "serving_queue_wait_seconds"
DISPATCH_SECONDS = "serving_dispatch_seconds"
FLUSH_SECONDS = "serving_flush_seconds"
QUEUE_DEPTH = "serving_queue_depth"
READ_STALENESS_SECONDS = "serving_read_staleness_seconds"
#: device-resident ingest (the staged flush path, docs/performance.md
#: "Device-resident ingest"): per-cohort stage time (ring→slot fill +
#: quarantine + pad + H2D), the portion of a PREFETCHED cohort's stage that
#: ran under a concurrent dispatch, and slot-pool occupancy at stage time
STAGING_FILL_SECONDS = "serving_staging_fill_seconds"
STAGING_OVERLAP_SECONDS = "serving_staging_overlap_seconds"
STAGING_OCCUPANCY = "serving_staging_occupancy"


def observe_ingest(seconds: float, policy: str) -> None:
    """Admission-to-dispatch-complete wall time of one row cohort."""
    HISTOGRAMS.observe(INGEST_SECONDS, seconds, unit="s", policy=policy)


def observe_queue_wait(seconds: float, policy: str) -> None:
    """Submit → flush-start wall time of one row: the host-queue component
    of :data:`INGEST_SECONDS`."""
    HISTOGRAMS.observe(QUEUE_WAIT_SECONDS, seconds, unit="s", policy=policy)


def observe_dispatch_latency(seconds: float, policy: str) -> None:
    """Flush-start → dispatch-complete wall time of one row's cohort: the
    device component of :data:`INGEST_SECONDS` (row-weighted — every row in
    a cohort records the cohort's dispatch time, so counts line up with the
    ingest series)."""
    HISTOGRAMS.observe(DISPATCH_SECONDS, seconds, unit="s", policy=policy)


def observe_read_staleness(seconds: float, outcome: str) -> None:
    """Cache-generation age a scheduler read observed (0 for fresh hits;
    the served age for stale serves)."""
    HISTOGRAMS.observe(READ_STALENESS_SECONDS, seconds, unit="s", outcome=outcome)


def observe_flush(seconds: float, trigger: str) -> None:
    """One coalesced dispatch's wall time, labeled by what triggered it
    (``size`` / ``deadline`` / ``manual`` / ``close``)."""
    HISTOGRAMS.observe(FLUSH_SECONDS, seconds, unit="s", trigger=trigger)


def observe_queue_depth(rows: int) -> None:
    """Rows resident in the queue at flush time (unit ``count``)."""
    HISTOGRAMS.observe(QUEUE_DEPTH, float(rows), unit="count")


def observe_staging_fill(seconds: float) -> None:
    """One staged cohort's total stage time: ring→slot slice copy,
    vectorized quarantine scan, in-place pad fold, and the H2D transfer."""
    HISTOGRAMS.observe(STAGING_FILL_SECONDS, seconds, unit="s")


def observe_staging_overlap(seconds: float) -> None:
    """The portion of a PREFETCHED cohort's stage window that ran while the
    previous cohort's dispatch was in flight — the double-buffer's yield."""
    HISTOGRAMS.observe(STAGING_OVERLAP_SECONDS, seconds, unit="s")


def observe_staging_occupancy(slots: int) -> None:
    """Staging slots in use at stage-complete time (unit ``count``)."""
    HISTOGRAMS.observe(STAGING_OCCUPANCY, float(slots), unit="count")


class ServingStats:
    """Thread-safe counters for the serving plane (one process-global
    instance, :data:`SERVING_STATS`; private instances supported for
    tests). ``touched`` stays False until the first queue registers, so an
    idle process's snapshot omits the section entirely."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._touched = False
        self._queues: "weakref.WeakSet" = weakref.WeakSet()
        self._counters: Dict[str, int] = {
            "submitted_rows": 0,
            "admitted_rows": 0,
            "shed_rows": 0,
            "dispatched_rows": 0,
            "flushes": 0,
            "dispatch_errors": 0,
            "reads": 0,
            "cache_hits": 0,
            "cache_misses": 0,
            "stale_serves": 0,
            "tenant_cache_hits": 0,
            "refreshes": 0,
            "coalesced_refreshes": 0,
            "generation_bumps": 0,
            "staged_cohorts": 0,
            "prefetched_cohorts": 0,
        }
        self._shed_by_reason: Dict[str, int] = {}
        self._flushes_by_trigger: Dict[str, int] = {}
        self._depth_high_water = 0

    # -- recording ----------------------------------------------------------

    def register_queue(self, queue: Any) -> None:
        with self._lock:
            self._touched = True
            self._queues.add(queue)

    def inc(self, counter: str, n: int = 1) -> None:
        if not TELEMETRY.enabled:
            return
        with self._lock:
            self._touched = True
            self._counters[counter] = self._counters.get(counter, 0) + int(n)

    def shed(self, reason: str, n: int) -> None:
        """One shed decision: ``n`` rows under ``reason`` — the per-reason
        split and the total move together, so the accounting can never
        drift."""
        if not TELEMETRY.enabled or n <= 0:
            return
        with self._lock:
            self._touched = True
            self._counters["shed_rows"] += int(n)
            self._shed_by_reason[reason] = self._shed_by_reason.get(reason, 0) + int(n)

    def flush(self, trigger: str, rows: int, depth: int) -> None:
        if not TELEMETRY.enabled:
            return
        with self._lock:
            self._touched = True
            self._counters["flushes"] += 1
            self._counters["dispatched_rows"] += int(rows)
            self._flushes_by_trigger[trigger] = (
                self._flushes_by_trigger.get(trigger, 0) + 1
            )
            if depth > self._depth_high_water:
                self._depth_high_water = int(depth)

    # -- reading ------------------------------------------------------------

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def summary(self) -> Dict[str, Any]:
        """The ``snapshot()["serving"]`` section (``{}`` when untouched)."""
        with self._lock:
            if not self._touched:
                return {}
            queues = list(self._queues)
            out = {
                "queues": len(queues),
                "depth": 0,
                "depth_high_water": self._depth_high_water,
                **dict(self._counters),
                "shed_by_reason": dict(self._shed_by_reason),
                "flushes_by_trigger": dict(self._flushes_by_trigger),
            }
        # depths are read OUTSIDE the stats lock: a queue records stats while
        # holding its own condition variable, so nesting the other way here
        # would be an ABBA deadlock
        depth = 0
        for q in queues:
            try:
                depth += q.depth()
            except Exception:  # pragma: no cover - a closing queue
                pass
        out["depth"] = depth
        return out

    def reset(self) -> None:
        """Zero every counter (live queues stay registered — their depths
        keep reporting)."""
        with self._lock:
            for k in self._counters:
                self._counters[k] = 0
            self._shed_by_reason.clear()
            self._flushes_by_trigger.clear()
            self._depth_high_water = 0


#: the process-global serving ledger
SERVING_STATS = ServingStats()


def summary() -> Dict[str, Any]:
    """Module-level accessor ``observability.snapshot()`` reads."""
    return SERVING_STATS.summary()
