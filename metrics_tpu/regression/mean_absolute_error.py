"""MeanAbsoluteError module metric (parity: ``torchmetrics/regression/mean_absolute_error.py:26``)."""
from typing import Any, Callable, Optional

import jax.numpy as jnp

from metrics_tpu.functional.regression.mean_absolute_error import (
    _mean_absolute_error_compute,
    _mean_absolute_error_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import Array


class MeanAbsoluteError(Metric):
    """MAE accumulated over batches.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanAbsoluteError
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> mean_absolute_error = MeanAbsoluteError()
        >>> print(f"{mean_absolute_error(preds, target):.4f}")
        0.5000
    """

    is_differentiable = True
    higher_is_better = False

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("sum_abs_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate absolute-error sums."""
        sum_abs_error, n_obs = _mean_absolute_error_update(preds, target)
        self.sum_abs_error = self.sum_abs_error + sum_abs_error
        self.total = self.total + n_obs

    def compute(self) -> Array:
        """MAE over everything seen so far."""
        return _mean_absolute_error_compute(self.sum_abs_error, self.total)
