"""MeanSquaredError module metric (parity: ``torchmetrics/regression/mean_squared_error.py:26``)."""
from typing import Any, Callable, Optional

import jax.numpy as jnp

from metrics_tpu.functional.regression.mean_squared_error import (
    _mean_squared_error_compute,
    _mean_squared_error_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import Array


class MeanSquaredError(Metric):
    """MSE (or RMSE with ``squared=False``) accumulated over batches.

    Args:
        squared: if ``False``, return the root mean squared error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredError
        >>> target = jnp.asarray([2.5, 5.0, 4.0, 8.0])
        >>> preds = jnp.asarray([3.0, 5.0, 2.5, 7.0])
        >>> mean_squared_error = MeanSquaredError()
        >>> print(f"{mean_squared_error(preds, target):.4f}")
        0.8750
    """

    is_differentiable = True
    higher_is_better = False

    def __init__(
        self,
        squared: bool = True,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("sum_squared_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")
        self.squared = squared

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate squared-error sums."""
        sum_squared_error, n_obs = _mean_squared_error_update(preds, target)
        self.sum_squared_error = self.sum_squared_error + sum_squared_error
        self.total = self.total + n_obs

    def compute(self) -> Array:
        """MSE over everything seen so far."""
        return _mean_squared_error_compute(self.sum_squared_error, self.total, squared=self.squared)
