"""ExplainedVariance module metric (parity: ``torchmetrics/regression/explained_variance.py:26``)."""
from typing import Any, Callable, Optional, Sequence, Union

import jax.numpy as jnp

from metrics_tpu.functional.regression.explained_variance import (
    _explained_variance_compute,
    _explained_variance_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import Array


class ExplainedVariance(Metric):
    """Explained variance from streaming moment sums (fixed-shape states).

    Args:
        multioutput: ``'raw_values' | 'uniform_average' | 'variance_weighted'``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ExplainedVariance
        >>> target = jnp.asarray([3, -0.5, 2, 7])
        >>> preds = jnp.asarray([2.5, 0.0, 2, 8])
        >>> explained_variance = ExplainedVariance()
        >>> print(f"{explained_variance(preds, target):.4f}")
        0.9572
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        multioutput: str = "uniform_average",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        allowed_multioutput = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed_multioutput:
            raise ValueError(
                f"Invalid input to argument `multioutput`. Choose one of the following: {allowed_multioutput}"
            )
        self.multioutput = multioutput
        self.add_state("sum_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_squared_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_target", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_squared_target", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("n_obs", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate the five moment sums."""
        n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target = _explained_variance_update(
            preds, target
        )
        self.n_obs = self.n_obs + n_obs
        self.sum_error = self.sum_error + sum_error
        self.sum_squared_error = self.sum_squared_error + sum_squared_error
        self.sum_target = self.sum_target + sum_target
        self.sum_squared_target = self.sum_squared_target + sum_squared_target

    def compute(self) -> Union[Array, Sequence[Array]]:
        """Explained variance over everything seen so far."""
        return _explained_variance_compute(
            self.n_obs,
            self.sum_error,
            self.sum_squared_error,
            self.sum_target,
            self.sum_squared_target,
            self.multioutput,
        )
