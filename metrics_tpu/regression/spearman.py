"""SpearmanCorrcoef module metric (parity: ``torchmetrics/regression/spearman.py:25``)."""
from typing import Any, Callable, Optional

from metrics_tpu.functional.regression.spearman import _spearman_corrcoef_compute, _spearman_corrcoef_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import Array, dim_zero_cat
from metrics_tpu.utilities.prints import rank_zero_warn


class SpearmanCorrcoef(Metric):
    """Spearman rank correlation over all seen (preds, target) pairs.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SpearmanCorrcoef
        >>> target = jnp.asarray([3., -0.5, 2, 7])
        >>> preds = jnp.asarray([2.5, 0.0, 2, 8])
        >>> spearman = SpearmanCorrcoef()
        >>> print(f"{spearman(preds, target):.2f}")
        1.00
    """

    is_differentiable = False

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        rank_zero_warn(
            "Metric `SpearmanCorrcoef` will save all targets and predictions in the buffer."
            " For large datasets, this may lead to a large memory footprint."
        )
        self.add_state("preds_all", default=[], dist_reduce_fx="cat")
        self.add_state("target_all", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Append the batch pairs."""
        preds, target = _spearman_corrcoef_update(preds, target)
        self.preds_all.append(preds)
        self.target_all.append(target)

    def compute(self) -> Array:
        """Spearman correlation over everything seen so far."""
        preds = dim_zero_cat(self.preds_all)
        target = dim_zero_cat(self.target_all)
        return _spearman_corrcoef_compute(preds, target)
