"""SpearmanCorrcoef module metric (parity: ``torchmetrics/regression/spearman.py:25``).

TPU extension — ``capacity``: a preallocated ``(capacity,)`` sample buffer
(rank correlation needs the whole stream jointly, so unlike Pearson it cannot
stream to moments) whose state structure is step-invariant: updates write in
place under ``jit``, sync is a tiled ``all_gather`` + counter gather, and
compute is the masked searchsorted rank formula over the valid entries.

TPU extension — ``sketched``: TRUE bounded-memory streaming. The joint
(pred, target) distribution is accumulated into a fixed ``(num_bins,
num_bins)`` rank grid (:func:`~metrics_tpu.kernels.sketches.joint_grid_update`)
and rho is computed from the bin counts with midrank tie correction — exactly
the Spearman of the stream discretized onto the grid, so the error is
O(1/num_bins) for continuous in-range data and the state/sync cost is
O(num_bins²) regardless of traffic (one ``psum`` per sync). Requires an
explicit ``value_range`` (the grid must be static to stay mergeable across
processes); out-of-range values clip into the edge bins and are counted.
"""
from typing import Any, Callable, Optional, Tuple, Union

import jax.numpy as jnp

from metrics_tpu.utilities.capped_buffer import CappedBufferMixin
from metrics_tpu.functional.regression.spearman import (
    _spearman_corrcoef_compute,
    _spearman_corrcoef_update,
    masked_spearman_corrcoef,
)
from metrics_tpu.kernels.sketches import joint_grid_update, spearman_from_grid
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import Array, dim_zero_cat
from metrics_tpu.utilities.prints import rank_zero_warn
from metrics_tpu.utilities.sketching import SketchTelemetryMixin, _check_num_bins, _check_range


class SpearmanCorrcoef(SketchTelemetryMixin, CappedBufferMixin, Metric):
    """Spearman rank correlation over all seen (preds, target) pairs.

    Args:
        capacity: when set, accumulate into a fixed-size ``(capacity,)``
            buffer instead of unbounded lists — usable inside compiled
            programs without per-step retracing; samples past the capacity
            are dropped (warned about at eager compute, or raised with
            ``overflow="error"``).
        sketched: bounded-memory streaming mode — accumulate a fixed
            ``(num_bins, num_bins)`` joint rank grid instead of samples.
            Unlike ``capacity`` the state never saturates: every sample
            lands in the grid, memory and sync stay O(num_bins²) forever,
            and the whole lifecycle (update, ``psum`` sync, compute) is
            jit/donation/``update_many``/``keyed``-eligible. Accuracy is
            the exact rho of the grid-discretized stream (documented
            tolerance in ``docs/performance.md#bounded-memory-sketched-states``).
        num_bins: sketched-mode grid resolution per axis (default 512 —
            1 MB of state).
        value_range: REQUIRED with ``sketched=True``: the static grid
            bounds, either one ``(low, high)`` pair for both axes or
            ``((pred_low, pred_high), (target_low, target_high))``.
            Out-of-range values clip into the edge bins (rank clamping —
            counted in ``sketch_clipped``, reported in the telemetry
            snapshot).
        overflow: capacity-mode policy past the buffer — ``"warn"`` or
            ``"error"``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SpearmanCorrcoef
        >>> target = jnp.asarray([3., -0.5, 2, 7])
        >>> preds = jnp.asarray([2.5, 0.0, 2, 8])
        >>> spearman = SpearmanCorrcoef()
        >>> print(f"{spearman(preds, target):.2f}")
        1.00
    """

    is_differentiable = False
    _sketch_hint = (
        "Alternatively, SpearmanCorrcoef(sketched=True,"
        " value_range=(low, high)) keeps a fixed-size joint rank grid"
        " (bounded memory, one psum at sync; see"
        " docs/performance.md#bounded-memory-sketched-states)."
    )

    def __init__(
        self,
        capacity: Optional[int] = None,
        sketched: bool = False,
        num_bins: int = 512,
        value_range: Optional[Union[Tuple[float, float], Tuple[Tuple[float, float], ...]]] = None,
        overflow: str = "warn",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.capacity = capacity
        self.sketched = sketched
        self.num_classes = None  # raw-value buffer; no class semantics

        if sketched:
            if capacity is not None:
                raise ValueError("`sketched` and `capacity` modes are mutually exclusive")
            _check_num_bins(num_bins)
            if value_range is None:
                raise ValueError(
                    "SpearmanCorrcoef(sketched=True) needs an explicit `value_range`"
                    " — the rank grid must be static (the same on every process and"
                    " every step) to stay mergeable. Pass (low, high) covering your"
                    " preds/target values, or ((pred_low, pred_high), (target_low,"
                    " target_high)); out-of-range values clip into the edge bins."
                )
            if (
                isinstance(value_range, (tuple, list))
                and len(value_range) == 2
                and isinstance(value_range[0], (tuple, list))
            ):
                self._sketch_range_x = _check_range("value_range[0]", value_range[0])
                self._sketch_range_y = _check_range("value_range[1]", value_range[1])
            else:
                self._sketch_range_x = self._sketch_range_y = _check_range("value_range", value_range)
            self._sketch_bins = num_bins
            self.add_state("joint_grid", jnp.zeros((num_bins, num_bins), jnp.float32), dist_reduce_fx="sum")
            self.add_state("sketch_clipped", jnp.zeros((), jnp.float32), dist_reduce_fx="sum")
        elif capacity is not None:
            self._init_raw_buffer_states(capacity, overflow=overflow)
        else:
            rank_zero_warn(
                "Metric `SpearmanCorrcoef` will save all targets and predictions in the buffer."
                " For large datasets, this may lead to a large memory footprint."
            )
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Append the batch pairs (buffered/bucketed in place under
        ``capacity``/``sketched``)."""
        preds, target = _spearman_corrcoef_update(preds, target)
        if self.sketched:
            grid, clipped = joint_grid_update(
                self.joint_grid, preds, target, self._sketch_range_x, self._sketch_range_y
            )
            self.joint_grid = grid
            self.sketch_clipped = self.sketch_clipped + clipped
            return
        if self.capacity is not None:
            self._raw_buffer_update(preds, target)
            return
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        """Spearman correlation over everything seen so far."""
        if self.sketched:
            rho = spearman_from_grid(self.joint_grid)
            self._publish_sketch_info(
                kind="joint_grid",
                bins=self._sketch_bins,
                range=[list(self._sketch_range_x), list(self._sketch_range_y)],
                overflow=self.sketch_clipped,
            )
            return rho
        if self.capacity is not None:
            preds, target, valid = self._buffer_flatten()
            return masked_spearman_corrcoef(preds, target, valid)

        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _spearman_corrcoef_compute(preds, target)
