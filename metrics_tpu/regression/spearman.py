"""SpearmanCorrcoef module metric (parity: ``torchmetrics/regression/spearman.py:25``).

TPU extension — ``capacity``: a preallocated ``(capacity,)`` sample buffer
(rank correlation needs the whole stream jointly, so unlike Pearson it cannot
stream to moments) whose state structure is step-invariant: updates write in
place under ``jit``, sync is a tiled ``all_gather`` + counter gather, and
compute is the masked searchsorted rank formula over the valid entries.
"""
from typing import Any, Callable, Optional

from metrics_tpu.utilities.capped_buffer import CappedBufferMixin
from metrics_tpu.functional.regression.spearman import (
    _spearman_corrcoef_compute,
    _spearman_corrcoef_update,
    masked_spearman_corrcoef,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import Array, dim_zero_cat
from metrics_tpu.utilities.prints import rank_zero_warn


class SpearmanCorrcoef(CappedBufferMixin, Metric):
    """Spearman rank correlation over all seen (preds, target) pairs.

    Args:
        capacity: when set, accumulate into a fixed-size ``(capacity,)``
            buffer instead of unbounded lists — usable inside compiled
            programs without per-step retracing; samples past the capacity
            are dropped (warned about at eager compute).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SpearmanCorrcoef
        >>> target = jnp.asarray([3., -0.5, 2, 7])
        >>> preds = jnp.asarray([2.5, 0.0, 2, 8])
        >>> spearman = SpearmanCorrcoef()
        >>> print(f"{spearman(preds, target):.2f}")
        1.00
    """

    is_differentiable = False

    def __init__(
        self,
        capacity: Optional[int] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.capacity = capacity
        self.num_classes = None  # raw-value buffer; no class semantics

        if capacity is not None:
            self._init_raw_buffer_states(capacity)
        else:
            rank_zero_warn(
                "Metric `SpearmanCorrcoef` will save all targets and predictions in the buffer."
                " For large datasets, this may lead to a large memory footprint."
            )
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Append the batch pairs (buffered in place under ``capacity``)."""
        preds, target = _spearman_corrcoef_update(preds, target)
        if self.capacity is not None:
            self._raw_buffer_update(preds, target)
            return
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        """Spearman correlation over everything seen so far."""
        if self.capacity is not None:
            preds, target, valid = self._buffer_flatten()
            return masked_spearman_corrcoef(preds, target, valid)

        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _spearman_corrcoef_compute(preds, target)
