"""PearsonCorrcoef module metric (parity: ``torchmetrics/regression/pearson.py:25``).

TPU extension — ``streaming=True`` swaps the reference's cat states (buffer
every sample, ``regression/pearson.py:77-78``) for six co-moment sums: the
state is fixed-shape, updates fuse into compiled steps without retracing,
sync is one ``psum`` bundle, and memory is O(1) in the stream length.
Computed in float64 when x64 is enabled; the f32 path is documented as
adequate for data whose mean is not far larger than its spread.
"""
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.pearson import _pearson_corrcoef_compute, _pearson_corrcoef_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import Array, dim_zero_cat


class PearsonCorrcoef(Metric):
    """Pearson correlation over all seen (preds, target) pairs.

    Args:
        streaming: accumulate co-moment sums instead of buffering samples —
            constant memory, jit-native state (TPU extension; the reference
            always buffers).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PearsonCorrcoef
        >>> target = jnp.asarray([3., -0.5, 2, 7])
        >>> preds = jnp.asarray([2.5, 0.0, 2, 8])
        >>> pearson = PearsonCorrcoef()
        >>> print(f"{pearson(preds, target):.4f}")
        0.9849
    """

    is_differentiable = True

    def __init__(
        self,
        streaming: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.streaming = streaming
        if streaming:
            dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
            self.add_state("n_total", default=jnp.zeros((), jnp.int32), dist_reduce_fx="sum")
            for name in ("sum_x", "sum_y", "sum_xx", "sum_yy", "sum_xy"):
                self.add_state(name, default=jnp.zeros((), dtype), dist_reduce_fx="sum")
        else:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Append the batch pairs (or fold them into the co-moment sums)."""
        preds, target = _pearson_corrcoef_update(preds, target)
        if self.streaming:
            x = jnp.atleast_1d(preds).astype(self.sum_x.dtype)
            y = jnp.atleast_1d(target).astype(self.sum_y.dtype)
            self.n_total = self.n_total + x.size
            self.sum_x = self.sum_x + jnp.sum(x)
            self.sum_y = self.sum_y + jnp.sum(y)
            self.sum_xx = self.sum_xx + jnp.sum(x * x)
            self.sum_yy = self.sum_yy + jnp.sum(y * y)
            self.sum_xy = self.sum_xy + jnp.sum(x * y)
        else:
            self.preds.append(preds)
            self.target.append(target)

    def compute(self) -> Array:
        """Pearson correlation over everything seen so far."""
        if self.streaming:
            dtype = self.sum_xy.dtype
            n = jnp.maximum(self.n_total, 1).astype(dtype)
            mean_x = self.sum_x / n
            mean_y = self.sum_y / n
            cov = self.sum_xy / n - mean_x * mean_y
            var_x = self.sum_xx / n - mean_x**2
            var_y = self.sum_yy / n - mean_y**2
            # a variance below the cancellation noise of its raw second moment
            # is numerically zero -> correlation 0 (the buffered path's
            # eps-guarded-denominator semantics, functional/pearson.py)
            eps = 1e-12 if dtype == jnp.float64 else 1e-6
            degenerate = (var_x <= eps * jnp.abs(self.sum_xx / n)) | (var_y <= eps * jnp.abs(self.sum_yy / n))
            denom = jnp.sqrt(jnp.clip(var_x, 0, None) * jnp.clip(var_y, 0, None))
            corr = jnp.where(degenerate, 0.0, cov / jnp.where(degenerate, 1.0, denom))
            # keep the accumulation dtype: under x64 the buffered path
            # returns f64 too, and the parity test pins ~1e-14 agreement
            return jnp.clip(corr, -1.0, 1.0).astype(dtype)

        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _pearson_corrcoef_compute(preds, target)
