"""PearsonCorrcoef module metric (parity: ``torchmetrics/regression/pearson.py:25``)."""
from typing import Any, Callable, Optional

from metrics_tpu.functional.regression.pearson import _pearson_corrcoef_compute, _pearson_corrcoef_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import Array, dim_zero_cat


class PearsonCorrcoef(Metric):
    """Pearson correlation over all seen (preds, target) pairs (cat states).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PearsonCorrcoef
        >>> target = jnp.asarray([3., -0.5, 2, 7])
        >>> preds = jnp.asarray([2.5, 0.0, 2, 8])
        >>> pearson = PearsonCorrcoef()
        >>> print(f"{pearson(preds, target):.4f}")
        0.9849
    """

    is_differentiable = True

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("preds_all", default=[], dist_reduce_fx="cat")
        self.add_state("target_all", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Append the batch pairs."""
        preds, target = _pearson_corrcoef_update(preds, target)
        self.preds_all.append(preds)
        self.target_all.append(target)

    def compute(self) -> Array:
        """Pearson correlation over everything seen so far."""
        preds = dim_zero_cat(self.preds_all)
        target = dim_zero_cat(self.target_all)
        return _pearson_corrcoef_compute(preds, target)
