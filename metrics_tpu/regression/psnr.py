"""Deprecated location shim (parity: ``torchmetrics/regression/psnr.py:20``) —
``PSNR`` moved to :mod:`metrics_tpu.image.psnr`."""
from typing import Any, Callable, Optional, Tuple, Union
from warnings import warn

from metrics_tpu.image.psnr import PSNR as _PSNR


class PSNR(_PSNR):
    """.. deprecated::
        ``PSNR`` was moved to ``metrics_tpu.image.psnr``.
    """

    def __init__(
        self,
        data_range: Optional[float] = None,
        base: float = 10.0,
        reduction: str = "elementwise_mean",
        dim: Optional[Union[int, Tuple[int, ...]]] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        warn(
            "This `PSNR` was moved to `metrics_tpu.image.psnr` and this shell will be removed"
            " in a future release. Use `metrics_tpu.image.psnr.PSNR` instead.",
            DeprecationWarning,
        )
        super().__init__(
            data_range=data_range,
            base=base,
            reduction=reduction,
            dim=dim,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
