"""MeanAbsolutePercentageError module metric (parity: ``torchmetrics/regression/mean_absolute_percentage_error.py:26``)."""
from typing import Any, Callable, Optional

import jax.numpy as jnp

from metrics_tpu.functional.regression.mean_absolute_percentage_error import (
    _mean_absolute_percentage_error_compute,
    _mean_absolute_percentage_error_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import Array


class MeanAbsolutePercentageError(Metric):
    """MAPE accumulated over batches.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanAbsolutePercentageError
        >>> target = jnp.asarray([1., 10, 1e6])
        >>> preds = jnp.asarray([0.9, 15, 1.2e6])
        >>> mean_abs_percentage_error = MeanAbsolutePercentageError()
        >>> print(f"{mean_abs_percentage_error(preds, target):.4f}")
        0.2667
    """

    is_differentiable = True
    higher_is_better = False

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("sum_abs_per_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate absolute-percentage-error sums."""
        sum_abs_per_error, num_obs = _mean_absolute_percentage_error_update(preds, target)
        self.sum_abs_per_error = self.sum_abs_per_error + sum_abs_per_error
        self.total = self.total + num_obs

    def compute(self) -> Array:
        """MAPE over everything seen so far."""
        return _mean_absolute_percentage_error_compute(self.sum_abs_per_error, self.total)
