"""R2Score module metric (parity: ``torchmetrics/regression/r2score.py:23``)."""
from typing import Any, Callable, Optional

import jax.numpy as jnp

from metrics_tpu.functional.regression.r2score import _r2score_compute, _r2score_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import Array


class R2Score(Metric):
    """R2 score from streaming moment sums, ``(num_outputs,)``-shaped states.

    Args:
        num_outputs: regression target dimensionality.
        adjusted: degrees of freedom for the adjusted-R2 penalty (0 = plain).
        multioutput: ``'uniform_average'`` | ``'raw_values'`` |
            ``'variance_weighted'`` combination of per-output scores.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import R2Score
        >>> target = jnp.asarray([3, -0.5, 2, 7])
        >>> preds = jnp.asarray([2.5, 0.0, 2, 8])
        >>> r2score = R2Score()
        >>> print(f"{r2score(preds, target):.4f}")
        0.9486
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        num_outputs: int = 1,
        adjusted: int = 0,
        multioutput: str = "uniform_average",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.num_outputs = num_outputs

        if adjusted < 0 or not isinstance(adjusted, int):
            raise ValueError("`adjusted` parameter should be an integer larger or equal to 0.")
        self.adjusted = adjusted

        allowed_multioutput = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed_multioutput:
            raise ValueError(
                f"Invalid input to argument `multioutput`. Choose one of the following: {allowed_multioutput}"
            )
        self.multioutput = multioutput

        self.add_state("sum_squared_error", default=jnp.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("sum_error", default=jnp.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("residual", default=jnp.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate the moment sums."""
        sum_squared_error, sum_error, residual, total = _r2score_update(preds, target)
        self.sum_squared_error = self.sum_squared_error + sum_squared_error
        self.sum_error = self.sum_error + sum_error
        self.residual = self.residual + residual
        self.total = self.total + total

    def compute(self) -> Array:
        """R2 score over everything seen so far."""
        return _r2score_compute(
            self.sum_squared_error, self.sum_error, self.residual, self.total, self.adjusted, self.multioutput
        )
