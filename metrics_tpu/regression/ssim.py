"""Deprecated location shim (parity: ``torchmetrics/regression/ssim.py:20``) —
``SSIM`` moved to :mod:`metrics_tpu.image.ssim`."""
from typing import Any, Callable, Optional, Sequence
from warnings import warn

from metrics_tpu.image.ssim import SSIM as _SSIM


class SSIM(_SSIM):
    """.. deprecated::
        ``SSIM`` was moved to ``metrics_tpu.image.ssim``.
    """

    def __init__(
        self,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        reduction: str = "elementwise_mean",
        data_range: Optional[float] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        warn(
            "This `SSIM` was moved to `metrics_tpu.image.ssim` and this shell will be removed"
            " in a future release. Use `metrics_tpu.image.ssim.SSIM` instead.",
            DeprecationWarning,
        )
        super().__init__(
            kernel_size=kernel_size,
            sigma=sigma,
            reduction=reduction,
            data_range=data_range,
            k1=k1,
            k2=k2,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
