"""CosineSimilarity module metric (parity: ``torchmetrics/regression/cosine_similarity.py:24``).

TPU extension — ``streaming=True`` (for ``'sum'``/``'mean'`` reductions):
the per-row cosine values accumulate as a running sum + count instead of
buffering every pair, giving a fixed-shape state that fuses into compiled
steps and syncs with one ``psum``.
"""
from typing import Any, Callable, Optional

import jax.numpy as jnp

from metrics_tpu.functional.regression.cosine_similarity import (
    _cosine_similarity_compute,
    _cosine_similarity_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import Array, dim_zero_cat


class CosineSimilarity(Metric):
    """Row-wise cosine similarity over all seen pairs.

    Args:
        reduction: ``'sum' | 'mean' | 'none'``.
        streaming: accumulate the reduced value instead of buffering samples
            (``'sum'``/``'mean'`` only) — constant memory, jit-native state.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CosineSimilarity
        >>> target = jnp.asarray([[1., 2, 3, 4], [1., 2, 3, 4]])
        >>> preds = jnp.asarray([[1., 2, 3, 4], [-1., -2, -3, -4]])
        >>> cosine_similarity = CosineSimilarity(reduction='mean')
        >>> print(f"{cosine_similarity(preds, target):.4f}")
        0.0000
    """

    is_differentiable = True

    def __init__(
        self,
        reduction: str = "sum",
        streaming: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        allowed_reduction = ("sum", "mean", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        self.streaming = streaming

        if streaming:
            if reduction not in ("sum", "mean"):
                raise ValueError("`streaming=True` requires reduction 'sum' or 'mean'")
            self.add_state("sim_sum", default=jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("n_total", default=jnp.zeros((), jnp.int32), dist_reduce_fx="sum")
        else:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        """Append the batch pairs (or fold their reduced similarity in)."""
        preds, target = _cosine_similarity_update(preds, target)
        if self.streaming:
            self.sim_sum = self.sim_sum + _cosine_similarity_compute(preds, target, "sum")
            # one similarity value per vector (= everything but the feature axis)
            self.n_total = self.n_total + preds[..., 0].size
        else:
            self.preds.append(preds)
            self.target.append(target)

    def compute(self) -> Array:
        """Cosine similarity over everything seen so far."""
        if self.streaming:
            if self.reduction == "mean":
                return self.sim_sum / jnp.maximum(self.n_total, 1)
            return self.sim_sum

        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _cosine_similarity_compute(preds, target, self.reduction)
