"""The ``durability.*`` telemetry family: exact accounting for the state
lifecycle plane.

One process-global :class:`DurabilityStats` ledger records every checkpoint
outcome (full / delta saves, bytes written, tenants stamped, restores, bytes
read), every spill decision (evictions, fault-backs, the resident/spilled
occupancy gauges with a high-water mark), and every elastic resize (grows,
compactions). The ledger surfaces in the same three places as the serving
family:

* ``observability.snapshot()["durability"]`` — the JSON view below, ``{}``
  until the durability plane is first touched (processes that never
  checkpoint or spill keep a clean snapshot). Fleet aggregation works day
  one: :data:`~metrics_tpu.observability.aggregate.MERGE_RULES` declares
  counters sum, occupancy gauges sum (fleet totals), the high-water gauge
  maxes.
* the ``metrics_tpu_durability_*`` Prometheus series
  (:func:`~metrics_tpu.observability.export.render_prometheus`).
* fast-path log2 histograms: ``durability_save_seconds`` (one snapshot
  write, labeled ``kind=full|delta``), ``durability_restore_seconds`` (one
  chain restore), and ``durability_faultback_seconds`` (one spill
  fault-back cohort) — mergeable bucket tables like every other family.

Everything here is host-side bookkeeping behind the lock-free
``TELEMETRY.enabled`` gate; the compiled metric programs are untouched (the
zero-overhead gate's ``durability_off`` digests pin it).
"""
import threading
import weakref
from typing import Any, Dict

from metrics_tpu.observability.events import EVENTS
from metrics_tpu.observability.histogram import HISTOGRAMS
from metrics_tpu.observability.registry import TELEMETRY

__all__ = [
    "DURABILITY_STATS",
    "DurabilityStats",
    "note_resize",
    "observe_faultback",
    "observe_restore",
    "observe_save",
    "pin_tenant_traffic",
    "summary",
    "unpin_tenant_traffic",
]

def pin_tenant_traffic(metric: Any) -> None:
    """Hold ``metric``'s per-tenant traffic ledger OPEN (refcounted): while
    at least one pin is held, the keyed wrappers feed the ledger on every
    update even with ``TELEMETRY`` disabled. A durability actor that reads
    the ledger as ground truth — the checkpoint delta dirty set, the
    spiller's staleness stamps — MUST pin it: a ledger frozen by a telemetry
    toggle would silently drop touched tenants from the next delta save and
    stale the eviction signal."""
    d = metric.__dict__
    d["_durability_traffic_pin"] = int(d.get("_durability_traffic_pin", 0)) + 1


def unpin_tenant_traffic(metric: Any) -> None:
    """Release one :func:`pin_tenant_traffic` hold."""
    d = metric.__dict__
    n = int(d.get("_durability_traffic_pin", 0)) - 1
    if n > 0:
        d["_durability_traffic_pin"] = n
    else:
        d.pop("_durability_traffic_pin", None)


#: canonical fast-path histogram series of the durability plane
SAVE_SECONDS = "durability_save_seconds"
RESTORE_SECONDS = "durability_restore_seconds"
FAULTBACK_SECONDS = "durability_faultback_seconds"


def observe_save(seconds: float, kind: str) -> None:
    """One snapshot write's wall time, labeled ``kind=full|delta``."""
    HISTOGRAMS.observe(SAVE_SECONDS, seconds, unit="s", kind=kind)


def observe_restore(seconds: float) -> None:
    """One chain restore's wall time (manifest reads + payload decode +
    placement)."""
    HISTOGRAMS.observe(RESTORE_SECONDS, seconds, unit="s")


def observe_faultback(seconds: float) -> None:
    """One fault-back cohort's wall time (host rows -> device scatter)."""
    HISTOGRAMS.observe(FAULTBACK_SECONDS, seconds, unit="s")


class DurabilityStats:
    """Thread-safe counters for the durability plane (one process-global
    instance, :data:`DURABILITY_STATS`; private instances supported for
    tests). ``touched`` stays False until the first save/evict/resize, so an
    idle process's snapshot omits the section entirely."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._touched = False
        self._spillers: "weakref.WeakSet" = weakref.WeakSet()
        self._counters: Dict[str, int] = {
            "saves": 0,
            "delta_saves": 0,
            "auto_saves": 0,
            "save_errors": 0,
            "restores": 0,
            "restore_errors": 0,
            "bytes_written": 0,
            "bytes_read": 0,
            "tenants_stamped": 0,
            "evictions": 0,
            "fault_backs": 0,
            "grows": 0,
            "compactions": 0,
        }
        self._spilled_high_water = 0

    # -- recording ----------------------------------------------------------

    def register_spiller(self, spiller: Any) -> None:
        with self._lock:
            self._touched = True
            self._spillers.add(spiller)

    def inc(self, counter: str, n: int = 1) -> None:
        if not TELEMETRY.enabled:
            return
        with self._lock:
            self._touched = True
            self._counters[counter] = self._counters.get(counter, 0) + int(n)

    def note_spill_occupancy(self, spilled: int) -> None:
        """Point-in-time spilled-tenant count after an evict/fault-back —
        feeds the high-water mark (the gauges themselves read live spillers
        at snapshot time, so they can never go stale)."""
        if not TELEMETRY.enabled:
            return
        with self._lock:
            self._touched = True
            if spilled > self._spilled_high_water:
                self._spilled_high_water = int(spilled)

    # -- reading ------------------------------------------------------------

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def summary(self) -> Dict[str, Any]:
        """The ``snapshot()["durability"]`` section (``{}`` when untouched)."""
        with self._lock:
            if not self._touched:
                return {}
            spillers = list(self._spillers)
            out: Dict[str, Any] = {
                **dict(self._counters),
                "spillers": len(spillers),
                "spilled_tenants": 0,
                "resident_tenants": 0,
                "spilled_bytes": 0,
                "spilled_high_water": self._spilled_high_water,
            }
        # occupancy is read OUTSIDE the stats lock: a spiller mutates under
        # its metric's ingest lock, and nesting the other way here would be
        # an ABBA deadlock (the serving ledger's discipline)
        for sp in spillers:
            try:
                occ = sp.occupancy()
            except Exception:  # pragma: no cover - a detaching spiller
                continue
            out["spilled_tenants"] += occ["spilled"]
            out["resident_tenants"] += occ["resident_active"]
            out["spilled_bytes"] += occ["spilled_bytes"]
        return out

    def reset(self) -> None:
        """Zero every counter (live spillers stay registered — their
        occupancy keeps reporting)."""
        with self._lock:
            for k in self._counters:
                self._counters[k] = 0
            self._spilled_high_water = 0


#: the process-global durability ledger
DURABILITY_STATS = DurabilityStats()


def summary() -> Dict[str, Any]:
    """Module-level accessor ``observability.snapshot()`` reads."""
    return DURABILITY_STATS.summary()


def note_resize(key: str, kind: str, num_tenants: int, capacity: int) -> None:
    """One elastic resize (``kind`` = ``grow``/``compact``) — counter + a
    ``durability`` timeline event carrying the new logical/physical sizes."""
    DURABILITY_STATS.inc("grows" if kind == "grow" else "compactions")
    if TELEMETRY.enabled:
        TELEMETRY.inc(key, f"capacity_{kind}s")
    if EVENTS.enabled:
        EVENTS.record(
            "durability",
            key,
            path=kind,
            num_tenants=int(num_tenants),
            capacity=int(capacity),
        )
