"""Incremental checkpointing: mergeable snapshots with a crash-safe manifest
protocol.

A snapshot is a directory of **payload shards** plus one ``MANIFEST.json``,
riding the packed-bundle byte encoding the eager gather transport already
uses (``utilities/distributed.py``): every leaf is a contiguous raw-byte
span of one shard file, and the descriptors — name, shape, dtype, declared
reduction, byte offset — live in the manifest instead of an int64 descriptor
row. Three properties fall out of that encoding:

* **Mergeable by construction.** A shard holds one participant's *partial*
  state under the leaves' declared reductions; restoring a multi-shard
  snapshot re-reduces the shards (``sum`` adds, ``max``/``min`` fold) —
  bit-identical for integer and extremal states, exactly like the packed
  transport's collectives. A single-process save is the one-shard special
  case.
* **Topology-flexible restore.** The payload carries host bytes, never
  device layouts: a snapshot saved on an 8-way mesh restores onto a 4-way
  mesh, onto a :class:`~metrics_tpu.transport.sharded.ShardedTransport`
  placement (``Transport.place_state``), or into a metric with a different
  padded tenant capacity — only the logical ``[:num_tenants]`` rows are
  ever saved, so the physical padding is the *target's* business.
* **Delta checkpoints.** A save stamps only the tenants whose per-tenant
  write marks moved since the previous save — the serving scheduler's
  per-tenant generation ledger when one is attached, the PR-7 traffic
  ledger's row counts otherwise — so touching k of N tenants writes an
  O(k) payload (assertable from ``MANIFEST.json``: ``payload_bytes`` and
  ``len(tenants)``). Restore replays the chain: full snapshot, then each
  delta's rows in order.

**Crash consistency** is the atomic-rename protocol: shards are written and
fsynced into a dot-prefixed temp directory, the manifest is written last
(also fsynced), the whole directory is renamed into place with one atomic
``os.replace``, and only then does the ``LATEST`` pointer move (itself via
write-temp + rename). A crash at ANY step leaves the previous complete
snapshot restorable: temp directories are invisible to restore, a snapshot
without a checksum-valid manifest+shards never enters a restore chain, and
``LATEST`` is an optimization — restore falls back to scanning for the
newest snapshot whose full parent chain validates. The
:func:`inject_crash` hook lets the fault-injection tests kill a save at
every one of those steps.

Saves run synchronously (:meth:`CheckpointManager.save`) or on the
durability lane of the PR-9 background engine
(:meth:`CheckpointManager.save_async` —
``get_engine("durability")``), overlapping serialization and disk writes
with live update traffic: the state snapshot is a set of immutable device
array references taken under the metric's ingest lock (consistent by
construction, even mid-soak), and the donation audit routes concurrent
updates through the copying executable while those references are held.
"""
import hashlib
import json
import os
import threading
import time
import weakref
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from metrics_tpu.durability.telemetry import (
    DURABILITY_STATS,
    observe_restore,
    observe_save,
    pin_tenant_traffic,
    unpin_tenant_traffic,
)
from metrics_tpu.observability.events import EVENTS
from metrics_tpu.observability.registry import TELEMETRY

__all__ = [
    "CheckpointCrash",
    "CheckpointError",
    "CheckpointManager",
    "inject_crash",
    "list_snapshots",
    "load_manifest",
    "merge_shard_states",
    "read_snapshot_state",
    "resolve_chain",
    "restore_checkpoint",
    "save_checkpoint",
    "write_snapshot",
]

#: manifest schema version (bumped on incompatible layout changes)
MANIFEST_SCHEMA = 1
MANIFEST_NAME = "MANIFEST.json"
LATEST_NAME = "LATEST"
#: the ledger pseudo-bundle: per-tenant routed-row counts ride the payload
#: so delta marks survive a restore (never a metric state leaf)
LEDGER_BUNDLE = "__ledger__"


class CheckpointError(RuntimeError):
    """A checkpoint operation failed (no restorable snapshot, layout
    mismatch, target too small)."""


class CheckpointCrash(RuntimeError):
    """Raised by the fault-injection hook to simulate a crash mid-save."""


#: armed crash points (fault-injection tests only; empty in production)
_CRASH_POINTS: set = set()

#: the protocol steps a save walks, in order — each is injectable
CRASH_POINTS = (
    "before_shard",
    "after_shard",
    "before_manifest",
    "after_manifest",
    "before_rename",
    "after_rename",
    "before_latest",
)


def _maybe_crash(point: str) -> None:
    if point in _CRASH_POINTS:
        raise CheckpointCrash(f"injected crash at {point!r}")
    # the unified resilience seams subsume the legacy hook: a FaultPlan spec
    # armed at ``checkpoint.<point>`` (any raising mode — crash/error/drop)
    # kills the save exactly where inject_crash would, translated to the
    # protocol's native CheckpointCrash so every crash-consistency test and
    # the chaos soak share one vocabulary (metrics_tpu/resilience/faults.py)
    try:
        from metrics_tpu.resilience.faults import FaultInjected, maybe_fault
    except Exception:  # pragma: no cover - resilience plane optional
        return
    try:
        maybe_fault(f"checkpoint.{point}")
    except FaultInjected as err:
        raise CheckpointCrash(f"injected crash at {point!r} ({err})") from err


@contextmanager
def inject_crash(point: str):
    """Arm one crash point for the duration of the block (the
    fault-injection tests' hook). Raises ``ValueError`` on an unknown
    point so a typo cannot silently test nothing."""
    if point not in CRASH_POINTS:
        raise ValueError(f"unknown crash point {point!r}; one of {CRASH_POINTS}")
    _CRASH_POINTS.add(point)
    try:
        yield
    finally:
        _CRASH_POINTS.discard(point)


# ---------------------------------------------------------------------------
# payload encoding (the packed-bundle byte contract, descriptors in JSON)
# ---------------------------------------------------------------------------


def _encode_payload(
    leaves: Sequence[Tuple[str, str, np.ndarray, Any]]
) -> Tuple[bytes, List[Dict[str, Any]]]:
    """Pack ``(bundle, name, array, reduction)`` leaves into one contiguous
    byte payload + the manifest layout rows describing each span."""
    parts: List[bytes] = []
    layout: List[Dict[str, Any]] = []
    offset = 0
    for bundle, name, arr, reduction in leaves:
        arr = np.ascontiguousarray(arr)
        raw = arr.tobytes()
        layout.append(
            {
                "bundle": bundle,
                "name": name,
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "reduction": reduction if isinstance(reduction, str) else None,
                "offset": offset,
                "nbytes": len(raw),
            }
        )
        parts.append(raw)
        offset += len(raw)
    return b"".join(parts), layout


def _decode_payload(
    payload: bytes, layout: Sequence[Dict[str, Any]]
) -> Dict[str, Dict[str, np.ndarray]]:
    """The inverse of :func:`_encode_payload`: ``{bundle: {name: array}}``."""
    out: Dict[str, Dict[str, np.ndarray]] = {}
    for row in layout:
        raw = payload[row["offset"] : row["offset"] + row["nbytes"]]
        arr = np.frombuffer(raw, dtype=np.dtype(row["dtype"])).reshape(row["shape"])
        out.setdefault(row["bundle"], {})[row["name"]] = arr.copy()
    return out


def merge_shard_states(
    shard_states: Sequence[Dict[str, Dict[str, np.ndarray]]],
    layout: Sequence[Dict[str, Any]],
) -> Dict[str, Dict[str, np.ndarray]]:
    """Re-reduce per-shard partial states into one state by each leaf's
    declared reduction — the restore-side analogue of the packed
    collectives: ``sum`` adds shard contributions, ``max``/``min`` fold
    elementwise (bit-identical for integer/extremal leaves), a leaf with no
    declared reduction takes the first shard's value."""
    if len(shard_states) == 1:
        return shard_states[0]
    reductions = {(r["bundle"], r["name"]): r.get("reduction") for r in layout}
    out: Dict[str, Dict[str, np.ndarray]] = {}
    for bundle, leaves in shard_states[0].items():
        out[bundle] = {}
        for name, first in leaves.items():
            fx = reductions.get((bundle, name))
            acc = first.copy()
            for other in shard_states[1:]:
                contrib = other[bundle][name]
                if fx == "sum" or fx == "mean":
                    acc = acc + contrib
                elif fx == "max":
                    acc = np.maximum(acc, contrib)
                elif fx == "min":
                    acc = np.minimum(acc, contrib)
                # no declared reduction: first shard wins (replicated leaf)
            if fx == "mean":
                acc = acc / len(shard_states)
            out[bundle][name] = acc
    return out


# ---------------------------------------------------------------------------
# on-disk protocol
# ---------------------------------------------------------------------------


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover - platform without dir fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_snapshot(
    directory: str,
    manifest: Dict[str, Any],
    shard_payloads: Sequence[bytes],
) -> Dict[str, Any]:
    """Write one snapshot atomically: shards + manifest into a temp dir,
    one ``os.replace`` into place, then the ``LATEST`` pointer. Returns the
    completed manifest. The caller provides ``manifest`` WITHOUT the
    ``shards`` section — checksums and byte counts are computed here so the
    manifest can never disagree with the bytes on disk."""
    name = manifest["name"]
    os.makedirs(directory, exist_ok=True)
    tmp = os.path.join(directory, f".tmp-{name}")
    final = os.path.join(directory, name)
    if os.path.exists(tmp):
        import shutil

        shutil.rmtree(tmp)
    os.makedirs(tmp)

    shards: List[Dict[str, Any]] = []
    _maybe_crash("before_shard")
    for i, payload in enumerate(shard_payloads):
        fn = f"shard-{i:05d}.bin"
        path = os.path.join(tmp, fn)
        with open(path, "wb") as fh:
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
        shards.append(
            {
                "file": fn,
                "bytes": len(payload),
                "sha256": hashlib.sha256(payload).hexdigest(),
            }
        )
    _maybe_crash("after_shard")

    manifest = dict(manifest)
    manifest["shards"] = shards
    manifest["payload_bytes"] = int(sum(s["bytes"] for s in shards))
    manifest["complete"] = True
    _maybe_crash("before_manifest")
    mpath = os.path.join(tmp, MANIFEST_NAME)
    with open(mpath, "w") as fh:
        json.dump(manifest, fh, indent=1)
        fh.write("\n")
        fh.flush()
        os.fsync(fh.fileno())
    _maybe_crash("after_manifest")

    _maybe_crash("before_rename")
    os.replace(tmp, final)
    _fsync_dir(directory)
    _maybe_crash("after_rename")

    _maybe_crash("before_latest")
    latest_tmp = os.path.join(directory, f".{LATEST_NAME}.tmp")
    with open(latest_tmp, "w") as fh:
        fh.write(name + "\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(latest_tmp, os.path.join(directory, LATEST_NAME))
    _fsync_dir(directory)
    return manifest


def list_snapshots(directory: str) -> List[str]:
    """Snapshot directory names present on disk (complete or not),
    ascending; temp dirs and pointer files are invisible."""
    if not os.path.isdir(directory):
        return []
    return sorted(
        d
        for d in os.listdir(directory)
        if d.startswith("snap-") and os.path.isdir(os.path.join(directory, d))
    )


def load_manifest(directory: str, name: str) -> Optional[Dict[str, Any]]:
    """The snapshot's manifest, checksum-verified against its shard files;
    ``None`` for anything torn, truncated, or tampered — an invalid
    snapshot simply does not exist as far as restore is concerned."""
    path = os.path.join(directory, name, MANIFEST_NAME)
    try:
        with open(path) as fh:
            manifest = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(manifest, dict) or not manifest.get("complete"):
        return None
    if manifest.get("schema") != MANIFEST_SCHEMA:
        return None
    for shard in manifest.get("shards", []):
        spath = os.path.join(directory, name, shard["file"])
        try:
            with open(spath, "rb") as fh:
                payload = fh.read()
        except OSError:
            return None
        if len(payload) != shard["bytes"]:
            return None
        if hashlib.sha256(payload).hexdigest() != shard["sha256"]:
            return None
    return manifest


def resolve_chain(directory: str) -> List[Dict[str, Any]]:
    """The newest restorable chain, full snapshot first: the latest valid
    snapshot whose whole parent ancestry validates. The ``LATEST`` pointer
    is consulted first; a stale/missing/torn pointer degrades to a scan.
    Returns ``[]`` when nothing restorable exists."""
    # newest-first scan: a crash between the snapshot rename and the LATEST
    # pointer update leaves the pointer one snapshot behind — the completed
    # (renamed) snapshot is restorable and must win, so the pointer is never
    # trusted over a newer on-disk candidate (it only serves tooling)
    ordered = list(reversed(list_snapshots(directory)))

    manifests: Dict[str, Optional[Dict[str, Any]]] = {}

    def valid(name: str) -> Optional[Dict[str, Any]]:
        if name not in manifests:
            manifests[name] = load_manifest(directory, name)
        return manifests[name]

    for head in ordered:
        chain: List[Dict[str, Any]] = []
        cursor: Optional[str] = head
        ok = True
        while cursor is not None:
            manifest = valid(cursor)
            if manifest is None:
                ok = False
                break
            chain.append(manifest)
            cursor = manifest.get("parent")
            if manifest["kind"] == "full":
                cursor = None
        if ok and chain and chain[-1]["kind"] == "full":
            return list(reversed(chain))
    return []


def read_snapshot_state(
    directory: str, manifest: Dict[str, Any]
) -> Dict[str, Dict[str, np.ndarray]]:
    """Decode one snapshot's payload into ``{bundle: {leaf: array}}``,
    re-reducing multi-shard payloads by the declared reductions."""
    shard_states = []
    for shard in manifest["shards"]:
        with open(os.path.join(directory, manifest["name"], shard["file"]), "rb") as fh:
            payload = fh.read()
        DURABILITY_STATS.inc("bytes_read", len(payload))
        shard_states.append(_decode_payload(payload, manifest["layout"]))
    return merge_shard_states(shard_states, manifest["layout"])


#: lazily-jitted fused row gather: ALL of a bundle's leaves gather their
#: dirty rows in ONE dispatch (a per-leaf gather pays one XLA dispatch per
#: state leaf — dispatch overhead dominating the O(k) payload is exactly
#: the cost profile delta saves exist to avoid). jit's own aval/treedef
#: cache bounds executables: one per (bundle layout, dirty-count) pair.
_ROW_GATHER = None


def _gather_bundle_rows(state: Dict[str, Any], dirty: np.ndarray) -> Dict[str, np.ndarray]:
    global _ROW_GATHER
    import jax
    import jax.numpy as jnp

    if _ROW_GATHER is None:
        _ROW_GATHER = jax.jit(lambda s, ids: {k: v[ids] for k, v in s.items()})
    out = _ROW_GATHER(state, jnp.asarray(dirty))
    return {k: np.asarray(v) for k, v in out.items()}


# ---------------------------------------------------------------------------
# metric adapters
# ---------------------------------------------------------------------------


def _unwrap(metric: Any) -> Tuple[Any, Optional[Any]]:
    """``(state-owning metric, scheduler-or-None)`` — accepts a bare
    metric/wrapper or a serving ``SLOScheduler`` (duck-typed: the scheduler
    owns the per-tenant write-generation ledger the delta marks prefer)."""
    if hasattr(metric, "tenant_generations") and hasattr(metric, "_metric"):
        return metric._metric, metric
    return metric, None


def _fault_back_all(metric: Any) -> None:
    hooks = getattr(metric, "__dict__", {}).get("_durability_hooks")
    if hooks is not None:
        hooks.before_snapshot()


def _is_collection(metric: Any) -> bool:
    return hasattr(metric, "_require_built") and hasattr(metric, "_keyed")


def _is_keyed(metric: Any) -> bool:
    return hasattr(metric, "num_tenants") and hasattr(metric, "_segment_scatter")


def _serial_lock(metric: Any):
    lock = getattr(metric, "_serial_lock", None)
    if callable(lock):
        return lock()
    return threading.RLock()


def _bundles(metric: Any) -> Dict[str, Any]:
    """``{bundle key: keyed-or-plain metric}`` — the state owners a
    snapshot serializes. List ("cat") states are refused: durable snapshots
    target fixed-shape mergeable states (use ``state_dict`` for unbounded
    accumulators)."""
    if _is_collection(metric):
        return dict(metric._require_built())
    owners = {"": metric}
    for name, value in metric._get_states().items():
        if isinstance(value, (list, tuple)):
            hint = getattr(metric, "_sketch_hint", None)
            raise CheckpointError(
                f"{type(metric).__name__} holds unbounded list state `{name}`;"
                " durable snapshots need fixed-shape mergeable states."
                + (f" {hint}" if hint else "")
            )
    return owners


# ---------------------------------------------------------------------------
# the manager
# ---------------------------------------------------------------------------


class CheckpointManager:
    """Own one metric's snapshot trail under ``directory``.

    ``metric`` is a :class:`~metrics_tpu.wrappers.KeyedMetric`, a
    :class:`~metrics_tpu.wrappers.MultiTenantCollection`, a plain
    :class:`~metrics_tpu.Metric` with fixed-shape states, or a serving
    :class:`~metrics_tpu.serving.SLOScheduler` (saves its metric; delta
    marks ride the scheduler's per-tenant write generations).

    ``history`` bounds retained snapshots: after a successful FULL save,
    older snapshots beyond the newest ``history`` are deleted (a delta's
    ancestry is never broken — pruning only ever happens behind a full).
    """

    def __init__(self, directory: str, metric: Any, *, history: Optional[int] = None):
        self.directory = str(directory)
        self._target, self._scheduler = _unwrap(metric)
        self.history = None if history is None else int(history)
        self._lock = threading.Lock()
        self._last_marks: Optional[Tuple[str, Any]] = None
        self._last_meta: Optional[Dict[str, Any]] = None
        existing = resolve_chain(self.directory)
        if existing:
            self._last_meta = {
                "name": existing[-1]["name"],
                "num_tenants": existing[-1].get("num_tenants"),
            }
        self.telemetry_key = TELEMETRY.register(self)
        #: wall clock of the last COMPLETED save (the auto-save interval
        #: trigger's reference point; starts at construction so an idle
        #: manager's first auto save still waits one full interval)
        self._last_save_at = time.monotonic()
        # background auto-save state (enable_auto_save)
        self._auto_stop: Optional[threading.Event] = None
        self._auto_thread: Optional[threading.Thread] = None
        self._auto_future: Optional[Any] = None
        self._auto_failures = 0
        self._auto_saves = 0
        self._auto_skipped_inflight = 0
        # rows marks read the traffic ledger as ground truth, so hold it
        # open for the manager's lifetime: with the ledger fed only behind
        # TELEMETRY.enabled, a telemetry toggle between two saves would
        # freeze the rows and silently drop those tenants from the next
        # delta's dirty set
        if getattr(self._target, "_traffic", None) is not None:
            pin_tenant_traffic(self._target)
            self._traffic_unpin = weakref.finalize(
                self, unpin_tenant_traffic, self._target
            )

    # -- marks (the delta dirty-set source) ---------------------------------

    def _current_marks(self) -> Optional[Tuple[str, Any]]:
        if self._scheduler is not None:
            return ("gen", dict(self._scheduler.tenant_generations()))
        traffic = getattr(self._target, "_traffic", None)
        if traffic is not None and (
            TELEMETRY.enabled
            or self._target.__dict__.get("_durability_traffic_pin")
        ):
            # a dead ledger (no pin, telemetry off) must force a full save:
            # its rows can be arbitrarily stale, and a delta diffed against
            # frozen rows drops data from the snapshot chain
            rows, _ = traffic.arrays()
            if rows is not None:
                return ("rows", rows)
        return None

    @staticmethod
    def _dirty_tenants(
        prev: Tuple[str, Any], cur: Tuple[str, Any]
    ) -> Optional[np.ndarray]:
        """Tenants whose write marks moved between two snapshots; ``None``
        when the mark kinds/shapes are incomparable (falls back to full)."""
        if prev[0] != cur[0]:
            return None
        if cur[0] == "gen":
            prev_map, cur_map = prev[1], cur[1]
            dirty = [t for t, g in cur_map.items() if g > prev_map.get(t, 0)]
            return np.asarray(sorted(dirty), dtype=np.int64)
        prev_rows, cur_rows = prev[1], cur[1]
        if prev_rows.shape != cur_rows.shape:
            return None
        return np.nonzero(cur_rows != prev_rows)[0].astype(np.int64)

    # -- save ---------------------------------------------------------------

    def _next_name(self) -> str:
        existing = list_snapshots(self.directory)
        seq = 0
        for name in existing:
            try:
                seq = max(seq, int(name.split("-", 1)[1]))
            except (IndexError, ValueError):
                continue
        return f"snap-{seq + 1:08d}"

    def _snapshot_refs(self) -> Tuple[Dict[str, Any], Optional[Tuple[str, Any]], Dict[str, Any]]:
        """Under the metric's ingest lock: immutable device-array references
        for every bundle leaf (+ the ledger), the current write marks, and
        the keyed-geometry metadata — one consistent cut, even mid-soak."""
        metric = self._target
        with _serial_lock(metric):
            _fault_back_all(metric)
            bundles = _bundles(metric)
            refs: Dict[str, Any] = {
                key: dict(owner._get_states()) for key, owner in bundles.items()
            }
            marks = self._current_marks()
            meta: Dict[str, Any] = {"metric": type(metric).__name__}
            if _is_keyed(metric) or _is_collection(metric):
                meta["keyed"] = True
                meta["num_tenants"] = int(metric.num_tenants)
                meta["capacity"] = int(getattr(metric, "capacity", metric.num_tenants))
                traffic = getattr(metric, "_traffic", None)
                rows = traffic.arrays()[0] if traffic is not None else None
                if rows is not None:
                    refs[LEDGER_BUNDLE] = {"rows": rows}
            else:
                meta["keyed"] = False
        return refs, marks, meta

    def save(self, *, delta: Optional[bool] = None) -> Dict[str, Any]:
        """Write one snapshot synchronously and return its manifest.

        ``delta=None`` (default) writes a delta when one is possible — a
        prior snapshot exists, the write marks are comparable, and the
        keyed geometry did not change — and a full snapshot otherwise;
        ``True`` forces delta (raises when impossible), ``False`` forces
        full."""
        refs, marks, meta = self._snapshot_refs()
        return self._write(refs, marks, meta, delta=delta)

    def save_async(self, *, delta: Optional[bool] = None) -> Any:
        """Queue the snapshot write on the durability lane of the
        background engine (``get_engine("durability")``) and return its
        :class:`~metrics_tpu.utilities.async_sync.SyncFuture` (resolves to
        the manifest). The state cut happens NOW, on the caller thread,
        under the ingest lock — everything after (host transfer,
        serialization, fsync, rename) overlaps live traffic."""
        from metrics_tpu.utilities.async_sync import get_engine

        refs, marks, meta = self._snapshot_refs()
        return get_engine("durability").submit(
            f"checkpoint:{self.telemetry_key}",
            lambda: self._write(refs, marks, meta, delta=delta),
        )

    # -- background auto-save policy ----------------------------------------

    def dirty_count(self) -> Optional[int]:
        """Tenants whose write marks moved since the last completed save
        (``None`` when unknowable: no marks source, no prior save, or
        incomparable marks — the cases a save resolves as a full)."""
        cur = self._current_marks()
        if cur is None:
            return None
        with self._lock:
            prev = self._last_marks
        if prev is None:
            # no marks baseline (first save predated any traffic): every
            # tenant with ANY write mark is dirty relative to that save
            if cur[0] == "rows":
                return int(np.count_nonzero(cur[1]))
            return int(len(cur[1]))
        dirty = self._dirty_tenants(prev, cur)
        return None if dirty is None else int(len(dirty))

    def enable_auto_save(
        self,
        *,
        interval_s: Optional[float] = None,
        dirty_threshold: Optional[int] = None,
        delta: Optional[bool] = None,
        retry_policy: Optional[Any] = None,
        tick_s: Optional[float] = None,
    ) -> None:
        """Arm the background auto-save policy: a daemon thread triggers
        :meth:`save_async` on the durability lane whenever

        * ``interval_s`` elapsed since the last completed save, OR
        * at least ``dirty_threshold`` tenants' write marks moved since the
          last completed save (the delta dirty set — so the trigger scales
          with actual write pressure, not wall time)

        (either trigger alone is allowed; at least one is required). At
        most ONE auto save is in flight at a time — a tick that finds the
        previous save still writing skips (counted); a tick after a FAILED
        save backs off through ``retry_policy`` (default: the unified
        ``checkpoint`` plane policy,
        :func:`metrics_tpu.resilience.policies.retry_policy_for`) — a
        crashed save never advances the marks, so the retry re-covers its
        dirty set by construction. Idempotent: re-enabling reconfigures."""
        if interval_s is None and dirty_threshold is None:
            raise ValueError("enable_auto_save needs interval_s and/or dirty_threshold")
        if interval_s is not None and float(interval_s) <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        if dirty_threshold is not None and int(dirty_threshold) < 1:
            raise ValueError(f"dirty_threshold must be >= 1, got {dirty_threshold}")
        from metrics_tpu.resilience.policies import retry_policy_for

        self.disable_auto_save()
        retry = retry_policy if retry_policy is not None else retry_policy_for("checkpoint")
        if tick_s is None:
            candidates = [0.25]
            if interval_s is not None:
                candidates.append(float(interval_s) / 4.0)
            tick_s = max(0.005, min(candidates))
        stop = threading.Event()
        self._auto_stop = stop
        self._auto_config = {
            "interval_s": None if interval_s is None else float(interval_s),
            "dirty_threshold": None if dirty_threshold is None else int(dirty_threshold),
            "delta": delta,
            "tick_s": float(tick_s),
        }

        def loop() -> None:
            backoff_until = 0.0
            while not stop.wait(tick_s):
                try:
                    # settle the previous save first: its outcome gates the
                    # single-flight and failure-backoff rules
                    future = self._auto_future
                    if future is not None:
                        if not future.done():
                            if self._auto_due():
                                self._auto_skipped_inflight += 1
                            continue
                        self._auto_future = None
                        if future.exception(timeout=0) is None:
                            self._auto_failures = 0
                        else:
                            # save_errors already counted by _write; the
                            # unified policy spaces the re-attempts
                            self._auto_failures += 1
                            backoff_until = time.monotonic() + retry.backoff(
                                self._auto_failures
                            )
                    if time.monotonic() < backoff_until or not self._auto_due():
                        continue
                    self._auto_saves += 1
                    DURABILITY_STATS.inc("auto_saves")
                    self._auto_future = self.save_async(delta=delta)
                except Exception:  # pragma: no cover - the policy must survive
                    self._auto_failures += 1
                    backoff_until = time.monotonic() + retry.backoff(self._auto_failures)

        self._auto_thread = threading.Thread(
            target=loop, name="metrics-tpu-auto-save", daemon=True
        )
        self._auto_thread.start()

    def _auto_due(self) -> bool:
        cfg = getattr(self, "_auto_config", None)
        if cfg is None:
            return False
        if cfg["interval_s"] is not None and (
            time.monotonic() - self._last_save_at >= cfg["interval_s"]
        ):
            return True
        if cfg["dirty_threshold"] is not None:
            dirty = self.dirty_count()
            # unknowable marks ask for a (full) save only when traffic is
            # possible at all — a plain metric with no ledger would
            # otherwise save every tick
            if dirty is not None and dirty >= cfg["dirty_threshold"]:
                return True
        return False

    def disable_auto_save(self, timeout: Optional[float] = 2.0) -> None:
        """Stop the auto-save thread (waits for it; an in-flight save
        finishes on the durability lane regardless). Idempotent."""
        stop, thread = self._auto_stop, self._auto_thread
        self._auto_stop = None
        self._auto_thread = None
        if stop is not None:
            stop.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout)

    def auto_save_report(self) -> Dict[str, Any]:
        """The auto-save policy's state: config, saves triggered, ticks
        skipped on an in-flight save, consecutive failures."""
        cfg = getattr(self, "_auto_config", None)
        return {
            "enabled": bool(self._auto_thread is not None and self._auto_thread.is_alive()),
            "config": dict(cfg) if cfg else None,
            "auto_saves": self._auto_saves,
            "skipped_in_flight": self._auto_skipped_inflight,
            "consecutive_failures": self._auto_failures,
            "dirty_count": self.dirty_count(),
        }

    def _write(
        self,
        refs: Dict[str, Any],
        marks: Optional[Tuple[str, Any]],
        meta: Dict[str, Any],
        *,
        delta: Optional[bool],
    ) -> Dict[str, Any]:
        import jax.numpy as jnp

        start = time.perf_counter()
        with self._lock:
            kind = "full"
            dirty: Optional[np.ndarray] = None
            parent = self._last_meta["name"] if self._last_meta else None
            can_delta = (
                meta.get("keyed", False)
                and parent is not None
                and marks is not None
                and self._last_marks is not None
                and self._last_meta.get("num_tenants") == meta.get("num_tenants")
            )
            if can_delta:
                dirty = self._dirty_tenants(self._last_marks, marks)
            if delta is True and (not can_delta or dirty is None):
                raise CheckpointError(
                    "delta save impossible: no comparable prior snapshot/marks"
                    " (geometry changed, first save, or no write ledger)"
                )
            if delta is not False and can_delta and dirty is not None:
                kind = "delta"

            n = meta.get("num_tenants")
            leaves: List[Tuple[str, str, np.ndarray, Any]] = []
            try:
                for bundle, state in refs.items():
                    if bundle == LEDGER_BUNDLE:
                        rows = state["rows"]
                        if kind == "delta":
                            rows = rows[dirty]
                        leaves.append((bundle, "rows", np.asarray(rows), None))
                        continue
                    owner = self._bundle_owner(bundle)
                    reductions = getattr(owner, "_reductions", {})
                    if kind == "delta":
                        gathered = _gather_bundle_rows(state, dirty)
                    for name, leaf in state.items():
                        if kind == "delta":
                            rows = gathered[name]
                        elif meta.get("keyed", False):
                            # capacity padding is never saved; the slice is
                            # skipped entirely when there is none (no XLA
                            # dispatch for the common exact-capacity case)
                            rows = (
                                np.asarray(leaf)
                                if leaf.shape[0] == n
                                else np.asarray(leaf[:n])
                            )
                        else:
                            rows = np.asarray(leaf)
                        leaves.append((bundle, name, rows, reductions.get(name)))

                payload, layout = _encode_payload(leaves)
                manifest = {
                    "schema": MANIFEST_SCHEMA,
                    "name": self._next_name(),
                    "kind": kind,
                    "parent": parent if kind == "delta" else None,
                    "created_unix_s": round(time.time(), 3),
                    "layout": layout,
                    "tenants": (
                        [int(t) for t in dirty] if kind == "delta" else None
                    ),
                    **meta,
                }
                manifest = write_snapshot(self.directory, manifest, [payload])
            except BaseException:
                DURABILITY_STATS.inc("save_errors")
                if EVENTS.enabled:
                    EVENTS.record(
                        "durability", self.telemetry_key, path="save_error", snapshot_kind=kind
                    )
                raise
            # marks advance only on a COMPLETED snapshot: a crashed save
            # must leave the dirty set intact for the retry
            self._last_marks = marks
            self._last_meta = {
                "name": manifest["name"],
                "num_tenants": meta.get("num_tenants"),
            }
            self._last_save_at = time.monotonic()
            if kind == "full" and self.history is not None:
                self._prune(keep=self.history)

        dur = time.perf_counter() - start
        DURABILITY_STATS.inc("saves")
        if kind == "delta":
            DURABILITY_STATS.inc("delta_saves")
            DURABILITY_STATS.inc("tenants_stamped", int(len(dirty)))
        DURABILITY_STATS.inc("bytes_written", manifest["payload_bytes"])
        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "saves")
            observe_save(dur, kind)
        if EVENTS.enabled:
            EVENTS.record(
                "durability",
                self.telemetry_key,
                dur_s=dur,
                t_start=start,
                path="save",
                snapshot_kind=kind,
                snapshot=manifest["name"],
                payload_bytes=manifest["payload_bytes"],
                tenants_stamped=(len(dirty) if kind == "delta" else None),
            )
        return manifest

    def _bundle_owner(self, bundle: str) -> Any:
        if bundle == "" or not _is_collection(self._target):
            return getattr(self._target, "_child", self._target)
        return self._target._require_built()[bundle]._child

    def _prune(self, keep: int) -> None:
        """Drop snapshots older than the newest ``keep`` — called only
        behind a completed FULL save, so no surviving delta's ancestry can
        dangle."""
        import shutil

        names = list_snapshots(self.directory)
        for name in names[: max(0, len(names) - keep)]:
            shutil.rmtree(os.path.join(self.directory, name), ignore_errors=True)

    # -- restore ------------------------------------------------------------

    def restore(
        self,
        metric: Optional[Any] = None,
        *,
        transport: Optional[Any] = None,
    ) -> Any:
        """Restore the newest complete chain into ``metric`` (default: the
        managed metric) and return it.

        The assembled host state is re-placed for the TARGET's topology:
        ``transport.place_state`` when a transport is given (e.g. a
        :class:`~metrics_tpu.transport.sharded.ShardedTransport` shards the
        tenant axis), else the target's own ``tenant_sharding``, else plain
        device arrays — restore never assumes the saving topology. A keyed
        target needs ``num_tenants >=`` the saved logical count; extra
        capacity rows stay at the defaults."""
        start = time.perf_counter()
        target = self._target if metric is None else _unwrap(metric)[0]
        chain = resolve_chain(self.directory)
        if not chain:
            DURABILITY_STATS.inc("restore_errors")
            raise CheckpointError(
                f"no restorable snapshot under {self.directory!r} (nothing"
                " complete, or every chain has a torn ancestor)"
            )
        state = read_snapshot_state(self.directory, chain[0])
        if chain[0].get("keyed") and LEDGER_BUNDLE not in state:
            # the full snapshot predates any routed row (ledger untracked at
            # its cut) but a later delta carries ledger rows: zero base
            state[LEDGER_BUNDLE] = {
                "rows": np.zeros(int(chain[0]["num_tenants"]), np.int64)
            }
        for manifest in chain[1:]:
            delta = read_snapshot_state(self.directory, manifest)
            ids = np.asarray(manifest["tenants"], dtype=np.int64)
            for bundle, leaves in delta.items():
                for name, rows in leaves.items():
                    base = state[bundle][name]
                    base[ids] = rows
        marks: Optional[Tuple[str, Any]] = None
        with _serial_lock(target):
            self._install(target, chain[-1], state, transport)
            if target is self._target:
                # cut the marks baseline atomically with the install: an
                # update slipping in between would be invisible to the next
                # delta's dirty set (the serial lock is reentrant, so the
                # nested acquisition inside _install is free)
                marks = self._current_marks()

        # the restore replaced whole bundles: re-note the memory ledger at
        # this seam, outside the serial lock (a pressure callback may evict,
        # which re-takes the target's lock)
        from metrics_tpu.observability.memory import LEDGER

        LEDGER.note(target)

        dur = time.perf_counter() - start
        DURABILITY_STATS.inc("restores")
        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "restores")
            observe_restore(dur)
        if EVENTS.enabled:
            EVENTS.record(
                "durability",
                self.telemetry_key,
                dur_s=dur,
                t_start=start,
                path="restore",
                snapshot=chain[-1]["name"],
                chain=len(chain),
            )
        # restored state == last completed snapshot: the next delta's dirty
        # set is "everything touched since that snapshot"
        with self._lock:
            if target is self._target:
                self._last_marks = marks
                self._last_meta = {
                    "name": chain[-1]["name"],
                    "num_tenants": chain[-1].get("num_tenants"),
                }
        return target

    def _install(
        self,
        target: Any,
        manifest: Dict[str, Any],
        state: Dict[str, Dict[str, np.ndarray]],
        transport: Optional[Any],
    ) -> None:
        import jax
        import jax.numpy as jnp

        ledger = state.pop(LEDGER_BUNDLE, None)
        saved_n = manifest.get("num_tenants")
        keyed = bool(manifest.get("keyed"))

        # the whole installation — state swap, ledger overwrite, spiller
        # invalidation — is one cut under the target's ingest lock, exactly
        # like _snapshot_refs on the save side: a restore concurrent with
        # live ingest must never interleave an update's read-modify-write
        with _serial_lock(target):
            targets: Dict[str, Any]
            if _is_collection(target):
                owners = target._require_built()
                missing = set(state) - set(owners)
                if missing:
                    raise CheckpointError(
                        f"restore target collection lacks state bundles {sorted(missing)}"
                        " — build() it with the same members/groups as the saved one"
                    )
                targets = {k: owners[k] for k in state}
            else:
                if set(state) != {""}:
                    raise CheckpointError(
                        "snapshot holds a collection's bundles"
                        f" ({sorted(state)}); the restore target is a single metric"
                    )
                targets = {"": target}

            for bundle, owner in targets.items():
                leaves = state[bundle]
                if set(leaves) != set(owner._defaults):
                    raise CheckpointError(
                        f"snapshot leaves {sorted(leaves)} do not match the target's"
                        f" states {sorted(owner._defaults)} (bundle {bundle!r})"
                    )
                new_state: Dict[str, Any] = {}
                if keyed:
                    if owner.num_tenants < saved_n:
                        raise CheckpointError(
                            f"restore target has num_tenants={owner.num_tenants} <"
                            f" saved {saved_n}; grow() the target first"
                        )
                    for name, rows in leaves.items():
                        leaf = jnp.asarray(owner._defaults[name]).at[:saved_n].set(
                            jnp.asarray(rows)
                        )
                        new_state[name] = leaf
                else:
                    for name, arr in leaves.items():
                        new_state[name] = jnp.asarray(arr)
                if transport is not None:
                    new_state = transport.place_state(new_state)
                elif getattr(owner, "tenant_sharding", None) is not None:
                    new_state = {
                        k: jax.device_put(v, owner.tenant_sharding)
                        for k, v in new_state.items()
                    }
                owner._set_states(new_state)
                owner._computed = None
                owner._forward_cache = None
                owner._update_called = True
                # metrics that learn config from data (Accuracy.mode, ...)
                # decode it from the restored states — a fresh restore target
                # never saw a batch, so the clone/pickle channel is absent
                derived_host = getattr(owner, "_child", owner)
                derived_host._restore_derived(leaves)

            wrapper = target
            traffic = getattr(wrapper, "_traffic", None)
            if ledger is not None and traffic is not None and keyed:
                rows = np.zeros(wrapper.num_tenants, dtype=np.int64)
                saved_rows = ledger["rows"]
                rows[: min(len(saved_rows), len(rows))] = saved_rows[: len(rows)]
                with traffic._lock:
                    traffic.rows = rows
                    traffic.last_seen = np.full(wrapper.num_tenants, np.nan)

            # every device row was just replaced: host rows a spiller still
            # holds predate the restore, and the next fault-back would
            # scatter them over the restored tenants — the hooks drop them
            # and re-seed activity from the restored ledger (the save side's
            # _fault_back_all counterpart)
            hooks = getattr(target, "__dict__", {}).get("_durability_hooks")
            on_restore = getattr(hooks, "on_restore", None)
            if on_restore is not None:
                on_restore()

    # -- introspection ------------------------------------------------------

    def latest(self) -> Optional[str]:
        """Name of the newest restorable snapshot (``None`` when nothing
        restorable exists)."""
        chain = resolve_chain(self.directory)
        return chain[-1]["name"] if chain else None

    def report(self) -> Dict[str, Any]:
        chain = resolve_chain(self.directory)
        return {
            "directory": self.directory,
            "snapshots_on_disk": len(list_snapshots(self.directory)),
            "restorable_chain": [m["name"] for m in chain],
            "latest": chain[-1]["name"] if chain else None,
            "latest_kind": chain[-1]["kind"] if chain else None,
            "payload_bytes_latest": chain[-1]["payload_bytes"] if chain else None,
        }


# ---------------------------------------------------------------------------
# one-shot helpers
# ---------------------------------------------------------------------------


def save_checkpoint(directory: str, metric: Any, **kwargs: Any) -> Dict[str, Any]:
    """One full snapshot of ``metric`` under ``directory`` (a throwaway
    :class:`CheckpointManager`; keep a manager for delta trails)."""
    return CheckpointManager(directory, metric).save(**kwargs)


def restore_checkpoint(directory: str, metric: Any, **kwargs: Any) -> Any:
    """Restore the newest complete chain under ``directory`` into
    ``metric`` and return it."""
    return CheckpointManager(directory, metric).restore(metric, **kwargs)
