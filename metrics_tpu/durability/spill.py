"""Cold-tenant spill: LRU-evict idle tenants' rows to host memory.

A weeks-long multi-tenant service accumulates state for every tenant that
EVER appeared; device HBM pays for all of them forever even though traffic
is heavily skewed. :class:`TenantSpiller` bounds the device-resident
working set: tenants idle longest (the PR-7 staleness ledger's
``last_seen`` is the signal; the spiller keeps its own stamp as a fallback
so eviction works with telemetry disabled) are **evicted** — their rows of
every stacked leaf copy to host numpy and the device rows reset to the
child defaults — and **fault back transparently**:

* an ``update``/``update_many`` naming a spilled tenant faults its rows
  back BEFORE the dispatch (under the metric's ingest lock), so every
  routable reduction accumulates exactly — no merge arithmetic, no drift;
* a ``compute()``/rollup/clone/checkpoint faults back every spilled tenant
  first (``before_read``/``before_snapshot``), so reads are bit-identical
  to a never-evicted metric.

The spiller installs itself as the metric's durability hooks
(``metric._durability_hooks``) — the wrappers call ``before_update``/
``after_update``/``before_read``/``before_snapshot``/``on_resize`` from
their stateful paths, and the checkpoint plane calls ``on_restore`` after
installing a snapshot (spilled host rows predate the restored state and
must be dropped, never faulted back); the pure ``apply_update`` path and
every compiled program are untouched (the zero-overhead ``durability_off``
digests pin it).
Eviction/fault-back scatters pad their tenant cohorts to power-of-two
buckets (ids repeated — an idempotent row write), so the executable cache
stays log2-bounded exactly like the serving queue's ``pad_to_bucket``.

**Conservation law** (checked by :meth:`report`, pinned by the spill soak):
``resident_active + spilled == active_total`` — every tenant that ever
received a row is either device-resident or host-spilled, never both,
never neither — and the serving ledger's
``submitted − shed == dispatched == rows_routed`` invariant is untouched
because fault-back precedes every dispatch.
"""
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

import numpy as np

from metrics_tpu.durability.telemetry import (
    DURABILITY_STATS,
    observe_faultback,
    pin_tenant_traffic,
    unpin_tenant_traffic,
)
from metrics_tpu.observability.events import EVENTS
from metrics_tpu.observability.registry import TELEMETRY

__all__ = ["TenantSpiller"]


def _pad_pow2(ids: np.ndarray) -> np.ndarray:
    """Pad a tenant cohort to the next power-of-two length by repeating the
    last id — duplicate scatter-writes of the same row value are
    idempotent, and the padded shapes bound the executable cache."""
    n = len(ids)
    bucket = 1 << max(0, n - 1).bit_length()
    if bucket == n:
        return ids
    return np.concatenate([ids, np.full(bucket - n, ids[-1], ids.dtype)])


class TenantSpiller:
    """Bound a keyed metric's device-resident tenant rows.

    Args:
        metric: a :class:`~metrics_tpu.wrappers.KeyedMetric` or
            :class:`~metrics_tpu.wrappers.MultiTenantCollection` (a
            collection spills the same tenant's rows across EVERY state
            bundle together — a tenant is resident or spilled as a unit).
        resident_cap: target bound on device-resident ACTIVE tenants;
            ``maybe_evict`` (run automatically after every update when
            ``auto=True``) evicts the coldest active tenants down to it.
        min_idle_s: never evict a tenant updated more recently than this
            (hot tenants stay resident even over the cap).
        auto: evict automatically after each update dispatch.
        pressure_high: optional BYTE watermark — when the memory ledger's
            tracked device total crosses it, the spiller evicts the coldest
            ``pressure_fraction`` of resident active tenants (staleness
            still orders the victims; byte pressure triggers the pass).
            Arms a :func:`metrics_tpu.observability.memory.on_pressure`
            subscription; re-arms below ``pressure_low``.
        pressure_low: re-arm watermark (default ``pressure_high // 2``).
        pressure_fraction: share of resident active tenants a pressure
            pass evicts (at least one, never the last resident).
    """

    def __init__(
        self,
        metric: Any,
        *,
        resident_cap: int,
        min_idle_s: float = 0.0,
        auto: bool = True,
        pressure_high: Optional[int] = None,
        pressure_low: Optional[int] = None,
        pressure_fraction: float = 0.5,
    ) -> None:
        if int(resident_cap) < 1:
            raise ValueError(f"resident_cap must be >= 1, got {resident_cap}")
        existing = metric.__dict__.get("_durability_hooks")
        if existing is not None:
            raise ValueError(
                f"{type(metric).__name__} already has durability hooks"
                f" ({type(existing).__name__}); detach() the old spiller first"
            )
        self._metric = metric
        self.resident_cap = int(resident_cap)
        self.min_idle_s = float(min_idle_s)
        self.auto = bool(auto)
        n = int(metric.num_tenants)
        #: tenant -> {bundle -> {leaf -> host row}} (the spilled rows)
        self._spilled: Dict[int, Dict[str, Dict[str, np.ndarray]]] = {}
        #: own touch stamps/active mask: correct even with telemetry off
        self._last_touch = np.full(n, -np.inf)
        self._touched = np.zeros(n, dtype=bool)
        # seed from the PR-7 traffic ledger so tenants active BEFORE the
        # spiller attached are eviction candidates from the first pass
        traffic = getattr(metric, "_traffic", None)
        if traffic is not None:
            rows, last_seen = traffic.arrays()
            if rows is not None:
                k = min(n, len(rows))
                self._touched[:k] = rows[:k] > 0
                seen = last_seen[:k] - time.time() + time.monotonic()
                self._last_touch[:k] = np.where(np.isnan(last_seen[:k]), -np.inf, seen)
        self._spilled_bytes = 0
        self.telemetry_key = TELEMETRY.register(self)
        # the eviction signal prefers the traffic ledger's staleness stamps,
        # so hold the ledger open: a telemetry toggle must not freeze it
        # (frozen stamps would evict hot tenants / keep cold ones resident)
        self._traffic_unpin = None
        if traffic is not None:
            pin_tenant_traffic(metric)
            self._traffic_unpin = weakref.finalize(
                self, unpin_tenant_traffic, metric
            )
        metric.__dict__["_durability_hooks"] = self
        DURABILITY_STATS.register_spiller(self)
        # memory-ledger integration: the wrapped metric's device bytes are
        # tracked from attach, and an optional byte watermark turns ledger
        # pressure into eviction passes (ROADMAP item 1's disk-tier seam)
        from metrics_tpu.observability.memory import LEDGER

        LEDGER.track(metric)
        self.pressure_evictions = 0
        self._pressure_handle = None
        if pressure_high is not None:
            if not 0.0 < float(pressure_fraction) <= 1.0:
                raise ValueError(
                    f"pressure_fraction must be in (0, 1], got {pressure_fraction}"
                )
            self._pressure_fraction = float(pressure_fraction)
            self._pressure_handle = LEDGER.on_pressure(
                self._on_pressure, high=int(pressure_high), low=pressure_low
            )

    # ------------------------------------------------------------------
    # hook protocol (called by the wrappers' stateful paths)
    # ------------------------------------------------------------------

    def before_update(self, ids: np.ndarray) -> None:
        """Fault back any spilled tenant named in this batch (exactness:
        the dispatch must accumulate onto the true rows)."""
        if self._spilled:
            hit = sorted({int(t) for t in np.unique(ids) if int(t) in self._spilled})
            if hit:
                self._fault_back_ids(hit)

    def after_update(self, ids: np.ndarray) -> None:
        now = time.monotonic()
        valid = ids[(ids >= 0) & (ids < len(self._last_touch))]
        if valid.size:
            self._last_touch[valid] = now
            self._touched[valid] = True
        if self.auto:
            self.maybe_evict()

    def before_read(self) -> None:
        """Full-residency barrier for reads: every spilled tenant faults
        back so per-tenant values are bit-identical to never-evicted."""
        self.fault_back()

    def before_snapshot(self) -> None:
        """Same barrier for clones/pickles/checkpoints."""
        self.fault_back()

    def on_resize(self, num_tenants: int) -> None:
        n = int(num_tenants)
        old = len(self._last_touch)
        keep = min(old, n)
        last, touched = self._last_touch, self._touched
        self._last_touch = np.full(n, -np.inf)
        self._touched = np.zeros(n, dtype=bool)
        self._last_touch[:keep] = last[:keep]
        self._touched[:keep] = touched[:keep]
        for t in [t for t in self._spilled if t >= n]:
            entry = self._spilled.pop(t)
            self._spilled_bytes -= sum(
                r.nbytes for leaves in entry.values() for r in leaves.values()
            )
        self._note_ledger_spilled()

    def on_restore(self) -> None:
        """Restore invalidation — the checkpoint plane calls this under the
        metric's serial lock right after installing a snapshot. Every
        device row was just replaced, so all spilled host rows predate the
        restore: faulting them back would silently corrupt the restored
        tenants. Drop them and re-seed the activity set from the restored
        traffic ledger (restored tenants are active and immediately
        eviction-eligible — their stamps start at cold)."""
        self._spilled.clear()
        self._spilled_bytes = 0
        self._note_ledger_spilled()
        self._last_touch.fill(-np.inf)
        self._touched.fill(False)
        traffic = getattr(self._metric, "_traffic", None)
        if traffic is not None:
            rows, _ = traffic.arrays()
            if rows is not None:
                k = min(len(self._touched), len(rows))
                self._touched[:k] = rows[:k] > 0

    # ------------------------------------------------------------------
    # the spill mechanics
    # ------------------------------------------------------------------

    def _note_ledger_spilled(self) -> None:
        """Mirror the host-spilled byte gauge into the memory ledger (device
        bytes are untouched by evict/fault-back — rows reset in place — so
        this is a spilled-gauge update, never a watermark trigger)."""
        from metrics_tpu.observability.memory import LEDGER

        LEDGER.note_spilled(self._metric, self._spilled_bytes)

    def _bundles(self) -> Dict[str, Any]:
        m = self._metric
        if hasattr(m, "_require_built"):
            return dict(m._require_built())
        return {"": m}

    def _evict_ids(self, ids: List[int]) -> None:
        import jax.numpy as jnp

        padded = _pad_pow2(np.asarray(sorted(ids), dtype=np.int64))
        idx = jnp.asarray(padded)
        for t in ids:
            self._spilled[t] = {}
        for bundle, owner in self._bundles().items():
            defaults = owner._child._defaults
            new_state: Dict[str, Any] = {}
            for name in owner._defaults:
                leaf = getattr(owner, name)
                rows = np.asarray(leaf[idx])
                for i, t in enumerate(sorted(ids)):
                    row = rows[i].copy()
                    self._spilled[t].setdefault(bundle, {})[name] = row
                    self._spilled_bytes += row.nbytes
                new_state[name] = leaf.at[idx].set(jnp.asarray(defaults[name]))
            owner._set_states(new_state)
            owner._computed = None
            owner._forward_cache = None
        DURABILITY_STATS.inc("evictions", len(ids))
        DURABILITY_STATS.note_spill_occupancy(len(self._spilled))
        self._note_ledger_spilled()
        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "evictions", len(ids))
        if EVENTS.enabled:
            EVENTS.record(
                "durability",
                self.telemetry_key,
                path="evict",
                tenants=len(ids),
                spilled=len(self._spilled),
            )

    def _fault_back_ids(self, ids: List[int]) -> None:
        import jax.numpy as jnp

        start = time.perf_counter()
        ordered = sorted(ids)
        padded = _pad_pow2(np.asarray(ordered, dtype=np.int64))
        idx = jnp.asarray(padded)
        pad_tail = len(padded) - len(ordered)
        for bundle, owner in self._bundles().items():
            new_state: Dict[str, Any] = {}
            for name in owner._defaults:
                rows = np.stack(
                    [self._spilled[t][bundle][name] for t in ordered]
                    + [self._spilled[ordered[-1]][bundle][name]] * pad_tail
                )
                new_state[name] = getattr(owner, name).at[idx].set(jnp.asarray(rows))
            owner._set_states(new_state)
            owner._computed = None
            owner._forward_cache = None
        for t in ordered:
            entry = self._spilled.pop(t)
            self._spilled_bytes -= sum(
                r.nbytes for leaves in entry.values() for r in leaves.values()
            )
        dur = time.perf_counter() - start
        DURABILITY_STATS.inc("fault_backs", len(ordered))
        DURABILITY_STATS.note_spill_occupancy(len(self._spilled))
        self._note_ledger_spilled()
        if TELEMETRY.enabled:
            TELEMETRY.inc(self.telemetry_key, "fault_backs", len(ordered))
            observe_faultback(dur)
        if EVENTS.enabled:
            EVENTS.record(
                "durability",
                self.telemetry_key,
                dur_s=dur,
                t_start=start,
                path="fault_back",
                tenants=len(ordered),
                spilled=len(self._spilled),
            )

    # ------------------------------------------------------------------
    # public control plane
    # ------------------------------------------------------------------

    def _lock(self):
        return self._metric._serial_lock()

    def _stamps(self) -> np.ndarray:
        """Eviction signal: the metric's staleness ledger when it is
        tracking (PR-7), the spiller's own touch stamps otherwise."""
        traffic = getattr(self._metric, "_traffic", None)
        if traffic is not None:
            rows, last_seen = traffic.arrays()
            if last_seen is not None:
                stamps = np.where(np.isnan(last_seen), -np.inf, last_seen)
                # ledger stamps are wall-clock; shift into the monotonic
                # frame the min_idle_s comparison uses
                return stamps - time.time() + time.monotonic()
        return self._last_touch

    def maybe_evict(self) -> int:
        """Evict the coldest eligible active tenants down to
        ``resident_cap``; returns tenants evicted. Called automatically
        after each update when ``auto=True``."""
        with self._lock():
            active = np.nonzero(self._touched)[0]
            resident = [int(t) for t in active if int(t) not in self._spilled]
            excess = len(resident) - self.resident_cap
            if excess <= 0:
                return 0
            stamps = self._stamps()
            now = time.monotonic()
            eligible = [
                t for t in resident if now - stamps[t] >= self.min_idle_s
            ]
            if not eligible:
                return 0
            eligible.sort(key=lambda t: stamps[t])
            victims = eligible[: min(excess, len(eligible))]
            if victims:
                self._evict_ids(victims)
            return len(victims)

    def _on_pressure(self, tracked_bytes: int) -> None:
        """Ledger watermark callback: byte pressure triggers an eviction
        pass over the coldest ``pressure_fraction`` of resident active
        tenants (``min_idle_s`` still protects hot tenants, and the last
        resident tenant never spills). Fires outside the ledger lock; takes
        the metric's serial lock like every other eviction."""
        import math

        with self._lock():
            active = np.nonzero(self._touched)[0]
            resident = [int(t) for t in active if int(t) not in self._spilled]
            if len(resident) <= 1:
                return
            stamps = self._stamps()
            now = time.monotonic()
            eligible = [t for t in resident if now - stamps[t] >= self.min_idle_s]
            if not eligible:
                return
            eligible.sort(key=lambda t: stamps[t])
            quota = max(1, math.ceil(len(resident) * self._pressure_fraction))
            quota = min(quota, len(resident) - 1, len(eligible))
            victims = eligible[:quota]
            if not victims:
                return
            self._evict_ids(victims)
            self.pressure_evictions += len(victims)
            if TELEMETRY.enabled:
                TELEMETRY.inc(self.telemetry_key, "pressure_evictions", len(victims))
            if EVENTS.enabled:
                EVENTS.record(
                    "durability",
                    self.telemetry_key,
                    path="pressure_evict",
                    tenants=len(victims),
                    tracked_bytes=int(tracked_bytes),
                )

    def evict(self, tenant_ids: Optional[Any] = None) -> int:
        """Evict ``tenant_ids`` (or run one :meth:`maybe_evict` pass);
        already-spilled / never-active ids are skipped. Returns tenants
        evicted."""
        if tenant_ids is None:
            return self.maybe_evict()
        with self._lock():
            ids = [
                int(t)
                for t in np.asarray(tenant_ids).reshape(-1)
                if 0 <= int(t) < len(self._touched)
                and self._touched[int(t)]
                and int(t) not in self._spilled
            ]
            if ids:
                self._evict_ids(ids)
            return len(ids)

    def fault_back(self, tenant_ids: Optional[Any] = None) -> int:
        """Fault spilled tenants back to the device (all of them by
        default). Returns tenants restored."""
        with self._lock():
            if tenant_ids is None:
                ids = list(self._spilled)
            else:
                ids = [
                    int(t)
                    for t in np.asarray(tenant_ids).reshape(-1)
                    if int(t) in self._spilled
                ]
            if ids:
                self._fault_back_ids(ids)
            return len(ids)

    def occupancy(self) -> Dict[str, int]:
        """Point-in-time occupancy (the durability snapshot's gauge feed).
        ``resident_active`` is counted independently of ``spilled`` —
        touched tenants whose ids are NOT in the spill table — so the
        conservation law :meth:`report` checks is falsifiable: a stranded
        or duplicated spill entry (a spilled tenant outside the active set)
        breaks ``resident_active + spilled == active`` instead of hiding in
        derived arithmetic."""
        spilled_map = self._spilled
        active_ids = np.nonzero(self._touched)[0]
        resident_active = sum(1 for t in active_ids if int(t) not in spilled_map)
        return {
            "active": int(active_ids.size),
            "spilled": len(spilled_map),
            "resident_active": int(resident_active),
            "spilled_bytes": int(self._spilled_bytes),
        }

    def report(self) -> Dict[str, Any]:
        """Occupancy + the conservation check:
        ``resident_active + spilled == active`` exactly (both sides counted
        independently — see :meth:`occupancy`), plus the byte view —
        ``resident_bytes`` is the metric's live device footprint recomputed
        from aval metadata, ``spilled_bytes`` the host-side rows."""
        from metrics_tpu.observability.memory import bundle_bytes

        occ = self.occupancy()
        return {
            **occ,
            "resident_bytes": int(bundle_bytes(self._metric)),
            "resident_cap": self.resident_cap,
            "min_idle_s": self.min_idle_s,
            "auto": self.auto,
            "pressure_evictions": int(self.pressure_evictions),
            "conservation_ok": occ["resident_active"] + occ["spilled"] == occ["active"],
            "resident_under_cap": occ["resident_active"] <= self.resident_cap,
        }

    def detach(self) -> None:
        """Fault everything back and uninstall the hooks (the metric
        reverts to plain always-resident behavior)."""
        self.fault_back()
        if self._pressure_handle is not None:
            self._pressure_handle.cancel()
            self._pressure_handle = None
        if self._metric.__dict__.get("_durability_hooks") is self:
            del self._metric.__dict__["_durability_hooks"]
        if self._traffic_unpin is not None:
            self._traffic_unpin()

    def __repr__(self) -> str:
        occ = self.occupancy()
        return (
            f"TenantSpiller({type(self._metric).__name__},"
            f" resident_cap={self.resident_cap}, spilled={occ['spilled']})"
        )
