"""Durability plane: the state lifecycle between serving and transport.

Three coupled capabilities for a metrics service that runs for weeks (see
``docs/durability.md``):

* **Incremental checkpointing**
  (:mod:`~metrics_tpu.durability.checkpoint`) —
  :class:`CheckpointManager` writes mergeable snapshots over the packed
  byte-bundle encoding with a manifest + atomic-rename protocol (a crash
  mid-save always leaves the previous complete snapshot restorable), delta
  saves stamping only the tenants touched since the last save (O(k)
  payload, asserted from the manifest), and asynchronous saves overlapping
  update traffic on the durability lane of the PR-9 background engine.
* **Topology-flexible restore** — a snapshot saved on one mesh/process
  topology restores onto a different one (8-way → 4-way, replicated ↔
  :class:`~metrics_tpu.transport.ShardedTransport` via
  ``Transport.place_state``, different tenant-capacity padding): restore
  is a re-reduce of mergeable shards, bit-identical for integer/extremal
  states by construction.
* **Elastic capacity + cold-tenant spill** —
  :meth:`KeyedMetric.grow <metrics_tpu.wrappers.KeyedMetric.grow>` /
  :meth:`compact <metrics_tpu.wrappers.KeyedMetric.compact>` resize the
  keyed axis with pow2-padded capacities (at most ``log2(max N) + 1``
  keyed programs, ever), and :class:`TenantSpiller` LRU-evicts idle
  tenants' rows to host memory on the PR-7 staleness signal, faulting
  them back transparently on the next update/read with exact conservation
  (``resident_active + spilled == active``).

Everything is host-side: with durability features unused, every
pre-existing hot-path jaxpr is byte-identical
(``scripts/check_zero_overhead.py``, the ``durability_off`` digests). The
``durability.*`` telemetry family
(:mod:`~metrics_tpu.durability.telemetry`) surfaces in
``observability.snapshot()["durability"]``, the
``metrics_tpu_durability_*`` Prometheus series, ``durability`` timeline
events, and the save/restore/fault-back log2 histograms.
"""
from metrics_tpu.durability.checkpoint import (  # noqa: F401
    CheckpointCrash,
    CheckpointError,
    CheckpointManager,
    inject_crash,
    restore_checkpoint,
    save_checkpoint,
)
from metrics_tpu.durability.spill import TenantSpiller  # noqa: F401
from metrics_tpu.durability.telemetry import (  # noqa: F401
    DURABILITY_STATS,
    DurabilityStats,
    summary,
)

__all__ = [
    "CheckpointCrash",
    "CheckpointError",
    "CheckpointManager",
    "DURABILITY_STATS",
    "DurabilityStats",
    "TenantSpiller",
    "inject_crash",
    "restore_checkpoint",
    "save_checkpoint",
    "summary",
]
