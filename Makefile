# Headless CI entry points — `make ci` reproduces the green state locally
# exactly as .github/workflows/ci.yml runs it.
.PHONY: ci test doctest doctest-docs dryrun examples bench export-weights zero-overhead bench-regress trace-check soak checkpoint-smoke chaos-smoke slo-smoke profile-smoke

ci: test doctest doctest-docs dryrun examples zero-overhead bench-regress trace-check checkpoint-smoke chaos-smoke slo-smoke profile-smoke

# Full suite on the virtual 8-device CPU mesh (tests/conftest.py), including
# the real 2-process jax.distributed sync test (tests/bases/test_multiprocess.py).
# -rs is in setup.cfg addopts, so every skip prints its reason.
test:
	python -m pytest tests/ -q --durations=25

# Docstring examples over the whole library (also collected by default via
# --doctest-modules in setup.cfg addopts; root conftest.py forces CPU).
doctest:
	python -m pytest --doctest-modules metrics_tpu/ -q

# Markdown documentation examples (docs/ + README) as doctests.
doctest-docs:
	python -m pytest --doctest-glob='*.md' docs/ README.md -q

# The driver's multi-chip sharding gate: full distributed metric step on an
# 8-device mesh (falls back to virtual CPU devices when chips are missing).
dryrun:
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('DRYRUN 8 OK')"
	python -c "import __graft_entry__ as g; g.dryrun_multichip(5); print('DRYRUN 5 OK')"

# Every example script end to end (CPU; the distributed one on the virtual
# 8-device mesh) — examples are user-facing docs and must not rot. The
# flag pins the CPU backend even where site config force-registers an
# accelerator (a plain JAX_PLATFORMS=cpu env var cannot).
examples:
	METRICS_TPU_FORCE_CPU_MESH=1 python examples/train_eval.py
	METRICS_TPU_FORCE_CPU_MESH=1 python examples/generative_eval.py
	METRICS_TPU_FORCE_CPU_MESH=1 python examples/distributed_train.py

# Zero-overhead + zero-copy gate (scripts/check_zero_overhead.py): the
# observability stack must add zero traced ops to the compiled hot paths,
# the packed sync must stay bucketed, and the donated jit_forward /
# update_many lowerings must alias every state buffer (no per-step copies).
# Also runs inside the suite as tests/observability/test_zero_overhead.py.
zero-overhead:
	python scripts/check_zero_overhead.py

# Chrome-trace validity gate (scripts/check_trace.py): timeline.export and
# timeline.export_fleet must emit traces the Perfetto/chrome://tracing
# viewers load — required keys per phase, monotonic timestamps per track,
# paired flow events. Also runs inside the suite as
# tests/observability/test_trace_check.py.
trace-check:
	python scripts/check_trace.py --selftest

# Perf-regression gate (scripts/bench_regress.py): the latest committed
# BENCH_r*.json capture must stay within tolerance of the per-config
# baselines fitted from the prior rounds (degraded/rerun records excluded),
# and the committed MULTICHIP_r*.json dryrun trajectory must stay healthy
# (latest rc judged against the prior healthy rounds) — one table, one gate.
bench-regress:
	python scripts/bench_regress.py --check

# Full benchmark suite on the default backend (the real TPU chip under axon).
bench:
	python bench.py

# Checkpoint save→crash→restore smoke (scripts/checkpoint_smoke.py): full +
# O(k)-delta snapshots, a save killed at every injectable protocol step with
# restore pinned to the last COMPLETE snapshot, topology/capacity-flexible
# restore bit-identity, and an async save overlapping live updates. Exit 1
# on any violation. The durability plane's CI leg.
checkpoint-smoke:
	JAX_PLATFORMS=cpu python scripts/checkpoint_smoke.py

# Serving-layer soak (scripts/soak.py): sustained synthetic QPS over 10k
# tenants for 60 s, p50/p99 ingest latency + the zero-lost-updates invariant
# (rows submitted - rows shed == rows ingested into tenant state, exactly).
# Exit 1 if the accounting invariant is violated. CPU-safe; the CI smoke leg
# runs a short variant via bench_suite.py --config bench_serving_soak.
soak:
	JAX_PLATFORMS=cpu python scripts/soak.py --out SOAK.json

# Chaos soak smoke (scripts/soak.py --chaos): the resilience plane's
# end-to-end acceptance on a short seeded schedule — a killed peer, a
# dropped payload round, a hung channel get, injected dispatch errors,
# poisoned rows, and a mid-save checkpoint crash, with serving ingest +
# auto-saved checkpoints + background reads running simultaneously. Exits 1
# unless submitted − shed == dispatched == rows_routed EXACTLY, the last
# checkpoint restores bit-identical, no poison leaked, failover MTTR was
# measured, and nothing deadlocked.
chaos-smoke:
	JAX_PLATFORMS=cpu python scripts/soak.py --chaos --tenants 256 \
	  --duration-s 4 --qps 4000 --max-batch 256

# SLO-plane smoke (scripts/soak.py --slo): the breach watchdog's end-to-end
# acceptance as a control + fault pair. The control run declares ingest-p99
# and read-staleness SLOs and must finish breach-free with its error budget
# intact; the fault run installs a seeded dispatch-delay FaultPlan and must
# DETECT the breach (burn-rate > 1 on both windows) within one fast window
# of the first bad observation, with breaches()/snapshot()["slo"]/
# Prometheus/timeline all naming the same SLO. Exit 1 on either failure.
slo-smoke:
	JAX_PLATFORMS=cpu python scripts/soak.py --slo --tenants 200 \
	  --duration-s 4 --qps 2000 --producers 2 --max-batch 256 \
	  --read-interval-s 0.2 --max-staleness-s 0.5
	JAX_PLATFORMS=cpu python scripts/soak.py --slo --slo-fault --tenants 200 \
	  --duration-s 4 --qps 2000 --producers 2 --max-batch 256 \
	  --read-interval-s 0.2 --max-staleness-s 0.5

# Profiling & memory-accounting smoke (scripts/profile_smoke.py): the
# deterministic sampling law (ceil(steps/N) host-queue/device splits per
# dispatch path), byte-exact live-buffer conservation through
# grow/evict/fault-back/compact, a byte-pressure watermark driving real
# spiller evictions, and the disabled-mode strict no-op. Exit 1 on any
# violation. The profiling/capacity plane's CI leg.
profile-smoke:
	JAX_PLATFORMS=cpu python scripts/profile_smoke.py

# Convert a torchvision Inception3 checkpoint into the .npz the Flax
# extractor loads: make export-weights CKPT=inception_v3.pth OUT=weights.npz
# Then METRICS_TPU_INCEPTION_WEIGHTS=weights.npz enables FID/KID/IS(feature=N)
# and the opt-in real-weights battery (tests/image/test_real_inception_weights.py).
export-weights:
	python scripts/export_inception_weights.py $(CKPT) $(OUT)
