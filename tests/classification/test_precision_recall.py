"""Precision / Recall / FBeta / F1 / Specificity parity vs sklearn."""
from functools import partial

import numpy as np
import pytest
from sklearn.metrics import fbeta_score, multilabel_confusion_matrix, precision_score, recall_score

from metrics_tpu import F1, FBeta, Precision, Recall, Specificity
from metrics_tpu.functional import f1, fbeta, precision, recall, specificity
from tests.classification.inputs import (
    _binary_prob_inputs,
    _multiclass_inputs,
    _multiclass_prob_inputs,
    _multilabel_prob_inputs,
)
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester

# each case: (preds, target, canonicalize -> (y_pred, y_true, labels));
# canons see the average because multilabel macro/weighted score per label
# (2-D indicator form) while multilabel micro flattens (class-1 positive)


def _canon_binary_prob(preds, target, average):
    return (preds >= THRESHOLD).astype(int).reshape(-1), target.reshape(-1), [0, 1]


def _canon_multiclass(preds, target, average):
    return preds.reshape(-1), target.reshape(-1), list(range(NUM_CLASSES))


def _canon_multiclass_prob(preds, target, average):
    return np.argmax(preds, axis=1).reshape(-1), target.reshape(-1), list(range(NUM_CLASSES))


def _canon_multilabel_prob(preds, target, average):
    p = (preds >= THRESHOLD).astype(int)
    if average == "micro":
        return p.reshape(-1), target.reshape(-1), [0, 1]
    return (
        p.reshape(-1, p.shape[-1]),
        np.asarray(target).reshape(-1, np.asarray(target).shape[-1]),
        list(range(NUM_CLASSES)),
    )


def _sk_prec_recall(preds, target, sk_fn, canon, average, **fn_kwargs):
    y_pred, y_true, labels = canon(preds, target, average)
    if y_pred.ndim == 1 and len(labels) == 2:
        # binary data (any average at num_classes=1 reduces to the positive-
        # class score, mirroring the reference's `num_classes == 1 ->
        # average = "binary"` oracle) and flattened multilabel micro
        return sk_fn(y_true, y_pred, average="binary", zero_division=0, **fn_kwargs)
    if y_pred.ndim == 2:
        # multilabel indicator form: sklearn scores per label directly
        return sk_fn(y_true, y_pred, average=average, zero_division=0, **fn_kwargs)
    return sk_fn(y_true, y_pred, average=average, labels=labels, zero_division=0, **fn_kwargs)


def _sk_specificity(preds, target, canon, average):
    y_pred, y_true, labels = canon(preds, target, average)
    if y_pred.ndim == 1 and len(labels) == 2:
        # binary: positive class only
        tn = np.sum((y_pred == 0) & (y_true == 0))
        fp = np.sum((y_pred == 1) & (y_true == 0))
        return tn / max(tn + fp, 1)
    mcm = multilabel_confusion_matrix(y_true, y_pred, labels=None if y_pred.ndim == 2 else labels)
    tn, fp = mcm[:, 0, 0], mcm[:, 0, 1]
    if average == "micro":
        return tn.sum() / max((tn + fp).sum(), 1)
    per_class = np.where((tn + fp) == 0, 0.0, tn / np.maximum(tn + fp, 1))
    if average == "macro":
        return per_class.mean()
    if average == "weighted":
        support = mcm[:, 1, 0] + mcm[:, 1, 1]  # fn + tp
        weights = np.where((tn + fp) == 0, 0, tn + fp)
        return np.average(per_class, weights=weights) if weights.sum() else 0.0
    return per_class


# (preds, target, canon, num_classes for micro, num_classes for macro/weighted)
# — mirroring the reference's full matrix: binary runs macro/weighted at
# num_classes=1 (== the positive-class score), multilabel at the label count
_cases = [
    (_binary_prob_inputs.preds, _binary_prob_inputs.target, _canon_binary_prob, None, 1),
    (_multiclass_inputs.preds, _multiclass_inputs.target, _canon_multiclass, NUM_CLASSES, NUM_CLASSES),
    (_multiclass_prob_inputs.preds, _multiclass_prob_inputs.target, _canon_multiclass_prob, NUM_CLASSES, NUM_CLASSES),
    (_multilabel_prob_inputs.preds, _multilabel_prob_inputs.target, _canon_multilabel_prob, None, NUM_CLASSES),
]


@pytest.mark.parametrize("preds, target, canon, micro_nc, macro_nc", _cases)
@pytest.mark.parametrize("average", ["micro", "macro", "weighted"])
class TestPrecisionRecall(MetricTester):

    def _needed_args(self, average, micro_nc, macro_nc):
        num_classes = micro_nc if average == "micro" else macro_nc
        args = {"average": average}
        if num_classes is not None:
            args["num_classes"] = num_classes
        return args

    @pytest.mark.parametrize("ddp", [False, True])
    def test_precision_class(self, ddp, preds, target, canon, micro_nc, macro_nc, average):
        args = self._needed_args(average, micro_nc, macro_nc)
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=Precision,
            sk_metric=partial(_sk_prec_recall, sk_fn=precision_score, canon=canon, average=average),
            metric_args=args,
            atol=1e-6,
        )

    def test_precision_fn(self, preds, target, canon, micro_nc, macro_nc, average):
        args = self._needed_args(average, micro_nc, macro_nc)
        self.run_functional_metric_test(
            preds, target, metric_functional=precision,
            sk_metric=partial(_sk_prec_recall, sk_fn=precision_score, canon=canon, average=average),
            metric_args=args, atol=1e-6,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_recall_class(self, ddp, preds, target, canon, micro_nc, macro_nc, average):
        args = self._needed_args(average, micro_nc, macro_nc)
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=Recall,
            sk_metric=partial(_sk_prec_recall, sk_fn=recall_score, canon=canon, average=average),
            metric_args=args,
            atol=1e-6,
        )

    def test_recall_fn(self, preds, target, canon, micro_nc, macro_nc, average):
        args = self._needed_args(average, micro_nc, macro_nc)
        self.run_functional_metric_test(
            preds, target, metric_functional=recall,
            sk_metric=partial(_sk_prec_recall, sk_fn=recall_score, canon=canon, average=average),
            metric_args=args, atol=1e-6,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_fbeta_class(self, ddp, preds, target, canon, micro_nc, macro_nc, average):
        args = self._needed_args(average, micro_nc, macro_nc)
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=FBeta,
            sk_metric=partial(_sk_prec_recall, sk_fn=fbeta_score, canon=canon, average=average, beta=2.0),
            metric_args={**args, "beta": 2.0},
            atol=1e-6,
        )

    def test_f1_fn(self, preds, target, canon, micro_nc, macro_nc, average):
        args = self._needed_args(average, micro_nc, macro_nc)
        self.run_functional_metric_test(
            preds, target, metric_functional=f1,
            sk_metric=partial(_sk_prec_recall, sk_fn=fbeta_score, canon=canon, average=average, beta=1.0),
            metric_args=args, atol=1e-6,
        )

    @pytest.mark.parametrize("ddp", [False])
    def test_specificity_class(self, ddp, preds, target, canon, micro_nc, macro_nc, average):
        args = self._needed_args(average, micro_nc, macro_nc)
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=Specificity,
            sk_metric=partial(_sk_specificity, canon=canon, average=average),
            metric_args=args,
            atol=1e-6,
        )


def test_f1_module_matches_fbeta1():
    import jax.numpy as jnp

    target = jnp.asarray([0, 1, 2, 0, 1, 2])
    preds = jnp.asarray([0, 2, 1, 0, 0, 1])
    np.testing.assert_allclose(
        F1(num_classes=3)(preds, target), FBeta(num_classes=3, beta=1.0)(preds, target), atol=1e-8
    )


def test_precision_recall_combo_fn():
    import jax.numpy as jnp

    from metrics_tpu.functional import precision_recall

    preds = jnp.asarray([2, 0, 2, 1])
    target = jnp.asarray([1, 1, 2, 0])
    p, r = precision_recall(preds, target, average="micro")
    np.testing.assert_allclose(p, 0.25, atol=1e-6)
    np.testing.assert_allclose(r, 0.25, atol=1e-6)


def test_average_none_matches_none_string():
    """average=None and average='none' are the same mode, incl. absent-class NaN."""
    import jax.numpy as jnp

    preds = jnp.asarray([0, 0, 1, 1])
    target = jnp.asarray([0, 0, 1, 1])
    for avg in (None, "none"):
        out = np.asarray(precision(preds, target, average=avg, num_classes=3))
        np.testing.assert_allclose(out[:2], [1.0, 1.0])
        assert np.isnan(out[2]), f"absent class must be NaN for average={avg!r}"
