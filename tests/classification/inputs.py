"""Deterministic random input bundles for classification tests.

Mirrors the reference's fixture strategy (``tests/classification/inputs.py``):
one ``Input(preds, target)`` namedtuple per input case — binary
probs/labels, multilabel, multiclass probs/labels, multidim multiclass —
including the adversarial no-match case.
"""
from collections import namedtuple

import numpy as np

from tests.helpers.testers import BATCH_SIZE, EXTRA_DIM, NUM_BATCHES, NUM_CLASSES

Input = namedtuple("Input", ["preds", "target"])

_rng = np.random.RandomState(42)

_binary_prob_inputs = Input(
    preds=_rng.rand(NUM_BATCHES, BATCH_SIZE),
    target=_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE)),
)

_binary_inputs = Input(
    preds=_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE)),
    target=_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE)),
)

_multilabel_prob_inputs = Input(
    preds=_rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES),
    target=_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
)

_multilabel_inputs = Input(
    preds=_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
    target=_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)),
)


def _softmax(x: np.ndarray, axis: int) -> np.ndarray:
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


_multiclass_prob_inputs = Input(
    preds=_softmax(_rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES), axis=-1),
    target=_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
)

_multiclass_inputs = Input(
    preds=_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
    target=_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
)

_multidim_multiclass_prob_inputs = Input(
    preds=_softmax(_rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM), axis=2),
    target=_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM)),
)

_multidim_multiclass_inputs = Input(
    preds=_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM)),
    target=_rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA_DIM)),
)

_multilabel_multidim_prob_inputs = Input(
    preds=_rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM),
    target=_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)),
)

_multilabel_multidim_inputs = Input(
    preds=_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)),
    target=_rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM)),
)

# adversarial case: no predictions match targets
__temp_preds = _rng.randint(1, 2, (NUM_BATCHES, BATCH_SIZE))
_no_match_inputs = Input(
    preds=__temp_preds,
    target=1 - __temp_preds,
)
