"""Curve metrics (PR-curve / ROC / AUROC / AP / AUC) parity vs sklearn."""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import auc as sk_auc
from sklearn.metrics import average_precision_score as sk_average_precision
from sklearn.metrics import precision_recall_curve as sk_precision_recall_curve
from sklearn.metrics import roc_auc_score as sk_roc_auc
from sklearn.metrics import roc_curve as sk_roc_curve

from metrics_tpu import AUC, AUROC, ROC, AveragePrecision, PrecisionRecallCurve
from metrics_tpu.functional import auc, auroc, average_precision, precision_recall_curve, roc
from tests.classification.inputs import _binary_prob_inputs, _multiclass_prob_inputs
from tests.helpers.testers import NUM_BATCHES, NUM_CLASSES, MetricTester


def _sk_pr_curve_trimmed(y_true, y_score):
    """sklearn PR curve trimmed at first full recall (the reference-era
    convention this library follows): drop redundant leading recall==1 points
    that modern sklearn keeps."""
    prec, rec, thr = sk_precision_recall_curve(y_true, y_score)
    lead = int(np.sum(rec == 1.0)) - 1
    if lead > 0:
        prec, rec, thr = prec[lead:], rec[lead:], thr[lead:]
    return prec, rec, thr


class TestBinaryCurves(MetricTester):
    preds = _binary_prob_inputs.preds
    target = _binary_prob_inputs.target

    def test_roc_fn(self):
        for i in range(NUM_BATCHES):
            fpr, tpr, thr = roc(jnp.asarray(self.preds[i]), jnp.asarray(self.target[i]), pos_label=1)
            sk_fpr, sk_tpr, sk_thr = sk_roc_curve(self.target[i], self.preds[i], drop_intermediate=False)
            np.testing.assert_allclose(np.asarray(fpr), sk_fpr, atol=1e-6)
            np.testing.assert_allclose(np.asarray(tpr), sk_tpr, atol=1e-6)
            np.testing.assert_allclose(np.asarray(thr)[1:], sk_thr[1:], atol=1e-6)

    def test_pr_curve_fn(self):
        for i in range(NUM_BATCHES):
            prec, rec, thr = precision_recall_curve(
                jnp.asarray(self.preds[i]), jnp.asarray(self.target[i]), pos_label=1
            )
            sk_prec, sk_rec, sk_thr = _sk_pr_curve_trimmed(self.target[i], self.preds[i])
            np.testing.assert_allclose(np.asarray(prec), sk_prec, atol=1e-6)
            np.testing.assert_allclose(np.asarray(rec), sk_rec, atol=1e-6)
            np.testing.assert_allclose(np.asarray(thr), sk_thr, atol=1e-6)

    @pytest.mark.parametrize("ddp", [False, True])
    def test_auroc_class(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=self.preds,
            target=self.target,
            metric_class=AUROC,
            sk_metric=lambda p, t: sk_roc_auc(t.reshape(-1), p.reshape(-1)),
            atol=1e-6,
        )

    def test_auroc_fn(self):
        self.run_functional_metric_test(
            self.preds, self.target, metric_functional=auroc,
            sk_metric=lambda p, t: sk_roc_auc(t.reshape(-1), p.reshape(-1)), atol=1e-6,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_average_precision_class(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=self.preds,
            target=self.target,
            metric_class=AveragePrecision,
            sk_metric=lambda p, t: sk_average_precision(t.reshape(-1), p.reshape(-1)),
            atol=1e-6,
        )

    def test_average_precision_fn(self):
        self.run_functional_metric_test(
            self.preds, self.target, metric_functional=average_precision,
            sk_metric=lambda p, t: sk_average_precision(t.reshape(-1), p.reshape(-1)), atol=1e-6,
        )

    def test_auroc_max_fpr(self):
        for max_fpr in (0.25, 0.5, 0.75):
            for i in range(3):
                ours = auroc(jnp.asarray(self.preds[i]), jnp.asarray(self.target[i]), max_fpr=max_fpr)
                expected = sk_roc_auc(self.target[i], self.preds[i], max_fpr=max_fpr)
                np.testing.assert_allclose(np.asarray(ours), expected, atol=1e-5)


class TestMulticlassCurves(MetricTester):
    preds = _multiclass_prob_inputs.preds
    target = _multiclass_prob_inputs.target

    @pytest.mark.parametrize("average", ["macro", "weighted"])
    @pytest.mark.parametrize("ddp", [False, True])
    def test_auroc_class(self, ddp, average):
        self.run_class_metric_test(
            ddp=ddp,
            preds=self.preds,
            target=self.target,
            metric_class=AUROC,
            sk_metric=lambda p, t: sk_roc_auc(t, p, multi_class="ovr", average=average,
                                              labels=list(range(NUM_CLASSES))),
            metric_args={"num_classes": NUM_CLASSES, "average": average},
            atol=1e-6,
        )

    def test_average_precision_class(self):
        def sk_ap(p, t):
            return [sk_average_precision((t == c).astype(int), p[:, c]) for c in range(NUM_CLASSES)]

        self.run_class_metric_test(
            ddp=False,
            preds=self.preds,
            target=self.target,
            metric_class=AveragePrecision,
            sk_metric=sk_ap,
            metric_args={"num_classes": NUM_CLASSES},
            atol=1e-6,
        )

    def test_pr_curve_class(self):
        metric = PrecisionRecallCurve(num_classes=NUM_CLASSES)
        for i in range(NUM_BATCHES):
            metric.update(jnp.asarray(self.preds[i]), jnp.asarray(self.target[i]))
        prec, rec, thr = metric.compute()
        all_preds = self.preds.reshape(-1, NUM_CLASSES)
        all_target = self.target.reshape(-1)
        for c in range(NUM_CLASSES):
            sk_prec, sk_rec, sk_thr = _sk_pr_curve_trimmed((all_target == c).astype(int), all_preds[:, c])
            np.testing.assert_allclose(np.asarray(prec[c]), sk_prec, atol=1e-6)
            np.testing.assert_allclose(np.asarray(rec[c]), sk_rec, atol=1e-6)

    def test_roc_class(self):
        metric = ROC(num_classes=NUM_CLASSES)
        for i in range(NUM_BATCHES):
            metric.update(jnp.asarray(self.preds[i]), jnp.asarray(self.target[i]))
        fpr, tpr, thr = metric.compute()
        all_preds = self.preds.reshape(-1, NUM_CLASSES)
        all_target = self.target.reshape(-1)
        for c in range(NUM_CLASSES):
            sk_fpr, sk_tpr, _ = sk_roc_curve((all_target == c).astype(int), all_preds[:, c],
                                             drop_intermediate=False)
            np.testing.assert_allclose(np.asarray(fpr[c]), sk_fpr, atol=1e-6)
            np.testing.assert_allclose(np.asarray(tpr[c]), sk_tpr, atol=1e-6)


def test_auc_fn():
    x = jnp.asarray([0, 1, 2, 3])
    y = jnp.asarray([0, 1, 2, 2])
    np.testing.assert_allclose(auc(x, y), 4.0)
    np.testing.assert_allclose(auc(x, y, reorder=True), 4.0)
    # decreasing x: direction flip keeps the area positive
    np.testing.assert_allclose(auc(jnp.flip(x), jnp.flip(y)), 4.0)


def test_auc_class_vs_sklearn():
    rng = np.random.RandomState(9)
    x = np.sort(rng.rand(64))
    y = rng.rand(64)
    metric = AUC()
    for i in range(4):
        metric.update(jnp.asarray(x[i * 16:(i + 1) * 16]), jnp.asarray(y[i * 16:(i + 1) * 16]))
    np.testing.assert_allclose(np.asarray(metric.compute()), sk_auc(x, y), atol=1e-6)


def test_auroc_multilabel():
    rng = np.random.RandomState(10)
    preds = rng.rand(128, 4)
    target = rng.randint(0, 2, (128, 4))
    ours = auroc(jnp.asarray(preds), jnp.asarray(target), num_classes=4)
    expected = sk_roc_auc(target, preds, average="macro")
    np.testing.assert_allclose(np.asarray(ours), expected, atol=1e-6)
