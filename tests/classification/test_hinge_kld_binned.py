"""Hinge / KLDivergence / Binned curve metrics parity tests."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.stats import entropy as scipy_entropy
from sklearn.metrics import average_precision_score as sk_average_precision
from sklearn.metrics import hinge_loss as sk_hinge

from metrics_tpu import (
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedRecallAtFixedPrecision,
    Hinge,
    KLDivergence,
)
from metrics_tpu.functional import hinge, kldivergence
from tests.helpers.testers import MetricTester


class TestHinge(MetricTester):

    def test_hinge_binary_vs_sklearn(self):
        rng = np.random.RandomState(3)
        preds = rng.randn(128)
        target = rng.randint(0, 2, 128)
        expected = sk_hinge(target, preds, labels=[0, 1])
        np.testing.assert_allclose(np.asarray(hinge(jnp.asarray(preds), jnp.asarray(target))), expected, atol=1e-6)

    def test_hinge_multiclass_crammer_singer(self):
        rng = np.random.RandomState(4)
        preds = rng.randn(128, 5)
        target = rng.randint(0, 5, 128)
        expected = sk_hinge(target, preds, labels=list(range(5)))
        np.testing.assert_allclose(np.asarray(hinge(jnp.asarray(preds), jnp.asarray(target))), expected, atol=1e-6)

    def test_hinge_one_vs_all(self):
        rng = np.random.RandomState(5)
        preds = rng.randn(64, 3)
        target = rng.randint(0, 3, 64)
        onehot = np.eye(3)[target].astype(bool)
        margin = np.where(onehot, preds, -preds)
        expected = np.clip(1 - margin, 0, None).mean(axis=0)
        result = hinge(jnp.asarray(preds), jnp.asarray(target), multiclass_mode="one-vs-all")
        np.testing.assert_allclose(np.asarray(result), expected, atol=1e-6)

    def test_hinge_module_accumulates(self):
        rng = np.random.RandomState(6)
        preds = rng.randn(4, 32)
        target = rng.randint(0, 2, (4, 32))
        metric = Hinge()
        for i in range(4):
            metric.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
        expected = sk_hinge(target.reshape(-1), preds.reshape(-1), labels=[0, 1])
        np.testing.assert_allclose(np.asarray(metric.compute()), expected, atol=1e-6)


class TestKLDivergence(MetricTester):

    def test_kld_vs_scipy(self):
        rng = np.random.RandomState(7)
        p = rng.rand(64, 8); p /= p.sum(-1, keepdims=True)
        q = rng.rand(64, 8); q /= q.sum(-1, keepdims=True)
        expected = np.mean([scipy_entropy(pi, qi) for pi, qi in zip(p, q)])
        np.testing.assert_allclose(np.asarray(kldivergence(jnp.asarray(p), jnp.asarray(q))), expected, atol=1e-5)

    def test_kld_log_prob(self):
        rng = np.random.RandomState(8)
        p = rng.rand(32, 4); p /= p.sum(-1, keepdims=True)
        q = rng.rand(32, 4); q /= q.sum(-1, keepdims=True)
        expected = np.mean([scipy_entropy(pi, qi) for pi, qi in zip(p, q)])
        result = kldivergence(jnp.asarray(np.log(p)), jnp.asarray(np.log(q)), log_prob=True)
        np.testing.assert_allclose(np.asarray(result), expected, atol=1e-5)

    @pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
    def test_kld_module(self, reduction):
        rng = np.random.RandomState(9)
        p = rng.rand(4, 16, 4); p /= p.sum(-1, keepdims=True)
        q = rng.rand(4, 16, 4); q /= q.sum(-1, keepdims=True)
        metric = KLDivergence(reduction=reduction)
        for i in range(4):
            metric.update(jnp.asarray(p[i]), jnp.asarray(q[i]))
        result = np.asarray(metric.compute())
        rows = np.array([scipy_entropy(pi, qi) for pi, qi in zip(p.reshape(-1, 4), q.reshape(-1, 4))])
        if reduction == "mean":
            np.testing.assert_allclose(result, rows.mean(), atol=1e-5)
        elif reduction == "sum":
            np.testing.assert_allclose(result, rows.sum(), atol=1e-4)
        else:
            np.testing.assert_allclose(result, rows, atol=1e-5)


class TestBinned(MetricTester):

    def test_binned_pr_curve_binary_reference_example(self):
        pred = jnp.asarray([0, 0.1, 0.8, 0.4])
        target = jnp.asarray([0, 1, 1, 0])
        pr_curve = BinnedPrecisionRecallCurve(num_classes=1, num_thresholds=5)
        precision, recall, thresholds = pr_curve(pred, target)
        np.testing.assert_allclose(np.asarray(precision), [0.5, 0.5, 1.0, 1.0, 1.0, 1.0], atol=1e-4)
        np.testing.assert_allclose(np.asarray(recall), [1.0, 0.5, 0.5, 0.5, 0.0, 0.0], atol=1e-4)
        np.testing.assert_allclose(np.asarray(thresholds), [0.0, 0.25, 0.5, 0.75, 1.0], atol=1e-6)

    def test_binned_ap_close_to_exact(self):
        """With many thresholds the binned AP approaches sklearn's exact AP."""
        rng = np.random.RandomState(11)
        preds = rng.rand(512)
        target = rng.randint(0, 2, 512)
        metric = BinnedAveragePrecision(num_classes=1, num_thresholds=500)
        result = metric(jnp.asarray(preds), jnp.asarray(target))
        expected = sk_average_precision(target, preds)
        np.testing.assert_allclose(np.asarray(result), expected, atol=0.01)

    def test_binned_recall_at_fixed_precision(self):
        pred = jnp.asarray([0, 0.2, 0.5, 0.8])
        target = jnp.asarray([0, 1, 1, 0])
        metric = BinnedRecallAtFixedPrecision(num_classes=1, num_thresholds=10, min_precision=0.5)
        recall, threshold = metric(pred, target)
        np.testing.assert_allclose(np.asarray(recall), 1.0, atol=1e-6)
        np.testing.assert_allclose(np.asarray(threshold), 1 / 9, atol=1e-6)

    def test_binned_multiclass_shapes(self):
        pred = jnp.asarray([
            [0.75, 0.05, 0.05, 0.05, 0.05],
            [0.05, 0.75, 0.05, 0.05, 0.05],
            [0.05, 0.05, 0.75, 0.05, 0.05],
            [0.05, 0.05, 0.05, 0.75, 0.05],
        ])
        target = jnp.asarray([0, 1, 3, 2])
        pr_curve = BinnedPrecisionRecallCurve(num_classes=5, num_thresholds=3)
        precision, recall, thresholds = pr_curve(pred, target)
        assert len(precision) == 5 and len(recall) == 5 and len(thresholds) == 5
        np.testing.assert_allclose(np.asarray(precision[0]), [0.25, 1.0, 1.0, 1.0], atol=1e-4)
        np.testing.assert_allclose(np.asarray(recall[0]), [1.0, 1.0, 0.0, 0.0], atol=1e-4)

    @pytest.mark.parametrize("ddp", [False, True])
    def test_binned_ap_class_ddp(self, ddp):
        rng = np.random.RandomState(12)
        preds = rng.rand(10, 32)
        target = rng.randint(0, 2, (10, 32))

        def sk_binned_ap(p, t):
            # oracle: exact AP is close enough at 500 thresholds
            return sk_average_precision(t.reshape(-1), p.reshape(-1))

        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=BinnedAveragePrecision,
            sk_metric=sk_binned_ap,
            metric_args={"num_classes": 1, "num_thresholds": 500},
            check_batch=False,
            atol=0.01,
        )


def test_binned_fused_forward_matches_double_update():
    """The binned family is mergeable (sum counts + idempotent thresholds),
    so forward() takes the fused single-update path; its per-step values and
    epoch compute must equal the reference-faithful double-update protocol."""
    rng = np.random.RandomState(5)
    for cls, kwargs in (
        (BinnedPrecisionRecallCurve, dict(num_classes=3, num_thresholds=20)),
        (BinnedPrecisionRecallCurve, dict(num_classes=1, num_thresholds=20)),
        (BinnedAveragePrecision, dict(num_classes=3, num_thresholds=20)),
        (BinnedAveragePrecision, dict(num_classes=1, num_thresholds=20)),
        (BinnedRecallAtFixedPrecision, dict(num_classes=3, num_thresholds=20, min_precision=0.4)),
    ):
        fused, double = cls(**kwargs), cls(**kwargs)
        assert fused._states_mergeable(), cls.__name__
        double._fusable = False  # force the reference double-update protocol
        nc = kwargs["num_classes"]
        for _ in range(4):
            if nc == 1:
                p = jnp.asarray(rng.rand(32).astype(np.float32))
                t = jnp.asarray(rng.randint(0, 2, 32))
            else:
                p = jnp.asarray(rng.rand(32, nc).astype(np.float32))
                t = jnp.asarray(rng.randint(0, nc, 32))
            va, vb = fused(p, t), double(p, t)
            jax.tree.map(  # validates treedef equality, then values
                lambda x, y: np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6),
                va,
                vb,
            )
        jax.tree.map(
            lambda x, y: np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6),
            fused.compute(),
            double.compute(),
        )
