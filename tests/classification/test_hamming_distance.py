"""HammingDistance parity vs sklearn / numpy oracle."""
import numpy as np
import pytest
from sklearn.metrics import hamming_loss as sk_hamming_loss

from metrics_tpu import HammingDistance
from metrics_tpu.functional import hamming_distance
from tests.classification.inputs import (
    _binary_inputs,
    _binary_prob_inputs,
    _multiclass_inputs,
    _multilabel_inputs,
    _multilabel_prob_inputs,
)
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester


def _sk_binary_prob(preds, target):
    return sk_hamming_loss(target.reshape(-1), (preds >= THRESHOLD).astype(int).reshape(-1))


def _sk_labels(preds, target):
    return sk_hamming_loss(target.reshape(-1), preds.reshape(-1))


def _sk_multiclass_onehot(preds, target):
    # the library treats multiclass labels as one-hot multi-label columns
    p = np.eye(NUM_CLASSES, dtype=int)[preds.reshape(-1)]
    t = np.eye(NUM_CLASSES, dtype=int)[target.reshape(-1)]
    return np.mean(p != t)


@pytest.mark.parametrize(
    "preds, target, sk_metric",
    [
        (_binary_prob_inputs.preds, _binary_prob_inputs.target, _sk_binary_prob),
        (_binary_inputs.preds, _binary_inputs.target, _sk_labels),
        (_multilabel_prob_inputs.preds, _multilabel_prob_inputs.target, _sk_binary_prob),
        (_multilabel_inputs.preds, _multilabel_inputs.target, _sk_labels),
        (_multiclass_inputs.preds, _multiclass_inputs.target, _sk_multiclass_onehot),
    ],
)
class TestHammingDistance(MetricTester):

    @pytest.mark.parametrize("ddp", [False, True])
    def test_hamming_class(self, ddp, preds, target, sk_metric):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=HammingDistance,
            sk_metric=sk_metric,
            atol=1e-6,
        )

    def test_hamming_fn(self, preds, target, sk_metric):
        self.run_functional_metric_test(
            preds, target, metric_functional=hamming_distance, sk_metric=sk_metric, atol=1e-6
        )
