"""Input canonicalization matrix — port of the reference's
``tests/classification/test_inputs.py``: every (case, num_classes,
multiclass, top_k) combination of ``_input_format_classification`` checked
against explicitly constructed expected outputs, plus the error matrix."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.utilities.checks import _input_format_classification
from metrics_tpu.utilities.data import select_topk, to_onehot
from metrics_tpu.utilities.enums import DataType
from tests.classification.inputs import (
    Input,
    _binary_inputs as _bin,
    _binary_prob_inputs as _bin_prob,
    _multiclass_inputs as _mc,
    _multiclass_prob_inputs as _mc_prob,
    _multidim_multiclass_inputs as _mdmc,
    _multidim_multiclass_prob_inputs as _mdmc_prob,
    _multilabel_inputs as _ml,
    _multilabel_multidim_inputs as _mlmd,
    _multilabel_multidim_prob_inputs as _mlmd_prob,
    _multilabel_prob_inputs as _ml_prob,
)
from tests.helpers.testers import BATCH_SIZE, EXTRA_DIM, NUM_CLASSES, THRESHOLD

_rng = np.random.RandomState(13)

# additional special-case fixtures (reference test_inputs.py:38-54)
_ml_prob_half = Input(_ml_prob.preds.astype(np.float16), _ml_prob.target)

_mc_prob_2cls_preds = _rng.rand(2, BATCH_SIZE, 2)
_mc_prob_2cls_preds /= _mc_prob_2cls_preds.sum(axis=2, keepdims=True)
_mc_prob_2cls = Input(_mc_prob_2cls_preds, _rng.randint(0, 2, (2, BATCH_SIZE)))

_mdmc_prob_many_dims_preds = _rng.rand(2, BATCH_SIZE, NUM_CLASSES, EXTRA_DIM, EXTRA_DIM)
_mdmc_prob_many_dims_preds /= _mdmc_prob_many_dims_preds.sum(axis=2, keepdims=True)
_mdmc_prob_many_dims = Input(
    _mdmc_prob_many_dims_preds, _rng.randint(0, 2, (2, BATCH_SIZE, EXTRA_DIM, EXTRA_DIM))
)

_mdmc_prob_2cls_preds = _rng.rand(2, BATCH_SIZE, 2, EXTRA_DIM)
_mdmc_prob_2cls_preds /= _mdmc_prob_2cls_preds.sum(axis=2, keepdims=True)
_mdmc_prob_2cls = Input(_mdmc_prob_2cls_preds, _rng.randint(0, 2, (2, BATCH_SIZE, EXTRA_DIM)))


# expected-output transforms (numpy/jnp mirrors of the reference helpers)
def _idn(x):
    return jnp.asarray(x)


def _usq(x):
    return jnp.asarray(x)[..., None]


def _thrs(x):
    return jnp.asarray(x) >= THRESHOLD


def _rshp1(x):
    x = jnp.asarray(x)
    return x.reshape(x.shape[0], -1)


def _rshp2(x):
    x = jnp.asarray(x)
    return x.reshape(x.shape[0], x.shape[1], -1)


def _onehot(x):
    return to_onehot(jnp.asarray(x), NUM_CLASSES)


def _onehot2(x):
    return to_onehot(jnp.asarray(x), 2)


def _top1(x):
    return select_topk(jnp.asarray(x), 1)


def _top2(x):
    return select_topk(jnp.asarray(x), 2)


def _ml_preds_tr(x):
    return _rshp1(_thrs(x))


def _onehot_rshp1(x):
    return _onehot(_rshp1(x))


def _onehot2_rshp1(x):
    return _onehot2(_rshp1(x))


def _top1_rshp2(x):
    return _top1(_rshp2(x))


def _top2_rshp2(x):
    return _top2(_rshp2(x))


def _probs_to_mc_preds_tr(x):
    return _onehot2(_thrs(x))


def _mlmd_prob_to_mc_preds_tr(x):
    return _onehot2(_rshp1(_thrs(x)))


@pytest.mark.parametrize(
    "inputs, num_classes, multiclass, top_k, exp_mode, post_preds, post_target",
    [
        # usual expected cases (reference test_inputs.py:125-147)
        (_bin, None, False, None, "multi-class", _usq, _usq),
        (_bin, 1, False, None, "multi-class", _usq, _usq),
        (_bin_prob, None, None, None, "binary", lambda x: _usq(_thrs(x)), _usq),
        (_ml_prob, None, None, None, "multi-label", _thrs, _idn),
        (_ml, None, False, None, "multi-dim multi-class", _idn, _idn),
        (_ml_prob, None, None, 2, "multi-label", _top2, _rshp1),
        (_mlmd, None, False, None, "multi-dim multi-class", _rshp1, _rshp1),
        (_mc, NUM_CLASSES, None, None, "multi-class", _onehot, _onehot),
        (_mc_prob, None, None, None, "multi-class", _top1, _onehot),
        (_mc_prob, None, None, 2, "multi-class", _top2, _onehot),
        (_mdmc, NUM_CLASSES, None, None, "multi-dim multi-class", _onehot, _onehot),
        (_mdmc_prob, None, None, None, "multi-dim multi-class", _top1_rshp2, _onehot),
        (_mdmc_prob, None, None, 2, "multi-dim multi-class", _top2_rshp2, _onehot),
        (_mdmc_prob_many_dims, None, None, None, "multi-dim multi-class", _top1_rshp2, _onehot_rshp1),
        (_mdmc_prob_many_dims, None, None, 2, "multi-dim multi-class", _top2_rshp2, _onehot_rshp1),
        # special cases (reference test_inputs.py:148-170)
        # half precision is upcast before thresholding
        (_ml_prob_half, None, None, None, "multi-label", lambda x: _ml_preds_tr(np.asarray(x, np.float32)), _rshp1),
        # binary as multiclass
        (_bin, None, None, None, "multi-class", _onehot2, _onehot2),
        # binary probs as multiclass
        (_bin_prob, None, True, None, "binary", _probs_to_mc_preds_tr, _onehot2),
        # multilabel as multiclass
        (_ml, None, True, None, "multi-dim multi-class", _onehot2, _onehot2),
        # multilabel probs as multiclass
        (_ml_prob, None, True, None, "multi-label", _probs_to_mc_preds_tr, _onehot2),
        # multidim multilabel as multiclass
        (_mlmd, None, True, None, "multi-dim multi-class", _onehot2_rshp1, _onehot2_rshp1),
        # multidim multilabel probs as multiclass
        (_mlmd_prob, None, True, None, "multi-label", _mlmd_prob_to_mc_preds_tr, _onehot2_rshp1),
        # multiclass probs with 2 classes as binary
        (_mc_prob_2cls, None, False, None, "multi-class", lambda x: _top1(x)[:, [1]], _usq),
        # multidim multiclass with 2 classes as multilabel
        (_mdmc_prob_2cls, None, False, None, "multi-dim multi-class", lambda x: _top1(x)[:, 1], _idn),
    ],
)
def test_usual_cases(inputs, num_classes, multiclass, top_k, exp_mode, post_preds, post_target):
    def _case(preds_in, target_in):
        preds_out, target_out, mode = _input_format_classification(
            preds=jnp.asarray(preds_in),
            target=jnp.asarray(target_in),
            threshold=THRESHOLD,
            num_classes=num_classes,
            multiclass=multiclass,
            top_k=top_k,
        )
        assert mode == exp_mode
        np.testing.assert_array_equal(
            np.asarray(preds_out), np.asarray(post_preds(preds_in)).astype(np.int32)
        )
        np.testing.assert_array_equal(
            np.asarray(target_out), np.asarray(post_target(target_in)).astype(np.int32)
        )

    _case(inputs.preds[0], inputs.target[0])
    # batch_size = 1 must behave identically (squeeze rules)
    _case(inputs.preds[0][[0], ...], inputs.target[0][[0], ...])


def test_threshold():
    target = jnp.asarray([1, 1, 1])
    preds_probs = jnp.asarray([0.5 - 1e-5, 0.5, 0.5 + 1e-5])
    preds_out, _, _ = _input_format_classification(preds_probs, target, threshold=0.5)
    np.testing.assert_array_equal(np.asarray(preds_out).squeeze(), [0, 1, 1])


def _ri(*shape, low=0, high=2):
    return _rng.randint(low, high, shape)


@pytest.mark.parametrize(
    "preds, target, num_classes, multiclass",
    [
        (_ri(7), _ri(7).astype(float), None, None),  # target not integer
        (_ri(7), -_ri(7), None, None),  # target negative
        (-_ri(7), _ri(7), None, None),  # preds negative integers
        (_rng.rand(7), _ri(7, low=2, high=4), None, False),  # multiclass=False, target > 1
        (_ri(7, low=2, high=4), _ri(7), None, False),  # multiclass=False, int preds > 1
        (_ri(8), _ri(7), None, None),  # wrong batch size
        (_ri(7), _ri(7, 4), None, None),  # completely wrong shape
        (_ri(7, 3), _ri(7, 4), None, None),  # same ndim, different shape
        (_rng.rand(7, 3), _ri(7, 3, low=2, high=4), None, None),  # float preds, non-binary target
        (_rng.rand(7, 3, 4, 3), _ri(7, 3, 3, high=4), None, None),  # C not in dim 1
        (_ri(7, 3, 3, 4), _ri(7, 3, 3, high=4), None, None),  # extra dim but int preds
        (_mc_prob.preds[0], _ri(BATCH_SIZE), None, False),  # multiclass=False, C > 2
        (_mc_prob.preds[0], _ri(BATCH_SIZE, low=NUM_CLASSES + 1, high=100), None, None),  # target >= C
        (_mc_prob.preds[0], _mc_prob.target[0], NUM_CLASSES + 1, None),  # C != num_classes
        (_ri(7, 3, high=4), _ri(7, 3, low=5, high=7), 4, None),  # target > num_classes
        (_ri(7, 3, low=5, high=7), _ri(7, 3, high=4), 4, None),  # preds > num_classes
        (_ri(7), _ri(7), 1, None),  # num_classes=1 without multiclass=False
        (_ri(7, 3, 3), _ri(7, 3, 3), 4, False),  # implied class dim != num_classes
        (_rng.rand(7, 3, 3), _ri(7, 3, 3), 4, False),  # ml with implied dim != num_classes
        (_rng.rand(7, 3), _ri(7, 3), 4, True),  # ml multiclass=True but num_classes != 2
        (_rng.rand(7), _ri(7), 4, None),  # binary, num_classes > 2
        (_rng.rand(7), _ri(7), 2, None),  # binary, num_classes=2 without multiclass=True
        (_rng.rand(7), _ri(7), 2, False),
        (_rng.rand(7), _ri(7), 1, True),  # binary, num_classes=1 with multiclass=True
    ],
)
def test_incorrect_inputs(preds, target, num_classes, multiclass):
    with pytest.raises(ValueError):
        _input_format_classification(
            preds=jnp.asarray(preds),
            target=jnp.asarray(target),
            threshold=THRESHOLD,
            num_classes=num_classes,
            multiclass=multiclass,
        )


@pytest.mark.parametrize(
    "preds, target, num_classes, multiclass, top_k",
    [
        (_bin.preds[0], _bin.target[0], None, None, 2),  # top_k on label data
        (_bin_prob.preds[0], _bin_prob.target[0], None, None, 2),  # top_k on binary probs
        (_mc.preds[0], _mc.target[0], None, None, 2),  # top_k on mc labels
        (_ml.preds[0], _ml.target[0], None, None, 2),  # top_k on ml labels
        (_mlmd.preds[0], _mlmd.target[0], None, None, 2),  # top_k on mlmd labels
        (_mdmc.preds[0], _mdmc.target[0], None, None, 2),  # top_k on mdmc labels
        (_mc_prob_2cls.preds[0], _mc_prob_2cls.target[0], None, None, 0),  # top_k = 0
        (_mc_prob_2cls.preds[0], _mc_prob_2cls.target[0], None, None, 0.123),  # top_k float
        (_mc_prob_2cls.preds[0], _mc_prob_2cls.target[0], None, False, 2),  # top_k = C with mc=False
        (_mc_prob.preds[0], _mc_prob.target[0], None, None, NUM_CLASSES),  # top_k = C
        (_ml_prob.preds[0], _ml_prob.target[0], None, True, 2),  # ml probs mc=True with top_k
        (_ml_prob.preds[0], _ml_prob.target[0], None, True, NUM_CLASSES),
    ],
)
def test_incorrect_inputs_topk(preds, target, num_classes, multiclass, top_k):
    with pytest.raises(ValueError):
        _input_format_classification(
            preds=jnp.asarray(preds),
            target=jnp.asarray(target),
            threshold=THRESHOLD,
            num_classes=num_classes,
            multiclass=multiclass,
            top_k=top_k,
        )
