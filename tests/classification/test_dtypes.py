"""Half-precision smoke tests for the classification stack (reference
pattern: ``run_precision_test_cpu/gpu``, ``testers.py:416-462`` — fp16
inputs are upcast by the canonicalization and must produce the same result
as f32 inputs)."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.functional import accuracy, auroc, confusion_matrix, f1, precision, recall

_rng = np.random.RandomState(21)
_N, _C = 128, 5
_probs = _rng.rand(_N, _C).astype(np.float32)
_probs /= _probs.sum(-1, keepdims=True)
_target = _rng.randint(0, _C, _N)
_bin_probs = _rng.rand(_N).astype(np.float32)
_bin_target = _rng.randint(0, 2, _N)


@pytest.mark.parametrize("dtype", [jnp.float16, jnp.bfloat16])
@pytest.mark.parametrize(
    "fn, args",
    [
        (accuracy, {}),
        (precision, dict(average="macro", num_classes=_C)),
        (recall, dict(average="macro", num_classes=_C)),
        (f1, dict(average="macro", num_classes=_C)),
        (confusion_matrix, dict(num_classes=_C)),
    ],
)
def test_half_precision_matches_f32(dtype, fn, args):
    full = fn(jnp.asarray(_probs), jnp.asarray(_target), **args)
    half = fn(jnp.asarray(_probs, dtype=dtype), jnp.asarray(_target), **args)
    # canonicalization thresholds/top-ks in f32, so int statistics may differ
    # only where the dtype cast moved a probability across a decision boundary
    np.testing.assert_allclose(np.asarray(half, np.float64), np.asarray(full, np.float64), atol=0.02)


@pytest.mark.parametrize("dtype", [jnp.float16, jnp.bfloat16])
def test_half_precision_binary_auroc(dtype):
    full = auroc(jnp.asarray(_bin_probs), jnp.asarray(_bin_target))
    half = auroc(jnp.asarray(_bin_probs, dtype=dtype), jnp.asarray(_bin_target))
    np.testing.assert_allclose(float(half), float(full), atol=0.02)
