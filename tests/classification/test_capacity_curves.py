"""Fixed-capacity (masked-buffer) AUROC / AveragePrecision: the jit-native
curve-scalar path (state structure is step-invariant -> one compilation for
every step, pure collective sync in-graph)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import average_precision_score, roc_auc_score

from metrics_tpu import AUROC, AveragePrecision
from metrics_tpu.functional.classification.masked_curves import (
    masked_binary_auroc,
    masked_binary_average_precision,
)
from tests.conftest import NUM_DEVICES
from metrics_tpu.utilities.distributed import shard_map_compat

_rng = np.random.RandomState(17)


def _normalize_rows(x):
    # plain row normalization (rows sum to 1) so mode inference sees MULTICLASS
    return x / x.sum(-1, keepdims=True)


class TestMaskedKernels:
    @pytest.mark.parametrize("ties", [False, True])
    def test_auroc_vs_sklearn_with_padding(self, ties):
        n, cap = 300, 384
        preds = _rng.rand(n)
        if ties:
            preds = np.round(preds, 1)  # heavy tie groups
        target = _rng.randint(0, 2, n)
        pp = np.full(cap, -np.inf, np.float32)
        pp[:n] = preds
        tt = np.zeros(cap, np.int32)
        tt[:n] = target
        valid = jnp.asarray(np.arange(cap) < n)
        got = float(masked_binary_auroc(jnp.asarray(pp), jnp.asarray(tt), valid))
        np.testing.assert_allclose(got, roc_auc_score(target, preds), atol=1e-6)

    @pytest.mark.parametrize("ties", [False, True])
    def test_ap_vs_sklearn_with_padding(self, ties):
        n, cap = 300, 384
        preds = _rng.rand(n)
        if ties:
            preds = np.round(preds, 1)
        target = _rng.randint(0, 2, n)
        pp = np.full(cap, -np.inf, np.float32)
        pp[:n] = preds
        tt = np.zeros(cap, np.int32)
        tt[:n] = target
        valid = jnp.asarray(np.arange(cap) < n)
        got = float(masked_binary_average_precision(jnp.asarray(pp), jnp.asarray(tt), valid))
        np.testing.assert_allclose(got, average_precision_score(target, preds), atol=1e-6)


@pytest.mark.parametrize("metric_cls, sk_fn", [(AUROC, roc_auc_score), (AveragePrecision, average_precision_score)])
class TestCapacityMode:
    def test_matches_list_mode_and_sklearn(self, metric_cls, sk_fn):
        preds = _rng.rand(10, 32).astype(np.float32)
        target = _rng.randint(0, 2, (10, 32))
        capped = metric_cls(capacity=512)
        listed = metric_cls()
        for i in range(10):
            capped.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
            listed.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
        expected = sk_fn(target.reshape(-1), preds.reshape(-1))
        np.testing.assert_allclose(float(capped.compute()), expected, atol=1e-6)
        np.testing.assert_allclose(float(listed.compute()), expected, atol=1e-6)

    def test_no_retrace_across_steps(self, metric_cls, sk_fn):
        metric = metric_cls(capacity=256)
        traces = {"n": 0}

        def step(state, p, t):
            traces["n"] += 1
            return metric.apply_update(state, p, t)

        jitted = jax.jit(step)
        state = metric.init_state()
        for i in range(6):
            p = jnp.asarray(_rng.rand(32).astype(np.float32))
            t = jnp.asarray(_rng.randint(0, 2, 32))
            state = jitted(state, p, t)
        assert traces["n"] == 1  # state structure is step-invariant

    def test_sharded_compute_matches_sequential(self, metric_cls, sk_fn):
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        n = NUM_DEVICES * 48
        preds = jnp.asarray(_rng.rand(n).astype(np.float32))
        target = jnp.asarray(_rng.randint(0, 2, n))

        metric = metric_cls(capacity=64)
        mesh = Mesh(np.array(jax.devices()[:NUM_DEVICES]), ("data",))

        def step(p, t):
            state = metric.apply_update(metric.init_state(), p, t)
            return metric.apply_compute(state, axis_name="data")

        fn = jax.jit(
            shard_map_compat(step, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P(), check_vma=False)
        )
        value = float(
            fn(
                jax.device_put(preds, NamedSharding(mesh, P("data"))),
                jax.device_put(target, NamedSharding(mesh, P("data"))),
            )
        )
        expected = sk_fn(np.asarray(target), np.asarray(preds))
        np.testing.assert_allclose(value, expected, atol=1e-6)

    def test_overflow_drops_and_warns(self, metric_cls, sk_fn):
        metric = metric_cls(capacity=64)
        preds = _rng.rand(100).astype(np.float32)
        target = _rng.randint(0, 2, 100)
        metric.update(jnp.asarray(preds), jnp.asarray(target))
        with pytest.warns(UserWarning, match="dropped"):
            value = float(metric.compute())
        expected = sk_fn(target[:64], preds[:64])
        np.testing.assert_allclose(value, expected, atol=1e-6)

    def test_invalid_args(self, metric_cls, sk_fn):
        with pytest.raises(ValueError, match="capacity"):
            metric_cls(capacity=0)
        # num_classes > 1 selects the multiclass layout: C score columns + 1
        # label column per row of the flat merged buffer (plus the slack
        # zone, which scales down with small capacities)
        m = metric_cls(capacity=16, num_classes=5)
        assert m._buf_width == 6
        assert m._buf_slack == 16
        assert m.buf.shape == ((16 + 16) * 6,)

    def test_reset(self, metric_cls, sk_fn):
        metric = metric_cls(capacity=32)
        metric.update(jnp.asarray(_rng.rand(8).astype(np.float32)), jnp.asarray(_rng.randint(0, 2, 8)))
        metric.reset()
        assert int(metric.count) == 0
        assert float(metric.buf[0]) == -np.inf


@pytest.mark.parametrize(
    "metric_cls, sk_fn", [(AUROC, roc_auc_score), (AveragePrecision, average_precision_score)]
)
def test_capacity_honors_pos_label_zero(metric_cls, sk_fn):
    preds = _rng.rand(64).astype(np.float32)
    target = _rng.randint(0, 2, 64)
    metric = metric_cls(capacity=128, pos_label=0)
    metric.update(jnp.asarray(preds), jnp.asarray(target))
    expected = sk_fn(1 - target, preds)
    np.testing.assert_allclose(float(metric.compute()), expected, atol=1e-6)


def test_capacity_rejects_out_of_range_pos_label():
    with pytest.raises(ValueError, match="pos_label"):
        AUROC(capacity=16, pos_label=2)


class TestCapacityDegenerateStreams:
    """Degenerate-stream parity with the cat path (found by the curve
    fuzz): single-class AUROC raises the roc errors eagerly, no-positive
    AP is NaN, and an empty buffer is NaN — never a misleading raise."""

    def test_binary_all_positive_raises(self):
        m = AUROC(capacity=16)
        m.update(jnp.asarray([0.2, 0.8]), jnp.asarray([1, 1]))
        with pytest.raises(ValueError, match="No negative samples"):
            m.compute()

    def test_binary_all_negative_raises(self):
        m = AUROC(capacity=16)
        m.update(jnp.asarray([0.2, 0.8]), jnp.asarray([0, 0]))
        with pytest.raises(ValueError, match="No positive samples"):
            m.compute()

    def test_multiclass_absent_class_raises(self):
        m = AUROC(capacity=16, num_classes=3)
        probs = _normalize_rows(_rng.rand(8, 3).astype(np.float32))
        m.update(jnp.asarray(probs), jnp.asarray(np.array([0, 1] * 4)))  # class 2 absent
        with pytest.raises(ValueError, match="No positive samples"):
            m.compute()

    def test_multilabel_constant_column_raises(self):
        m = AUROC(capacity=16, num_classes=3, multilabel=True)
        preds = _rng.rand(4, 3).astype(np.float32)
        # fixed pattern: columns 0/2 mixed, column 1 always on — the raise
        # must be deterministic regardless of shared-_rng state
        target = np.array([[0, 1, 1], [1, 1, 0], [0, 1, 1], [1, 1, 0]])
        m.update(jnp.asarray(preds), jnp.asarray(target))
        with pytest.raises(ValueError, match="No negative samples"):
            m.compute()

    def test_ap_all_negative_is_nan(self):
        m = AveragePrecision(capacity=16)
        m.update(jnp.asarray([0.2, 0.8, 0.4]), jnp.asarray([0, 0, 0]))
        assert np.isnan(float(m.compute()))

    def test_in_graph_single_class_is_nan_not_zero(self):
        """The IN-GRAPH contract behind the eager raises above: under jit the
        host check cannot run, and a single-class buffer must propagate the
        reference-arithmetic 0/0 NaN — a guard silently returning 0 is the
        regression this pins (ADVICE r4; fuzz seed 3001 found the eager
        analogue)."""
        m = AUROC(capacity=16)
        state = m.apply_update(
            m.init_state(), jnp.asarray([0.2, 0.8]), jnp.asarray([1, 1])
        )
        value = jax.jit(m.apply_compute)(state)
        assert np.isnan(float(value)), float(value)

    def test_in_graph_multiclass_absent_class_is_nan_not_zero(self):
        """Macro and support-weighted averages must carry the absent-class
        NaN through (NaN*0 weight included), not zero it."""
        for average in ("macro", "weighted"):
            m = AUROC(capacity=16, num_classes=3, average=average)
            probs = _normalize_rows(_rng.rand(8, 3).astype(np.float32))
            state = m.apply_update(
                m.init_state(), jnp.asarray(probs), jnp.asarray(np.array([0, 1] * 4))
            )
            value = np.asarray(jax.jit(m.apply_compute)(state))
            assert np.isnan(value).any(), (average, value)

    def test_empty_buffer_is_nan_not_a_raise(self):
        m = AUROC(capacity=16)
        with pytest.warns(UserWarning, match="called before"):
            assert np.isnan(float(m.compute()))


class TestMulticlassCapacity:
    def _data(self, n=200, c=4):
        logits = _rng.rand(n, c).astype(np.float32)
        probs = logits / logits.sum(-1, keepdims=True)
        target = _rng.randint(0, c, n)
        return probs, target

    @pytest.mark.parametrize("average", ["macro", "weighted"])
    def test_auroc_multiclass_vs_sklearn(self, average):
        probs, target = self._data()
        metric = AUROC(capacity=256, num_classes=4, average=average)
        metric.update(jnp.asarray(probs), jnp.asarray(target))
        expected = roc_auc_score(target, probs, multi_class="ovr", average=average)
        np.testing.assert_allclose(float(metric.compute()), expected, atol=1e-6)

    def test_ap_multiclass_per_class_vs_sklearn(self):
        probs, target = self._data()
        metric = AveragePrecision(capacity=256, num_classes=4)
        metric.update(jnp.asarray(probs), jnp.asarray(target))
        got = np.asarray(metric.compute())
        for c in range(4):
            np.testing.assert_allclose(
                got[c], average_precision_score((target == c).astype(int), probs[:, c]), atol=1e-6
            )

    def test_multiclass_capacity_matches_list_mode(self):
        probs, target = self._data()
        capped = AUROC(capacity=256, num_classes=4, average="macro")
        listed = AUROC(num_classes=4, average="macro")
        capped.update(jnp.asarray(probs), jnp.asarray(target))
        listed.update(jnp.asarray(probs), jnp.asarray(target))
        np.testing.assert_allclose(float(capped.compute()), float(listed.compute()), atol=1e-6)

    def test_multiclass_capacity_sharded(self):
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        probs, target = self._data(n=NUM_DEVICES * 32)
        metric = AUROC(capacity=32, num_classes=4, average="macro")
        mesh = Mesh(np.array(jax.devices()[:NUM_DEVICES]), ("data",))

        def step(p, t):
            state = metric.apply_update(metric.init_state(), p, t)
            return metric.apply_compute(state, axis_name="data")

        fn = jax.jit(
            shard_map_compat(step, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P(), check_vma=False)
        )
        value = float(fn(
            jax.device_put(jnp.asarray(probs), NamedSharding(mesh, P("data"))),
            jax.device_put(jnp.asarray(target), NamedSharding(mesh, P("data"))),
        ))
        expected = roc_auc_score(target, probs, multi_class="ovr", average="macro")
        np.testing.assert_allclose(value, expected, atol=1e-6)

    @pytest.mark.parametrize("average", ["macro", "weighted"])
    def test_auroc_multilabel_capacity_vs_sklearn(self, average):
        n, c = 200, 4
        probs = _rng.rand(n, c).astype(np.float32)
        target = _rng.randint(0, 2, (n, c))
        metric = AUROC(capacity=256, num_classes=c, multilabel=True, average=average)
        metric.update(jnp.asarray(probs), jnp.asarray(target))
        expected = roc_auc_score(target, probs, average=average)
        np.testing.assert_allclose(float(metric.compute()), expected, atol=1e-6)

    def test_ap_multilabel_capacity_vs_sklearn(self):
        n, c = 200, 4
        probs = _rng.rand(n, c).astype(np.float32)
        target = _rng.randint(0, 2, (n, c))
        metric = AveragePrecision(capacity=256, num_classes=c, multilabel=True)
        metric.update(jnp.asarray(probs), jnp.asarray(target))
        got = np.asarray(metric.compute())
        for label in range(c):
            np.testing.assert_allclose(
                got[label], average_precision_score(target[:, label], probs[:, label]), atol=1e-6
            )

    def test_auroc_multilabel_capacity_accumulates_and_jits(self):
        import jax as _jax

        n, c = 64, 3
        metric = AUROC(capacity=256, num_classes=c, multilabel=True)
        step = _jax.jit(lambda s, p, t: metric.apply_update(s, p, t))
        state = metric.init_state()
        all_p, all_t = [], []
        for _ in range(3):
            p = _rng.rand(n, c).astype(np.float32)
            t = _rng.randint(0, 2, (n, c))
            all_p.append(p)
            all_t.append(t)
            state = step(state, jnp.asarray(p), jnp.asarray(t))
        got = float(metric.apply_compute(state))
        expected = roc_auc_score(np.concatenate(all_t), np.concatenate(all_p), average="macro")
        np.testing.assert_allclose(got, expected, atol=1e-6)

    def test_auroc_multilabel_capacity_sharded(self):
        # the multilabel mode is the only one pushing a 2-D target buffer
        # through the cat sync + flatten path — cover it on the mesh
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        n, c = NUM_DEVICES * 24, 3
        probs = _rng.rand(n, c).astype(np.float32)
        target = _rng.randint(0, 2, (n, c))
        metric = AUROC(capacity=24, num_classes=c, multilabel=True)
        mesh = Mesh(np.array(jax.devices()[:NUM_DEVICES]), ("data",))

        def step(p, t):
            state = metric.apply_update(metric.init_state(), p, t)
            return metric.apply_compute(state, axis_name="data")

        fn = jax.jit(
            shard_map_compat(step, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P(), check_vma=False)
        )
        value = float(fn(
            jax.device_put(jnp.asarray(probs), NamedSharding(mesh, P("data"))),
            jax.device_put(jnp.asarray(target), NamedSharding(mesh, P("data"))),
        ))
        np.testing.assert_allclose(value, roc_auc_score(target, probs, average="macro"), atol=1e-6)

    def test_multilabel_capacity_invalid_args(self):
        with pytest.raises(ValueError, match="num_classes"):
            AUROC(capacity=16, multilabel=True)
        with pytest.raises(ValueError, match="capacity"):
            AUROC(multilabel=True)
        metric = AUROC(capacity=16, num_classes=3, multilabel=True)
        with pytest.raises(ValueError, match="multilabel"):
            # multiclass-style integer labels are not (N, C) binaries
            metric.update(
                jnp.asarray(_normalize_rows(_rng.rand(8, 3).astype(np.float32))),
                jnp.asarray(_rng.randint(0, 3, 8)),
            )

    def test_multiclass_capacity_invalid_args(self):
        with pytest.raises(ValueError, match="average"):
            AUROC(capacity=16, num_classes=3, average="micro")
        with pytest.raises(ValueError, match="pos_label"):
            AUROC(capacity=16, num_classes=3, pos_label=1)
        metric = AUROC(capacity=16, num_classes=3)
        with pytest.raises(ValueError, match="expects"):
            metric.update(jnp.asarray(_rng.rand(8).astype(np.float32)), jnp.asarray(_rng.randint(0, 2, 8)))


def test_auroc_capacity_rejects_max_fpr():
    with pytest.raises(ValueError, match="max_fpr"):
        AUROC(capacity=16, max_fpr=0.5)


def test_capacity_rejects_multiclass_inputs():
    metric = AUROC(capacity=16)
    probs = _rng.rand(8, 4).astype(np.float32)
    probs /= probs.sum(-1, keepdims=True)
    with pytest.raises(ValueError, match="binary"):
        metric.update(jnp.asarray(probs), jnp.asarray(_rng.randint(0, 4, 8)))


class TestSlackZoneWrites:
    """Adversarial battery for the flat slack-zone append: odd batch sizes,
    boundary-straddling writes, and batches past BUF_SLACK_ROWS (the chunked
    path). Oracle: sklearn on exactly the first `capacity` samples."""

    def _stream(self, sizes, capacity, seed=0):
        from sklearn.metrics import roc_auc_score

        rng = np.random.RandomState(seed)
        metric = AUROC(capacity=capacity)
        all_p, all_t = [], []
        for n in sizes:
            p = rng.rand(n).astype(np.float32)
            t = rng.randint(0, 2, n)
            # ensure both classes appear inside the kept prefix
            if not all_p:
                k = min(n, 2)
                t[:k] = [0, 1][:k]
            metric.update(jnp.asarray(p), jnp.asarray(t))
            all_p.append(p)
            all_t.append(t)
        kept_p = np.concatenate(all_p)[:capacity]
        kept_t = np.concatenate(all_t)[:capacity]
        with pytest.warns(UserWarning, match="dropped") if sum(sizes) > capacity else _nullcontext():
            value = float(metric.compute())
        np.testing.assert_allclose(value, roc_auc_score(kept_t, kept_p), atol=1e-6)

    def test_odd_batches_cross_capacity_boundary(self):
        # 97+151+13+251 = 512 total against capacity 300: the third/fourth
        # writes straddle and then fully overflow at unaligned offsets
        self._stream([97, 151, 13, 251], capacity=300)

    def test_single_sample_batches(self):
        self._stream([1] * 40, capacity=25, seed=1)

    def test_batch_larger_than_slack_uses_chunked_path(self):
        from metrics_tpu.utilities.capped_buffer import BUF_SLACK_ROWS

        n = BUF_SLACK_ROWS + 1777  # forces two chunks in one append
        self._stream([n], capacity=2000, seed=2)
        self._stream([n, 333], capacity=n + 100, seed=3)

    def test_exact_fill_then_overflow(self):
        self._stream([128, 128, 64], capacity=256, seed=4)


class _nullcontext:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
