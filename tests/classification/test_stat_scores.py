"""StatScores parity vs an independent numpy oracle."""
from functools import partial

import numpy as np
import pytest

from metrics_tpu import StatScores
from metrics_tpu.functional import stat_scores
from tests.classification.inputs import (
    _binary_prob_inputs,
    _multiclass_inputs,
    _multiclass_prob_inputs,
    _multilabel_prob_inputs,
)
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester


def _onehot(labels, num_classes):
    return np.eye(num_classes, dtype=int)[labels]


def _canonical_binary_cols(preds, target):
    """Canonical (N, C) binary arrays for each fixture type."""
    if preds.ndim == target.ndim and np.issubdtype(np.asarray(preds).dtype, np.floating):
        if preds.ndim == 1:  # binary probs
            return (preds >= THRESHOLD).astype(int)[:, None], target[:, None]
        return (preds >= THRESHOLD).astype(int), target  # multilabel probs
    if preds.ndim == target.ndim + 1:  # multiclass probs
        return _onehot(np.argmax(preds, axis=1), preds.shape[1]), _onehot(target, preds.shape[1])
    # multiclass labels
    return _onehot(preds, NUM_CLASSES), _onehot(target, NUM_CLASSES)


def _np_stat_scores(preds, target, reduce="micro"):
    p, t = _canonical_binary_cols(np.asarray(preds), np.asarray(target))
    axis = None if reduce == "micro" else (0 if reduce == "macro" else 1)
    tp = np.sum((p == 1) & (t == 1), axis=axis)
    fp = np.sum((p == 1) & (t == 0), axis=axis)
    tn = np.sum((p == 0) & (t == 0), axis=axis)
    fn = np.sum((p == 0) & (t == 1), axis=axis)
    return np.stack([tp, fp, tn, fn, tp + fn], axis=-1)


# (preds, target, num_classes for macro) — binary macro runs at
# num_classes=1: one canonical positive-class column, (1, 5) counts
_cases = [
    (_binary_prob_inputs.preds, _binary_prob_inputs.target, 1),
    (_multiclass_inputs.preds, _multiclass_inputs.target, NUM_CLASSES),
    (_multiclass_prob_inputs.preds, _multiclass_prob_inputs.target, NUM_CLASSES),
    (_multilabel_prob_inputs.preds, _multilabel_prob_inputs.target, NUM_CLASSES),
]


@pytest.mark.parametrize("preds, target, num_classes", _cases)
@pytest.mark.parametrize("reduce_", ["micro", "macro"])
class TestStatScores(MetricTester):

    def _args(self, reduce_, num_classes):
        if reduce_ == "macro":
            return {"reduce": reduce_, "num_classes": num_classes}
        return {"reduce": reduce_}

    @pytest.mark.parametrize("ddp", [False, True])
    def test_stat_scores_class(self, ddp, preds, target, num_classes, reduce_):
        args = self._args(reduce_, num_classes)
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=StatScores,
            sk_metric=partial(_np_stat_scores, reduce=reduce_),
            metric_args=args,
        )

    def test_stat_scores_fn(self, preds, target, num_classes, reduce_):
        args = self._args(reduce_, num_classes)
        self.run_functional_metric_test(
            preds, target, metric_functional=stat_scores,
            sk_metric=partial(_np_stat_scores, reduce=reduce_), metric_args=args,
        )


def test_stat_scores_samples_reduce():
    """samples reduce keeps a per-sample axis and accumulates by concatenation."""
    rng = np.random.RandomState(7)
    preds = rng.randint(0, NUM_CLASSES, (4, 16))
    target = rng.randint(0, NUM_CLASSES, (4, 16))

    metric = StatScores(reduce="samples", num_classes=NUM_CLASSES)
    for i in range(4):
        metric.update(preds[i], target[i])
    result = np.asarray(metric.compute())
    assert result.shape == (64, 5)

    p = np.eye(NUM_CLASSES, dtype=int)[preds.reshape(-1)]
    t = np.eye(NUM_CLASSES, dtype=int)[target.reshape(-1)]
    tp = np.sum((p == 1) & (t == 1), axis=1)
    np.testing.assert_array_equal(result[:, 0], tp)


def test_stat_scores_ignore_index_macro():
    """macro + ignore_index flags the ignored class with -1."""
    preds = np.asarray([1, 0, 2, 1])
    target = np.asarray([1, 1, 2, 0])
    result = np.asarray(stat_scores(preds, target, reduce="macro", num_classes=3, ignore_index=1))
    assert (result[1] == -1).all()
    assert (result[[0, 2]] >= 0).all()


def test_stat_scores_mdmc():
    """multi-dim inputs under both mdmc_reduce modes."""
    rng = np.random.RandomState(11)
    preds = rng.randint(0, 3, (8, 6))
    target = rng.randint(0, 3, (8, 6))

    glob = np.asarray(stat_scores(preds, target, reduce="micro", mdmc_reduce="global"))
    assert glob.shape == (5,)
    p = np.eye(3, dtype=int)[preds.reshape(-1)]
    t = np.eye(3, dtype=int)[target.reshape(-1)]
    np.testing.assert_array_equal(glob[0], np.sum((p == 1) & (t == 1)))

    sw = np.asarray(stat_scores(preds, target, reduce="micro", mdmc_reduce="samplewise"))
    assert sw.shape == (8, 5)
    np.testing.assert_array_equal(sw[:, 0].sum(), glob[0])
