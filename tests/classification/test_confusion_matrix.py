"""ConfusionMatrix / CohenKappa / MatthewsCorrcoef / IoU / dice parity vs sklearn."""
from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import cohen_kappa_score as sk_cohen_kappa
from sklearn.metrics import confusion_matrix as sk_confusion_matrix
from sklearn.metrics import jaccard_score as sk_jaccard
from sklearn.metrics import matthews_corrcoef as sk_matthews
from sklearn.metrics import multilabel_confusion_matrix as sk_multilabel_cm

from metrics_tpu import CohenKappa, ConfusionMatrix, IoU, MatthewsCorrcoef
from metrics_tpu.functional import cohen_kappa, confusion_matrix, dice_score, iou, matthews_corrcoef
from tests.classification.inputs import (
    _binary_prob_inputs,
    _multiclass_inputs,
    _multiclass_prob_inputs,
    _multilabel_prob_inputs,
)
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester


def _canon(preds, target):
    preds, target = np.asarray(preds), np.asarray(target)
    if preds.ndim == target.ndim + 1:  # multiclass probs
        return np.argmax(preds, axis=1).reshape(-1), target.reshape(-1)
    if np.issubdtype(preds.dtype, np.floating):
        return (preds >= THRESHOLD).astype(int).reshape(-1), target.reshape(-1)
    return preds.reshape(-1), target.reshape(-1)


def _sk_cm(preds, target, num_classes, normalize=None):
    y_pred, y_true = _canon(preds, target)
    return sk_confusion_matrix(y_true, y_pred, labels=list(range(num_classes)), normalize=normalize)


def _sk_cm_multilabel(preds, target):
    p = (np.asarray(preds) >= THRESHOLD).astype(int)
    return sk_multilabel_cm(np.asarray(target).reshape(-1, p.shape[-1]), p.reshape(-1, p.shape[-1]))


_cases = [
    (_binary_prob_inputs.preds, _binary_prob_inputs.target, 2),
    (_multiclass_inputs.preds, _multiclass_inputs.target, NUM_CLASSES),
    (_multiclass_prob_inputs.preds, _multiclass_prob_inputs.target, NUM_CLASSES),
]


@pytest.mark.parametrize("preds, target, num_classes", _cases)
class TestConfusionMatrixFamily(MetricTester):

    @pytest.mark.parametrize("ddp", [False, True])
    @pytest.mark.parametrize("normalize", [None, "true", "pred", "all"])
    def test_confusion_matrix_class(self, ddp, preds, target, num_classes, normalize):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=ConfusionMatrix,
            sk_metric=partial(_sk_cm, num_classes=num_classes, normalize=normalize),
            metric_args={"num_classes": num_classes, "normalize": normalize},
            check_batch=True,
            atol=1e-6,
        )

    def test_confusion_matrix_fn(self, preds, target, num_classes):
        self.run_functional_metric_test(
            preds, target, metric_functional=confusion_matrix,
            sk_metric=partial(_sk_cm, num_classes=num_classes),
            metric_args={"num_classes": num_classes}, atol=1e-6,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    @pytest.mark.parametrize("weights", [None, "linear", "quadratic"])
    def test_cohen_kappa_class(self, ddp, preds, target, num_classes, weights):
        def sk_kappa(p, t):
            y_pred, y_true = _canon(p, t)
            return sk_cohen_kappa(y_true, y_pred, weights=weights, labels=list(range(num_classes)))

        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=CohenKappa,
            sk_metric=sk_kappa,
            metric_args={"num_classes": num_classes, "weights": weights},
            atol=1e-5,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_matthews_class(self, ddp, preds, target, num_classes):
        def sk_mcc(p, t):
            y_pred, y_true = _canon(p, t)
            return sk_matthews(y_true, y_pred)

        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=MatthewsCorrcoef,
            sk_metric=sk_mcc,
            metric_args={"num_classes": num_classes},
            atol=1e-5,
        )

    def test_matthews_fn(self, preds, target, num_classes):
        def sk_mcc(p, t):
            y_pred, y_true = _canon(p, t)
            return sk_matthews(y_true, y_pred)

        self.run_functional_metric_test(
            preds, target, metric_functional=matthews_corrcoef, sk_metric=sk_mcc,
            metric_args={"num_classes": num_classes}, atol=1e-5,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_iou_class(self, ddp, preds, target, num_classes):
        def sk_iou(p, t):
            y_pred, y_true = _canon(p, t)
            return sk_jaccard(y_true, y_pred, labels=list(range(num_classes)), average="macro")

        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=IoU,
            sk_metric=sk_iou,
            metric_args={"num_classes": num_classes},
            atol=1e-5,
        )


def test_confusion_matrix_multidim_multiclass():
    """(N, C, X) probs / (N, X) targets flow through the one-hot tensordot
    counting path with the extra dim contracted alongside the sample dim."""
    rng = np.random.RandomState(11)
    preds = rng.rand(32, 4, 5).astype(np.float32)
    target = rng.randint(0, 4, (32, 5))
    got = np.asarray(confusion_matrix(jnp.asarray(preds), jnp.asarray(target), num_classes=4))
    expected = sk_confusion_matrix(target.reshape(-1), preds.argmax(1).reshape(-1), labels=range(4))
    np.testing.assert_array_equal(got, expected)


def test_confusion_matrix_num_classes_mismatch_large_probs():
    """A (N, C) probs input whose C exceeds num_classes must fail loudly on
    the host (the tensordot fast path must not silently return the wrong
    shape; parity: the reference's bincount raises on the same input)."""
    preds = jnp.asarray(np.random.RandomState(12).rand(8, 6).astype(np.float32))
    target = jnp.asarray(np.zeros(8, dtype=np.int64))
    with pytest.raises(ValueError):
        confusion_matrix(preds, target, num_classes=3)


def test_confusion_matrix_multilabel():
    preds = _multilabel_prob_inputs.preds[0]
    target = _multilabel_prob_inputs.target[0]
    ours = np.asarray(confusion_matrix(jnp.asarray(preds), jnp.asarray(target),
                                       num_classes=NUM_CLASSES, multilabel=True))
    expected = _sk_cm_multilabel(preds, target)
    np.testing.assert_array_equal(ours, expected)


def test_cohen_kappa_fn_example():
    target = jnp.asarray([1, 1, 0, 0])
    preds = jnp.asarray([0, 1, 0, 0])
    np.testing.assert_allclose(cohen_kappa(preds, target, num_classes=2), 0.5, atol=1e-6)


def test_iou_absent_and_ignore():
    target = jnp.asarray([0, 0, 0, 0])
    preds = jnp.asarray([0, 0, 0, 0])
    # class 1 absent from both -> absent_score
    out = np.asarray(iou(preds, target, num_classes=2, absent_score=0.77, reduction="none"))
    np.testing.assert_allclose(out, [1.0, 0.77], atol=1e-6)
    # ignore_index drops the class
    out2 = np.asarray(iou(preds, target, num_classes=2, ignore_index=1, reduction="none"))
    np.testing.assert_allclose(out2, [1.0], atol=1e-6)


def test_dice_score_example():
    pred = jnp.asarray([
        [0.85, 0.05, 0.05, 0.05],
        [0.05, 0.85, 0.05, 0.05],
        [0.05, 0.05, 0.85, 0.05],
        [0.05, 0.05, 0.05, 0.85],
    ])
    target = jnp.asarray([0, 1, 3, 2])
    np.testing.assert_allclose(dice_score(pred, target), 1 / 3, atol=1e-6)
    # with background
    np.testing.assert_allclose(dice_score(pred, target, bg=True), 0.5, atol=1e-6)
