"""Accuracy parity vs sklearn (oracle canonicalizes independently in numpy)."""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import accuracy_score as sk_accuracy

from metrics_tpu import Accuracy
from metrics_tpu.functional import accuracy
from tests.classification.inputs import (
    _binary_inputs,
    _binary_prob_inputs,
    _multiclass_inputs,
    _multiclass_prob_inputs,
    _multidim_multiclass_inputs,
    _multidim_multiclass_prob_inputs,
    _multilabel_inputs,
    _multilabel_prob_inputs,
    _no_match_inputs,
)
from tests.helpers.testers import THRESHOLD, MetricTester


def _sk_binary_prob(preds, target):
    return sk_accuracy(target.reshape(-1), (preds >= THRESHOLD).astype(int).reshape(-1))


def _sk_labels(preds, target):
    return sk_accuracy(target.reshape(-1), preds.reshape(-1))


def _sk_multiclass_prob(preds, target):
    return sk_accuracy(target.reshape(-1), np.argmax(preds, axis=1).reshape(-1))


def _sk_multilabel_prob(preds, target):
    return sk_accuracy(target.reshape(-1), (preds >= THRESHOLD).astype(int).reshape(-1))


def _sk_mdmc_prob(preds, target):
    # (N, C, X) probs -> argmax over C, flatten with target (global micro)
    return sk_accuracy(target.reshape(-1), np.argmax(preds, axis=1).reshape(-1))


@pytest.mark.parametrize(
    "preds, target, sk_metric",
    [
        (_binary_prob_inputs.preds, _binary_prob_inputs.target, _sk_binary_prob),
        (_binary_inputs.preds, _binary_inputs.target, _sk_labels),
        (_multilabel_prob_inputs.preds, _multilabel_prob_inputs.target, _sk_multilabel_prob),
        (_multilabel_inputs.preds, _multilabel_inputs.target, _sk_labels),
        (_multiclass_prob_inputs.preds, _multiclass_prob_inputs.target, _sk_multiclass_prob),
        (_multiclass_inputs.preds, _multiclass_inputs.target, _sk_labels),
        (_multidim_multiclass_prob_inputs.preds, _multidim_multiclass_prob_inputs.target, _sk_mdmc_prob),
        (_multidim_multiclass_inputs.preds, _multidim_multiclass_inputs.target, _sk_labels),
        (_no_match_inputs.preds, _no_match_inputs.target, _sk_labels),
    ],
)
class TestAccuracy(MetricTester):

    @pytest.mark.parametrize("ddp", [False, True])
    def test_accuracy_class(self, ddp, preds, target, sk_metric):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=Accuracy,
            sk_metric=sk_metric,
            atol=1e-6,
        )

    def test_accuracy_fn(self, preds, target, sk_metric):
        self.run_functional_metric_test(
            preds, target, metric_functional=accuracy, sk_metric=sk_metric, atol=1e-6
        )


def test_accuracy_topk():
    """Top-2 accuracy on a hand-computed example (reference docstring case)."""
    target = jnp.asarray([0, 1, 2])
    preds = jnp.asarray([[0.1, 0.9, 0.0], [0.3, 0.1, 0.6], [0.2, 0.5, 0.3]])
    np.testing.assert_allclose(accuracy(preds, target, top_k=2), 2 / 3, atol=1e-6)
    acc = Accuracy(top_k=2)
    np.testing.assert_allclose(acc(preds, target), 2 / 3, atol=1e-6)


def test_subset_accuracy_multilabel():
    """Multilabel subset accuracy requires whole rows to match."""
    rng = np.random.RandomState(0)
    preds = rng.rand(64, 4)
    target = rng.randint(0, 2, (64, 4))
    expected = np.mean(((preds >= THRESHOLD).astype(int) == target).all(axis=1))
    result = accuracy(jnp.asarray(preds), jnp.asarray(target), subset_accuracy=True)
    np.testing.assert_allclose(result, expected, atol=1e-6)


def test_subset_accuracy_mdmc():
    """Multidim multiclass subset accuracy: all sub-samples must be correct."""
    rng = np.random.RandomState(1)
    preds = rng.randint(0, 3, (32, 6))
    target = rng.randint(0, 3, (32, 6))
    expected = np.mean((preds == target).all(axis=1))
    result = accuracy(jnp.asarray(preds), jnp.asarray(target), subset_accuracy=True)
    np.testing.assert_allclose(result, expected, atol=1e-6)


def test_accuracy_average_macro():
    """Macro accuracy equals sklearn balanced recall over present classes."""
    from sklearn.metrics import recall_score

    rng = np.random.RandomState(2)
    preds = rng.randint(0, 5, 200)
    target = rng.randint(0, 5, 200)
    expected = recall_score(target, preds, average="macro", labels=list(range(5)), zero_division=0)
    result = accuracy(jnp.asarray(preds), jnp.asarray(target), average="macro", num_classes=5)
    np.testing.assert_allclose(result, expected, atol=1e-6)


def test_accuracy_mode_locking():
    """Feeding a different input case than previous updates raises."""
    acc = Accuracy()
    acc(jnp.asarray([0.3, 0.8, 0.9]), jnp.asarray([1, 1, 0]))  # binary probs
    with pytest.raises(ValueError, match="You can not use"):
        acc(jnp.asarray([[0.1, 0.9], [0.8, 0.2]]), jnp.asarray([[1, 0], [0, 1]]))  # multilabel
