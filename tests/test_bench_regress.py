"""The perf-regression gate's decision paths: regression / no-regression /
degraded-excluded / rerun-deduped / insufficient-history, the capture-format
parsing, and the committed BENCH_r* trajectory staying green."""
import json
import os
import sys

import pytest

_SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts")
if _SCRIPTS not in sys.path:
    sys.path.insert(0, _SCRIPTS)
import bench_regress  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _record(metric="m", value=10.0, unit="us/step", degraded=False, rerun=False, **extra):
    rec = {
        "metric": metric, "value": value, "unit": unit, "vs_baseline": 5.0,
        "degraded": degraded,
    }
    if rerun:
        rec["rerun"] = True
    rec.update(extra)
    return rec


def _capture(tmp_path, n, records, tail_prefix=""):
    """One driver-format capture file: records as the recorded output tail."""
    tail = tail_prefix + "\n".join(json.dumps(r) for r in records)
    path = tmp_path / f"BENCH_r{n:02d}.json"
    path.write_text(json.dumps({"n": n, "cmd": "python bench.py", "rc": 0, "tail": tail}))
    return str(path)


def _rounds(tmp_path, values, degraded_flags=None, metric="m"):
    degraded_flags = degraded_flags or [False] * len(values)
    return [
        _capture(tmp_path, i + 1, [_record(metric, v, degraded=d)])
        for i, (v, d) in enumerate(zip(values, degraded_flags))
    ]


def test_no_regression_passes(tmp_path):
    paths = _rounds(tmp_path, [10.0, 11.0, 9.5, 10.5])
    rows = bench_regress.check_trajectory(bench_regress.load_trajectory(paths))
    (row,) = rows
    assert row["status"] == bench_regress.OK
    assert row["baseline"] == 10.0  # median of 10, 11, 9.5
    assert bench_regress.main(paths + ["--check"]) == 0


def test_two_x_regression_fails(tmp_path):
    """Acceptance: a synthetic 2x regression record demonstrably fails."""
    paths = _rounds(tmp_path, [10.0, 11.0, 9.5, 20.0])
    rows = bench_regress.check_trajectory(bench_regress.load_trajectory(paths))
    (row,) = rows
    assert row["status"] == bench_regress.REGRESSED
    assert row["delta_pct"] == pytest.approx(100.0)
    assert bench_regress.main(paths + ["--check"]) == 1
    # the failure prints a readable delta table naming the config
    table = bench_regress.render_table(rows, bench_regress.DEFAULT_TOLERANCE)
    assert "REGRESSED" in table and "m" in table and "+100.0%" in table


def test_tolerance_is_configurable(tmp_path):
    paths = _rounds(tmp_path, [10.0, 10.0, 13.0])
    rows = bench_regress.check_trajectory(
        bench_regress.load_trajectory(paths), tolerance=0.5
    )
    assert rows[0]["status"] == bench_regress.OK  # +30% < +50%
    rows = bench_regress.check_trajectory(
        bench_regress.load_trajectory(paths), tolerance=0.2
    )
    assert rows[0]["status"] == bench_regress.REGRESSED  # +30% > +20%


def test_degraded_records_are_excluded_from_the_baseline(tmp_path):
    """A sick-endpoint round (10-20x slow, flagged) must not poison the
    baseline: with it excluded the clean latest round passes, and a 2x true
    regression still fails."""
    paths = _rounds(
        tmp_path, [10.0, 150.0, 10.5, 10.2], degraded_flags=[False, True, False, False]
    )
    (row,) = bench_regress.check_trajectory(bench_regress.load_trajectory(paths))
    assert row["baseline"] == pytest.approx(10.25)  # median(10, 10.5) — not 150
    assert row["status"] == bench_regress.OK


def test_degraded_latest_round_is_skipped_not_judged(tmp_path):
    paths = _rounds(
        tmp_path, [10.0, 10.5, 150.0], degraded_flags=[False, False, True]
    )
    (row,) = bench_regress.check_trajectory(bench_regress.load_trajectory(paths))
    assert row["status"] == bench_regress.SKIPPED_DEGRADED
    assert bench_regress.main(paths + ["--check"]) == 0  # a sick chip is not a code bug


def test_null_value_latest_is_skipped(tmp_path):
    paths = _rounds(tmp_path, [10.0, 10.5]) + [
        _capture(tmp_path, 3, [_record(value=None)])
    ]
    (row,) = bench_regress.check_trajectory(bench_regress.load_trajectory(paths))
    assert row["status"] == bench_regress.SKIPPED_NO_VALUE


def test_insufficient_history_is_reported_not_judged(tmp_path):
    paths = _rounds(tmp_path, [10.0, 20.0])  # one prior round < min_history=2
    (row,) = bench_regress.check_trajectory(bench_regress.load_trajectory(paths))
    assert row["status"] == bench_regress.SKIPPED_NO_HISTORY
    assert bench_regress.main(paths + ["--check"]) == 0


def test_rerun_records_do_not_double_count(tmp_path):
    """The end-of-suite re-emission (tagged ``rerun``) and the pre-tag
    literal duplicates both collapse to one record per config per round."""
    records = [
        _record("m", 10.0),
        _record("other", 5.0),
        # the final re-emitted block: tagged copies
        _record("m", 10.0, rerun=True),
        _record("other", 5.0, rerun=True),
    ]
    path = _capture(tmp_path, 1, records)
    n, by_metric = bench_regress.load_round(path)
    assert n == 1 and set(by_metric) == {"m", "other"}
    assert "rerun" not in by_metric["m"]
    # pre-tag captures: identical duplicate lines keep the last occurrence
    legacy = _capture(tmp_path, 2, [_record("m", 10.0), _record("m", 10.0)])
    _, by_metric = bench_regress.load_round(legacy)
    assert by_metric["m"]["value"] == 10.0


def test_truncated_tail_lines_are_dropped(tmp_path):
    # the driver records a bounded tail: the first line is typically cut
    path = _capture(
        tmp_path, 1, [_record("m", 10.0)],
        tail_prefix='p_fused", "value": 3.878, "unit": "us/step"}\n',
    )
    _, by_metric = bench_regress.load_round(path)
    assert set(by_metric) == {"m"}


def test_jsonl_and_list_formats_also_load(tmp_path):
    jsonl = tmp_path / "BENCH_r07.json"
    jsonl.write_text("\n".join(json.dumps(_record("m", v)) for v in (1.0, 2.0)))
    n, by_metric = bench_regress.load_round(str(jsonl))
    assert n == 7 and by_metric["m"]["value"] == 2.0  # last wins
    aslist = tmp_path / "BENCH_r08.json"
    aslist.write_text(json.dumps([_record("m", 3.0), _record("k", 4.0)]))
    n, by_metric = bench_regress.load_round(str(aslist))
    assert n == 8 and by_metric["m"]["value"] == 3.0 and by_metric["k"]["value"] == 4.0


def test_new_config_in_latest_round_cannot_fail(tmp_path):
    paths = _rounds(tmp_path, [10.0, 10.0, 10.0])
    extra = _capture(tmp_path, 4, [_record("m", 10.0), _record("brand_new", 99.0)])
    rows = bench_regress.check_trajectory(bench_regress.load_trajectory(paths + [extra]))
    by_metric = {r["metric"]: r for r in rows}
    assert by_metric["m"]["status"] == bench_regress.OK
    assert by_metric["brand_new"]["status"] == bench_regress.SKIPPED_NO_HISTORY


def test_per_config_tolerance_override_widens_only_the_named_band(tmp_path):
    """Satellite: a noisy config's own +100% band lets its 1.8x latest pass
    while a second config at the same delta still fails the global +50%."""
    paths = [
        _capture(tmp_path, i + 1, [_record("noisy", 10.0), _record("steady", 10.0)])
        for i in range(3)
    ]
    paths.append(_capture(tmp_path, 4, [_record("noisy", 18.0), _record("steady", 18.0)]))
    trajectory = bench_regress.load_trajectory(paths)

    # no override: both 1.8x deltas regress at the +50% default
    rows = {r["metric"]: r for r in bench_regress.check_trajectory(trajectory)}
    assert rows["noisy"]["status"] == bench_regress.REGRESSED
    assert rows["steady"]["status"] == bench_regress.REGRESSED

    rows = {
        r["metric"]: r
        for r in bench_regress.check_trajectory(
            trajectory, tolerance_overrides={"noisy": 1.0}
        )
    }
    assert rows["noisy"]["status"] == bench_regress.OK
    assert rows["noisy"]["tolerance"] == 1.0
    assert rows["steady"]["status"] == bench_regress.REGRESSED
    assert rows["steady"]["tolerance"] == bench_regress.DEFAULT_TOLERANCE

    # CLI: the override flips the exit code once it also covers "steady",
    # and the rendered table shows the per-config band
    assert bench_regress.main(paths + ["--check", "--tolerance-config", "noisy=1.0"]) == 1
    assert (
        bench_regress.main(
            paths + ["--check", "--tolerance-config", "noisy=1.0",
                     "--tolerance-config", "steady=100%"]
        )
        == 0
    )
    table = bench_regress.render_table(
        bench_regress.check_trajectory(trajectory, tolerance_overrides={"noisy": 1.0}),
        bench_regress.DEFAULT_TOLERANCE,
    )
    assert "+100%" in table and "+50%" in table and "1 per-config override" in table


def test_tolerance_sidecar_file_and_flag_precedence(tmp_path):
    sidecar = tmp_path / "tolerances.json"
    sidecar.write_text(json.dumps({"noisy": 0.8, "other": "25%"}))
    overrides = bench_regress.parse_tolerance_overrides([], str(sidecar))
    assert overrides == {"noisy": 0.8, "other": 0.25}
    # explicit flags win over the sidecar
    overrides = bench_regress.parse_tolerance_overrides(["noisy=2.0"], str(sidecar))
    assert overrides["noisy"] == 2.0 and overrides["other"] == 0.25
    paths = _rounds(tmp_path, [10.0, 10.0, 10.0, 18.0], metric="noisy")
    assert bench_regress.main(paths + ["--check", "--tolerance-file", str(sidecar)]) == 0


def test_tolerance_parse_errors_are_descriptive(tmp_path):
    with pytest.raises(ValueError, match="NAME=PCT"):
        bench_regress.parse_tolerance_overrides(["missing-equals"])
    with pytest.raises(ValueError, match=">= 0"):
        bench_regress.parse_tolerance_overrides(["m=-0.5"])
    assert bench_regress.parse_tolerance("80%") == pytest.approx(0.8)
    bad = tmp_path / "bad.json"
    bad.write_text("[1, 2]")
    with pytest.raises(ValueError, match="JSON object"):
        bench_regress.parse_tolerance_overrides([], str(bad))
    # CLI surfaces parse failures as exit 2, not a traceback
    paths = _rounds(tmp_path, [10.0, 10.0, 10.0])
    assert bench_regress.main(paths + ["--tolerance-config", "bogus"]) == 2


def test_committed_trajectory_passes():
    """Acceptance: ``bench_regress --check`` stays green on the repo's own
    BENCH_r01..r05 history."""
    import glob

    paths = sorted(glob.glob(os.path.join(REPO_ROOT, "BENCH_r*.json")))
    assert len(paths) >= 5
    assert bench_regress.main(paths + ["--check"]) == 0
    rows = bench_regress.check_trajectory(bench_regress.load_trajectory(paths))
    assert any(r["metric"] == "metric_collection_update_step_fused" for r in rows)
    assert all(r["status"] != bench_regress.REGRESSED for r in rows)


# ---------------------------------------------------------------------------
# the MULTICHIP_r* dryrun trajectory (satellite: gate both trajectories)
# ---------------------------------------------------------------------------


def _multichip_capture(tmp_path, n, rc=0, ok=None, skipped=False, n_devices=8):
    doc = {
        "n_devices": n_devices,
        "rc": rc,
        "ok": (rc == 0) if ok is None else ok,
        "skipped": skipped,
        "tail": "dryrun tail",
    }
    path = tmp_path / f"MULTICHIP_r{n:02d}.json"
    path.write_text(json.dumps(doc))
    return str(path)


def test_multichip_capture_adapts_to_record_shape(tmp_path):
    n, by_metric = bench_regress.load_multichip_round(
        _multichip_capture(tmp_path, 3, rc=0)
    )
    assert n == 3
    (rec,) = by_metric.values()
    assert rec["metric"] == "multichip_dryrun_8dev"
    assert rec["value"] == 0.0 and rec["unit"] == "rc" and rec["degraded"] is False


def test_multichip_skipped_capture_is_degraded(tmp_path):
    _, by_metric = bench_regress.load_multichip_round(
        _multichip_capture(tmp_path, 2, rc=0, skipped=True)
    )
    (rec,) = by_metric.values()
    assert rec["degraded"] is True
    # a degraded latest is skipped, not judged — same rule as bench records
    paths = [
        _multichip_capture(tmp_path, i, rc=0) for i in (3, 4, 5)
    ] + [_multichip_capture(tmp_path, 6, rc=0, skipped=True)]
    rows = bench_regress.check_trajectory(bench_regress.load_multichip_trajectory(paths))
    (row,) = rows
    assert row["status"] == bench_regress.SKIPPED_DEGRADED


def test_multichip_corrupt_capture_degrades_to_failure(tmp_path):
    path = tmp_path / "MULTICHIP_r07.json"
    path.write_text("not json at all")
    _, by_metric = bench_regress.load_multichip_round(str(path))
    (rec,) = by_metric.values()
    assert rec["value"] == 1.0  # unparseable capture cannot silently pass


def test_multichip_failed_latest_dryrun_regresses(tmp_path):
    """With a healthy rc=0 baseline, a latest rc=1 dryrun fails the gate —
    the zero baseline judges by sign (any positive latest regresses)."""
    paths = [_multichip_capture(tmp_path, i, rc=0) for i in (1, 2, 3)]
    paths.append(_multichip_capture(tmp_path, 4, rc=1))
    rows = bench_regress.check_trajectory(bench_regress.load_multichip_trajectory(paths))
    (row,) = rows
    assert row["status"] == bench_regress.REGRESSED
    assert row["baseline"] == 0.0 and row["delta_pct"] is None


def test_multichip_healthy_latest_passes_and_early_failure_does_not_poison(tmp_path):
    """An rc=1 round in the HISTORY (the committed r01 shape) does not move
    the median-of-healthy baseline; a healthy latest stays OK."""
    paths = [_multichip_capture(tmp_path, 1, rc=1)]
    paths += [_multichip_capture(tmp_path, i, rc=0) for i in (2, 3, 4, 5)]
    rows = bench_regress.check_trajectory(bench_regress.load_multichip_trajectory(paths))
    (row,) = rows
    assert row["status"] == bench_regress.OK and row["baseline"] == 0.0


def test_main_gates_both_trajectories_in_one_table(tmp_path, capsys):
    bench_paths = _rounds(tmp_path, [10.0, 11.0, 9.5, 10.5])
    mc_paths = [_multichip_capture(tmp_path, i, rc=0) for i in (1, 2, 3)]
    mc_paths.append(_multichip_capture(tmp_path, 4, rc=1))
    rc = bench_regress.main(bench_paths + ["--check", "--multichip"] + mc_paths)
    out = capsys.readouterr().out
    assert rc == 1  # the failed dryrun fails the combined gate
    assert "multichip_dryrun_8dev" in out and "m " in out


def test_main_explicit_bench_paths_skip_multichip_by_default(tmp_path):
    # hermetic unit runs: naming bench captures does not drag the committed
    # repo MULTICHIP trajectory into the table
    paths = _rounds(tmp_path, [10.0, 11.0, 9.5, 10.5])
    assert bench_regress.main(paths + ["--check"]) == 0


def test_committed_multichip_trajectory_passes():
    """Acceptance: the repo's own MULTICHIP_r01..r05 history stays green
    (r01's failed dryrun is history, not the latest round)."""
    import glob

    paths = sorted(glob.glob(os.path.join(REPO_ROOT, "MULTICHIP_r*.json")))
    assert len(paths) >= 5
    rows = bench_regress.check_trajectory(bench_regress.load_multichip_trajectory(paths))
    assert rows and all(r["status"] != bench_regress.REGRESSED for r in rows)
    # ... and the default no-args gate (make bench-regress) judges BOTH
    # committed trajectories green
    assert bench_regress.main(["--check"]) == 0


def test_partial_latest_round_still_judges_absent_configs(tmp_path):
    """A partial newest round (a capture that re-measured only new configs,
    e.g. BENCH_r06's transport records) must not shrink the judged set: a
    config absent from it is judged at its newest record anywhere in the
    trajectory, against the rounds before that record."""
    paths = [
        _capture(tmp_path, 1, [_record("old", 10.0), _record("stale_reg", 10.0)]),
        _capture(tmp_path, 2, [_record("old", 10.5), _record("stale_reg", 10.5)]),
        _capture(tmp_path, 3, [_record("old", 11.0), _record("stale_reg", 25.0)]),
        # the partial round: ONLY the new config
        _capture(tmp_path, 4, [_record("new", 5.0)]),
    ]
    rows = bench_regress.check_trajectory(bench_regress.load_trajectory(paths))
    by_metric = {r["metric"]: r for r in rows}
    assert set(by_metric) == {"old", "stale_reg", "new"}
    # "old": newest record is r3, judged against the r1/r2 median — OK
    assert by_metric["old"]["status"] == bench_regress.OK
    assert by_metric["old"]["round"] == 3
    # "stale_reg": its r3 regression is still CAUGHT despite the partial r4
    assert by_metric["stale_reg"]["status"] == bench_regress.REGRESSED
    # "new": first appearance — reported, not judged
    assert by_metric["new"]["status"] == bench_regress.SKIPPED_NO_HISTORY
    assert bench_regress.main(paths + ["--check"]) == 1


def test_dispatch_path_never_cross_compares():
    """A pallas record must not be judged against xla history (and vice
    versa): the trajectory here would read as a 100x regression if the paths
    cross-compared, but the xla rounds are simply a different program."""
    import bench_regress

    def rec(value, path=None, metric="pallas_scatter_step"):
        out = {"metric": metric, "value": value, "unit": "us/step"}
        if path is not None:
            out["dispatch_path"] = path
        return out

    rounds = [
        (1, {"pallas_scatter_step": rec(100.0, "xla")}),
        (2, {"pallas_scatter_step": rec(102.0, "xla")}),
        (3, {"pallas_scatter_step": rec(98.0, "xla")}),
        # the first TPU capture: 10x faster AND a different program
        (4, {"pallas_scatter_step": rec(9.0, "pallas")}),
    ]
    rows = bench_regress.check_trajectory(rounds, min_history=2)
    (row,) = rows
    # no xla round votes into the pallas baseline: insufficient same-path history
    assert row["status"] == bench_regress.SKIPPED_NO_HISTORY
    assert row["history"] == 0

    # same-path history judges normally
    rounds.append((5, {"pallas_scatter_step": rec(9.5, "pallas")}))
    rounds.append((6, {"pallas_scatter_step": rec(9.2, "pallas")}))
    rounds.append((7, {"pallas_scatter_step": rec(9.4, "pallas")}))
    rows = bench_regress.check_trajectory(rounds, min_history=2)
    (row,) = rows
    assert row["status"] == bench_regress.OK
    assert row["history"] == 3  # only the pallas rounds (r4-r6) vote
