"""Utility-layer tests (reference: ``tests/test_utilities.py`` covers the
rank-zero prints; ``tests/functional/test_reduction.py`` covers
``reduce``/``class_reduce``; tensor-helper coverage added on top)."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.utilities import class_reduce, rank_zero_debug, rank_zero_info, rank_zero_warn, reduce
from metrics_tpu.utilities.data import (
    select_topk,
    to_categorical,
    to_onehot,
)


def test_prints():
    rank_zero_debug("DEBUG")
    rank_zero_info("INFO")
    with pytest.warns(UserWarning):
        rank_zero_warn("WARN")


def test_reduce():
    start = jnp.arange(50.0).reshape(5, 10)
    np.testing.assert_allclose(np.asarray(reduce(start, "elementwise_mean")), np.mean(np.asarray(start)))
    np.testing.assert_allclose(np.asarray(reduce(start, "sum")), np.sum(np.asarray(start)))
    np.testing.assert_allclose(np.asarray(reduce(start, "none")), np.asarray(start))
    with pytest.raises(ValueError):
        reduce(start, "error_reduction")


def test_class_reduce():
    num = jnp.asarray([2.0, 3.0, 5.0])
    denom = jnp.asarray([4.0, 6.0, 10.0])
    weights = jnp.asarray([10.0, 20.0, 30.0])

    np.testing.assert_allclose(np.asarray(class_reduce(num, denom, weights, "micro")), 10.0 / 20.0)
    np.testing.assert_allclose(np.asarray(class_reduce(num, denom, weights, "macro")), 0.5)
    np.testing.assert_allclose(
        np.asarray(class_reduce(num, denom, weights, "weighted")),
        np.sum(np.asarray(num / denom) * np.asarray(weights / weights.sum())),
    )
    np.testing.assert_allclose(np.asarray(class_reduce(num, denom, weights, "none")), [0.5, 0.5, 0.5])


def test_class_reduce_nan_zeroing():
    # 0/0 classes contribute 0, not NaN (parity: utilities/distributed.py:44-89)
    num = jnp.asarray([0.0, 1.0])
    denom = jnp.asarray([0.0, 2.0])
    weights = jnp.asarray([0.0, 2.0])
    out = np.asarray(class_reduce(num, denom, weights, "macro"))
    np.testing.assert_allclose(out, (0.0 + 0.5) / 2)


def test_onehot():
    test_tensor = jnp.stack([jnp.arange(5), jnp.arange(5)])
    expected = np.stack([np.eye(5, dtype=int)] * 2)  # (2, C, 5): identity per row
    onehot = to_onehot(test_tensor, num_classes=5)
    assert onehot.shape == (2, 5, 5)
    np.testing.assert_array_equal(np.asarray(onehot), expected)
    # inferred num_classes (eager)
    np.testing.assert_array_equal(np.asarray(to_onehot(test_tensor)), expected)


def test_onehot_bool_input():
    out = to_onehot(jnp.asarray([True, False]), num_classes=2)
    np.testing.assert_array_equal(np.asarray(out), [[0, 1], [1, 0]])


def test_to_categorical():
    probs = jnp.asarray([[0.1, 0.9], [0.8, 0.2]])
    np.testing.assert_array_equal(np.asarray(to_categorical(probs)), [1, 0])


def test_select_topk():
    probs = jnp.asarray([[0.1, 0.5, 0.4], [0.6, 0.1, 0.3]])
    np.testing.assert_array_equal(np.asarray(select_topk(probs, 1)), [[0, 1, 0], [1, 0, 0]])
    np.testing.assert_array_equal(np.asarray(select_topk(probs, 2)), [[0, 1, 1], [1, 0, 1]])
