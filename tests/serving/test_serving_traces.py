"""Request-scoped serving traces: every ingest runs submit → wait →
dispatch (→ read) as ``serving`` spans, the dispatch span carries the
admitted cohorts' submit-span ids as its correlation keys, and
``timeline.export`` renders the chain on the ``<serving>`` track with flow
arrows — pinned against the ``check_trace`` serving-trace contract."""
import json
import os
import sys

import numpy as np
import pytest

from metrics_tpu import observability
from metrics_tpu.observability import timeline
from metrics_tpu.observability.tracing import TRACER
from metrics_tpu.serving import AdmissionQueue, SLOScheduler
from metrics_tpu.serving.queue import SPAN_COHORT_CAP

_SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "scripts"
)
if _SCRIPTS not in sys.path:
    sys.path.insert(0, _SCRIPTS)
import check_trace  # noqa: E402


@pytest.fixture(autouse=True)
def clean_observability():
    observability.reset()
    observability.enable()
    yield
    observability.reset()
    observability.enable()


def _serving_spans(bucket=None):
    spans = [s for s in TRACER.records() if s.kind == "serving"]
    if bucket is None:
        return spans
    return [s for s in spans if s.bucket == bucket]


def _drain(q, n):
    total = 0
    while total < n:
        got = q._flush_once("manual")
        if got == 0:
            break
        total += got
    return total


def test_submit_span_carries_admission_accounting():
    q = AdmissionQueue(lambda *a: None, max_batch=8, start=False, capacity_rows=8,
                       policy="shed_oldest")
    q.submit_many(np.arange(12), np.zeros(12, np.float32))
    (span,) = _serving_spans("submit")
    assert span.group == q.telemetry_key
    assert span.payload["rows"] == 12
    # shed_oldest evicted 4 residents, but all 12 of THIS cohort were let in
    assert span.payload["admitted"] == 12
    assert span.payload["shed"] == 0
    assert span.exit_s >= span.enter_s


def test_dispatch_span_links_back_to_its_submit_cohorts():
    q = AdmissionQueue(lambda *a: None, max_batch=64, start=False)
    q.submit_many(np.arange(4), np.zeros(4, np.float32))
    q.submit_many(np.arange(4), np.ones(4, np.float32))
    assert _drain(q, 8) == 8

    submit_ids = [s.span_id for s in _serving_spans("submit")]
    assert len(submit_ids) == 2 and len(set(submit_ids)) == 2

    (wait,) = _serving_spans("wait")
    (dispatch,) = _serving_spans("dispatch")
    # the correlation key: every admitted cohort's submit span id rides the
    # dispatch span payload, in admission order, none dropped at this scale
    assert dispatch.payload["cohorts"] == submit_ids
    assert dispatch.payload["dropped_cohorts"] == 0
    assert dispatch.payload["rows"] == 8 and dispatch.payload["error"] is None
    # the retro-dated chain tiles the ingest interval: submit-enter <=
    # wait-enter < wait-exit == dispatch-enter <= dispatch-exit
    assert wait.exit_s == pytest.approx(dispatch.enter_s, abs=5e-3)
    assert wait.enter_s <= wait.exit_s <= dispatch.exit_s
    assert q.last_dispatch_span() == dispatch.span_id


def test_cohort_list_is_capped_with_explicit_drop_count():
    q = AdmissionQueue(lambda *a: None, max_batch=1024, start=False,
                       capacity_rows=4096)
    n = SPAN_COHORT_CAP + 3
    for i in range(n):  # one single-row cohort each -> n distinct submit spans
        q.submit_many([i], np.zeros(1, np.float32))
    assert _drain(q, n) == n
    (dispatch,) = _serving_spans("dispatch")
    assert len(dispatch.payload["cohorts"]) == SPAN_COHORT_CAP
    assert dispatch.payload["dropped_cohorts"] == 3


def test_read_span_references_the_serving_flush():
    svc = SLOScheduler(_metric(), max_batch=8, max_delay_ms=10_000.0, start=False)
    try:
        svc.submit(2, 5.0)
        svc.read(max_staleness_s=0.0)  # miss: flush + recompute
        svc.read([2])  # fresh hit off the cache the flush produced
    finally:
        svc.close()
    reads = _serving_spans("read")
    assert len(reads) >= 2
    hit = reads[-1]
    assert hit.payload["outcome"] in ("cache_hit", "fresh_hit", "recompute", "stale_hit")
    assert "staleness_s" in hit.payload
    # the read joins the request chain: its flush_span names the dispatch
    # span whose flush produced the cache it served
    dispatch_ids = {s.span_id for s in _serving_spans("dispatch")}
    assert hit.payload["flush_span"] in dispatch_ids


def _metric():
    class _M:
        def __init__(self, n=8):
            self.sums = np.zeros(n)

        def update(self, tenant_ids, values):
            np.add.at(self.sums, np.asarray(tenant_ids), np.asarray(values))

        def compute(self):
            return self.sums.copy()

        def clone(self):
            m = _M(len(self.sums))
            m.sums = self.sums.copy()
            return m

    return _M()


def test_disabled_tracer_records_no_serving_spans():
    observability.disable()
    try:
        q = AdmissionQueue(lambda *a: None, max_batch=8, start=False)
        q.submit_many(np.arange(4), np.zeros(4, np.float32))
        _drain(q, 4)
        assert _serving_spans() == []
        assert q.last_dispatch_span() is None
    finally:
        observability.enable()


# ---------------------------------------------------------------------------
# the exported timeline: serving track + flow arrows, checker-pinned
# ---------------------------------------------------------------------------


def _export_served_timeline(tmp_path):
    svc = SLOScheduler(_metric(), max_batch=4, max_delay_ms=10_000.0, start=False)
    try:
        svc.submit_many(np.arange(4), np.arange(4, dtype=np.float64))
        svc.read(max_staleness_s=0.0)
    finally:
        svc.close()
    path = timeline.export(str(tmp_path / "serving.json"))
    with open(path) as fh:
        return path, json.load(fh)


def test_timeline_export_renders_the_serving_track(tmp_path):
    path, doc = _export_served_timeline(tmp_path)
    # the general Chrome-trace contract AND the serving-specific one
    assert check_trace.validate_chrome_trace(doc) == []
    assert check_trace.validate_serving_trace(doc) == []

    events = doc["traceEvents"]
    # span slices only — the event-log's serving flush events share the
    # "serving" category but render on their own per-metric tracks
    slices = [
        e for e in events
        if e.get("ph") == "X" and e.get("cat") == "serving"
        and str(e.get("name", "")).startswith("serving.")
    ]
    names = {e["name"] for e in slices}
    assert {"serving.submit", "serving.wait", "serving.dispatch", "serving.read"} <= names
    # every serving slice sits on the named <serving> track
    tids = {e["tid"] for e in slices}
    assert len(tids) == 1
    (tid,) = tids
    assert any(
        e.get("ph") == "M" and e.get("name") == "thread_name"
        and e.get("tid") == tid and e["args"]["name"] == "<serving>"
        for e in events
    )
    # the request chain renders as paired flow arrows (submit -> dispatch)
    flows = [e for e in events if e.get("cat") == "serving_flow"]
    starts = [e for e in flows if e["ph"] == "s"]
    finishes = [e for e in flows if e["ph"] == "f"]
    assert starts and len(starts) == len(finishes)
    assert {e["id"] for e in starts} == {e["id"] for e in finishes}
    # slices carry the span payloads as args for the viewer
    dispatch = next(e for e in slices if e["name"] == "serving.dispatch")
    assert dispatch["args"]["rows"] == 4


def test_validate_serving_trace_flags_missing_stages():
    # a trace without the serving track at all
    doc = {"traceEvents": []}
    errs = check_trace.validate_serving_trace(doc)
    assert any("<serving>" in e for e in errs)
    assert any("serving.submit" in e for e in errs)
    # a named track missing one stage and the flow arrows is still flagged
    doc = {
        "traceEvents": [
            {"ph": "M", "name": "thread_name", "pid": 0, "tid": 9,
             "args": {"name": "<serving>"}},
            {"ph": "X", "cat": "serving", "name": "serving.submit",
             "pid": 0, "tid": 9, "ts": 1.0, "dur": 1.0},
        ]
    }
    errs = check_trace.validate_serving_trace(doc)
    assert any("serving.dispatch" in e for e in errs)
    assert not any("serving.submit" in e for e in errs)
