"""AdmissionQueue: micro-batch coalescing, backpressure policies, and the
exact shed accounting the zero-lost-updates invariant stands on.

The queue is host-side threading code; these tests drive it with a recording
target (no jax needed for the mechanics) and with a real ``KeyedMetric`` for
the end-to-end ingest ledger, and pin:

* size- AND deadline-triggered flushes — a full ``max_batch`` dispatches at
  once, a lone row dispatches within ``max_delay_ms``;
* each policy's capacity behavior with per-reason shed accounting
  (``block`` waits/sheds on timeout, ``shed_oldest`` evicts the oldest
  resident rows, ``shed_tenant_over_quota`` isolates hot tenants);
* the internal invariant ``admitted == dispatched + shed_dispatch_error +
  resident`` at every quiescent point, including through dispatch errors;
* the ``serving.*`` snapshot/Prometheus/event surfaces.
"""
import threading
import time

import numpy as np
import pytest

from metrics_tpu import observability
from metrics_tpu.serving import AdmissionQueue, QueueClosedError
from metrics_tpu.serving.policy import AdmissionPolicy, resolve_policy


class _Recorder:
    """Flush target that records every dispatched cohort."""

    def __init__(self, fail_times: int = 0, delay_s: float = 0.0):
        self.calls = []
        self.fail_times = fail_times
        self.delay_s = delay_s
        self.lock = threading.Lock()

    def __call__(self, ids, *cols):
        if self.delay_s:
            time.sleep(self.delay_s)
        with self.lock:
            if self.fail_times > 0:
                self.fail_times -= 1
                raise RuntimeError("injected dispatch failure")
            self.calls.append((np.asarray(ids).copy(), [np.asarray(c).copy() for c in cols]))

    @property
    def rows(self):
        with self.lock:
            return sum(len(ids) for ids, _ in self.calls)


def _assert_invariant(q):
    """The conservation laws of the exact ledger (see ``stats()``)."""
    s = q.stats()
    post_admission = s["shed_by_reason"].get("dispatch_error", 0) + s[
        "shed_by_reason"
    ].get("shed_oldest", 0)
    # rows shed AFTER admission are the only gap between admitted and
    # dispatched+resident ...
    assert s["admitted"] == s["dispatched"] + s["resident"] + post_admission, s
    # ... and end to end: submitted − shed(total) == dispatched + resident
    assert s["submitted"] - s["shed"] == s["dispatched"] + s["resident"], s


# ---------------------------------------------------------------- policies


def test_resolve_policy_validates():
    with pytest.raises(ValueError, match="one of"):
        resolve_policy("drop_everything")
    with pytest.raises(ValueError, match="block_timeout_s"):
        AdmissionPolicy("block", block_timeout_s=-1)
    with pytest.raises(ValueError, match="tenant_quota_rows"):
        AdmissionPolicy("shed_tenant_over_quota", tenant_quota_rows=0)
    with pytest.raises(ValueError, match="inside the AdmissionPolicy"):
        resolve_policy(AdmissionPolicy("block"), block_timeout_s=1.0)
    assert "shed_oldest" in repr(AdmissionPolicy("shed_oldest"))


def test_queue_constructor_validates():
    with pytest.raises(TypeError, match="callable"):
        AdmissionQueue(None)
    with pytest.raises(ValueError, match="max_batch"):
        AdmissionQueue(lambda *a: None, max_batch=0)
    with pytest.raises(ValueError, match="max_delay_ms"):
        AdmissionQueue(lambda *a: None, max_delay_ms=0)
    with pytest.raises(ValueError, match="capacity_rows"):
        AdmissionQueue(lambda *a: None, max_batch=8, capacity_rows=4)


# ---------------------------------------------------------------- triggers


def test_size_triggered_flush_coalesces_exactly_max_batch():
    rec = _Recorder()
    q = AdmissionQueue(rec, max_batch=8, max_delay_ms=10_000.0, start=False)
    admitted = q.submit_many(np.arange(8), np.arange(8, dtype=np.float32))
    assert admitted == 8
    assert q._flush_once("size") == 8
    ids, cols = rec.calls[0]
    np.testing.assert_array_equal(ids, np.arange(8))
    np.testing.assert_array_equal(cols[0], np.arange(8, dtype=np.float32))
    _assert_invariant(q)
    assert q.stats()["flushes"] == 1


def test_deadline_triggered_flush_dispatches_partial_batch():
    rec = _Recorder()
    q = AdmissionQueue(rec, max_batch=1024, max_delay_ms=20.0)
    q.submit(3, np.float32(0.5))
    deadline = time.monotonic() + 5.0
    while rec.rows < 1 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert rec.rows == 1  # one row flushed without ever reaching max_batch
    s = q.stats()
    assert s["flushes"] == 1 and s["resident"] == 0
    _assert_invariant(q)
    q.close()


def test_size_trigger_fires_before_deadline():
    rec = _Recorder()
    q = AdmissionQueue(rec, max_batch=4, max_delay_ms=60_000.0)
    q.submit_many(np.arange(4), np.zeros(4, np.float32))
    deadline = time.monotonic() + 5.0
    while rec.rows < 4 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert rec.rows == 4  # the deadline (60 s) can not have fired
    q.close()


def test_submit_many_validates_column_shapes():
    q = AdmissionQueue(_Recorder(), start=False)
    with pytest.raises(ValueError, match="one entry per row"):
        q.submit_many([1, 2], np.zeros(3))
    assert q.submit_many([], np.zeros(0)) == 0


# ---------------------------------------------------------------- policies @ capacity


def test_block_policy_waits_for_room():
    rec = _Recorder()
    q = AdmissionQueue(rec, max_batch=4, max_delay_ms=5.0, capacity_rows=4, policy="block")
    # 8 rows through a 4-row queue: the producer blocks until the flusher
    # drains room; nothing is ever shed
    admitted = q.submit_many(np.arange(8) % 4, np.zeros(8, np.float32))
    assert admitted == 8
    assert q.drain(5.0)
    s = q.stats()
    assert s["shed"] == 0 and s["dispatched"] == 8
    _assert_invariant(q)
    q.close()


def test_block_policy_timeout_sheds_exactly():
    rec = _Recorder()
    q = AdmissionQueue(
        rec, max_batch=4, max_delay_ms=10_000.0, capacity_rows=4,
        policy="block", block_timeout_s=0.05, start=False,
    )
    assert q.submit_many(np.arange(4), np.zeros(4, np.float32)) == 4
    t0 = time.monotonic()
    assert q.submit(0, np.float32(0.0)) is False  # full, no flusher: times out
    assert time.monotonic() - t0 >= 0.04
    s = q.stats()
    assert s["shed_by_reason"] == {"block_timeout": 1}
    assert s["admitted"] == 4 and s["shed"] == 1
    _assert_invariant(q)


def test_shed_oldest_evicts_oldest_rows():
    rec = _Recorder()
    q = AdmissionQueue(
        rec, max_batch=4, max_delay_ms=10_000.0, capacity_rows=4,
        policy="shed_oldest", start=False,
    )
    q.submit_many([0, 1, 2, 3], np.arange(4, dtype=np.float32))
    q.submit_many([4, 5], np.asarray([4.0, 5.0], np.float32))
    s = q.stats()
    # rows 0 and 1 (the oldest) were evicted to admit 4 and 5
    assert s["shed_by_reason"] == {"shed_oldest": 2}
    assert s["admitted"] == 6 and s["resident"] == 4
    q.flush()
    ids, cols = rec.calls[0]
    np.testing.assert_array_equal(ids, [2, 3, 4, 5])
    np.testing.assert_array_equal(cols[0], [2.0, 3.0, 4.0, 5.0])
    _assert_invariant(q)


def test_shed_tenant_over_quota_isolates_hot_tenant():
    rec = _Recorder()
    q = AdmissionQueue(
        rec, max_batch=64, max_delay_ms=10_000.0, capacity_rows=64,
        policy="shed_tenant_over_quota", tenant_quota_rows=3, start=False,
    )
    # tenant 7 floods; tenants 1..3 trickle — the flood is capped at quota,
    # the trickle is untouched
    admitted_hot = q.submit_many(np.full(10, 7), np.zeros(10, np.float32))
    admitted_cold = q.submit_many([1, 2, 3], np.zeros(3, np.float32))
    assert admitted_hot == 3 and admitted_cold == 3
    s = q.stats()
    assert s["shed_by_reason"] == {"tenant_over_quota": 7}
    q.flush()
    ids, _ = rec.calls[0]
    assert (ids == 7).sum() == 3
    _assert_invariant(q)


def test_shed_tenant_over_quota_full_queue_sheds_incoming():
    q = AdmissionQueue(
        _Recorder(), max_batch=4, max_delay_ms=10_000.0, capacity_rows=4,
        policy="shed_tenant_over_quota", tenant_quota_rows=2, start=False,
    )
    q.submit_many([0, 1, 2, 3], np.zeros(4, np.float32))
    assert q.submit(4, np.float32(0.0)) is False
    assert q.stats()["shed_by_reason"] == {"queue_full": 1}
    _assert_invariant(q)


def test_quota_default_derived_from_capacity():
    q = AdmissionQueue(
        _Recorder(), max_batch=4, capacity_rows=64,
        policy="shed_tenant_over_quota", start=False,
    )
    assert q.policy.tenant_quota_rows == 8  # capacity_rows // 8


# ---------------------------------------------------------------- errors / lifecycle


def test_dispatch_error_rows_are_accounted_shed():
    rec = _Recorder(fail_times=1)
    q = AdmissionQueue(rec, max_batch=4, max_delay_ms=10_000.0, start=False)
    q.submit_many(np.arange(4), np.zeros(4, np.float32))
    with pytest.warns(UserWarning, match="dispatch failed"):
        q.flush()
    q.submit_many(np.arange(4), np.zeros(4, np.float32))
    q.flush()  # second cohort succeeds
    s = q.stats()
    assert s["shed_by_reason"] == {"dispatch_error": 4}
    assert s["dispatched"] == 4 and s["admitted"] == 8
    assert "injected dispatch failure" in s["last_error"]
    _assert_invariant(q)


def test_closed_queue_rejects_submissions():
    q = AdmissionQueue(_Recorder(), max_batch=4)
    q.submit(0, np.float32(1.0))
    q.close()
    with pytest.raises(QueueClosedError):
        q.submit(0, np.float32(1.0))
    s = q.stats()
    assert s["closed"] is True and s["resident"] == 0 and s["dispatched"] == 1


def test_close_flushes_residue():
    rec = _Recorder()
    q = AdmissionQueue(rec, max_batch=1024, max_delay_ms=60_000.0)
    q.submit_many(np.arange(5), np.zeros(5, np.float32))
    q.close()
    assert rec.rows == 5


def test_drain_timeout_returns_false():
    rec = _Recorder(delay_s=0.5)
    q = AdmissionQueue(rec, max_batch=2, max_delay_ms=1.0)
    q.submit_many([0, 1], np.zeros(2, np.float32))
    assert q.drain(0.05) is False
    assert q.drain(5.0) is True
    q.close()


def test_concurrent_producers_lose_nothing():
    rec = _Recorder()
    q = AdmissionQueue(rec, max_batch=64, max_delay_ms=2.0, capacity_rows=512, policy="block")
    threads = [
        threading.Thread(
            target=lambda: [q.submit(i % 32, np.float32(i)) for i in range(200)]
        )
        for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert q.drain(10.0)
    s = q.stats()
    assert s["admitted"] == 800 and s["shed"] == 0
    assert rec.rows == 800
    _assert_invariant(q)
    q.close()


# ---------------------------------------------------------------- telemetry


def test_serving_snapshot_events_and_prometheus():
    observability.reset()
    rec = _Recorder()
    q = AdmissionQueue(
        rec, max_batch=4, max_delay_ms=10_000.0, capacity_rows=4,
        policy="shed_oldest", start=False,
    )
    q.submit_many(np.arange(6), np.zeros(6, np.float32))  # 2 evictions
    q.flush()
    snap = observability.snapshot()
    serving = snap["serving"]
    assert serving["admitted_rows"] >= 6
    assert serving["shed_by_reason"].get("shed_oldest", 0) >= 2
    assert serving["flushes_by_trigger"].get("manual", 0) >= 1
    assert serving["shed_rows"] == sum(serving["shed_by_reason"].values())
    # fast-path histograms materialized with the serving series
    hists = snap["histograms"]
    assert any(k.startswith("serving_flush_seconds") for k in hists)
    assert any(k.startswith("serving_ingest_seconds") for k in hists)
    assert any(k.startswith("serving_queue_depth") for k in hists)
    # serving events landed on the timeline
    kinds = [e.kind for e in observability.EVENTS.events()]
    assert "serving" in kinds
    text = observability.render_prometheus(snap)
    assert "metrics_tpu_serving_admitted_rows_total" in text
    assert 'metrics_tpu_serving_shed_by_reason_total{reason="shed_oldest"}' in text
    assert 'metrics_tpu_serving_flushes_by_trigger_total{trigger="manual"}' in text
    import json

    assert json.loads(json.dumps(snap))["serving"] == serving


def test_serving_section_merges_by_declared_rules():
    from metrics_tpu.observability.aggregate import leaf_reduction, merge_snapshots

    assert leaf_reduction(("serving", "admitted_rows")) == "sum"
    assert leaf_reduction(("serving", "shed_by_reason", "shed_oldest")) == "sum"
    assert leaf_reduction(("serving", "depth_high_water")) == "max"
    a = {"serving": {"admitted_rows": 5, "depth_high_water": 9,
                     "shed_by_reason": {"shed_oldest": 2}}}
    b = {"serving": {"admitted_rows": 7, "depth_high_water": 3,
                     "shed_by_reason": {"shed_oldest": 1, "queue_full": 4}}}
    merged = merge_snapshots([a, b])["serving"]
    assert merged["admitted_rows"] == 12
    assert merged["depth_high_water"] == 9
    assert merged["shed_by_reason"] == {"shed_oldest": 3, "queue_full": 4}


def test_count_unit_histogram_layout():
    from metrics_tpu.observability.histogram import Log2Histogram

    h = Log2Histogram("count")
    assert h.bounds()[0] == 1.0 and h.bounds()[-1] == 2.0**20
    h.observe(5.0)  # -> bucket with upper bound 8
    h.observe(1.0)  # exact power of two: le semantics, bound 1
    d = h.to_dict()
    assert d["buckets"]["le_1"] == 1 and d["buckets"]["le_8"] == 1


def test_pad_to_bucket_dispatches_pow2_cohorts_with_discard_rows():
    rec = _Recorder()
    q = AdmissionQueue(
        rec, max_batch=8, max_delay_ms=10_000.0, pad_to_bucket=True, start=False
    )
    q.submit_many([4, 2, 9], np.asarray([1.0, 2.0, 3.0], np.float32))
    q.flush()
    ids, cols = rec.calls[0]
    assert len(ids) == 4  # 3 rows -> pow2 bucket of 4
    np.testing.assert_array_equal(ids, [4, 2, 9, -1])  # discard row appended
    np.testing.assert_array_equal(cols[0], [1.0, 2.0, 3.0, 0.0])
    s = q.stats()
    assert s["dispatched"] == 3  # padding rows are NOT accounted as traffic
    _assert_invariant(q)
    # a full batch is never padded
    q.submit_many(np.arange(8), np.zeros(8, np.float32))
    q.flush()
    ids, _ = rec.calls[1]
    assert len(ids) == 8 and (ids >= 0).all()


def test_pad_to_bucket_end_to_end_with_clip_and_drop_keyed_metric():
    """The padding contract end to end: a KeyedMetric built with
    validate_ids=False drops the -1 discard rows inside the compiled
    scatter, the ledger counts only real rows, and the executable cache
    stays bounded at one program per pow2 bucket."""
    from metrics_tpu import Accuracy, KeyedMetric

    m = KeyedMetric(Accuracy(), num_tenants=8, validate_ids=False)
    q = AdmissionQueue(m.update, max_batch=8, max_delay_ms=10_000.0,
                       pad_to_bucket=True, start=False)
    rng = np.random.RandomState(0)
    for n in (1, 3, 5, 7, 2, 6):  # six distinct cohort sizes...
        ids = rng.randint(0, 8, n)
        preds = rng.rand(n).astype(np.float32)
        q.submit_many(ids, preds, (preds > 0.5).astype(np.int32))
        q.flush()
    total = 1 + 3 + 5 + 7 + 2 + 6
    assert m.tenant_report()["rows_routed"] == total
    # ...but only 4 distinct dispatch shapes (pow2 buckets 1, 2, 4, 8)
    fn = m._keyed_update_fn or m._keyed_update_copy_fn
    assert fn._cache_size() <= 4
    _assert_invariant(q)
