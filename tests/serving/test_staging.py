"""Device-resident ingest: the columnar staging ring, the double-buffered
slot pool, and the staged AdmissionQueue flush path.

The staged path moves cohort formation to submit time (ring writes) and the
H2D transfer ahead of the dispatch (prefetch on the ``staging`` async lane),
so these tests pin what the refactor must NOT change:

* every conservation law of the exact ledger holds bit-for-bit on the staged
  path — through racing concurrent writers, dispatch errors, an open
  breaker, and quarantine sheds;
* N concurrent writers × racing flushes ingest EXACTLY what a serial
  referee ingests (integer data: per-tenant sums compare bit-identically
  even though cohort boundaries differ);
* pickle/clone drops every staging buffer (rings, slots, device twins) and
  the rebuilt object re-binds lazily;
* the staged hand-off semantics: pow2 pad folded in place (ids ``-1``),
  :class:`StagedColumn` views carrying the device twin only on the exact
  view the stager attached it to.
"""
import pickle
import threading

import numpy as np
import pytest

from metrics_tpu import observability
from metrics_tpu.serving import AdmissionQueue
from metrics_tpu.serving.staging import (
    StagedColumn,
    StagingRing,
    StagingSlotPool,
    as_staged,
    stage_layout,
)

from .test_queue import _Recorder, _assert_invariant


def _assert_staged_invariant(q):
    """All four post-admission shed reasons (test_queue's helper covers only
    the two its scenarios raise): every admitted row lands in exactly one of
    dispatched / resident / shed_oldest / dispatch_error / poisoned /
    breaker_open."""
    s = q.stats()
    reasons = s["shed_by_reason"]
    post = sum(
        reasons.get(k, 0)
        for k in ("shed_oldest", "dispatch_error", "poisoned", "breaker_open")
    )
    assert s["admitted"] == s["dispatched"] + s["resident"] + post, s
    assert s["submitted"] - s["shed"] == s["dispatched"] + s["resident"], s


# ------------------------------------------------------------- ring


class TestStagingRing:
    def test_capacity_rounds_to_pow2(self):
        assert StagingRing(1).capacity == 1
        assert StagingRing(5).capacity == 8
        assert StagingRing(64).capacity == 64
        with pytest.raises(ValueError, match="capacity_rows"):
            StagingRing(0)

    def test_lazy_bind_and_layout(self):
        r = StagingRing(8)
        assert not r.bound
        layout = stage_layout([np.zeros((4,), np.float32), np.zeros((4, 3), np.int32)])
        assert layout == (("float32", ()), ("int32", (3,)))
        r.bind(layout)
        assert r.bound
        assert r.cols[0].shape == (8,)
        assert r.cols[1].shape == (8, 3)

    def test_write_read_roundtrip_with_wraparound(self):
        r = StagingRing(8)
        r.bind(stage_layout([np.zeros((1,), np.float32)]))
        # push the head past capacity so the bulk write wraps
        r.alloc(6)
        seq0 = r.alloc(4)  # occupies indices 6,7,0,1
        tenants = np.asarray([10, 11, 12, 13], np.int32)
        cols = [np.asarray([1.0, 2.0, 3.0, 4.0], np.float32)]
        r.write_rows(seq0, tenants, 5.0, "c", cols)
        np.testing.assert_array_equal(r.read_ids(seq0, 4), tenants)

        class Slot:
            ids = np.empty(4, np.int32)
            t_submit = np.empty(4, np.float64)
            cohorts = np.empty(4, object)
            cols = [np.empty(4, np.float32)]

        r.copy_out(seq0, 4, Slot)
        np.testing.assert_array_equal(Slot.ids, tenants)
        np.testing.assert_array_equal(Slot.cols[0], cols[0])
        np.testing.assert_array_equal(Slot.t_submit, 5.0)
        assert list(Slot.cohorts) == ["c"] * 4

    def test_per_row_write_matches_bulk(self):
        bulk, single = StagingRing(8), StagingRing(8)
        layout = stage_layout([np.zeros((1,), np.float32)])
        bulk.bind(layout)
        single.bind(layout)
        tenants = np.asarray([1, 2, 3], np.int32)
        col = np.asarray([7.0, 8.0, 9.0], np.float32)
        s0 = bulk.alloc(3)
        bulk.write_rows(s0, tenants, 1.0, None, [col])
        for i in range(3):
            single.write_row(single.alloc(), int(tenants[i]), 1.0, None, (col[i],))
        np.testing.assert_array_equal(bulk.ids[:3], single.ids[:3])
        np.testing.assert_array_equal(bulk.cols[0][:3], single.cols[0][:3])

    def test_pickle_drops_buffers(self):
        r = StagingRing(16)
        r.bind(stage_layout([np.zeros((2,), np.float32)]))
        r.write_rows(
            r.alloc(2), np.asarray([1, 2], np.int32), 0.0, None,
            [np.asarray([1.0, 2.0], np.float32)],
        )
        clone = pickle.loads(pickle.dumps(r))
        assert clone.capacity == 16
        assert not clone.bound  # buffers are process-local scratch
        assert clone.head == 0


# ------------------------------------------------------------- slot pool


class TestStagingSlotPool:
    def test_needs_two_slots(self):
        with pytest.raises(ValueError, match=">= 2 slots"):
            StagingSlotPool(1, 8)

    def test_acquire_release_cycle(self):
        pool = StagingSlotPool(2, 4)
        pool.bind(stage_layout([np.zeros((1,), np.float32)]))
        a = pool.acquire()
        b = pool.try_acquire()
        assert a is not None and b is not None
        assert pool.in_use() == 2
        assert pool.try_acquire() is None  # exhausted: never blocks
        assert pool.acquire(timeout=0.01) is None  # bounded block
        pool.release(a)
        assert pool.in_use() == 1
        c = pool.acquire()
        assert c.index == a.index  # the freed slot comes back
        pool.release(b)
        pool.release(c)

    def test_rebind_bumps_generation(self):
        pool = StagingSlotPool(2, 4)
        pool.bind(stage_layout([np.zeros((1,), np.float32)]))
        a = pool.acquire()
        assert a.cols[0].shape == (4,)
        pool.release(a)
        pool.bind(stage_layout([np.zeros((1, 3), np.int32)]))
        b = pool.acquire()
        assert b.cols[0].shape == (4, 3)  # stale slot reallocated
        pool.release(b)

    def test_pickle_drops_slots(self):
        pool = StagingSlotPool(3, 8)
        pool.bind(stage_layout([np.zeros((1,), np.float32)]))
        a = pool.acquire()  # leave one slot checked out
        clone = pickle.loads(pickle.dumps(pool))
        assert clone.num_slots == 3 and clone.rows == 8
        assert clone.in_use() == 0  # rebuilt pool is all-free
        pool.release(a)


# ------------------------------------------------------------- staged column


class TestStagedColumn:
    def test_as_staged_none_is_passthrough(self):
        host = np.arange(4).astype(np.float32)
        assert as_staged(host, None) is host

    def test_twin_attached_and_dropped_on_derivation(self):
        host = np.arange(4).astype(np.float32)
        view = as_staged(host, "DEVICE")
        assert isinstance(view, StagedColumn)
        assert view.jax_array == "DEVICE"
        np.testing.assert_array_equal(np.asarray(view), host)
        # any derived view no longer matches the transferred buffer
        assert view[:2].jax_array is None
        assert (view + 1).jax_array is None
        assert view.copy().jax_array is None

    def test_pickle_drops_twin(self):
        view = as_staged(np.arange(3).astype(np.float32), "DEVICE")
        clone = pickle.loads(pickle.dumps(view))
        assert clone.jax_array is None
        np.testing.assert_array_equal(np.asarray(clone), np.asarray(view))


# ------------------------------------------------------------- staged queue


def _staged_queue(target, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("start", False)
    kw.setdefault("staging", True)
    return AdmissionQueue(target, **kw)


class TestStagedFlush:
    def test_rows_dispatch_with_device_twins(self):
        rec = _Recorder()
        q = _staged_queue(rec)
        seen = {}

        def target(ids, *cols):
            seen["ids_twin"] = getattr(ids, "jax_array", None)
            seen["col_twins"] = [getattr(c, "jax_array", None) for c in cols]
            rec(ids, *cols)

        q._target = target
        for i in range(8):
            q.submit(i, np.float32(i * 2))
        assert q._flush_once("manual") == 8
        _assert_invariant(q)
        ids, cols = rec.calls[0]
        np.testing.assert_array_equal(ids, np.arange(8))
        np.testing.assert_array_equal(cols[0], np.arange(8) * 2.0)
        # the hand-off carried pre-transferred device twins
        assert seen["ids_twin"] is not None
        assert all(t is not None for t in seen["col_twins"])
        np.testing.assert_array_equal(np.asarray(seen["ids_twin"]), ids)
        q.close()

    def test_transfer_off_hands_plain_owning_numpy(self):
        calls = []

        def target(ids, *cols):
            calls.append((ids, cols))

        q = _staged_queue(target, staging_transfer=False)
        for i in range(4):
            q.submit(i, np.float32(i))
        q._flush_once("manual")
        first_ids, first_cols = calls[0]
        assert type(first_ids) is np.ndarray  # no StagedColumn wrapper
        assert all(type(c) is np.ndarray for c in first_cols)
        # the hand-off owns its memory: later submits/flushes recycling the
        # same staging slot must not mutate the first cohort retroactively
        for i in range(4):
            q.submit(9, np.float32(99.0))
        q._flush_once("manual")
        np.testing.assert_array_equal(first_ids, np.arange(4))
        np.testing.assert_array_equal(first_cols[0], np.arange(4, dtype=np.float32))
        q.close()

    def test_pad_folds_into_slot(self):
        rec = _Recorder()
        q = _staged_queue(rec, max_batch=8, pad_to_bucket=True)
        for i in range(3):
            q.submit(i, np.float32(1.0))
        q._flush_once("manual")
        ids, cols = rec.calls[0]
        assert len(ids) == 4  # pow2 bucket
        np.testing.assert_array_equal(ids, [0, 1, 2, -1])
        np.testing.assert_array_equal(cols[0], [1.0, 1.0, 1.0, 0.0])
        _assert_invariant(q)
        assert q.stats()["dispatched"] == 3  # the pad row is not a row
        q.close()

    def test_schema_change_with_resident_rows_raises(self):
        q = _staged_queue(_Recorder())
        q.submit(0, np.float32(1.0))
        with pytest.raises(ValueError, match="schema"):
            q.submit(1, np.float32(1.0), np.float32(2.0))
        # the rejected cohort never skewed the ledger
        s = q.stats()
        assert s["submitted"] == 1 and s["admitted"] == 1
        q._flush_once("manual")
        # drained: the ring re-binds to the new layout
        assert q.submit(1, np.float32(1.0), np.float32(2.0))
        q._flush_once("manual")
        _assert_invariant(q)
        assert q.stats()["dispatched"] == 2
        q.close()

    def test_stats_staging_block(self):
        q = _staged_queue(_Recorder(), staging_slots=3)
        for i in range(8):
            q.submit(i, np.float32(i))
        q._flush_once("manual")
        st = q.stats()["staging"]
        assert st["enabled"] is True
        assert st["slots"] == 3
        assert st["staged_cohorts"] == 1
        assert st["stage_seconds"] > 0
        assert 0.0 <= st["overlap_fraction"] <= 1.0
        q.close()
        off = AdmissionQueue(_Recorder(), max_batch=8, start=False)
        assert off.stats()["staging"]["enabled"] is False
        off.close()

    def test_dispatch_error_sheds_exactly(self):
        rec = _Recorder(fail_times=1)
        q = _staged_queue(rec)
        for i in range(8):
            q.submit(i, np.float32(i))
        q._flush_once("manual")
        for i in range(4):
            q.submit(i, np.float32(i))
        q._flush_once("manual")
        s = q.stats()
        assert s["shed_by_reason"]["dispatch_error"] == 8
        assert s["dispatched"] == 4
        _assert_invariant(q)
        q.close()

    def test_breaker_open_sheds_under_exact_reason(self):
        from metrics_tpu.resilience import CircuitBreaker

        rec = _Recorder(fail_times=2)
        q = _staged_queue(
            rec, breaker=CircuitBreaker(failure_threshold=2, reset_after_s=60.0)
        )
        for round_rows in (4, 4, 4):
            for i in range(round_rows):
                q.submit(i, np.float32(i))
            q._flush_once("manual")
        s = q.stats()
        assert s["shed_by_reason"]["dispatch_error"] == 8  # two failed cohorts
        assert s["shed_by_reason"]["breaker_open"] == 4  # third never attempted
        assert s["dispatched"] == 0
        assert rec.rows == 0
        _assert_staged_invariant(q)
        q.close()

    def test_quarantine_sheds_with_dead_letters(self):
        rec = _Recorder()
        q = _staged_queue(rec, quarantine="on")
        vals = np.arange(8, dtype=np.float32)
        vals[2] = np.nan
        vals[5] = np.inf
        for i, v in enumerate(vals):
            q.submit(i, np.float32(v))
        q._flush_once("manual")
        s = q.stats()
        assert s["shed_by_reason"]["poisoned"] == 2
        assert s["dispatched"] == 6
        _assert_staged_invariant(q)
        ids, cols = rec.calls[0]
        assert np.isfinite(np.asarray(cols[0], np.float64)).all()
        dead = q.dead_letters()
        assert sorted(t for t, _ in dead) == [2, 5]
        q.close()

    def test_pickled_staged_queue_rebuilds_buffers(self):
        q = _staged_queue(_Recorder())
        for i in range(4):
            q.submit(i, np.float32(i))
        q._flush_once("manual")
        ring, slots = pickle.loads(pickle.dumps(q._ring)), pickle.loads(
            pickle.dumps(q._slots)
        )
        assert not ring.bound and ring.head == 0
        assert slots.in_use() == 0
        # the live queue keeps working after its scratch was cloned
        q.submit(7, np.float32(7.0))
        q._flush_once("manual")
        _assert_invariant(q)
        q.close()


# ------------------------------------------------------------- concurrency


def _per_tenant_sums(calls, tenants):
    """Bit-exact per-tenant integer sums over every dispatched cohort
    (pad rows carry id -1 and are discarded, matching validate_ids=False)."""
    sums = np.zeros(tenants, dtype=np.int64)
    counts = np.zeros(tenants, dtype=np.int64)
    for ids, cols in calls:
        ids = np.asarray(ids)
        keep = ids >= 0
        np.add.at(sums, ids[keep], np.asarray(cols[0])[keep].astype(np.int64))
        np.add.at(counts, ids[keep], 1)
    return sums, counts


class TestConcurrentIngest:
    TENANTS = 16

    def _writer_rows(self, w, n_rows):
        rng = np.random.RandomState(1000 + w)
        ids = rng.randint(0, self.TENANTS, n_rows).astype(np.int64)
        vals = rng.randint(0, 1000, n_rows).astype(np.float32)  # integer-valued
        return ids, vals

    @pytest.mark.parametrize("staged", [True, False])
    def test_racing_writers_match_serial_referee(self, staged):
        """N writers × racing manual flushes ingest EXACTLY the serial
        referee's rows: per-tenant sums/counts bit-identical (integer data,
        so cohort-boundary permutations cannot hide behind float rounding)."""
        writers, rows_per = 4, 300
        rec = _Recorder()
        q = AdmissionQueue(
            rec, max_batch=32, capacity_rows=writers * rows_per,
            start=False, staging=staged,
        )
        stop = threading.Event()

        def flusher():
            while not stop.is_set():
                q._flush_once("manual")

        def writer(w):
            ids, vals = self._writer_rows(w, rows_per)
            for t, v in zip(ids, vals):
                q.submit(int(t), np.float32(v))

        flushers = [threading.Thread(target=flusher) for _ in range(2)]
        threads = [
            threading.Thread(target=writer, args=(w,)) for w in range(writers)
        ]
        for th in flushers + threads:
            th.start()
        for th in threads:
            th.join()
        stop.set()
        for th in flushers:
            th.join()
        while q.depth():
            q._flush_once("manual")
        _assert_invariant(q)
        s = q.stats()
        assert s["shed"] == 0 and s["resident"] == 0
        assert s["dispatched"] == writers * rows_per

        # the serial referee: same rows, one thread, one flush per batch
        ref_rec = _Recorder()
        ref = AdmissionQueue(
            ref_rec, max_batch=32, capacity_rows=writers * rows_per, start=False
        )
        for w in range(writers):
            ids, vals = self._writer_rows(w, rows_per)
            for t, v in zip(ids, vals):
                ref.submit(int(t), np.float32(v))
        while ref.depth():
            ref._flush_once("manual")

        got = _per_tenant_sums(rec.calls, self.TENANTS)
        want = _per_tenant_sums(ref_rec.calls, self.TENANTS)
        np.testing.assert_array_equal(got[0], want[0])
        np.testing.assert_array_equal(got[1], want[1])
        q.close()
        ref.close()

    def test_conservation_through_faults_under_concurrency(self):
        """Racing writers against a flaky dispatch + armed quarantine: every
        row lands in exactly one ledger bucket — no loss, no double-count."""
        writers, rows_per = 4, 200
        rec = _Recorder(fail_times=3)
        q = AdmissionQueue(
            rec, max_batch=16, capacity_rows=writers * rows_per,
            start=False, staging=True, quarantine="on",
        )
        stop = threading.Event()

        def flusher():
            while not stop.is_set():
                q._flush_once("manual")

        def writer(w):
            rng = np.random.RandomState(2000 + w)
            for i in range(rows_per):
                v = np.nan if rng.rand() < 0.05 else float(rng.randint(0, 100))
                q.submit(int(rng.randint(0, self.TENANTS)), np.float32(v))

        flushers = [threading.Thread(target=flusher) for _ in range(2)]
        threads = [
            threading.Thread(target=writer, args=(w,)) for w in range(writers)
        ]
        for th in flushers + threads:
            th.start()
        for th in threads:
            th.join()
        stop.set()
        for th in flushers:
            th.join()
        while q.depth():
            q._flush_once("manual")
        _assert_staged_invariant(q)
        s = q.stats()
        assert s["submitted"] == writers * rows_per
        assert s["resident"] == 0
        shed = s["shed_by_reason"]
        assert (
            s["dispatched"]
            + shed.get("poisoned", 0)
            + shed.get("dispatch_error", 0)
            == s["admitted"]
        )
        assert rec.rows == s["dispatched"]
        q.close()

    def test_staged_background_flusher_end_to_end(self):
        """The real flusher thread + prefetch lane against racing writers:
        drain() leaves the ledger exact and the recorder whole."""
        rec = _Recorder()
        q = AdmissionQueue(rec, max_batch=32, max_delay_ms=1.0, staging=True)
        writers, rows_per = 4, 250

        def writer(w):
            ids, vals = self._writer_rows(w, rows_per)
            for t, v in zip(ids, vals):
                q.submit(int(t), np.float32(v))

        threads = [
            threading.Thread(target=writer, args=(w,)) for w in range(writers)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        q.drain()
        _assert_invariant(q)
        s = q.stats()
        assert s["resident"] == 0
        assert s["dispatched"] + s["shed"] == writers * rows_per
        assert rec.rows == s["dispatched"]
        q.close()


# ------------------------------------------------------------- telemetry


def test_staging_series_and_counters_surface():
    observability.enable()
    from metrics_tpu.observability.histogram import HISTOGRAMS
    from metrics_tpu.serving.telemetry import SERVING_STATS

    base_staged = SERVING_STATS.counter("staged_cohorts")
    q = _staged_queue(_Recorder())
    for i in range(8):
        q.submit(i, np.float32(i))
    q._flush_once("manual")
    q.close()
    assert SERVING_STATS.counter("staged_cohorts") == base_staged + 1
    snap = HISTOGRAMS.snapshot()
    fill = snap.get("serving_staging_fill_seconds", {})
    assert fill.get("count", 0) >= 1
    occ = snap.get("serving_staging_occupancy", {})
    assert occ.get("count", 0) >= 1
