"""SLOScheduler: generation-invalidated result cache, stale-serving within
the SLO budget, refresh coalescing, and arbitration against the queue.

Driven both with a fake metric (deterministic compute counts, injectable
latency) and end-to-end with a real ``KeyedMetric``/``MultiTenantCollection``
over the PR-9 background engine.
"""
import threading
import time

import numpy as np
import pytest

from metrics_tpu import Accuracy, KeyedMetric, MultiTenantCollection, Precision, observability
from metrics_tpu.serving import SLOScheduler
from metrics_tpu.serving.telemetry import SERVING_STATS


class _FakeMetric:
    """Metric-shaped double: per-tenant running sums; the compute counter is
    SHARED with clones (the scheduler computes on detached snapshots, and
    the tests count those)."""

    def __init__(self, n=8, compute_delay_s=0.0, sums=None, counter=None):
        self.n = n
        self.compute_delay_s = compute_delay_s
        self.sums = np.zeros(n) if sums is None else sums.copy()
        self._computes = counter if counter is not None else [0]
        self.lock = threading.Lock()

    @property
    def computes(self):
        return self._computes[0]

    def update(self, tenant_ids, values):
        with self.lock:
            np.add.at(self.sums, np.asarray(tenant_ids), np.asarray(values))

    def compute(self):
        if self.compute_delay_s:
            time.sleep(self.compute_delay_s)
        with self.lock:
            self._computes[0] += 1
            return self.sums.copy()

    def clone(self):
        with self.lock:
            return _FakeMetric(self.n, self.compute_delay_s, self.sums, self._computes)


def test_scheduler_validates_metric():
    with pytest.raises(TypeError, match="update"):
        SLOScheduler(object())
    with pytest.raises(ValueError, match="max_staleness_s"):
        SLOScheduler(_FakeMetric(), max_staleness_s=-1)


def test_read_miss_then_fresh_hit():
    m = _FakeMetric()
    svc = SLOScheduler(m, max_batch=8, max_delay_ms=10_000.0, start=False)
    svc.submit(2, 5.0)
    v = svc.read(max_staleness_s=0.0)  # miss: flush + recompute
    assert v[2] == 5.0
    before = SERVING_STATS.counter("cache_hits")
    v2 = svc.read([2])
    assert v2[0] == 5.0
    assert SERVING_STATS.counter("cache_hits") == before + 1
    svc.close()


def test_generation_bump_invalidates_cache():
    """No stale cache is ever served after a generation bump when the read
    demands freshness — the invariant the concurrency battery leans on."""
    m = _FakeMetric()
    svc = SLOScheduler(m, max_batch=8, max_delay_ms=10_000.0, start=False)
    svc.submit(1, 1.0)
    assert svc.read(max_staleness_s=0.0)[1] == 1.0
    gen1 = svc.generation
    svc.submit(1, 2.0)
    svc.queue.flush()
    assert svc.generation == gen1 + 1
    assert svc.read(max_staleness_s=0.0)[1] == 3.0  # recomputed, never cached
    assert svc.report()["cache_fresh"] is True
    svc.close()


def test_resident_rows_defeat_cache_freshness():
    """A cache entry at the current generation is NOT fresh while rows sit
    undispatched in the queue — read-your-writes demands the flush."""
    m = _FakeMetric()
    svc = SLOScheduler(m, max_batch=8, max_delay_ms=10_000.0, start=False)
    svc.submit(0, 1.0)
    assert svc.read(max_staleness_s=0.0)[0] == 1.0
    svc.submit(0, 1.0)  # resident, generation unchanged
    assert svc.read(max_staleness_s=0.0)[0] == 2.0  # flushed + recomputed
    svc.close()


def test_stale_within_budget_serves_and_refreshes_in_background():
    m = _FakeMetric()
    svc = SLOScheduler(m, max_batch=8, max_delay_ms=10_000.0, start=False)
    svc.submit(3, 1.0)
    assert svc.read(max_staleness_s=0.0)[3] == 1.0
    svc.submit(3, 1.0)
    svc.queue.flush()  # generation bumped: the cache is now one gen behind
    before = SERVING_STATS.counter("stale_serves")
    v = svc.read(max_staleness_s=60.0)  # within budget: stale value, now
    assert v[3] == 1.0  # the PREVIOUS generation, served immediately
    assert SERVING_STATS.counter("stale_serves") == before + 1
    fut = svc.refresh()  # the background refresh was scheduled; join it
    fut.result(timeout=10.0)
    svc.refresh(wait=True)
    assert svc.read(max_staleness_s=60.0)[3] == 2.0  # cache caught up
    svc.close()


def test_concurrent_stale_reads_coalesce_one_refresh():
    m = _FakeMetric(compute_delay_s=0.2)
    svc = SLOScheduler(m, max_batch=8, max_delay_ms=10_000.0, start=False)
    svc.submit(0, 1.0)
    results = []
    threads = [
        threading.Thread(target=lambda: results.append(svc.read(max_staleness_s=0.0)))
        for _ in range(4)
    ]
    before = SERVING_STATS.counter("coalesced_refreshes")
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 4 and all(r[0] == 1.0 for r in results)
    # all four blocking reads resolved from AT MOST two computes (one
    # refresh per generation; late arrivals join the in-flight one)
    assert m.computes <= 2
    assert SERVING_STATS.counter("coalesced_refreshes") >= before + 2
    svc.close()


def test_updates_keep_flowing_during_inflight_read():
    """Arbitration: an epoch read (slow compute) never blocks the write
    path — flushes dispatch while the refresh is in flight."""
    m = _FakeMetric(compute_delay_s=0.3)
    svc = SLOScheduler(m, max_batch=4, max_delay_ms=5.0)
    svc.submit(0, 1.0)
    svc.drain(5.0)
    fut = svc.refresh()  # slow compute in flight on the engine
    t0 = time.monotonic()
    svc.submit_many(np.arange(4), np.ones(4))
    assert svc.drain(5.0)  # dispatched well before the compute resolves
    dispatched_in = time.monotonic() - t0
    assert dispatched_in < 0.25, dispatched_in
    fut.result(timeout=10.0)
    svc.close()


def test_keyed_metric_end_to_end():
    observability.reset()
    m = KeyedMetric(Accuracy(), num_tenants=16)
    svc = SLOScheduler(m, max_batch=32, max_delay_ms=5.0, max_staleness_s=0.0)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 16, 128)
    preds = rng.rand(128).astype(np.float32)
    target = (preds > 0.5).astype(np.int32)  # all-correct stream
    assert svc.submit_many(ids, preds, target) == 128
    values = svc.read()
    seen = np.unique(ids)
    np.testing.assert_allclose(np.asarray(values)[seen], 1.0)
    # the ledger agrees with the queue: zero-lost-updates
    s = svc.queue.stats()
    assert m.tenant_report()["rows_routed"] == s["admitted"] - s["shed"]
    svc.close()


def test_multitenant_collection_reads_select_per_member():
    coll = MultiTenantCollection(
        [Accuracy(), Precision(num_classes=2, average="macro", multiclass=True)], 8
    )
    svc = SLOScheduler(coll, max_batch=16, max_delay_ms=5.0, max_staleness_s=0.0)
    preds = np.asarray([0.9, 0.8, 0.2], np.float32)
    target = np.asarray([1, 1, 0], np.int32)
    svc.submit_many([2, 2, 5], preds, target)
    out = svc.read([2, 5])
    assert set(out) == {"Accuracy", "Precision"}
    np.testing.assert_allclose(out["Accuracy"], [1.0, 1.0])
    svc.close()


def test_refresh_rides_the_async_engine_generations():
    from metrics_tpu.utilities.async_sync import get_engine

    m = KeyedMetric(Accuracy(), num_tenants=4)
    svc = SLOScheduler(m, max_batch=8, max_delay_ms=10_000.0, start=False)
    svc.submit(0, np.float32(0.9), np.int32(1))
    svc.read(max_staleness_s=0.0)
    assert get_engine().last_generation(m.telemetry_key) >= 1
    snap = observability.snapshot()
    assert snap["async_sync"]["submitted"] >= 1
    svc.close()


def test_scheduler_report_shape():
    m = _FakeMetric()
    svc = SLOScheduler(m, max_batch=8, max_delay_ms=10_000.0, start=False)
    rep = svc.report()
    assert rep["cache_generation"] is None and rep["cache_fresh"] is False
    assert rep["tenant_generations_tracked"] == 0
    svc.submit(0, 1.0)
    svc.read(max_staleness_s=0.0)
    rep = svc.report()
    assert rep["generation"] == 1 and rep["cache_generation"] == 1
    assert rep["queue"]["admitted"] == 1
    assert rep["tenant_generations_tracked"] == 1
    import json

    json.dumps(rep)
    svc.close()


def test_untouched_tenant_cache_survives_other_tenants_flush():
    """The per-tenant generation ledger (PR-12 follow-up): a flush touching
    tenant 2 bumps the GLOBAL write generation, but tenant 1's cached
    compute() value is still the latest value tenant 1 has — a tenant-scoped
    read must serve it from cache (no refresh fan-out), counted under
    ``tenant_cache_hits``; a read of the TOUCHED tenant must still
    recompute."""
    m = _FakeMetric()
    svc = SLOScheduler(m, max_batch=8, max_delay_ms=10_000.0, start=False)
    svc.submit(1, 1.0)
    svc.submit(2, 5.0)
    assert svc.read(max_staleness_s=0.0)[1] == 1.0  # cache installed
    computes = m.computes
    svc.submit(2, 1.0)
    svc.queue.flush()  # touches ONLY tenant 2; global generation moves
    assert svc.report()["cache_fresh"] is False
    before = SERVING_STATS.counter("tenant_cache_hits")
    v = svc.read([1], max_staleness_s=0.0)  # untouched tenant: cache survives
    assert v[0] == 1.0
    assert m.computes == computes  # no refresh was scheduled for this read
    assert SERVING_STATS.counter("tenant_cache_hits") == before + 1
    # the touched tenant still observes read-your-writes freshness
    assert svc.read([2], max_staleness_s=0.0)[0] == 6.0
    assert m.computes == computes + 1
    # a FULL-vector strict read can never ride the tenant-scoped path
    svc.submit(2, 1.0)
    svc.queue.flush()
    assert svc.read(max_staleness_s=0.0)[2] == 7.0
    svc.close()


def test_never_written_tenant_reads_from_cache():
    """A tenant with no writes at all (absent from the ledger) counts as
    unchanged: its cached default value serves under the strictest
    budget."""
    m = _FakeMetric()
    svc = SLOScheduler(m, max_batch=8, max_delay_ms=10_000.0, start=False)
    svc.submit(0, 1.0)
    assert svc.read(max_staleness_s=0.0)[0] == 1.0
    computes = m.computes
    svc.submit(0, 1.0)
    svc.queue.flush()
    assert svc.read([7], max_staleness_s=0.0)[0] == 0.0  # never written
    assert m.computes == computes
    svc.close()


def test_tenant_generation_map_prunes_after_compaction():
    """Satellite fix: the per-tenant generation ledger must drop entries for
    tenants that no longer exist after an elastic shrink — it only ever
    GREW before, a slow leak in a weeks-long service (and a stale entry
    could mark a future tenant reusing the id as already-written)."""
    m = KeyedMetric(Accuracy(), 16, validate_ids=False)
    svc = SLOScheduler(m, max_batch=8, max_delay_ms=10_000.0, start=False)
    for t in range(16):
        svc.submit(t, np.float32(0.9), np.int32(1))
    svc.queue.flush()
    assert svc.report()["tenant_generations_tracked"] == 16

    m.compact(5)
    # the prune is opportunistic-on-dispatch AND explicit
    assert svc.prune_tenant_generations() == 11
    assert svc.report()["tenant_generations_tracked"] == 5
    assert set(svc.tenant_generations()) <= set(range(5))
    # a second call is a no-op (O(1) steady state)
    assert svc.prune_tenant_generations() == 0

    # the next dispatched flush also prunes without an explicit call
    m.grow(16)
    m.compact(3)
    svc.submit(1, np.float32(0.5), np.int32(0))
    svc.queue.flush()
    assert svc.report()["tenant_generations_tracked"] <= 3
    svc.close()


def test_tenant_generations_accessor_is_consistent_copy():
    m = KeyedMetric(Accuracy(), 4, validate_ids=False)
    svc = SLOScheduler(m, max_batch=8, max_delay_ms=10_000.0, start=False)
    svc.submit(2, np.float32(0.9), np.int32(1))
    svc.queue.flush()
    gens = svc.tenant_generations()
    assert gens == {2: 1}
    gens[3] = 99  # mutating the copy never touches the ledger
    assert svc.tenant_generations() == {2: 1}
    svc.close()
