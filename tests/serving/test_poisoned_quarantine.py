"""Poisoned-row quarantine: NaN/Inf event rows are shed with the exact
reason ``"poisoned"`` (dead-lettered) instead of corrupting a whole flush,
and the conservation laws extend to the new reason."""
import numpy as np
import pytest

from metrics_tpu import observability
from metrics_tpu.serving.queue import DEAD_LETTER_CAP, AdmissionQueue


@pytest.fixture(autouse=True)
def _clean_telemetry():
    observability.reset()
    yield
    observability.set_health_policy("off")
    observability.reset()


def _recording_queue(**kwargs):
    got = []

    def target(ids, *cols):
        got.append((np.asarray(ids).copy(), [np.asarray(c).copy() for c in cols]))

    return AdmissionQueue(target, max_batch=8, start=False, **kwargs), got


def test_poisoned_rows_shed_exactly_and_clean_rows_dispatch():
    q, got = _recording_queue(quarantine="on")
    preds = np.array([0.1, np.nan, 0.3, np.inf, -np.inf, 0.6], np.float32)
    target = np.array([1, 0, 1, 1, 0, 1], np.int32)
    assert q.submit_many(np.arange(6), preds, target) == 6
    q.flush()
    stats = q.stats()
    assert stats["shed_by_reason"] == {"poisoned": 3}
    assert stats["dead_letter_rows"] == 3
    assert stats["dispatched"] == 3
    # the conservation law extends to the quarantine
    assert stats["submitted"] - stats["shed"] == stats["dispatched"]
    # only the finite rows reached the target, in admission order
    ids, cols = got[0]
    assert ids.tolist() == [0, 2, 5]
    assert np.all(np.isfinite(cols[0]))
    # the dead-letter sample retains the poisoned rows' tenants
    assert [t for t, _ in q.dead_letters()] == [1, 3, 4]


def test_quarantine_auto_follows_the_health_policy():
    # health policy off: NaN rows pass through (the pre-quarantine behavior)
    q, got = _recording_queue(quarantine="auto")
    q.submit_many([0, 1], np.array([0.1, np.nan], np.float32))
    q.flush()
    assert q.stats()["shed"] == 0 and len(got) == 1
    # armed health policy arms the quarantine
    observability.set_health_policy("record")
    q2, got2 = _recording_queue(quarantine="auto")
    q2.submit_many([0, 1], np.array([0.1, np.nan], np.float32))
    q2.flush()
    assert q2.stats()["shed_by_reason"] == {"poisoned": 1}
    assert got2[0][0].tolist() == [0]


def test_quarantine_off_disables_scanning():
    q, got = _recording_queue(quarantine="off")
    observability.set_health_policy("record")
    q.submit_many([0, 1], np.array([0.1, np.nan], np.float32))
    q.flush()
    assert q.stats()["shed"] == 0 and len(got) == 1


def test_invalid_quarantine_mode_raises():
    with pytest.raises(ValueError, match="quarantine"):
        AdmissionQueue(lambda *a: None, quarantine="maybe", start=False)


def test_all_poisoned_cohort_dispatches_nothing_but_drains():
    q, got = _recording_queue(quarantine="on")
    q.submit_many([0, 1], np.full(2, np.nan, np.float32))
    assert q.flush() == 2  # the popped rows count, so flush() terminates
    assert got == []
    stats = q.stats()
    assert stats["shed_by_reason"] == {"poisoned": 2}
    assert stats["resident"] == 0
    assert stats["submitted"] - stats["shed"] == stats["dispatched"] == 0


def test_integer_columns_are_never_scanned():
    q, got = _recording_queue(quarantine="on")
    q.submit_many([0, 1], np.array([7, 9], np.int32))
    q.flush()
    assert q.stats()["shed"] == 0
    assert got[0][1][0].tolist() == [7, 9]


def test_dead_letter_sample_is_bounded_while_count_stays_exact():
    q, _ = _recording_queue(quarantine="on")
    n = DEAD_LETTER_CAP + 8
    q.submit_many(np.arange(n) % 4, np.full(n, np.nan, np.float32))
    q.flush()
    assert len(q.dead_letters()) == DEAD_LETTER_CAP
    assert q.stats()["dead_letter_rows"] == n  # the COUNT never truncates


def test_quarantine_telemetry_matches_the_ledger():
    q, _ = _recording_queue(quarantine="on")
    q.submit_many([0, 1, 2], np.array([np.nan, 0.5, np.nan], np.float32))
    q.flush()
    serving = observability.snapshot()["serving"]
    assert serving["shed_by_reason"].get("poisoned") == 2
    assert serving["shed_rows"] == 2
    assert serving["dispatched_rows"] == 1


def test_poisoned_rows_never_corrupt_keyed_state():
    """End to end through a real KeyedMetric: with quarantine on, a NaN row
    cannot poison the float sum states — every touched tenant still
    computes finite, and rows_routed matches dispatched exactly."""
    from metrics_tpu import Accuracy, KeyedMetric

    metric = KeyedMetric(Accuracy(), num_tenants=4, validate_ids=False)
    q = AdmissionQueue(metric.update, max_batch=8, quarantine="on", start=False)
    preds = np.array([0.9, np.nan, 0.8, 0.7], np.float32)
    target = np.array([1, 1, 1, 0], np.int32)
    q.submit_many([0, 1, 2, 3], preds, target)
    q.flush()
    stats = q.stats()
    assert stats["shed_by_reason"] == {"poisoned": 1}
    assert metric.tenant_report()["rows_routed"] == stats["dispatched"] == 3
    values = np.asarray(metric.compute())
    assert np.all(np.isfinite(values[[0, 2, 3]]))
