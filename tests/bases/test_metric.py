"""Base-class lifecycle tests — the engine spec.

Ports the behavioral contract of the reference's ``tests/bases/test_metric.py``
(add_state validation, reset, compute caching, forward double-result protocol,
hash, pickle, state_dict) to the JAX engine, plus tests of the pure-functional
interface that the reference has no analogue for.
"""
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.metric import Metric
from tests.helpers.testers import DummyListMetric, DummyMetric, DummyMetricDiff, DummyMetricSum


def test_inherit():
    DummyMetric()


def test_add_state():
    m = DummyMetric()

    m.add_state("a", jnp.asarray(0), "sum")
    assert np.asarray(m._defaults["a"]) == 0

    m.add_state("b", jnp.asarray(0), "mean")
    m.add_state("c", jnp.asarray(0), "cat")
    m.add_state("d", [], "cat")
    m.add_state("e", jnp.asarray(0), None)
    m.add_state("f", jnp.asarray(0), lambda x: x[0])

    with pytest.raises(ValueError):
        m.add_state("g", jnp.asarray(0), "xyz")
    with pytest.raises(ValueError):
        m.add_state("h", jnp.asarray(0), 42)
    with pytest.raises(ValueError):
        m.add_state("i", [jnp.asarray(0)], "sum")  # non-empty list
    with pytest.raises(ValueError):
        m.add_state("j", 42, "sum")  # not an array


def test_add_state_persistent():
    m = DummyMetric()
    m.add_state("a", jnp.asarray(0), "sum", persistent=True)
    assert m._persistent["a"]
    m.add_state("b", jnp.asarray(0), "sum", persistent=False)
    assert not m._persistent["b"]


def test_reset():
    class A(DummyMetric):
        pass

    class B(DummyListMetric):
        pass

    m = A()
    assert np.asarray(m.x) == 0
    m.x = jnp.asarray(5)
    m.reset()
    assert np.asarray(m.x) == 0

    m = B()
    assert isinstance(m.x, list) and len(m.x) == 0
    m.x = [jnp.asarray(5)]
    m.reset()
    assert isinstance(m.x, list) and len(m.x) == 0


def test_update():
    class A(DummyMetric):
        def update(self, x):
            self.x = self.x + x

    a = A()
    assert np.asarray(a.x) == 0
    assert a._computed is None
    a.update(1)
    assert a._computed is None
    assert np.asarray(a.x) == 1
    a.update(2)
    assert np.asarray(a.x) == 3
    assert a._computed is None


def test_compute():
    class A(DummyMetric):
        def update(self, x):
            self.x = self.x + x

        def compute(self):
            return self.x

    a = A()
    assert np.asarray(a.compute()) == 0
    a.update(1)
    assert a._computed is None
    assert np.asarray(a.compute()) == 1
    assert np.asarray(a._computed) == 1
    a.update(2)
    assert a._computed is None
    assert np.asarray(a.compute()) == 3

    a.reset()
    assert a._computed is None


def test_compute_warns_before_update():
    m = DummyMetricSum()
    with pytest.warns(UserWarning, match="before the ``update`` method"):
        m.compute()


def test_hash():
    m1, m2 = DummyMetric(), DummyMetric()
    assert hash(m1) != hash(m2)  # identity-based state hash

    m1, m2 = DummyListMetric(), DummyListMetric()
    assert hash(m1) == hash(m2)  # empty list states hash equal
    m1.x.append(jnp.asarray(5))
    assert hash(m1) != hash(m2)


def test_forward():
    m = DummyMetricSum()
    assert np.asarray(m(1)) == 1  # batch value
    assert np.asarray(m(2)) == 2  # batch value, not accumulated
    assert np.asarray(m.compute()) == 3  # accumulated

    m = DummyMetricSum(compute_on_step=False)
    assert m(1) is None
    assert m(2) is None
    assert np.asarray(m.compute()) == 3


def test_forward_resets_compute_cache():
    m = DummyMetricSum()
    m.update(1)
    assert np.asarray(m.compute()) == 1
    m(2)
    assert m._computed is None
    assert np.asarray(m.compute()) == 3


def test_pickle(tmp_path):
    m = DummyMetricSum()
    m.update(1)

    restored = pickle.loads(pickle.dumps(m))
    assert np.asarray(restored.compute()) == 1

    restored.update(5)
    assert np.asarray(restored.compute()) == 6


def test_state_dict():
    m = DummyMetric()
    assert m.state_dict() == {}
    m.persistent(True)
    sd = m.state_dict()
    assert "x" in sd and np.asarray(sd["x"]) == 0

    m2 = DummyMetricSum()
    m2.persistent(True)
    m2.update(7)
    sd = m2.state_dict()
    assert np.asarray(sd["x"]) == 7

    m3 = DummyMetricSum()
    m3.persistent(True)
    m3.load_state_dict(sd)
    assert np.asarray(m3.compute()) == 7


def test_load_state_dict_non_rank_zero(monkeypatch):
    """Saved states are rank-aggregated; non-zero ranks must not reload them."""
    monkeypatch.setenv("GLOBAL_RANK", "1")
    m = DummyMetricSum()
    m.load_state_dict({"x": np.asarray(7)})
    assert np.asarray(m.x) == 0
    monkeypatch.setenv("GLOBAL_RANK", "0")
    m.load_state_dict({"x": np.asarray(7)})
    assert np.asarray(m.x) == 7


def test_child_metric_state_dict():
    class TestModule:
        def __init__(self):
            self.metric = DummyMetric()
            self.metric.add_state("a", jnp.asarray(0), persistent=True)
            self.metric.add_state("b", [], persistent=True)
            self.metric.x = jnp.asarray(5)

    module = TestModule()
    sd = module.metric.state_dict(prefix="metric.")
    assert "metric.a" in sd and "metric.b" in sd and "metric.x" not in sd


def test_clone():
    m = DummyMetricSum()
    m.update(3)
    c = m.clone()
    c.update(2)
    assert np.asarray(m.compute()) == 3
    assert np.asarray(c.compute()) == 5


def test_device_put():
    m = DummyMetricSum()
    m.update(1)
    m.device_put(jax.devices()[0])
    assert np.asarray(m.compute()) == 1


# ---------------------------------------------------------------------------
# pure-functional interface
# ---------------------------------------------------------------------------


def test_pure_update_compute():
    m = DummyMetricSum()
    state = m.init_state()
    state = m.apply_update(state, 1)
    state = m.apply_update(state, 2)
    assert np.asarray(m.apply_compute(state)) == 3
    # the live metric is untouched by pure calls
    assert np.asarray(m.x) == 0


def test_pure_update_under_jit():
    m = DummyMetricSum()
    step = jax.jit(lambda s, x: m.apply_update(s, x))
    state = m.init_state()
    for i in range(5):
        state = step(state, jnp.asarray(float(i)))
    assert np.asarray(m.apply_compute(state)) == 10.0


def test_apply_forward_matches_stateful():
    m_pure = DummyMetricSum()
    m_stateful = DummyMetricSum()
    state = m_pure.init_state()
    for x in [1.0, 2.0, 3.0]:
        state, val = m_pure.apply_forward(state, jnp.asarray(x))
        assert np.asarray(val) == np.asarray(m_stateful(jnp.asarray(x)))
    assert np.asarray(m_pure.apply_compute(state)) == np.asarray(m_stateful.compute())


def test_merge_states():
    m = DummyMetricSum()
    a = m.apply_update(m.init_state(), 1)
    b = m.apply_update(m.init_state(), 2)
    merged = m.merge_states(a, b)
    assert np.asarray(m.apply_compute(merged)) == 3


def test_list_state_accumulation():
    class L(DummyListMetric):
        def update(self, x):
            self.x.append(jnp.asarray(x))

        def compute(self):
            from metrics_tpu.utilities.data import dim_zero_cat

            return dim_zero_cat(self.x)

    m = L()
    m(jnp.asarray([1.0, 2.0]))
    m(jnp.asarray([3.0]))
    np.testing.assert_array_equal(np.asarray(m.compute()), [1.0, 2.0, 3.0])


def test_filter_kwargs():
    class A(DummyMetric):
        def update(self, x, y):
            pass

    a = A()
    assert a._filter_kwargs(x=1, y=2, z=3) == {"x": 1, "y": 2}


def test_gradients_flow_through_forward_value():
    """The per-batch value is differentiable w.r.t. the inputs (the docs'
    'forward detaches nothing' contract — the reference asserts this via
    requires_grad on forward outputs, ``testers.py:464-497``): using a
    metric's batch value as a training loss must yield the same gradient as
    the raw functional."""
    from metrics_tpu import MeanSquaredError
    from metrics_tpu.functional import mean_squared_error

    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.randn(32).astype(np.float64))
    target = jnp.asarray(rng.randn(32).astype(np.float64))

    metric = MeanSquaredError()

    def loss_via_forward(p):
        _, value = metric.apply_forward(metric.init_state(), p, target)
        return value

    g_forward = jax.grad(loss_via_forward)(preds)
    g_functional = jax.grad(lambda p: mean_squared_error(p, target))(preds)
    assert bool(jnp.all(jnp.isfinite(g_forward)))
    np.testing.assert_allclose(np.asarray(g_forward), np.asarray(g_functional), atol=1e-12)
