"""MetricCollection semantics — port of ``tests/bases/test_collections.py``."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import MetricCollection
from tests.helpers.testers import DummyMetricDiff, DummyMetricSum


def test_metric_collection():
    collection = MetricCollection([DummyMetricSum(), DummyMetricDiff()])

    collection.update(5)
    results = collection.compute()
    assert np.asarray(results["DummyMetricSum"]) == 5
    assert np.asarray(results["DummyMetricDiff"]) == -5

    collection.reset()
    results = collection.compute()
    assert np.asarray(results["DummyMetricSum"]) == 0
    assert np.asarray(results["DummyMetricDiff"]) == 0


def test_construction_from_dict():
    collection = MetricCollection({"b_diff": DummyMetricDiff(), "a_sum": DummyMetricSum()})
    # deterministic sorted insertion order
    assert list(collection.keys()) == ["a_sum", "b_diff"]


def test_duplicate_names_raise():
    with pytest.raises(ValueError, match="two metrics both named"):
        MetricCollection([DummyMetricSum(), DummyMetricSum()])


def test_non_metric_raises():
    with pytest.raises(ValueError):
        MetricCollection([DummyMetricSum(), 5])
    with pytest.raises(ValueError):
        MetricCollection({"a": 5})


def test_collection_forward_filters_kwargs():
    collection = MetricCollection([DummyMetricSum(), DummyMetricDiff()])
    out = collection(x=5, y=3)
    assert np.asarray(out["DummyMetricSum"]) == 5
    assert np.asarray(out["DummyMetricDiff"]) == -3


def test_clone_with_prefix_postfix():
    collection = MetricCollection([DummyMetricSum()])
    pre = collection.clone(prefix="train_")
    post = collection.clone(postfix="_val")
    pre.update(2)
    post.update(2)
    assert list(pre.compute().keys()) == ["train_DummyMetricSum"]
    assert list(post.compute().keys()) == ["DummyMetricSum_val"]
    # base keys unchanged
    assert list(collection.keys()) == ["DummyMetricSum"]


def test_collection_state_dict_roundtrip():
    collection = MetricCollection([DummyMetricSum()])
    collection.persistent(True)
    collection.update(3)
    sd = collection.state_dict()
    assert np.asarray(sd["DummyMetricSum.x"]) == 3

    fresh = MetricCollection([DummyMetricSum()])
    fresh.persistent(True)
    fresh.load_state_dict(sd)
    assert np.asarray(fresh.compute()["DummyMetricSum"]) == 3


def test_collection_pure_api():
    collection = MetricCollection([DummyMetricSum(), DummyMetricDiff()])
    state = collection.init_state()
    state = collection.apply_update(state, 5)
    state = collection.apply_update(state, 2)
    out = collection.apply_compute(state)
    assert np.asarray(out["DummyMetricSum"]) == 7
    assert np.asarray(out["DummyMetricDiff"]) == -7


def test_collection_apply_forward():
    collection = MetricCollection([DummyMetricSum()])
    state = collection.init_state()
    state, vals = collection.apply_forward(state, 4)
    assert np.asarray(vals["DummyMetricSum"]) == 4
    state, vals = collection.apply_forward(state, 2)
    assert np.asarray(vals["DummyMetricSum"]) == 2
    assert np.asarray(collection.apply_compute(state)["DummyMetricSum"]) == 6


def test_collection_len_iter_contains():
    collection = MetricCollection([DummyMetricSum(), DummyMetricDiff()])
    assert len(collection) == 2
    assert "DummyMetricSum" in collection
    assert set(iter(collection)) == {"DummyMetricSum", "DummyMetricDiff"}


def test_shared_stat_scores_update_dedup(monkeypatch):
    """Precision/Recall/F1 with identical stat-scores settings must run ONE
    shared canonicalization + stat-scores pass per batch, with states equal
    to the unshared per-metric path."""
    import metrics_tpu.classification.stat_scores as ss_mod
    from metrics_tpu import F1, Precision, Recall

    calls = {"n": 0}
    real = ss_mod._stat_scores_update

    def counting(*args, **kwargs):
        calls["n"] += 1
        return real(*args, **kwargs)

    monkeypatch.setattr(ss_mod, "_stat_scores_update", counting)

    rng = np.random.RandomState(5)
    preds = jnp.asarray(rng.rand(64, 4).astype(np.float32))
    preds = preds / preds.sum(-1, keepdims=True)
    target = jnp.asarray(rng.randint(0, 4, 64))

    make = lambda: [
        Precision(average="macro", num_classes=4),
        Recall(average="macro", num_classes=4),
        F1(average="macro", num_classes=4),
    ]

    shared = MetricCollection(make())
    shared.update(preds, target)
    assert calls["n"] == 1  # one pass for all three metrics

    calls["n"] = 0
    loose = make()
    for m in loose:
        m.update(preds, target)
    assert calls["n"] == 3

    for m_shared, m_loose in zip(shared.values(), loose):
        for s in ("tp", "fp", "tn", "fn"):
            np.testing.assert_array_equal(
                np.asarray(getattr(m_shared, s)), np.asarray(getattr(m_loose, s))
            )
    shared.compute()  # must not raise on the shared states

    # pure path: same dedup, same states
    calls["n"] = 0
    pure = MetricCollection(make())
    state = pure.apply_update(pure.init_state(), preds, target)
    assert calls["n"] == 1
    for name, m_loose in zip(("Precision", "Recall", "F1"), loose):
        for s in ("tp", "fp", "tn", "fn"):
            np.testing.assert_array_equal(np.asarray(state[name][s]), np.asarray(getattr(m_loose, s)))


def test_shared_confmat_update_dedup(monkeypatch):
    """ConfusionMatrix/CohenKappa/MatthewsCorrcoef/IoU with matching settings
    must run ONE confusion-matrix pass per batch, with states equal to the
    unshared per-metric path."""
    import metrics_tpu.classification.confusion_matrix as cm_mod
    from metrics_tpu import CohenKappa, ConfusionMatrix, IoU, MatthewsCorrcoef

    calls = {"n": 0}
    real = cm_mod._confusion_matrix_update

    def counting(*args, **kwargs):
        calls["n"] += 1
        return real(*args, **kwargs)

    # every family member updates through the shared mixin, which resolves
    # the kernel via this single module-level name
    monkeypatch.setattr(cm_mod, "_confusion_matrix_update", counting)

    rng = np.random.RandomState(8)
    preds = jnp.asarray(rng.randint(0, 4, 64))
    target = jnp.asarray(rng.randint(0, 4, 64))

    make = lambda: [
        ConfusionMatrix(num_classes=4),
        CohenKappa(num_classes=4),
        MatthewsCorrcoef(num_classes=4),
        IoU(num_classes=4),
    ]

    shared = MetricCollection(make())
    shared.update(preds, target)
    assert calls["n"] == 1  # one confmat pass for all four metrics

    calls["n"] = 0
    loose = make()
    for m in loose:
        m.update(preds, target)
    assert calls["n"] == 4

    for m_shared, m_loose in zip(shared.values(), loose):
        np.testing.assert_array_equal(np.asarray(m_shared.confmat), np.asarray(m_loose.confmat))
    shared.compute()  # must not raise on the shared states

    # pure path: same dedup, same states
    calls["n"] = 0
    pure = MetricCollection(make())
    state = pure.apply_update(pure.init_state(), preds, target)
    assert calls["n"] == 1
    for name, m_loose in zip(("ConfusionMatrix", "CohenKappa", "MatthewsCorrcoef", "IoU"), loose):
        np.testing.assert_array_equal(np.asarray(state[name]["confmat"]), np.asarray(m_loose.confmat))

    # differing settings (threshold, multilabel) must NOT share
    calls["n"] = 0
    mixed = MetricCollection(
        {
            "cm": ConfusionMatrix(num_classes=4),
            "kappa_thr": CohenKappa(num_classes=4, threshold=0.3),
        }
    )
    mixed.update(preds, target)
    assert calls["n"] == 2


def test_shared_confmat_values_match_individual():
    """Collection compute values are unchanged by confmat-family sharing."""
    from metrics_tpu import CohenKappa, ConfusionMatrix, IoU, MatthewsCorrcoef

    rng = np.random.RandomState(9)
    preds = jnp.asarray(rng.randint(0, 3, 48))
    target = jnp.asarray(rng.randint(0, 3, 48))

    collection = MetricCollection(
        [
            ConfusionMatrix(num_classes=3),
            CohenKappa(num_classes=3),
            MatthewsCorrcoef(num_classes=3),
            IoU(num_classes=3),
        ]
    )
    state = collection.init_state()
    state, vals = collection.apply_forward(state, preds, target)
    out = collection.apply_compute(state)

    for cls, key in (
        (ConfusionMatrix, "ConfusionMatrix"),
        (CohenKappa, "CohenKappa"),
        (MatthewsCorrcoef, "MatthewsCorrcoef"),
        (IoU, "IoU"),
    ):
        solo = cls(num_classes=3)
        expected = solo(preds, target)
        np.testing.assert_allclose(np.asarray(vals[key]), np.asarray(expected), atol=1e-7, err_msg=key)
        np.testing.assert_allclose(
            np.asarray(out[key]), np.asarray(solo.compute()), atol=1e-7, err_msg=key
        )


def test_shared_update_respects_differing_configs(monkeypatch):
    """Metrics with different stat-scores settings must NOT share."""
    import metrics_tpu.classification.stat_scores as ss_mod
    from metrics_tpu import Precision, Recall

    calls = {"n": 0}
    real = ss_mod._stat_scores_update

    def counting(*args, **kwargs):
        calls["n"] += 1
        return real(*args, **kwargs)

    monkeypatch.setattr(ss_mod, "_stat_scores_update", counting)

    collection = MetricCollection(
        {
            "p_macro": Precision(average="macro", num_classes=3),
            "r_micro": Recall(average="micro"),
        }
    )
    preds = jnp.asarray([0, 1, 2, 1])
    target = jnp.asarray([0, 2, 2, 1])
    collection.update(preds, target)
    assert calls["n"] == 2  # different keys -> separate passes


def test_shared_update_forward_values_match_individual():
    """Collection forward/apply_forward step values are unchanged by sharing."""
    from metrics_tpu import F1, Precision, Recall

    rng = np.random.RandomState(6)
    preds = jnp.asarray(rng.rand(32, 3).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 3, 32))

    collection = MetricCollection(
        [
            Precision(average="macro", num_classes=3),
            Recall(average="macro", num_classes=3),
            F1(average="macro", num_classes=3),
        ]
    )
    state = collection.init_state()
    state, vals = collection.apply_forward(state, preds, target)

    for cls, key in ((Precision, "Precision"), (Recall, "Recall"), (F1, "F1")):
        solo = cls(average="macro", num_classes=3)
        expected = solo(preds, target)
        np.testing.assert_allclose(np.asarray(vals[key]), np.asarray(expected), atol=1e-7)
        np.testing.assert_allclose(
            np.asarray(collection.apply_compute(state)[key]), np.asarray(solo.compute()), atol=1e-7
        )


def test_shared_update_eager_forward_dedup(monkeypatch):
    """The eager `collection(preds, target)` path must also run one shared
    stat-scores pass, with step values equal to standalone metrics."""
    import metrics_tpu.classification.stat_scores as ss_mod
    from metrics_tpu import F1, Precision, Recall

    calls = {"n": 0}
    real = ss_mod._stat_scores_update

    def counting(*args, **kwargs):
        calls["n"] += 1
        return real(*args, **kwargs)

    monkeypatch.setattr(ss_mod, "_stat_scores_update", counting)

    rng = np.random.RandomState(7)
    preds = jnp.asarray(rng.rand(48, 3).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 3, 48))

    collection = MetricCollection(
        [
            Precision(average="macro", num_classes=3),
            Recall(average="macro", num_classes=3),
            F1(average="macro", num_classes=3),
        ]
    )
    vals = collection(preds, target)
    assert calls["n"] == 1

    for cls, key in ((Precision, "Precision"), (Recall, "Recall"), (F1, "F1")):
        solo = cls(average="macro", num_classes=3)
        np.testing.assert_allclose(np.asarray(vals[key]), np.asarray(solo(preds, target)), atol=1e-7)
        np.testing.assert_allclose(
            np.asarray(collection.compute()[key]), np.asarray(solo.compute()), atol=1e-7
        )


def test_collection_eager_compute_aliases_class_sync():
    """The eager epoch-boundary sync gathers each shared-update class ONCE:
    P/R/F1 with identical settings ship one tp/fp/tn/fn quartet (4 gather
    calls), not one per member (12) — and every member's value and local
    state are unchanged by the aliasing."""
    from metrics_tpu import F1, MetricCollection, Precision, Recall

    calls = {"n": 0}

    def fake_gather(x, group=None):  # simulate two identical ranks
        calls["n"] += 1
        return [x, x]

    rng = np.random.RandomState(9)
    preds = jnp.asarray(rng.rand(48, 3).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 3, 48))

    members = dict(average="macro", num_classes=3, dist_sync_fn=fake_gather)
    collection = MetricCollection([Precision(**members), Recall(**members), F1(**members)])
    collection.update(preds, target)
    values = collection.compute()
    assert calls["n"] == 4, f"expected ONE quartet gather, saw {calls['n']} calls"

    # values match a solo metric under the same 2-rank fake sync, and the
    # local (unsynced) states were restored on every member
    for cls, key in ((Precision, "Precision"), (Recall, "Recall"), (F1, "F1")):
        solo = cls(average="macro", num_classes=3, dist_sync_fn=fake_gather)
        solo.update(preds, target)
        np.testing.assert_allclose(np.asarray(values[key]), np.asarray(solo.compute()), atol=1e-7)
    for _, m in collection.items(keep_base=True):
        assert m._to_sync is True
        np.testing.assert_allclose(
            np.asarray(m.tp), np.asarray(collection["Precision"].tp), atol=0
        )


def test_collection_eager_compute_alias_skips_mismatched_members():
    """Members whose sync config differs (own dist_sync_fn) never adopt a
    peer's synced state."""
    from metrics_tpu import MetricCollection, Precision, Recall

    doubling = lambda x, group=None: [x, x]  # noqa: E731
    tripling = lambda x, group=None: [x, x, x]  # noqa: E731

    rng = np.random.RandomState(10)
    preds = jnp.asarray(rng.rand(32, 3).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 3, 32))

    collection = MetricCollection(
        [
            Precision(average="macro", num_classes=3, dist_sync_fn=doubling),
            Recall(average="macro", num_classes=3, dist_sync_fn=tripling),
        ]
    )
    collection.update(preds, target)
    values = collection.compute()
    from metrics_tpu import Precision as P, Recall as R

    solo_p = P(average="macro", num_classes=3, dist_sync_fn=doubling)
    solo_r = R(average="macro", num_classes=3, dist_sync_fn=tripling)
    solo_p.update(preds, target)
    solo_r.update(preds, target)
    np.testing.assert_allclose(np.asarray(values["Precision"]), np.asarray(solo_p.compute()), atol=1e-7)
    np.testing.assert_allclose(np.asarray(values["Recall"]), np.asarray(solo_r.compute()), atol=1e-7)


def test_collection_eager_alias_skips_gather_when_values_cached():
    """compute() twice without an update in between: the second call serves
    every member's cached value and must not re-gather the class bundle."""
    from metrics_tpu import F1, MetricCollection, Precision, Recall

    calls = {"n": 0}

    def fake_gather(x, group=None):
        calls["n"] += 1
        return [x, x]

    rng = np.random.RandomState(12)
    preds = jnp.asarray(rng.rand(32, 3).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 3, 32))
    members = dict(average="macro", num_classes=3, dist_sync_fn=fake_gather)
    collection = MetricCollection([Precision(**members), Recall(**members), F1(**members)])
    collection.update(preds, target)
    first = collection.compute()
    after_first = calls["n"]
    second = collection.compute()
    assert calls["n"] == after_first, "cached compute must not re-gather"
    for key in first:
        np.testing.assert_array_equal(np.asarray(first[key]), np.asarray(second[key]))


def test_collection_eager_alias_rolls_back_on_sync_failure():
    """A failure while adopting a LATER class must restore members of the
    classes adopted before it (states and sync flags) — otherwise they hold
    world-aggregated states and silently skip every future sync."""
    from metrics_tpu import CohenKappa, ConfusionMatrix, MetricCollection, Precision, Recall

    def fake_gather(x, group=None):
        return [x, x]

    def raising_gather(x, group=None):
        raise RuntimeError("link down")

    rng = np.random.RandomState(11)
    preds = jnp.asarray(rng.rand(32, 3).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 3, 32))

    # class 1 (stat-scores: P/R) syncs fine; class 2 (confmat family) raises
    collection = MetricCollection(
        [
            Precision(average="macro", num_classes=3, dist_sync_fn=fake_gather),
            Recall(average="macro", num_classes=3, dist_sync_fn=fake_gather),
            ConfusionMatrix(num_classes=3, dist_sync_fn=raising_gather),
            CohenKappa(num_classes=3, dist_sync_fn=raising_gather),
        ]
    )
    collection.update(preds, target)
    before_tp = np.asarray(collection["Precision"].tp).copy()
    with pytest.raises(RuntimeError, match="link down"):
        collection.compute()
    for name in ("Precision", "Recall"):
        m = collection[name]
        assert m._to_sync is True, name
        np.testing.assert_array_equal(np.asarray(m.tp), before_tp, err_msg=name)


def test_accuracy_persistent_default_matches_base():
    """persistent() with no argument means 'non-persistent' on every metric
    (the base default); Accuracy's override must not invert it."""
    import inspect

    from metrics_tpu import Accuracy
    from metrics_tpu.metric import Metric

    base_default = inspect.signature(Metric.persistent).parameters["mode"].default
    acc_default = inspect.signature(Accuracy.persistent).parameters["mode"].default
    assert acc_default == base_default
