"""MetricCollection semantics — port of ``tests/bases/test_collections.py``."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import MetricCollection
from tests.helpers.testers import DummyMetricDiff, DummyMetricSum


def test_metric_collection():
    collection = MetricCollection([DummyMetricSum(), DummyMetricDiff()])

    collection.update(5)
    results = collection.compute()
    assert np.asarray(results["DummyMetricSum"]) == 5
    assert np.asarray(results["DummyMetricDiff"]) == -5

    collection.reset()
    results = collection.compute()
    assert np.asarray(results["DummyMetricSum"]) == 0
    assert np.asarray(results["DummyMetricDiff"]) == 0


def test_construction_from_dict():
    collection = MetricCollection({"b_diff": DummyMetricDiff(), "a_sum": DummyMetricSum()})
    # deterministic sorted insertion order
    assert list(collection.keys()) == ["a_sum", "b_diff"]


def test_duplicate_names_raise():
    with pytest.raises(ValueError, match="two metrics both named"):
        MetricCollection([DummyMetricSum(), DummyMetricSum()])


def test_non_metric_raises():
    with pytest.raises(ValueError):
        MetricCollection([DummyMetricSum(), 5])
    with pytest.raises(ValueError):
        MetricCollection({"a": 5})


def test_collection_forward_filters_kwargs():
    collection = MetricCollection([DummyMetricSum(), DummyMetricDiff()])
    out = collection(x=5, y=3)
    assert np.asarray(out["DummyMetricSum"]) == 5
    assert np.asarray(out["DummyMetricDiff"]) == -3


def test_clone_with_prefix_postfix():
    collection = MetricCollection([DummyMetricSum()])
    pre = collection.clone(prefix="train_")
    post = collection.clone(postfix="_val")
    pre.update(2)
    post.update(2)
    assert list(pre.compute().keys()) == ["train_DummyMetricSum"]
    assert list(post.compute().keys()) == ["DummyMetricSum_val"]
    # base keys unchanged
    assert list(collection.keys()) == ["DummyMetricSum"]


def test_collection_state_dict_roundtrip():
    collection = MetricCollection([DummyMetricSum()])
    collection.persistent(True)
    collection.update(3)
    sd = collection.state_dict()
    assert np.asarray(sd["DummyMetricSum.x"]) == 3

    fresh = MetricCollection([DummyMetricSum()])
    fresh.persistent(True)
    fresh.load_state_dict(sd)
    assert np.asarray(fresh.compute()["DummyMetricSum"]) == 3


def test_collection_pure_api():
    collection = MetricCollection([DummyMetricSum(), DummyMetricDiff()])
    state = collection.init_state()
    state = collection.apply_update(state, 5)
    state = collection.apply_update(state, 2)
    out = collection.apply_compute(state)
    assert np.asarray(out["DummyMetricSum"]) == 7
    assert np.asarray(out["DummyMetricDiff"]) == -7


def test_collection_apply_forward():
    collection = MetricCollection([DummyMetricSum()])
    state = collection.init_state()
    state, vals = collection.apply_forward(state, 4)
    assert np.asarray(vals["DummyMetricSum"]) == 4
    state, vals = collection.apply_forward(state, 2)
    assert np.asarray(vals["DummyMetricSum"]) == 2
    assert np.asarray(collection.apply_compute(state)["DummyMetricSum"]) == 6


def test_collection_len_iter_contains():
    collection = MetricCollection([DummyMetricSum(), DummyMetricDiff()])
    assert len(collection) == 2
    assert "DummyMetricSum" in collection
    assert set(iter(collection)) == {"DummyMetricSum", "DummyMetricDiff"}
