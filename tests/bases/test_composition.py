"""Metric arithmetic tests — the 36 lazy-composition operators.

Port of the behavioral spec of the reference's ``tests/bases/test_composition.py``.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.metric import CompositionalMetric, Metric


class DummyMetric(Metric):

    def __init__(self, val_to_return):
        super().__init__()
        self.add_state("_num_updates", jnp.zeros(()), dist_reduce_fx="sum")
        self._val_to_return = val_to_return

    def update(self, *args, **kwargs) -> None:
        self._num_updates = self._num_updates + 1

    def compute(self):
        return jnp.asarray(self._val_to_return)


@pytest.mark.parametrize(
    ["second_operand", "expected_result"],
    [(DummyMetric(2), 4), (2, 4), (2.0, 4.0), (jnp.asarray(2), 4)],
)
def test_metrics_add(second_operand, expected_result):
    first = DummyMetric(2)
    final_add = first + second_operand
    final_radd = second_operand + first
    assert isinstance(final_add, CompositionalMetric)
    assert isinstance(final_radd, CompositionalMetric)
    final_add.update()
    final_radd.update()
    np.testing.assert_allclose(np.asarray(final_add.compute()), expected_result)
    np.testing.assert_allclose(np.asarray(final_radd.compute()), expected_result)


@pytest.mark.parametrize(
    ["second_operand", "expected_result"],
    [(DummyMetric(3), 6), (3, 6), (3.0, 6.0)],
)
def test_metrics_mul(second_operand, expected_result):
    first = DummyMetric(2)
    final_mul = first * second_operand
    final_rmul = second_operand * first
    final_mul.update()
    final_rmul.update()
    np.testing.assert_allclose(np.asarray(final_mul.compute()), expected_result)
    np.testing.assert_allclose(np.asarray(final_rmul.compute()), expected_result)


@pytest.mark.parametrize(
    ["second_operand", "expected_result"],
    [(DummyMetric(3), -1), (3, -1), (3.0, -1.0)],
)
def test_metrics_sub(second_operand, expected_result):
    first = DummyMetric(2)
    final_sub = first - second_operand
    final_sub.update()
    np.testing.assert_allclose(np.asarray(final_sub.compute()), expected_result)


@pytest.mark.parametrize(
    ["second_operand", "expected_result"],
    [(DummyMetric(3), 2 / 3), (3, 2 / 3), (3.0, 2 / 3)],
)
def test_metrics_truediv(second_operand, expected_result):
    first = DummyMetric(2)
    final_div = first / second_operand
    final_div.update()
    np.testing.assert_allclose(np.asarray(final_div.compute()), expected_result, rtol=1e-6)


def test_metrics_rsub_rtruediv():
    first = DummyMetric(2)
    final_rsub = 5 - first
    final_rdiv = 6 / first
    final_rsub.update()
    final_rdiv.update()
    np.testing.assert_allclose(np.asarray(final_rsub.compute()), 3)
    np.testing.assert_allclose(np.asarray(final_rdiv.compute()), 3.0)


def test_metrics_floordiv_mod_pow():
    first = DummyMetric(5)
    for op, expected in [(first // 2, 2), (first % 2, 1), (first**2, 25)]:
        op.update()
        np.testing.assert_allclose(np.asarray(op.compute()), expected)


def test_metrics_floordiv_matches_torch_semantics():
    """Float // follows torch/numpy: x // 0.0 is ±inf (jnp.floor_divide
    alone gives NaN — found by the composition fuzz battery, seed 449:
    recall // (accuracy - recall) with micro recall == accuracy),
    0.0 // 0.0 is NaN, and finite quotients get the fmod-based fixup so
    a rounded quotient just across an integer still floors correctly.
    Integer operands keep integer floor-division semantics.

    Version assumption: these expectations (and the fuzz battery's use of
    the installed torch as oracle) presume torch >= 1.13, where
    ``floor_divide`` floors; pre-1.13 torch TRUNCATED, so e.g.
    ``-7.0 // 2.0`` would be -3 there and this parity claim would change
    meaning if the reference pin ever moved that far back."""
    cases = [(5.0, 0.0, np.inf), (-5.0, 0.0, -np.inf), (0.0, 0.0, np.nan),
             (8.754882, -0.09516175, -93.0),  # fixup case: floor(a/b) would give -92
             (7.0, 2.0, 3.0), (-7.0, 2.0, -4.0),
             # finite // ±inf: IEEE fmod keeps the dividend (XLA's rem
             # gives NaN unguarded) — torch floors to 0 / -1 by sign
             (5.0, np.inf, 0.0), (-5.0, np.inf, -1.0), (5.0, -np.inf, -1.0)]
    for val, divisor, expected in cases:
        op = DummyMetric(val) // divisor
        op.update()
        np.testing.assert_array_equal(np.asarray(op.compute()), expected, err_msg=f"{val} // {divisor}")
    int_op = DummyMetric(5) // 2
    int_op.update()
    result = int_op.compute()
    assert jnp.issubdtype(result.dtype, jnp.integer) and int(result) == 2


def test_metrics_mod_matches_torch_semantics():
    """Float % is C-style fmod like the reference's torch.fmod (sign of
    the dividend), and x % ±inf keeps the dividend per IEEE — XLA's rem
    gives NaN there unguarded. x % 0.0 is NaN in both libraries."""
    cases = [(5.0, 3.0, 2.0), (-5.0, 3.0, -2.0), (5.0, -3.0, 2.0),
             (5.0, np.inf, 5.0), (-5.0, np.inf, -5.0), (5.0, -np.inf, 5.0),
             (0.0, np.inf, 0.0), (5.0, 0.0, np.nan)]
    for val, divisor, expected in cases:
        op = DummyMetric(val) % divisor
        op.update()
        np.testing.assert_array_equal(np.asarray(op.compute()), expected, err_msg=f"{val} % {divisor}")


def test_metrics_matmul():
    first = DummyMetric([2.0, 2.0, 2.0])
    final_matmul = first @ jnp.asarray([2.0, 2.0, 2.0])
    final_matmul.update()
    np.testing.assert_allclose(np.asarray(final_matmul.compute()), 12.0)


def test_metrics_comparisons():
    first = DummyMetric(2)
    cases = [
        (first == 2, True),
        (first != 2, False),
        (first > 1, True),
        (first >= 2, True),
        (first < 1, False),
        (first <= 2, True),
    ]
    for metric, expected in cases:
        metric.update()
        assert bool(np.asarray(metric.compute())) is expected


def test_metrics_bitwise():
    first = DummyMetric(5)
    cases = [
        (first & 3, 5 & 3),
        (first | 3, 5 | 3),
        (first ^ 3, 5 ^ 3),
    ]
    for metric, expected in cases:
        metric.update()
        np.testing.assert_allclose(np.asarray(metric.compute()), expected)


def test_metrics_reflected_arithmetic():
    first = DummyMetric(2)
    cases = [
        (5 // first, 5 // 2),
        (5 % first, 5 % 2),
        (5**first, 5**2),
        (5 & first, 5 & 2),
        (5 | first, 5 | 2),
        (5 ^ first, 5 ^ 2),
    ]
    for metric, expected in cases:
        metric.update()
        np.testing.assert_allclose(np.asarray(metric.compute()), expected)


def test_metrics_rmatmul():
    first = DummyMetric([2.0, 2.0, 2.0])
    final = jnp.asarray([1.0, 2.0, 3.0]) @ first
    final.update()
    np.testing.assert_allclose(np.asarray(final.compute()), 12.0)


def test_metrics_invert():
    first = DummyMetric(5)
    final = ~first
    final.update()
    np.testing.assert_allclose(np.asarray(final.compute()), ~np.int32(5))


def test_metrics_unary():
    first = DummyMetric(-2)
    for metric, expected in [(abs(first), 2), (-first, -2), (+first, 2)]:
        metric.update()
        np.testing.assert_allclose(np.asarray(metric.compute()), expected)


def test_metrics_getitem():
    first = DummyMetric([1.0, 2.0, 3.0])
    final = first[1]
    final.update()
    np.testing.assert_allclose(np.asarray(final.compute()), 2.0)


def test_compositional_update_fans_out():
    a, b = DummyMetric(2), DummyMetric(3)
    comp = a + b
    comp.update()
    assert np.asarray(a._num_updates) == 1
    assert np.asarray(b._num_updates) == 1
    comp.reset()
    assert np.asarray(a._num_updates) == 0
    assert np.asarray(b._num_updates) == 0


def test_nested_composition():
    a, b = DummyMetric(2), DummyMetric(3)
    comp = (a + b) * 2
    comp.update()
    np.testing.assert_allclose(np.asarray(comp.compute()), 10)


def test_compositional_forward_returns_value():
    a = DummyMetric(2)
    comp = a + 3
    val = comp()
    np.testing.assert_allclose(np.asarray(val), 5)


def test_compositional_pure_api_under_jit():
    """The pure path threads explicit child states (keyed a/b) and matches
    the eager composition — metric (op) metric, metric (op) constant, and
    unary forms, all inside one jitted step."""
    import jax

    from metrics_tpu import Accuracy, Precision

    rng = np.random.RandomState(0)
    cases = [
        Accuracy() + Precision(average="micro"),
        Accuracy() * 2.0,
        2.0 - Accuracy(),
        abs(-Accuracy()),
    ]
    for comp in cases:
        eager = comp.clone()
        state = comp.init_state()
        step = jax.jit(comp.apply_update)
        for _ in range(3):
            p = jnp.asarray(rng.rand(32, 4).astype(np.float32))
            t = jnp.asarray(rng.randint(0, 4, 32))
            state = step(state, p, t)
            eager.update(p, t)
        np.testing.assert_allclose(
            np.asarray(comp.apply_compute(state)), np.asarray(eager.compute()), atol=1e-6
        )


def test_compositional_pure_api_aliased_operand():
    """m + m shares one instance: eager updates it twice per step; the pure
    path must advance the single shared state twice to match."""
    from metrics_tpu import Accuracy

    m = Accuracy()
    comp = m + m
    eager_m = Accuracy()
    eager = eager_m + eager_m

    rng = np.random.RandomState(3)
    state = comp.init_state()
    assert set(state) == {"a"}
    for _ in range(2):
        p = jnp.asarray(rng.rand(16, 4).astype(np.float32))
        t = jnp.asarray(rng.randint(0, 4, 16))
        state = comp.apply_update(state, p, t)
        eager.update(p, t)
    np.testing.assert_allclose(
        np.asarray(comp.apply_compute(state)), np.asarray(eager.compute()), atol=1e-6
    )
